"""Pluggable SHA-256 hashing backend for SSZ Merkleization.

The reference delegates hashing to pycryptodome via a 9-line shim
(eth2spec/utils/hash_function.py:8) and remerkleable's per-node
`merkle_root()`. Here the hasher is an explicit, swappable backend whose
unit of work is a *batch* of 64-byte blocks — the natural shape for a
TPU kernel (one Merkle level = one batched call), while the default host
backend just loops hashlib.

Backend contract: ``fn(data: bytes) -> bytes`` where ``len(data) % 64 == 0``
and the result is the concatenation of the 32-byte SHA-256 digests of each
64-byte block.
"""
from __future__ import annotations

import hashlib
from typing import Callable, Optional

HashManyFn = Callable[[bytes], bytes]


def _host_hash_many(data: bytes) -> bytes:
    n = len(data) // 64
    out = bytearray(32 * n)
    sha = hashlib.sha256
    for i in range(n):
        out[32 * i : 32 * i + 32] = sha(data[64 * i : 64 * i + 64]).digest()
    return bytes(out)


_backend: HashManyFn = _host_hash_many
_backend_name: str = "hashlib"


def set_backend(fn: Optional[HashManyFn], name: str = "custom") -> None:
    """Install a batched hasher; ``None`` restores the hashlib host backend."""
    global _backend, _backend_name
    if fn is None:
        _backend, _backend_name = _host_hash_many, "hashlib"
    else:
        _backend, _backend_name = fn, name


def backend_name() -> str:
    return _backend_name


def hash_many(data: bytes) -> bytes:
    """SHA-256 of each consecutive 64-byte block of ``data``, concatenated."""
    if len(data) % 64:
        raise ValueError(f"hash_many input must be a multiple of 64 bytes, got {len(data)}")
    if not data:
        return b""
    return _backend(data)


_fused_root_backend: Optional[Callable] = None
FUSED_ROOT_MIN_CHUNKS = 256  # below this, dispatch overhead beats the device


def set_fused_root_backend(fn: Optional[Callable]) -> None:
    """Install a whole-tree root backend: ``fn(chunks: bytes, limit: int)
    -> bytes`` computes the Merkle root of packed 32-byte chunks with
    zero-padding to ``limit`` leaves in ONE device dispatch (no per-level
    host round-trips — see ops.sha256.merkle_root_device)."""
    global _fused_root_backend
    _fused_root_backend = fn


def fused_root(chunks: bytes, limit: int) -> Optional[bytes]:
    """The fused whole-tree root, or None when no backend is installed or
    the tree is too small to be worth a device dispatch."""
    if _fused_root_backend is None or len(chunks) < 32 * FUSED_ROOT_MIN_CHUNKS:
        return None
    return _fused_root_backend(chunks, limit)


_small_backend: Optional[Callable] = None


def set_small_backend(fn: Optional[Callable]) -> None:
    """Install a batched short-message hasher: ``fn(messages) -> [digest]``
    for messages of <=55 bytes (one compression block after padding)."""
    global _small_backend
    _small_backend = fn


def sha256_many_small(messages) -> list:
    """Batched SHA-256 of many short (<=55 byte) messages. Each fits a
    single compression block after standard padding, so a device backend
    (ops.sha256.hash_small_device) does the whole batch in one raw-block
    kernel call. Used by the shuffle's per-round source hashes
    (beacon-chain.md:760-785) and proposer sampling; host default loops
    hashlib."""
    if _small_backend is not None:
        return _small_backend(messages)
    sha = hashlib.sha256
    return [sha(m).digest() for m in messages]


def sha256(data: bytes) -> bytes:
    """Plain one-shot SHA-256 (arbitrary length) — always on host.

    Spec-level `hash()` (eth2spec/utils/hash_function.py:8). Used for seeds,
    shuffling, randao mixes; the batched path is `hash_many`.
    """
    return hashlib.sha256(data).digest()


def hash_pair(a: bytes, b: bytes) -> bytes:
    """SHA-256(a || b) for two 32-byte nodes, through the batched backend."""
    return hash_many(a + b)
