"""Pluggable SHA-256 hashing backend for SSZ Merkleization.

The reference delegates hashing to pycryptodome via a 9-line shim
(eth2spec/utils/hash_function.py:8) and remerkleable's per-node
`merkle_root()`. Here the hasher is an explicit, swappable backend whose
unit of work is a *batch* of 64-byte blocks — the natural shape for a
TPU kernel (one Merkle level = one batched call), while the default host
backend just loops hashlib.

Backend contract: ``fn(data: bytes) -> bytes`` where ``len(data) % 64 == 0``
and the result is the concatenation of the 32-byte SHA-256 digests of each
64-byte block.
"""
from __future__ import annotations

import hashlib
from typing import Callable, Optional

HashManyFn = Callable[[bytes], bytes]


def _hashlib_hash_many(data: bytes) -> bytes:
    n = len(data) // 64
    out = bytearray(32 * n)
    sha = hashlib.sha256
    for i in range(n):
        out[32 * i : 32 * i + 32] = sha(data[64 * i : 64 * i + 64]).digest()
    return bytes(out)


_native = None
_native_tried = False


def _get_native():
    """The in-tree C batch hasher (SHA-NI when the host has it) — the
    analog of the reference's pycryptodome C backend. Lazily built on
    first hash (not at import: the build shells out to gcc). None if the
    toolchain is unavailable; callers fall back to hashlib."""
    global _native, _native_tried
    if _native_tried:
        return _native
    _native_tried = True
    try:
        from ..native import load_sha256, sha256_pairs, sha256_raw_blocks

        if load_sha256() is not None:
            _native = (sha256_pairs, sha256_raw_blocks)
    except Exception as e:  # degraded but functional: record why for debugging
        global _native_error
        _native_error = e
    return _native


_native_error: Optional[Exception] = None


def _host_hash_many(data: bytes) -> bytes:
    native = _get_native()
    if native is not None:
        return native[0](data)
    return _hashlib_hash_many(data)


_backend: HashManyFn = _host_hash_many
_backend_name: str = "hashlib"

_DEFAULT_DEVICE_MIN_BLOCKS = 64
_DEFAULT_FUSED_ROOT_MIN_CHUNKS = 128


def set_backend(fn: Optional[HashManyFn], name: str = "custom") -> None:
    """Install a batched hasher; ``None`` restores the hashlib host backend."""
    global _backend, _backend_name, DEVICE_MIN_BLOCKS, FUSED_ROOT_MIN_CHUNKS
    if fn is None:
        _backend, _backend_name = _host_hash_many, "hashlib"
        DEVICE_MIN_BLOCKS = _DEFAULT_DEVICE_MIN_BLOCKS
        FUSED_ROOT_MIN_CHUNKS = _DEFAULT_FUSED_ROOT_MIN_CHUNKS
    else:
        from ..sched import configure_compile_cache

        configure_compile_cache()  # knob-gated; before the hasher's jits build
        _backend, _backend_name = fn, name


def backend_name() -> str:
    return _backend_name


DEVICE_MIN_BLOCKS = 64  # below this, host hashlib beats the dispatch overhead

HASH_CAPABILITY = "hash.device"


def _device_call(fn: Callable, host_fn: Callable, *args):
    """Supervised non-host hasher dispatch: transient faults retry,
    terminal faults quarantine ``hash.device`` and the host path (always
    bit-identical — same SHA-256) takes over with a recorded event."""
    from .. import obs
    from ..resilience import chaos, is_quarantined, supervised

    if is_quarantined(HASH_CAPABILITY):
        return host_fn(*args)

    def _attempt():
        chaos("hash.dispatch")
        return fn(*args)

    nbytes = sum(len(a) for a in args if isinstance(a, (bytes, bytearray)))
    with obs.kernel_span("hash.dispatch", backend=_backend_name, bytes=nbytes):
        return supervised(_attempt, domain="crypto.hash", capability=HASH_CAPABILITY,
                          fallback=lambda: host_fn(*args))


def hash_many(data: bytes) -> bytes:
    """SHA-256 of each consecutive 64-byte block of ``data``, concatenated.

    Small batches always run on host even when a device backend is
    installed: a device dispatch costs ~100µs while hashlib does a 64-byte
    block in ~1µs, so sub-``DEVICE_MIN_BLOCKS`` batches never win on device.
    """
    if len(data) % 64:
        raise ValueError(f"hash_many input must be a multiple of 64 bytes, got {len(data)}")
    if not data:
        return b""
    if _backend is not _host_hash_many:
        if len(data) < 64 * DEVICE_MIN_BLOCKS:
            return _host_hash_many(data)
        return _device_call(_backend, _host_hash_many, data)
    return _backend(data)


_fused_root_backend: Optional[Callable] = None
FUSED_ROOT_MIN_CHUNKS = 128  # below this, dispatch overhead beats the device


def set_fused_root_backend(fn: Optional[Callable]) -> None:
    """Install a whole-tree root backend: ``fn(chunks: bytes, limit: int)
    -> bytes`` computes the Merkle root of packed 32-byte chunks with
    zero-padding to ``limit`` leaves in ONE device dispatch (no per-level
    host round-trips — see ops.sha256.merkle_root_device)."""
    global _fused_root_backend
    _fused_root_backend = fn


def fused_root(chunks: bytes, limit: int) -> Optional[bytes]:
    """The fused whole-tree root, or None when no backend is installed,
    the tree is too small to be worth a device dispatch, or the device
    hasher is quarantined (callers' level-by-level path is the host
    fallback)."""
    if _fused_root_backend is None or len(chunks) < 32 * FUSED_ROOT_MIN_CHUNKS:
        return None
    return _device_call(_fused_root_backend, lambda *_: None, chunks, limit)


_tree_backend: Optional[Callable] = None
TREE_DEVICE_MIN_CHUNKS = 1 << 15


def set_tree_backend(fn: Optional[Callable]) -> None:
    """Install a whole-tree interior-level builder: ``fn(leaves: bytes) ->
    [level_bytes]`` returns every interior Merkle level (height 1 upward,
    pow2-padded) in ONE device dispatch — used by ChunkTree when
    materializing levels for incremental updates."""
    global _tree_backend
    _tree_backend = fn


def tree_levels(leaves: bytes) -> Optional[list]:
    """Fused interior-level build, or None when no backend is installed or
    the tree is too small for a dispatch to win."""
    if _tree_backend is None or len(leaves) < 32 * TREE_DEVICE_MIN_CHUNKS:
        return None
    return _tree_backend(leaves)


_item_roots_backend: Optional[Callable] = None
ITEM_ROOTS_MIN_ITEMS = 1 << 14


def set_item_roots_backend(fn: Optional[Callable]) -> None:
    """Install a per-item subtree-root kernel: ``fn(packed: bytes,
    chunks_per_item: int) -> bytes`` reduces N independent pow2-chunk
    subtrees (item-major layout) to N 32-byte roots in one dispatch."""
    global _item_roots_backend
    _item_roots_backend = fn


def item_roots(packed: bytes, chunks_per_item: int) -> bytes:
    """Batched independent-subtree roots; host fallback reduces level by
    level through `hash_many` (item-major layout keeps items disjoint)."""
    n_items = len(packed) // (32 * chunks_per_item)
    if _item_roots_backend is not None and n_items >= ITEM_ROOTS_MIN_ITEMS:
        return _item_roots_backend(packed, chunks_per_item)
    nodes = packed
    while len(nodes) > 32 * n_items:
        nodes = hash_many(nodes)
    return nodes


_small_backend: Optional[Callable] = None


def set_small_backend(fn: Optional[Callable]) -> None:
    """Install a batched short-message hasher: ``fn(messages) -> [digest]``
    for messages of <=55 bytes (one compression block after padding)."""
    global _small_backend
    _small_backend = fn


def sha256_many_small(messages) -> list:
    """Batched SHA-256 of many short (<=55 byte) messages. Each fits a
    single compression block after standard padding, so a device backend
    (ops.sha256.hash_small_device) does the whole batch in one raw-block
    kernel call. Used by the shuffle's per-round source hashes
    (beacon-chain.md:760-785) and proposer sampling; host default loops
    hashlib."""
    if _small_backend is not None:
        return _small_backend(messages)
    native = _get_native()
    if native is not None and len(messages) >= 16 and all(len(m) <= 55 for m in messages):
        # pad each message into one raw block on host, hash the batch in C
        # (>55 bytes would need a second compression block — hashlib path)
        buf = bytearray(64 * len(messages))
        for i, m in enumerate(messages):
            off = 64 * i
            buf[off : off + len(m)] = m
            buf[off + len(m)] = 0x80
            buf[off + 56 : off + 64] = (8 * len(m)).to_bytes(8, "big")
        raw = native[1](bytes(buf))
        return [raw[32 * i : 32 * i + 32] for i in range(len(messages))]
    sha = hashlib.sha256
    return [sha(m).digest() for m in messages]


def sha256(data: bytes) -> bytes:
    """Plain one-shot SHA-256 (arbitrary length) — always on host.

    Spec-level `hash()` (eth2spec/utils/hash_function.py:8). Used for seeds,
    shuffling, randao mixes; the batched path is `hash_many`.
    """
    return hashlib.sha256(data).digest()


def hash_pair(a: bytes, b: bytes) -> bytes:
    """SHA-256(a || b) for two 32-byte nodes, through the batched backend."""
    return hash_many(a + b)
