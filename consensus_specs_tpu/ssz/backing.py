"""Contiguous, dirty-tracked Merkle backing for SSZ sequences.

The reference gets incremental `hash_tree_root` from remerkleable's
persistent binary trees with per-node root caching
(eth2spec/utils/ssz/ssz_impl.py:11-13 — `get_backing().merkle_root()`).
That design is pointer-chasing-heavy and hostile to batched hashing.

This is the TPU-first equivalent: a sequence's chunk leaves live in ONE
contiguous bytearray; mutations mark dirty leaf indices; a root request
re-hashes only the dirty paths, with every Merkle level's dirty nodes
hashed in a single batched `hash_many` call. The first root of a large
un-mutated tree takes the fused whole-tree device path (one dispatch,
only 32 bytes return); interior levels are materialized lazily on the
first mutated root, after which updates cost O(dirty · log n) hashes.

Virtual zero-padding to the type's limit (e.g. `List[..., 2**40]`) is a
fold through the precomputed zero-hash table — never allocated.
"""
from __future__ import annotations

from typing import Optional

from . import hashing
from .merkle import ZERO_HASHES, ceil_log2, merkleize_chunks


class ChunkTree:
    """Merkle tree over 32-byte leaf chunks with dirty-index tracking.

    Leaves are stored packed in ``self.leaves`` (``count * 32`` bytes).
    ``set_leaf``/``truncate`` are the only mutators; ``root()`` folds the
    tree up to ``depth = ceil_log2(limit)`` with zero-subtree padding.
    """

    __slots__ = ("leaves", "limit", "_levels", "_dirty", "_root")

    def __init__(self, leaves: bytearray, limit: int):
        self.leaves = leaves
        self.limit = max(int(limit), 1)
        self._levels: Optional[list] = None  # _levels[k-1] = packed nodes at height k
        self._dirty: set = set()
        self._root: Optional[bytes] = None

    @property
    def count(self) -> int:
        return len(self.leaves) // 32

    def copy(self) -> "ChunkTree":
        t = ChunkTree(bytearray(self.leaves), self.limit)
        if self._levels is not None:
            t._levels = [bytearray(level) for level in self._levels]
        t._dirty = set(self._dirty)
        t._root = self._root
        return t

    def get_leaf(self, i: int) -> bytes:
        return bytes(self.leaves[32 * i : 32 * i + 32])

    def set_leaf(self, i: int, chunk: bytes) -> None:
        """Write leaf ``i``; ``i == count`` appends a new leaf."""
        n = self.count
        if i == n:
            if n + 1 > self.limit:
                raise ValueError(f"ChunkTree: leaf {i} exceeds limit {self.limit}")
            self.leaves += chunk
        elif i < n:
            self.leaves[32 * i : 32 * i + 32] = chunk
        else:
            raise IndexError(f"ChunkTree: leaf {i} out of range (count {n})")
        self._dirty.add(i)
        self._root = None

    def truncate(self, n: int) -> None:
        """Drop leaves past ``n``. Ancestors of the new last leaf are the
        only surviving nodes whose children change (the last surviving node
        at height k is (n-1)>>k — exactly the last leaf's ancestor), so
        marking leaf n-1 dirty plus truncating each level is sufficient."""
        old = self.count
        if n >= old:
            return
        del self.leaves[32 * n :]
        if self._levels is not None:
            size = n
            for k, level in enumerate(self._levels, start=1):
                size = (size + 1) // 2
                del level[32 * size :]
        self._dirty = {i for i in self._dirty if i < n}
        if n > 0:
            self._dirty.add(n - 1)
        self._root = None

    # -- root computation ---------------------------------------------------

    def _full_build(self) -> None:
        """Materialize all interior levels. Large trees: ONE fused device
        dispatch returning every level (hashing.tree_levels); otherwise
        level-by-level, each level one batched hash_many call."""
        fused = hashing.tree_levels(bytes(self.leaves))
        if fused is not None:
            # fused levels are pow2-padded; trim each to the real node count
            size = self.count
            levels = []
            for lv in fused:
                size = (size + 1) // 2
                levels.append(bytearray(lv[: 32 * size]))
                if size == 1:
                    break
            self._levels = levels
            self._dirty.clear()
            return
        levels = []
        nodes = bytes(self.leaves)
        k = 0
        while len(nodes) > 32:
            if (len(nodes) // 32) % 2:
                nodes += ZERO_HASHES[k]
            nodes = hashing.hash_many(nodes)
            levels.append(bytearray(nodes))
            k += 1
        self._levels = levels
        self._dirty.clear()

    def _incremental_update(self) -> None:
        levels = self._levels
        size = self.count
        idxs = self._dirty
        nodes = self.leaves
        k = 0
        while size > 1:
            parent_size = (size + 1) // 2
            parents = sorted({i >> 1 for i in idxs if i < size})
            level = levels[k] if k < len(levels) else None
            if level is None:
                level = bytearray()
                levels.append(level)
            if len(level) < 32 * parent_size:
                level += b"\x00" * (32 * parent_size - len(level))
            if parents:
                buf = bytearray()
                for p in parents:
                    li, ri = 2 * p, 2 * p + 1
                    buf += nodes[32 * li : 32 * li + 32]
                    if ri < size:
                        buf += nodes[32 * ri : 32 * ri + 32]
                    else:
                        buf += ZERO_HASHES[k]
                digests = hashing.hash_many(bytes(buf))
                for j, p in enumerate(parents):
                    level[32 * p : 32 * p + 32] = digests[32 * j : 32 * j + 32]
            idxs = set(parents)
            nodes = level
            size = parent_size
            k += 1
        del levels[k:]
        self._dirty.clear()

    def root(self) -> bytes:
        if self._root is not None:
            return self._root
        count = self.count
        depth = ceil_log2(self.limit)
        if count == 0:
            self._root = ZERO_HASHES[depth]
            return self._root
        if self._levels is None:
            if not self._dirty and count >= 2:
                # first root of a clean tree: fused one-dispatch device path
                # (or host merkleize); interior levels stay unmaterialized
                self._root = merkleize_chunks(bytes(self.leaves), limit=self.limit)
                return self._root
            self._full_build()
        elif self._dirty:
            self._incremental_update()
        top = self._levels[-1] if self._levels else self.leaves
        node = bytes(top[:32]) if len(top) >= 32 else ZERO_HASHES[0]
        level = len(self._levels) if self._levels else 0
        while level < depth:
            node = hashing.hash_many(node + ZERO_HASHES[level])
            level += 1
        self._root = node
        return node
