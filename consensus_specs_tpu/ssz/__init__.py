"""SSZ: types, serialization, Merkleization (ref: ssz/simple-serialize.md,
eth2spec/utils/ssz/{ssz_impl,ssz_typing}.py)."""
from .types import (
    BYTES_PER_CHUNK,
    Bit,
    Bitlist,
    Bitvector,
    ByteList,
    ByteVector,
    Bytes1,
    Bytes4,
    Bytes8,
    Bytes20,
    Bytes32,
    Bytes48,
    Bytes96,
    Container,
    List,
    SSZType,
    Union,
    Vector,
    boolean,
    byte,
    get_generalized_index,
    get_generalized_index_length,
    uint,
    uint8,
    uint16,
    uint32,
    uint64,
    uint128,
    uint256,
)
from .merkle import (
    ZERO_HASHES,
    calc_merkle_tree_from_leaves,
    compute_merkle_proof_root,
    get_merkle_proof,
    get_merkle_root,
    merkleize_chunks,
    mix_in_length,
    mix_in_selector,
    next_pow2,
)
from . import hashing


def serialize(obj) -> bytes:
    """ssz_impl.serialize (eth2spec/utils/ssz/ssz_impl.py:8)."""
    return obj.encode_bytes()


def hash_tree_root(obj) -> Bytes32:
    """ssz_impl.hash_tree_root (eth2spec/utils/ssz/ssz_impl.py:11-13)."""
    return Bytes32(obj.hash_tree_root())


def uint_to_bytes(n: uint) -> bytes:
    """ssz_impl.uint_to_bytes (eth2spec/utils/ssz/ssz_impl.py:17-18)."""
    return n.encode_bytes()


def copy(obj):
    return obj.copy()
