"""SSZ type system: value-backed, mutable views with on-demand Merkleization.

Feature parity with the reference's remerkleable-based typing surface
(eth2spec/utils/ssz/ssz_typing.py:4-12; normative rules ssz/simple-serialize.md):
uintN, boolean, Container, Vector, List, ByteVector, ByteList, Bitvector,
Bitlist, Union, plus generalized indices (ssz/merkle-proofs.md:58-189).

Design differences from remerkleable: objects are plain Python values (ints,
bytes, lists) rather than persistent binary trees. Roots are computed on
demand by flattening to chunk lists and reducing level-by-level through the
batched hasher (`hashing.hash_many`) — the shape a TPU kernel wants.

Assignment semantics: mutable composites (Containers, sequences, bit
types, Unions) pass through an ownership barrier on their way into any
parent slot (`_adopt`): a fresh value is adopted in place, while a value
already owned by some parent is snapshotted first. Two parents therefore
never share one mutable child — remerkleable's assignment-captures-the-
current-backing semantics, enforced structurally (regression:
tests/test_ssz_basic.py::test_no_aliasing_between_parents).
"""
from __future__ import annotations

import sys
import weakref
from array import array
from typing import Any, Dict, Optional, Sequence, Tuple

from . import hashing
from .backing import ChunkTree
from .merkle import (
    merkleize_chunks,
    mix_in_length,
    mix_in_selector,
    next_pow2,
)

BYTES_PER_CHUNK = 32
OFFSET_BYTE_LENGTH = 4


def _pad_to_chunks(data: bytes) -> bytes:
    if len(data) % BYTES_PER_CHUNK:
        return data + b"\x00" * (BYTES_PER_CHUNK - len(data) % BYTES_PER_CHUNK)
    return data


class _Cached:
    """Incremental-root machinery shared by all mutable composites.

    The remerkleable capability (ssz_impl.py:11-13 — per-node root caching
    with structural sharing) rebuilt for a value-backed object model:

    - every composite caches its `hash_tree_root` (`_ht_cache`);
    - children keep weakrefs to their parents + the slot they occupy, so an
      in-place mutation anywhere invalidates exactly the ancestor chain
      (O(depth), not O(state));
    - sequences additionally record WHICH slots went dirty, so a root
      recompute re-hashes only dirty subtrees (see `ChunkTree`).

    Invariant: whenever a composite's `_ht_cache` is None, every parent has
    already been notified (its cache is cleared and, for sequences, the
    child's slot is in its dirty set). Established at mutation time by
    `_mark_self_dirty`/`_receive_dirty` and at link time because linking
    happens during root computation (which fills the cache).
    """

    _ht_cache: Optional[bytes] = None
    _parents: Optional[list] = None
    _owned: bool = False

    def _set_cache(self, v: Optional[bytes]) -> None:
        object.__setattr__(self, "_ht_cache", v)

    def _link_child(self, child, slot) -> None:
        """Record that `child` occupies `slot` of self (idempotent)."""
        if not isinstance(child, _Cached):
            return
        ps = child._parents
        if ps is None:
            ps = []
            object.__setattr__(child, "_parents", ps)
        for r, s in ps:
            if s == slot and r() is self:
                return
        ps.append((weakref.ref(self), slot))

    def _receive_dirty(self, slot) -> bool:
        """A child at `slot` changed. Returns True if this node was clean
        (so its own parents need notifying in turn)."""
        if self._ht_cache is None:
            return False
        self._set_cache(None)
        return True

    def _bubble(self) -> None:
        """Propagate invalidation to all (live) ancestors."""
        stack: list = [self]
        while stack:
            obj = stack.pop()
            ps = obj._parents
            if not ps:
                continue
            dead = False
            for ref, slot in ps:
                p = ref()
                if p is None:
                    dead = True
                    continue
                if p._receive_dirty(slot):
                    stack.append(p)
            if dead:
                object.__setattr__(obj, "_parents", [(r, s) for r, s in ps if r() is not None])

    def _mark_self_dirty(self) -> None:
        """Call after any in-place mutation of this value."""
        if self._ht_cache is not None:
            self._set_cache(None)
            self._bubble()
        # cache already None ⇒ ancestors were notified when it was cleared


def _adopt(value):
    """Ownership barrier for mutable composites entering a parent slot.

    A freshly-built value is adopted in place (no copy); adopting a value
    that some parent already owns snapshots it first, so two parents can
    never share one mutable child. This is remerkleable's assignment
    semantics (a view assignment captures the value's current backing,
    ssz_impl.py:11-13) enforced on the value-backed model — the
    one-forgotten-`.copy()` root-corruption footgun cannot occur.
    Immutable leaves (uints, ByteVector/ByteList bytes) are shared freely.
    """
    if isinstance(value, _Cached):
        if value._owned:
            value = value.copy()
        object.__setattr__(value, "_owned", True)
    return value


class SSZType:
    """Shared classmethod protocol; every SSZ class also implements
    encode_bytes()/hash_tree_root() on instances."""

    @classmethod
    def is_fixed_byte_length(cls) -> bool:
        raise NotImplementedError

    @classmethod
    def type_byte_length(cls) -> int:
        raise NotImplementedError  # fixed-size types only

    @classmethod
    def decode_bytes(cls, data: bytes):
        raise NotImplementedError

    @classmethod
    def default(cls):
        raise NotImplementedError

    @classmethod
    def coerce(cls, value):
        if isinstance(value, cls):
            return value
        return cls(value)

    def encode_bytes(self) -> bytes:
        raise NotImplementedError

    def hash_tree_root(self) -> bytes:
        raise NotImplementedError

    def copy(self):
        return type(self).decode_bytes(self.encode_bytes())


# ---------------------------------------------------------------------------
# Basic types
# ---------------------------------------------------------------------------


class uint(int, SSZType):
    byte_len: int = 0

    def __new__(cls, value: Any = 0):
        if isinstance(value, (float,)) or (isinstance(value, bool) and cls.byte_len != 1):
            value = int(value)
        v = int(value)
        if v < 0 or v >> (cls.byte_len * 8):
            raise ValueError(f"{cls.__name__} out of range: {v}")
        return super().__new__(cls, v)

    @classmethod
    def is_fixed_byte_length(cls) -> bool:
        return True

    @classmethod
    def type_byte_length(cls) -> int:
        return cls.byte_len

    @classmethod
    def decode_bytes(cls, data: bytes):
        if len(data) != cls.byte_len:
            raise ValueError(f"{cls.__name__}: expected {cls.byte_len} bytes, got {len(data)}")
        return cls(int.from_bytes(data, "little"))

    @classmethod
    def default(cls):
        return cls(0)

    def encode_bytes(self) -> bytes:
        return int(self).to_bytes(self.byte_len, "little")

    def hash_tree_root(self) -> bytes:
        return self.encode_bytes() + b"\x00" * (32 - self.byte_len)

    def copy(self):
        return self


class uint8(uint):
    byte_len = 1


class uint16(uint):
    byte_len = 2


class uint32(uint):
    byte_len = 4


class uint64(uint):
    byte_len = 8


class uint128(uint):
    byte_len = 16


class uint256(uint):
    byte_len = 32


byte = uint8


class boolean(uint):
    byte_len = 1

    def __new__(cls, value: Any = 0):
        v = int(value)
        if v not in (0, 1):
            raise ValueError(f"boolean out of range: {v}")
        return super().__new__(cls, v)

    def __bool__(self):
        return int(self) == 1

    def __repr__(self):
        return f"boolean({int(self)})"


class Bit(boolean):
    pass


# ---------------------------------------------------------------------------
# Parameterized-type machinery
# ---------------------------------------------------------------------------

_param_cache: Dict[Tuple, type] = {}


def _parameterize(base: type, key: Tuple, name: str, ns: Dict[str, Any]) -> type:
    cache_key = (base, key)
    if cache_key not in _param_cache:
        _param_cache[cache_key] = type(name, (base,), ns)
    return _param_cache[cache_key]


# ---------------------------------------------------------------------------
# ByteVector / ByteList
# ---------------------------------------------------------------------------


class ByteVector(bytes, SSZType):
    length: int = 0

    def __class_getitem__(cls, length: int) -> type:
        return _parameterize(ByteVector, (length,), f"ByteVector[{length}]", {"length": length})

    def __new__(cls, *args):
        if cls.length == 0 and cls is ByteVector:
            raise TypeError("ByteVector must be parameterized: ByteVector[N]")
        if len(args) == 0:
            data = b"\x00" * cls.length
        elif len(args) == 1:
            v = args[0]
            if isinstance(v, str):
                data = bytes.fromhex(v[2:] if v.startswith("0x") else v)
            elif isinstance(v, (bytes, bytearray, memoryview)):
                data = bytes(v)
            else:
                data = bytes(v)
        else:
            data = bytes(args)
        if len(data) != cls.length:
            raise ValueError(f"{cls.__name__}: expected {cls.length} bytes, got {len(data)}")
        return super().__new__(cls, data)

    @classmethod
    def is_fixed_byte_length(cls) -> bool:
        return True

    @classmethod
    def type_byte_length(cls) -> int:
        return cls.length

    @classmethod
    def decode_bytes(cls, data: bytes):
        return cls(data)

    @classmethod
    def default(cls):
        return cls()

    def encode_bytes(self) -> bytes:
        return bytes(self)

    def hash_tree_root(self) -> bytes:
        # immutable: root cached per instance, computed lazily
        try:
            return self._htr
        except AttributeError:
            root = merkleize_chunks(_pad_to_chunks(bytes(self)), limit=(self.length + 31) // 32)
            self._htr = root
            return root

    def copy(self):
        return self

    def __repr__(self):
        return f"{type(self).__name__}(0x{bytes(self).hex()})"


Bytes1 = ByteVector[1]
Bytes4 = ByteVector[4]
Bytes8 = ByteVector[8]
Bytes20 = ByteVector[20]
Bytes32 = ByteVector[32]
Bytes48 = ByteVector[48]
Bytes96 = ByteVector[96]


class ByteList(bytes, SSZType):
    limit: int = 0

    def __class_getitem__(cls, limit: int) -> type:
        return _parameterize(ByteList, (limit,), f"ByteList[{limit}]", {"limit": limit})

    def __new__(cls, *args):
        if len(args) == 0:
            data = b""
        elif len(args) == 1:
            v = args[0]
            if isinstance(v, str):
                data = bytes.fromhex(v[2:] if v.startswith("0x") else v)
            else:
                data = bytes(v)
        else:
            data = bytes(args)
        if len(data) > cls.limit:
            raise ValueError(f"{cls.__name__}: {len(data)} bytes exceeds limit {cls.limit}")
        return super().__new__(cls, data)

    @classmethod
    def is_fixed_byte_length(cls) -> bool:
        return False

    @classmethod
    def decode_bytes(cls, data: bytes):
        return cls(data)

    @classmethod
    def default(cls):
        return cls()

    def encode_bytes(self) -> bytes:
        return bytes(self)

    def hash_tree_root(self) -> bytes:
        try:
            return self._htr
        except AttributeError:
            root = mix_in_length(
                merkleize_chunks(_pad_to_chunks(bytes(self)), limit=(self.limit + 31) // 32),
                len(self),
            )
            self._htr = root
            return root

    def copy(self):
        return self

    def __repr__(self):
        return f"{type(self).__name__}(0x{bytes(self).hex()})"


# ---------------------------------------------------------------------------
# Bitvector / Bitlist
# ---------------------------------------------------------------------------


def _bits_to_bytes(bits: Sequence[bool]) -> bytes:
    out = bytearray((len(bits) + 7) // 8)
    for i, b in enumerate(bits):
        if b:
            out[i // 8] |= 1 << (i % 8)
    return bytes(out)


class _BitsBase(_Cached, SSZType):
    def __init__(self, *args):
        if len(args) == 1 and isinstance(args[0], (list, tuple, _BitsBase)):
            bits = [bool(b) for b in args[0]]
        elif len(args) == 1 and isinstance(args[0], (bytes, bytearray)):
            raise TypeError("use decode_bytes for serialized bit data")
        else:
            bits = [bool(b) for b in args]
        self._check_len(len(bits))
        self._bits = bits

    def _check_len(self, n: int) -> None:
        raise NotImplementedError

    def __len__(self):
        return len(self._bits)

    def __iter__(self):
        return iter(self._bits)

    def __getitem__(self, i):
        return self._bits[i]

    def __setitem__(self, i, v):
        if isinstance(i, slice):
            # Validate the post-assignment length BEFORE committing so a
            # failed check can't leave the value corrupted.
            new_bits = list(self._bits)
            new_bits[i] = [bool(x) for x in v]
            self._check_len(len(new_bits))
            self._bits = new_bits
        else:
            self._bits[i] = bool(v)
        self._mark_self_dirty()

    def _type_key(self):
        bound = self.length if isinstance(self, Bitvector) else self.limit
        return (isinstance(self, Bitvector), int(bound))

    def __eq__(self, other):
        if isinstance(other, _BitsBase):
            # Same kind + same bound + equal bits; cross-module parameterized
            # classes compare by value (see _SequenceBase), and __hash__ uses
            # the same key so the eq/hash contract holds.
            if self._type_key() != other._type_key():
                return NotImplemented
            return self._bits == other._bits
        if isinstance(other, (list, tuple)):
            return self._bits == [bool(b) for b in other]
        return NotImplemented

    def __hash__(self):
        return hash((self._type_key(), tuple(self._bits)))

    def __repr__(self):
        return f"{type(self).__name__}({''.join('1' if b else '0' for b in self._bits)})"

    def copy(self):
        new = type(self)(self._bits)
        new._set_cache(self._ht_cache)
        return new


class Bitvector(_BitsBase):
    length: int = 0

    def __class_getitem__(cls, length: int) -> type:
        return _parameterize(Bitvector, (length,), f"Bitvector[{length}]", {"length": length})

    def __init__(self, *args):
        if len(args) == 0:
            args = ([False] * self.length,)
        super().__init__(*args)

    def _check_len(self, n: int) -> None:
        if n != self.length:
            raise ValueError(f"{type(self).__name__}: expected {self.length} bits, got {n}")

    @classmethod
    def is_fixed_byte_length(cls) -> bool:
        return True

    @classmethod
    def type_byte_length(cls) -> int:
        return (cls.length + 7) // 8

    @classmethod
    def decode_bytes(cls, data: bytes):
        if len(data) != (cls.length + 7) // 8:
            raise ValueError(f"{cls.__name__}: bad byte length {len(data)}")
        bits = [bool((data[i // 8] >> (i % 8)) & 1) for i in range(cls.length)]
        # Padding bits past `length` must be zero.
        for i in range(cls.length, len(data) * 8):
            if (data[i // 8] >> (i % 8)) & 1:
                raise ValueError(f"{cls.__name__}: nonzero padding bit")
        return cls(bits)

    @classmethod
    def default(cls):
        return cls()

    def encode_bytes(self) -> bytes:
        return _bits_to_bytes(self._bits)

    def hash_tree_root(self) -> bytes:
        c = self._ht_cache
        if c is None:
            c = merkleize_chunks(
                _pad_to_chunks(self.encode_bytes()), limit=(self.length + 255) // 256
            )
            self._set_cache(c)
        return c


class Bitlist(_BitsBase):
    limit: int = 0

    def __class_getitem__(cls, limit: int) -> type:
        return _parameterize(Bitlist, (limit,), f"Bitlist[{limit}]", {"limit": limit})

    def _check_len(self, n: int) -> None:
        if n > self.limit:
            raise ValueError(f"{type(self).__name__}: {n} bits exceeds limit {self.limit}")

    @classmethod
    def is_fixed_byte_length(cls) -> bool:
        return False

    @classmethod
    def decode_bytes(cls, data: bytes):
        if len(data) == 0:
            raise ValueError(f"{cls.__name__}: empty serialization (missing delimiter)")
        if data[-1] == 0:
            raise ValueError(f"{cls.__name__}: last byte must contain delimiter bit")
        total_bits = (len(data) - 1) * 8 + data[-1].bit_length() - 1
        if total_bits > cls.limit:
            raise ValueError(f"{cls.__name__}: {total_bits} bits exceeds limit {cls.limit}")
        bits = [bool((data[i // 8] >> (i % 8)) & 1) for i in range(total_bits)]
        return cls(bits)

    @classmethod
    def default(cls):
        return cls([])

    def encode_bytes(self) -> bytes:
        n = len(self._bits)
        out = bytearray((n // 8) + 1)
        for i, b in enumerate(self._bits):
            if b:
                out[i // 8] |= 1 << (i % 8)
        out[n // 8] |= 1 << (n % 8)  # delimiter bit
        return bytes(out)

    def hash_tree_root(self) -> bytes:
        c = self._ht_cache
        if c is None:
            root = merkleize_chunks(
                _pad_to_chunks(_bits_to_bytes(self._bits)), limit=(self.limit + 255) // 256
            )
            c = mix_in_length(root, len(self._bits))
            self._set_cache(c)
        return c


# ---------------------------------------------------------------------------
# Composite serialization helpers (simple-serialize.md:105-187)
# ---------------------------------------------------------------------------


def _serialize_parts(values: Sequence[Any]) -> bytes:
    fixed_parts = []
    variable_parts = []
    for v in values:
        if type(v).is_fixed_byte_length():
            fixed_parts.append(v.encode_bytes())
            variable_parts.append(b"")
        else:
            fixed_parts.append(None)
            variable_parts.append(v.encode_bytes())
    fixed_len = sum(OFFSET_BYTE_LENGTH if p is None else len(p) for p in fixed_parts)
    out = []
    offset = fixed_len
    for p, v in zip(fixed_parts, variable_parts):
        if p is None:
            out.append(offset.to_bytes(OFFSET_BYTE_LENGTH, "little"))
            offset += len(v)
        else:
            out.append(p)
    out.extend(v for v in variable_parts if v)
    return b"".join(out)


def _decode_parts(data: bytes, types: Sequence[type]) -> list:
    """Split a composite serialization into per-element byte ranges and decode."""
    n = len(types)
    fixed_lens = [t.type_byte_length() if t.is_fixed_byte_length() else None for t in types]
    fixed_total = sum(OFFSET_BYTE_LENGTH if fl is None else fl for fl in fixed_lens)
    if len(data) < fixed_total:
        raise ValueError(f"composite: {len(data)} bytes < fixed size {fixed_total}")
    offsets = []
    pos = 0
    for fl in fixed_lens:
        if fl is None:
            offsets.append(int.from_bytes(data[pos : pos + OFFSET_BYTE_LENGTH], "little"))
            pos += OFFSET_BYTE_LENGTH
        else:
            pos += fl
    if offsets:
        if offsets[0] != fixed_total:
            raise ValueError(f"composite: first offset {offsets[0]} != fixed size {fixed_total}")
        for a, b in zip(offsets, offsets[1:]):
            if b < a:
                raise ValueError("composite: offsets not monotonic")
        if offsets[-1] > len(data):
            raise ValueError("composite: offset past end")
    elif len(data) != fixed_total:
        raise ValueError(f"composite: trailing bytes ({len(data)} != {fixed_total})")
    values = []
    pos = 0
    oi = 0
    for t, fl in zip(types, fixed_lens):
        if fl is None:
            start = offsets[oi]
            end = offsets[oi + 1] if oi + 1 < len(offsets) else len(data)
            oi += 1
            values.append(t.decode_bytes(data[start:end]))
            pos += OFFSET_BYTE_LENGTH
        else:
            values.append(t.decode_bytes(data[pos : pos + fl]))
            pos += fl
    return values


# ---------------------------------------------------------------------------
# Vector / List
# ---------------------------------------------------------------------------


def _is_basic(t: type) -> bool:
    return issubclass(t, uint)


_ARRAY_CODES = {1: "B", 2: "H", 4: "I", 8: "Q"}


def _pack_basic_items(items, elem_type) -> bytearray:
    """Pack basic elements into chunk-padded contiguous bytes. uintN with a
    native array code takes the C fast path (little-endian platforms)."""
    size = elem_type.type_byte_length()
    code = _ARRAY_CODES.get(size)
    if code is not None and sys.byteorder == "little":
        buf = bytearray(array(code, items).tobytes())
    else:
        buf = bytearray(b"".join(v.encode_bytes() for v in items))
    if len(buf) % BYTES_PER_CHUNK:
        buf += b"\x00" * (BYTES_PER_CHUNK - len(buf) % BYTES_PER_CHUNK)
    return buf


def _container_flat_plan(cls) -> Optional[list]:
    """For fixed-size containers whose fields are all immutable scalars
    (uintN / boolean / ByteVector<=64), the per-field root recipe enabling
    batched whole-sequence leaf computation (the Validator case — the hot
    leaf type of the registry). Entries are (name, kind, nbytes). None when
    the container has mutable or large fields (falls back to per-item roots)."""
    plan = cls.__dict__.get("_flat_plan", False)
    if plan is not False:
        return plan
    plan = []
    for name, t in cls._fields.items():
        if issubclass(t, uint):
            plan.append((name, "uint", t.byte_len))
        elif issubclass(t, ByteVector):
            if t.length <= 32:
                plan.append((name, "bytes", t.length))
            elif t.length <= 64:
                plan.append((name, "hash2", t.length))
            else:
                plan = None
                break
        else:
            plan = None
            break
    cls._flat_plan = plan
    return plan


def _batched_container_roots(items, plan) -> bytes:
    """Roots of N same-type flat containers, column-at-a-time: each field's
    values are gathered once (numpy scatter into the (N, F'·32) chunk
    matrix), >32-byte fields get ONE batched hash over all items, then one
    hash_many per tree level reduces every item's root simultaneously
    (field counts pad to the same power of two, so a flat level-reduce
    never mixes chunks across items)."""
    import numpy as np
    from operator import attrgetter

    n = len(items)
    fp = next_pow2(len(plan))
    buf = np.zeros((n, fp * 32), dtype=np.uint8)
    for j, (name, kind, nbytes) in enumerate(plan):
        get = attrgetter(name)
        col = buf[:, 32 * j : 32 * j + 32]
        if kind == "uint" and nbytes in (1, 2, 4, 8):
            arr = np.fromiter(map(get, items), dtype=f"<u{nbytes}", count=n)
            col[:, :nbytes] = arr.view(np.uint8).reshape(n, nbytes)
        elif kind == "uint":
            raw = b"".join(v.encode_bytes() for v in map(get, items))
            col[:, :nbytes] = np.frombuffer(raw, dtype=np.uint8).reshape(n, nbytes)
        elif kind == "bytes":
            raw = b"".join(map(get, items))
            col[:, :nbytes] = np.frombuffer(raw, dtype=np.uint8).reshape(n, nbytes)
        else:  # hash2: two chunks -> one batched hash per field
            raw = b"".join(map(get, items))
            padded = np.zeros((n, 64), dtype=np.uint8)
            padded[:, :nbytes] = np.frombuffer(raw, dtype=np.uint8).reshape(n, nbytes)
            digests = hashing.hash_many(padded.tobytes())
            col[:] = np.frombuffer(digests, dtype=np.uint8).reshape(n, 32)
    return hashing.item_roots(buf.tobytes(), fp)


class _SequenceBase(_Cached, SSZType):
    element_type: type = None  # type: ignore

    def __init__(self, *args):
        if len(args) == 1 and isinstance(args[0], (list, tuple)) and not isinstance(args[0], (bytes,)):
            raw = list(args[0])
        elif len(args) == 1 and isinstance(args[0], _SequenceBase):
            raw = list(args[0])
        else:
            raw = list(args)
        self._items = [_adopt(self.element_type.coerce(v)) for v in raw]
        self._check_len(len(self._items))
        self._tree: Optional[ChunkTree] = None
        self._dirty: set = set()

    def _check_len(self, n: int) -> None:
        raise NotImplementedError

    def __len__(self):
        return len(self._items)

    def __iter__(self):
        return iter(self._items)

    def __getitem__(self, i):
        return self._items[i]

    def __setitem__(self, i, v):
        if isinstance(i, slice):
            raise TypeError("slice assignment not supported on SSZ sequences")
        n = len(self._items)
        if i < 0:
            i += n
        if not 0 <= i < n:
            raise IndexError(f"{type(self).__name__}: index {i} out of range")
        val = _adopt(self.element_type.coerce(v))
        self._items[i] = val
        self._link_child(val, i)
        self._mark_item_dirty(i)

    def _receive_dirty(self, slot) -> bool:
        self._dirty.add(slot)
        if self._ht_cache is None:
            return False
        self._set_cache(None)
        return True

    def _mark_item_dirty(self, i: int) -> None:
        self._dirty.add(i)
        self._mark_self_dirty()

    # -- incremental Merkleization (ChunkTree backing) -----------------------

    def _bound(self) -> int:
        raise NotImplementedError

    def _build_leaves(self) -> bytearray:
        items = self._items
        et = self.element_type
        if _is_basic(et):
            return _pack_basic_items(items, et)
        plan = _container_flat_plan(et) if issubclass(et, Container) else None
        # bulk-link: one shared weakref + direct __dict__ writes (the
        # per-item _link_child call costs more than the leaf hash at scale)
        ref = weakref.ref(self)
        if plan and len(items) >= 64:
            packed = _batched_container_roots(items, plan)
            for i, it in enumerate(items):
                d = it.__dict__
                ps = d.get("_parents")
                if ps is None:
                    d["_parents"] = [(ref, i)]
                else:
                    ps.append((ref, i))
                # plan admits only immutable fields, so caching the batched
                # root needs no child links inside the item
                d["_ht_cache"] = packed[32 * i : 32 * i + 32]
            return bytearray(packed)
        leaves = bytearray()
        for i, it in enumerate(items):
            self._link_child(it, i)
            leaves += it.hash_tree_root()
        return leaves

    def _pack_chunk(self, ci: int) -> bytes:
        """Re-pack the 32-byte chunk `ci` from current basic items."""
        et = self.element_type
        per = BYTES_PER_CHUNK // et.type_byte_length()
        start = ci * per
        end = min(len(self._items), start + per)
        b = b"".join(self._items[j].encode_bytes() for j in range(start, end))
        return b.ljust(BYTES_PER_CHUNK, b"\x00")

    def _sync_tree(self) -> ChunkTree:
        items = self._items
        et = self.element_type
        basic = _is_basic(et)
        if self._tree is None:
            self._tree = ChunkTree(self._build_leaves(), self._chunk_limit(self._bound()))
            self._dirty.clear()
            return self._tree
        tree = self._tree
        if basic:
            per = BYTES_PER_CHUNK // et.type_byte_length()
            need = (len(items) + per - 1) // per
        else:
            need = len(items)
        if tree.count > need:
            tree.truncate(need)
        if self._dirty:
            if basic:
                for ci in sorted({i // per for i in self._dirty}):
                    if ci < need:
                        tree.set_leaf(ci, self._pack_chunk(ci))
            else:
                for i in sorted(self._dirty):
                    if i < need:
                        it = items[i]
                        self._link_child(it, i)
                        tree.set_leaf(i, it.hash_tree_root())
            self._dirty.clear()
        return tree

    def copy(self):
        cls = type(self)
        new = cls.__new__(cls)
        new._items = [v.copy() for v in self._items]
        for i, v in enumerate(new._items):
            if isinstance(v, _Cached):
                object.__setattr__(v, "_owned", True)
            new._link_child(v, i)
        new._tree = self._tree.copy() if self._tree is not None else None
        new._dirty = set(self._dirty)
        new._set_cache(self._ht_cache)
        return new

    def index(self, v):
        return self._items.index(v)

    def count(self, v):
        return self._items.count(v)

    def __contains__(self, v):
        return v in self._items

    def _type_key(self):
        # (kind, bound, element-type name): what must match for two
        # parameterized classes from different spec modules to be "the same
        # type" — keeps __eq__ consistent with __hash__ (which hashes the
        # limit-padded tree root).
        bound = self.length if isinstance(self, Vector) else self.limit
        return (isinstance(self, Vector), int(bound), self.element_type.__name__)

    def __eq__(self, other):
        if isinstance(other, _SequenceBase):
            if self._type_key() != other._type_key():
                return NotImplemented
            return self._items == other._items
        if isinstance(other, (list, tuple)):
            return self._items == list(other)
        return NotImplemented

    def __hash__(self):
        return hash((type(self).__name__, self.hash_tree_root()))

    def __repr__(self):
        return f"{type(self).__name__}({self._items!r})"

    @classmethod
    def _chunk_limit(cls, bound: int) -> int:
        if _is_basic(cls.element_type):
            return (bound * cls.element_type.type_byte_length() + 31) // 32
        return bound


class Vector(_SequenceBase):
    length: int = 0

    def __class_getitem__(cls, params: Tuple[type, int]) -> type:
        elem, length = params
        return _parameterize(
            Vector, (elem, length), f"Vector[{elem.__name__}, {length}]",
            {"element_type": elem, "length": length},
        )

    def __init__(self, *args):
        if len(args) == 0:
            args = ([self.element_type.default() for _ in range(self.length)],)
        super().__init__(*args)

    def _check_len(self, n: int) -> None:
        if n != self.length:
            raise ValueError(f"{type(self).__name__}: expected {self.length} elements, got {n}")

    @classmethod
    def is_fixed_byte_length(cls) -> bool:
        return cls.element_type.is_fixed_byte_length()

    @classmethod
    def type_byte_length(cls) -> int:
        return cls.element_type.type_byte_length() * cls.length

    @classmethod
    def decode_bytes(cls, data: bytes):
        if cls.element_type.is_fixed_byte_length():
            el = cls.element_type.type_byte_length()
            if len(data) != el * cls.length:
                raise ValueError(f"{cls.__name__}: bad byte length {len(data)}")
            return cls([cls.element_type.decode_bytes(data[i * el : (i + 1) * el]) for i in range(cls.length)])
        return cls(_decode_parts(data, [cls.element_type] * cls.length))

    @classmethod
    def default(cls):
        return cls()

    def encode_bytes(self) -> bytes:
        if self.element_type.is_fixed_byte_length():
            return b"".join(v.encode_bytes() for v in self._items)
        return _serialize_parts(self._items)

    def _bound(self) -> int:
        return self.length

    def hash_tree_root(self) -> bytes:
        c = self._ht_cache
        if c is None:
            c = self._sync_tree().root()
            self._set_cache(c)
        return c


class List(_SequenceBase):
    limit: int = 0

    def __class_getitem__(cls, params: Tuple[type, int]) -> type:
        elem, limit = params
        return _parameterize(
            List, (elem, limit), f"List[{elem.__name__}, {limit}]",
            {"element_type": elem, "limit": limit},
        )

    def _check_len(self, n: int) -> None:
        if n > self.limit:
            raise ValueError(f"{type(self).__name__}: {n} elements exceeds limit {self.limit}")

    def append(self, v):
        if len(self._items) + 1 > self.limit:
            raise ValueError(f"{type(self).__name__}: append exceeds limit {self.limit}")
        val = _adopt(self.element_type.coerce(v))
        self._items.append(val)
        n = len(self._items) - 1
        self._link_child(val, n)
        self._mark_item_dirty(n)

    def pop(self):
        v = self._items.pop()
        # mark the vacated index: a shared trailing chunk gets re-packed at
        # sync time; fully-removed leaves are handled by ChunkTree.truncate
        self._mark_item_dirty(len(self._items))
        return v

    @classmethod
    def is_fixed_byte_length(cls) -> bool:
        return False

    @classmethod
    def decode_bytes(cls, data: bytes):
        if len(data) == 0:
            return cls([])
        if cls.element_type.is_fixed_byte_length():
            el = cls.element_type.type_byte_length()
            if len(data) % el:
                raise ValueError(f"{cls.__name__}: byte length {len(data)} not multiple of {el}")
            n = len(data) // el
            if n > cls.limit:
                raise ValueError(f"{cls.__name__}: {n} elements exceeds limit {cls.limit}")
            return cls([cls.element_type.decode_bytes(data[i * el : (i + 1) * el]) for i in range(n)])
        # variable-size elements: element count = first_offset / 4
        first = int.from_bytes(data[:OFFSET_BYTE_LENGTH], "little")
        if first % OFFSET_BYTE_LENGTH:
            raise ValueError(f"{cls.__name__}: misaligned first offset")
        n = first // OFFSET_BYTE_LENGTH
        if n > cls.limit:
            raise ValueError(f"{cls.__name__}: {n} elements exceeds limit {cls.limit}")
        return cls(_decode_parts(data, [cls.element_type] * n))

    @classmethod
    def default(cls):
        return cls([])

    def encode_bytes(self) -> bytes:
        if self.element_type.is_fixed_byte_length():
            return b"".join(v.encode_bytes() for v in self._items)
        return _serialize_parts(self._items)

    def _bound(self) -> int:
        return self.limit

    def hash_tree_root(self) -> bytes:
        c = self._ht_cache
        if c is None:
            c = mix_in_length(self._sync_tree().root(), len(self._items))
            self._set_cache(c)
        return c


# ---------------------------------------------------------------------------
# Container
# ---------------------------------------------------------------------------


class Container(_Cached, SSZType):
    _fields: Dict[str, type] = {}

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        fields: Dict[str, type] = {}
        for klass in reversed(cls.__mro__):
            anns = klass.__dict__.get("__annotations__", {})
            for name, typ in anns.items():
                if isinstance(typ, type):
                    fields[name] = typ
        cls._fields = fields

    def __init__(self, **kwargs):
        for name, typ in self._fields.items():
            if name in kwargs:
                object.__setattr__(self, name, _adopt(typ.coerce(kwargs.pop(name))))
            else:
                # fresh defaults must pass the same ownership barrier as
                # provided values: an unowned child assigned into a second
                # parent would alias instead of snapshotting (_adopt on a
                # brand-new object is a marking, not a copy)
                object.__setattr__(self, name, _adopt(typ.default()))
        if kwargs:
            raise TypeError(f"{type(self).__name__}: unknown fields {sorted(kwargs)}")

    @classmethod
    def fields(cls) -> Dict[str, type]:
        return cls._fields

    def __setattr__(self, name, value):
        if name.startswith("_"):
            object.__setattr__(self, name, value)
            return
        typ = self._fields.get(name)
        if typ is None:
            raise AttributeError(f"{type(self).__name__} has no SSZ field {name!r}")
        v = _adopt(typ.coerce(value))
        object.__setattr__(self, name, v)
        self._link_child(v, name)
        self._mark_self_dirty()

    @classmethod
    def coerce(cls, value):
        if isinstance(value, cls):
            return value
        if isinstance(value, Container):
            # Cross-class coercion (e.g. the same container type from another
            # (fork, preset) spec module, or a fork upgrade reusing unchanged
            # sub-containers): copy field-wise, coercing recursively.
            if set(cls._fields) != set(value._fields):
                raise TypeError(
                    f"cannot coerce {type(value).__name__} to {cls.__name__}: field mismatch"
                )
            return cls(**{n: getattr(value, n) for n in cls._fields})
        if isinstance(value, dict):
            return cls(**value)
        return cls(value)

    def __eq__(self, other):
        # Same field names + equal field values; class *identity* is not
        # required so values from differently-built spec modules compare equal.
        if not isinstance(other, Container):
            return NotImplemented
        if type(self) is not type(other):
            if type(self).__name__ != type(other).__name__ or set(self._fields) != set(other._fields):
                return NotImplemented
        return all(getattr(self, n) == getattr(other, n) for n in self._fields)

    def __hash__(self):
        return hash((type(self).__name__, self.hash_tree_root()))

    def __repr__(self):
        inner = ", ".join(f"{n}={getattr(self, n)!r}" for n in self._fields)
        return f"{type(self).__name__}({inner})"

    @classmethod
    def is_fixed_byte_length(cls) -> bool:
        return all(t.is_fixed_byte_length() for t in cls._fields.values())

    @classmethod
    def type_byte_length(cls) -> int:
        return sum(t.type_byte_length() for t in cls._fields.values())

    @classmethod
    def decode_bytes(cls, data: bytes):
        values = _decode_parts(data, list(cls._fields.values()))
        return cls(**dict(zip(cls._fields.keys(), values)))

    @classmethod
    def default(cls):
        return cls()

    def encode_bytes(self) -> bytes:
        return _serialize_parts([getattr(self, n) for n in self._fields])

    def hash_tree_root(self) -> bytes:
        c = self._ht_cache
        if c is not None:
            return c
        roots = []
        for n in self._fields:
            v = getattr(self, n)
            self._link_child(v, n)  # links established here keep the cache honest
            roots.append(v.hash_tree_root())
        c = merkleize_chunks(b"".join(roots))
        self._set_cache(c)
        return c

    def copy(self):
        cls = type(self)
        new = cls.__new__(cls)
        for n in self._fields:
            cv = getattr(self, n).copy()
            if isinstance(cv, _Cached):
                object.__setattr__(cv, "_owned", True)
            object.__setattr__(new, n, cv)
            new._link_child(cv, n)
        new._set_cache(self._ht_cache)
        return new


# ---------------------------------------------------------------------------
# Union
# ---------------------------------------------------------------------------


class Union(_Cached, SSZType):
    options: Tuple[Optional[type], ...] = ()

    def __class_getitem__(cls, params) -> type:
        if not isinstance(params, tuple):
            params = (params,)
        names = ", ".join("None" if p is None else p.__name__ for p in params)
        return _parameterize(Union, params, f"Union[{names}]", {"options": params})

    def __init__(self, selector: int, value: Any = None):
        self.change(selector, value)

    def __setattr__(self, name, value):
        object.__setattr__(self, name, value)
        if name in ("value", "selector"):
            if name == "value":
                self._link_child(value, 0)
            self._mark_self_dirty()

    def change(self, selector: int, value: Any = None) -> None:
        """Re-point the union in place (remerkleable's `.change` API the
        sharding spec mutates ShardWork.status with,
        specs/sharding/beacon-chain.md:659-671)."""
        if not (0 <= selector < len(self.options)):
            raise ValueError(f"{type(self).__name__}: bad selector {selector}")
        opt = self.options[selector]
        if opt is None:
            if value is not None:
                raise ValueError("Union: selector 0 (None) takes no value")
            self.value = None
        else:
            self.value = _adopt(opt.coerce(value))
        self.selector = selector

    @classmethod
    def is_fixed_byte_length(cls) -> bool:
        return False

    @classmethod
    def decode_bytes(cls, data: bytes):
        if len(data) == 0:
            raise ValueError("Union: empty data")
        selector = data[0]
        if selector >= len(cls.options):
            raise ValueError(f"Union: bad selector {selector}")
        opt = cls.options[selector]
        if opt is None:
            if len(data) != 1:
                raise ValueError("Union: trailing bytes after None selector")
            return cls(0, None)
        return cls(selector, opt.decode_bytes(data[1:]))

    @classmethod
    def default(cls):
        return cls(0, None if cls.options[0] is None else cls.options[0].default())

    def encode_bytes(self) -> bytes:
        body = b"" if self.value is None else self.value.encode_bytes()
        return bytes([self.selector]) + body

    def hash_tree_root(self) -> bytes:
        c = self._ht_cache
        if c is None:
            root = b"\x00" * 32 if self.value is None else self.value.hash_tree_root()
            c = mix_in_selector(root, self.selector)
            self._set_cache(c)
        return c

    def __eq__(self, other):
        if not isinstance(other, Union):
            return NotImplemented
        return type(self) is type(other) and self.selector == other.selector and self.value == other.value

    def __hash__(self):
        return hash((type(self).__name__, self.selector, self.hash_tree_root()))

    def __repr__(self):
        return f"{type(self).__name__}(selector={self.selector}, value={self.value!r})"


# ---------------------------------------------------------------------------
# Generalized indices (ssz/merkle-proofs.md:58-189)
# ---------------------------------------------------------------------------


def get_generalized_index(typ: type, *path) -> int:
    """Navigate `path` (field names / indices / '__len__') from `typ`'s root."""
    root = 1
    for p in path:
        if p == "__len__":
            if not (issubclass(typ, (List, Bitlist, ByteList))):
                raise TypeError(f"__len__ only valid on lists, not {typ}")
            root = root * 2 + 1
            typ = uint64
            continue
        if issubclass(typ, Container):
            fields = list(typ.fields().items())
            names = [n for n, _ in fields]
            idx = names.index(p)
            base = next_pow2(len(fields))
            root = root * base + idx
            typ = fields[idx][1]
        elif issubclass(typ, (List, Bitlist, ByteList)):
            root *= 2  # mix_in_length: data subtree is the left child
            if issubclass(typ, List):
                limit = typ._chunk_limit(typ.limit)
                elem = typ.element_type
            elif issubclass(typ, Bitlist):
                limit = (typ.limit + 255) // 256
                elem = None
            else:
                limit = (typ.limit + 31) // 32
                elem = None
            base = next_pow2(limit)
            if elem is not None and not _is_basic(elem):
                root = root * base + int(p)
                typ = elem
            else:
                per_chunk = 32 // elem.type_byte_length() if elem is not None else 256 if issubclass(typ, Bitlist) else 32
                root = root * base + int(p) // per_chunk
                typ = Bytes32
        elif issubclass(typ, (Vector, Bitvector, ByteVector)):
            if issubclass(typ, Vector):
                limit = typ._chunk_limit(typ.length)
                elem = typ.element_type
            elif issubclass(typ, Bitvector):
                limit = (typ.length + 255) // 256
                elem = None
            else:
                limit = (typ.length + 31) // 32
                elem = None
            base = next_pow2(limit)
            if elem is not None and not _is_basic(elem):
                root = root * base + int(p)
                typ = elem
            else:
                per_chunk = 32 // elem.type_byte_length() if elem is not None else 256 if issubclass(typ, Bitvector) else 32
                root = root * base + int(p) // per_chunk
                typ = Bytes32
        else:
            raise TypeError(f"cannot navigate into {typ}")
    return root


def get_generalized_index_length(index: int) -> int:
    """Depth of a generalized index (merkle-proofs.md:46)."""
    return index.bit_length() - 1
