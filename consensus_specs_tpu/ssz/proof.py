"""Merkle proofs against SSZ objects by generalized index, single and
multi (ref: ssz/merkle-proofs.md:58-357 — the proof side the light-client
sync protocol consumes, sync-protocol.md:159-231).

`compute_merkle_proof(obj, gindex)` returns the branch ordered leaf-level
first, matching `is_valid_merkle_branch` / `calculate_merkle_root` fold
order. Descent is supported through every composite kind — Containers,
composite- and basic-element Vectors/Lists (including the length mix-in:
data subtree = left child, length = right, merkle-proofs.md "merkleization
into a single root"), Bitvector/Bitlist, ByteVector/ByteList — with
virtual zero-subtree siblings for unmaterialized padding (a proof into a
`List[..., 2**40]` costs 40 zero-hash lookups, not 2**40 nodes).

Multiproofs (merkle-proofs.md:249-357): `get_helper_indices` computes the
minimal witness set; `compute_merkle_multiproof` extracts those nodes from
an object; `calculate_multi_merkle_root`/`verify_merkle_multiproof` fold
them back. These are host-side tree walks — batches of proofs feed the
batched hasher, not one-hash-at-a-time device calls.
"""
from __future__ import annotations

from typing import Dict, List as PyList, Sequence, Tuple

from .hashing import hash_many
from .merkle import ZERO_HASHES, ceil_log2, next_pow2
from .types import (
    Bitlist,
    Bitvector,
    ByteList,
    ByteVector,
    Container,
    List,
    Vector,
    _is_basic,
    _pad_to_chunks,
)


# ---------------------------------------------------------------------------
# Generalized-index arithmetic (merkle-proofs.md:197-252)
# ---------------------------------------------------------------------------


def concat_generalized_indices(*indices: int) -> int:
    """Gindex of the node addressed by following each index in turn
    (merkle-proofs.md:197)."""
    o = 1
    for i in indices:
        o = o * next_pow2(i + 1) // 2 + (i - next_pow2(i + 1) // 2)
    return o


def get_generalized_index_bit(index: int, position: int) -> bool:
    """(merkle-proofs.md:221)"""
    return (index & (1 << position)) > 0


def generalized_index_sibling(index: int) -> int:
    return index ^ 1


def generalized_index_child(index: int, right_side: bool) -> int:
    return index * 2 + int(right_side)


def generalized_index_parent(index: int) -> int:
    return index // 2


# ---------------------------------------------------------------------------
# Proof-index sets (merkle-proofs.md:265-305)
# ---------------------------------------------------------------------------


def get_branch_indices(tree_index: int) -> PyList[int]:
    """Sibling chain from the node to the root (merkle-proofs.md:265)."""
    o = []
    while tree_index > 1:
        o.append(tree_index ^ 1)
        tree_index //= 2
    return o


def get_path_indices(tree_index: int) -> PyList[int]:
    """The node's ancestor chain including itself, excluding the root
    (merkle-proofs.md:277)."""
    o = []
    while tree_index > 1:
        o.append(tree_index)
        tree_index //= 2
    return o


def get_helper_indices(indices: Sequence[int]) -> PyList[int]:
    """Minimal witness set for a multiproof of `indices`: all sibling-chain
    nodes not themselves on any proven path (merkle-proofs.md:289).
    Descending order, as the verifier folds bottom-up."""
    all_helper: set = set()
    all_path: set = set()
    for index in indices:
        all_helper.update(get_branch_indices(index))
        all_path.update(get_path_indices(index) + [1])
    return sorted(all_helper - all_path, reverse=True)


# ---------------------------------------------------------------------------
# Verification folds (merkle-proofs.md:307-357)
# ---------------------------------------------------------------------------


def calculate_merkle_root(leaf: bytes, proof: Sequence[bytes], index: int) -> bytes:
    """Fold a single branch upward (merkle-proofs.md:307)."""
    assert len(proof) == index.bit_length() - 1
    node = leaf
    for i, h in enumerate(proof):
        if index & (1 << i):
            node = hash_many(h + node)
        else:
            node = hash_many(node + h)
    return node


def verify_merkle_proof(leaf: bytes, proof: Sequence[bytes], index: int, root: bytes) -> bool:
    return calculate_merkle_root(leaf, proof, index) == root


def calculate_multi_merkle_root(
    leaves: Sequence[bytes], proof: Sequence[bytes], indices: Sequence[int]
) -> bytes:
    """Root from several proven leaves + their helper nodes
    (merkle-proofs.md:325)."""
    assert len(leaves) == len(indices)
    helper_indices = get_helper_indices(indices)
    assert len(proof) == len(helper_indices)
    objects: Dict[int, bytes] = {
        **{index: node for index, node in zip(indices, leaves)},
        **{index: node for index, node in zip(helper_indices, proof)},
    }
    keys = sorted(objects.keys(), reverse=True)
    pos = 0
    while pos < len(keys):
        k = keys[pos]
        if k in objects and k ^ 1 in objects and k // 2 not in objects:
            objects[k // 2] = hash_many(objects[(k | 1) ^ 1] + objects[k | 1])
            keys.append(k // 2)
        pos += 1
    return objects[1]


def verify_merkle_multiproof(
    leaves: Sequence[bytes], proof: Sequence[bytes], indices: Sequence[int], root: bytes
) -> bool:
    return calculate_multi_merkle_root(leaves, proof, indices) == root


# ---------------------------------------------------------------------------
# Object-tree navigation
# ---------------------------------------------------------------------------


def _chunk_info(obj) -> Tuple[PyList[bytes], int, object, bool]:
    """(chunks, depth, children, has_length_mixin) for a composite value.

    `chunks` are the actual subtree leaves (unpadded); `depth` the virtual
    tree depth to the type's bound; `children` the child objects aligned
    with chunks (None when leaves are opaque packed chunks)."""
    if isinstance(obj, Container):
        fields = list(obj.fields())
        chunks = [bytes(getattr(obj, n).hash_tree_root()) for n in fields]
        children = [getattr(obj, n) for n in fields]
        return chunks, ceil_log2(next_pow2(len(fields))), children, False
    if isinstance(obj, (Vector, List)):
        is_list = isinstance(obj, List)
        bound = obj.limit if is_list else obj.length
        limit = type(obj)._chunk_limit(bound)
        if _is_basic(obj.element_type):
            packed = _pad_to_chunks(b"".join(v.encode_bytes() for v in obj))
            chunks = [packed[i : i + 32] for i in range(0, len(packed), 32)]
            children = None
        else:
            chunks = [bytes(v.hash_tree_root()) for v in obj]
            children = list(obj)
        return chunks, ceil_log2(limit), children, is_list
    if isinstance(obj, (Bitvector, Bitlist)):
        is_list = isinstance(obj, Bitlist)
        bound = obj.limit if is_list else obj.length
        from .types import _bits_to_bytes

        packed = _pad_to_chunks(_bits_to_bytes(list(obj)))
        chunks = [packed[i : i + 32] for i in range(0, len(packed), 32)]
        return chunks, ceil_log2((bound + 255) // 256), None, is_list
    if isinstance(obj, (ByteVector, ByteList)):
        is_list = isinstance(obj, ByteList)
        bound = obj.limit if is_list else obj.length
        packed = _pad_to_chunks(bytes(obj))
        chunks = [packed[i : i + 32] for i in range(0, len(packed), 32)]
        return chunks, ceil_log2((bound + 31) // 32), None, is_list
    raise TypeError(f"proof descent through {type(obj).__name__} not supported")


def _levels(chunks: PyList[bytes], depth: int) -> PyList[PyList[bytes]]:
    """Real (unpadded) interior levels; virtual zero-subtree siblings are
    looked up from ZERO_HASHES by the callers. Each level is hashed in ONE
    hash_many batch (the whole level's sibling pairs at once)."""
    levels = [list(chunks)]
    level = list(chunks)
    for d in range(depth):
        if len(level) % 2:
            level.append(ZERO_HASHES[d])
        digests = hash_many(b"".join(level))
        level = [digests[32 * i : 32 * i + 32] for i in range(len(level) // 2)]
        levels.append(level)
    return levels


def _data_root(chunks: PyList[bytes], depth: int) -> bytes:
    if not chunks:
        return ZERO_HASHES[depth]
    lv = _levels(chunks, depth)
    return lv[depth][0] if lv[depth] else ZERO_HASHES[depth]


def _length_chunk(obj) -> bytes:
    return len(obj).to_bytes(32, "little")


def _proof(obj, bits: str) -> PyList[bytes]:
    if not bits:
        return []
    chunks, depth, children, mixin = _chunk_info(obj)
    if mixin:
        b, bits = bits[0], bits[1:]
        if b == "1":
            # proving the length mix-in; its sibling is the data-tree root
            assert not bits, "cannot descend inside the length mix-in"
            return [_data_root(chunks, depth)]
        # proving the data root itself needs only the length chunk
        inner = _subtree_proof(chunks, depth, children, bits) if bits else []
        return inner + [_length_chunk(obj)]
    return _subtree_proof(chunks, depth, children, bits)


def _sibling_walk(chunks, depth: int, idx: int, base: int) -> PyList[bytes]:
    """Siblings of node `idx` (at height `base`) up to this tree's root,
    proven-node-level sibling first."""
    levels = _levels(chunks, depth)
    siblings = []
    pos = idx
    for level in range(base, depth):
        row = levels[level]
        sib = pos ^ 1
        siblings.append(row[sib] if sib < len(row) else ZERO_HASHES[level])
        pos //= 2
    return siblings


def _subtree_proof(chunks, depth, children, bits: str) -> PyList[bytes]:
    if len(bits) <= depth:
        # the proven node lives in THIS tree — possibly an interior node
        # (e.g. a custody-chunk subtree root inside a ByteList's data
        # tree); base = its height, with base = 0 the plain leaf case
        idx = int(bits, 2) if bits else 0
        return _sibling_walk(chunks, depth, idx, depth - len(bits))
    tree_bits, rest = bits[:depth], bits[depth:]
    idx = int(tree_bits, 2) if tree_bits else 0  # depth-0 subtree: one child
    siblings = _sibling_walk(chunks, depth, idx, 0)
    assert children is not None, "cannot descend into packed basic chunks"
    assert idx < len(children), "path descends into zero padding"
    return _proof(children[idx], rest) + siblings


def compute_merkle_proof(obj, gindex: int) -> PyList[bytes]:
    """Branch proving the subtree at `gindex` inside `obj`'s hash tree."""
    gindex = int(gindex)
    assert gindex >= 1
    return _proof(obj, bin(gindex)[3:])


def hash_at_gindex(obj, gindex: int, _memo: Optional[Dict] = None) -> bytes:
    """The tree node (subtree root) at `gindex` of `obj`'s hash tree.

    `_memo` (keyed by object identity) caches each visited object's chunk
    info and interior levels so a multiproof's many lookups share one tree
    walk instead of re-merkleizing per helper index."""
    gindex = int(gindex)
    assert gindex >= 1
    return _node(obj, bin(gindex)[3:], _memo if _memo is not None else {})


def _tree_of(obj, memo: Dict):
    """(chunks, depth, children, mixin, levels) for `obj`, memoized."""
    key = id(obj)
    entry = memo.get(key)
    if entry is None:
        chunks, depth, children, mixin = _chunk_info(obj)
        entry = (chunks, depth, children, mixin, _levels(chunks, depth), obj)
        memo[key] = entry  # the obj ref in the entry keeps id(obj) stable
    return entry


def _node(obj, bits: str, memo: Dict) -> bytes:
    if not bits:
        return bytes(obj.hash_tree_root())
    chunks, depth, children, mixin, levels, _ = _tree_of(obj, memo)
    if mixin:
        b, bits = bits[0], bits[1:]
        if b == "1":
            assert not bits, "cannot descend inside the length mix-in"
            return _length_chunk(obj)
        return _subtree_node(levels, depth, children, bits, memo)
    return _subtree_node(levels, depth, children, bits, memo)


def _subtree_node(levels, depth, children, bits: str, memo: Dict) -> bytes:
    take = min(len(bits), depth)
    tree_bits, rest = bits[:take], bits[take:]
    idx = int(tree_bits, 2) if tree_bits else 0
    level = depth - len(tree_bits)  # height of the addressed node
    if not rest:
        row = levels[level]
        return row[idx] if idx < len(row) else ZERO_HASHES[level]
    assert children is not None, "cannot descend into packed basic chunks"
    if idx >= len(children):
        raise AssertionError("path descends into zero padding")
    return _node(children[idx], rest, memo)


def compute_merkle_multiproof(obj, gindices: Sequence[int]) -> PyList[bytes]:
    """Helper nodes (descending gindex order) proving all `gindices` of
    `obj` at once — the witness `verify_merkle_multiproof` consumes. One
    memoized tree walk serves every helper index."""
    memo: Dict = {}
    return [hash_at_gindex(obj, gi, memo) for gi in get_helper_indices(gindices)]
