"""Single Merkle proofs against SSZ objects by generalized index
(ref: ssz/merkle-proofs.md:58-249 — the proof-construction side the
light-client sync protocol consumes, sync-protocol.md:159-231).

`compute_merkle_proof(obj, gindex)` returns the branch ordered leaf-level
first, matching `is_valid_merkle_branch` / `compute_merkle_proof_root`
fold order. Descent across Container boundaries is supported (the
light-client gindices FINALIZED_ROOT_INDEX / NEXT_SYNC_COMMITTEE_INDEX
never descend through a List's length mix-in).
"""
from __future__ import annotations

from typing import List as PyList

from .merkle import ZERO_HASHES, ceil_log2, next_pow2
from .hashing import hash_many
from .types import Container


def _container_chunk_levels(obj: Container) -> PyList[PyList[bytes]]:
    """Bottom-up levels of the container's field-root tree, padded to the
    pow2 leaf count with zero hashes."""
    fields = list(obj.fields())
    chunks = [bytes(getattr(obj, name).hash_tree_root()) for name in fields]
    size = next_pow2(max(len(chunks), 1))
    depth = ceil_log2(size)
    level = chunks + [ZERO_HASHES[0]] * (size - len(chunks))
    levels = [level]
    for d in range(depth):
        nxt = [
            hash_many(level[2 * i] + level[2 * i + 1])
            for i in range(len(level) // 2)
        ]
        levels.append(nxt)
        level = nxt
    return levels


def compute_merkle_proof(obj, gindex: int) -> PyList[bytes]:
    """Branch proving the subtree at `gindex` inside `obj`'s hash tree."""
    gindex = int(gindex)
    assert gindex >= 1
    bits = bin(gindex)[3:]  # descent path from the root, MSB first
    return _proof(obj, bits)


def _proof(obj, bits: str) -> PyList[bytes]:
    if not bits:
        return []
    if not isinstance(obj, Container):
        raise NotImplementedError(
            f"proof descent through {type(obj).__name__} not supported "
            "(only Container paths needed by the light-client gindices)"
        )
    fields = list(obj.fields())
    levels = _container_chunk_levels(obj)
    depth = len(levels) - 1
    tree_bits, rest = bits[:depth], bits[depth:]
    assert len(tree_bits) == depth, "generalized index path ends inside padding"
    idx = int(tree_bits, 2) if tree_bits else 0

    siblings = []
    pos = idx
    for level in range(depth):  # leaf-level sibling first
        siblings.append(levels[level][pos ^ 1])
        pos //= 2

    if not rest:
        return siblings
    assert idx < len(fields), "path descends into zero padding"
    deeper = _proof(getattr(obj, fields[idx]), rest)
    return deeper + siblings
