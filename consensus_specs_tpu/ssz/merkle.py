"""Merkleization primitives (ref: ssz/simple-serialize.md:210-249,
eth2spec/utils/merkle_minimal.py:7-89).

All level reductions go through `hashing.hash_many`, so one call hashes an
entire Merkle level — the batching boundary the TPU backend exploits.
Virtual zero-padding via the precomputed zero-hash table means a
`List[..., 2**40]` limit costs 40 extra hashes, not 2**40 chunks.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

from .hashing import fused_root, hash_many

ZERO_CHUNK = b"\x00" * 32

# zerohashes[i] = root of a depth-i tree of zero chunks (merkle_minimal.py:7-9)
ZERO_HASHES: List[bytes] = [ZERO_CHUNK]
for _ in range(64):
    ZERO_HASHES.append(hash_many(ZERO_HASHES[-1] + ZERO_HASHES[-1]))


def next_pow2(x: int) -> int:
    return 1 if x <= 1 else 1 << (x - 1).bit_length()


def ceil_log2(x: int) -> int:
    return 0 if x <= 1 else (x - 1).bit_length()


def _reduce_level(nodes: List[bytes], zero: bytes) -> List[bytes]:
    if len(nodes) % 2:
        nodes = nodes + [zero]
    digests = hash_many(b"".join(nodes))
    return [digests[32 * i : 32 * i + 32] for i in range(len(nodes) // 2)]


def merkleize_chunks(chunks, limit: Optional[int] = None) -> bytes:
    """Root of the Merkle tree over `chunks`, zero-padded to `limit` leaves.

    `chunks` is either packed bytes (length a multiple of 32 — the fast,
    contiguous path) or a sequence of 32-byte chunk objects. `limit=None`
    pads to next_pow2(count) (simple-serialize.md merkleize with no limit).
    Matches merkle_minimal.merkleize_chunks:47-89 semantics.
    """
    if isinstance(chunks, (bytes, bytearray, memoryview)):
        data = bytes(chunks)
    else:
        data = b"".join(chunks)
    count = len(data) // 32
    if limit is None:
        limit = max(count, 1)
    if count > limit:
        raise ValueError(f"merkleize: {count} chunks exceeds limit {limit}")
    depth = ceil_log2(limit)
    if count == 0:
        return ZERO_HASHES[depth]
    if count >= 2:
        # large trees: whole-tree device reduce in one dispatch (chunk
        # data crosses to HBM once; only the 32-byte root returns)
        root = fused_root(data, limit)
        if root is not None:
            return root
    nodes = data
    level = 0
    while len(nodes) > 32:
        if (len(nodes) // 32) % 2:
            nodes = nodes + ZERO_HASHES[level]
        nodes = hash_many(nodes)
        level += 1
    root = nodes
    while level < depth:
        root = hash_many(root + ZERO_HASHES[level])
        level += 1
    return root


def mix_in_length(root: bytes, length: int) -> bytes:
    return hash_many(root + length.to_bytes(32, "little"))


def mix_in_selector(root: bytes, selector: int) -> bytes:
    return hash_many(root + selector.to_bytes(32, "little"))


# -- Full-tree helpers for proofs (merkle_minimal.py:12-45) ------------------


def calc_merkle_tree_from_leaves(values: Sequence[bytes], layer_count: int = 32) -> List[List[bytes]]:
    """All layers bottom-up; layer i has the nodes at depth (layer_count - i)."""
    values = list(values)
    tree: List[List[bytes]] = [values[:]]
    for h in range(layer_count):
        if len(values) % 2:
            values.append(ZERO_HASHES[h])
        values = _reduce_level(values, ZERO_HASHES[h])
        tree.append(values[:])
    return tree


def get_merkle_root(values: Sequence[bytes], pad_to: int = 1) -> bytes:
    return merkleize_chunks(values, limit=max(pad_to, 1))


def get_merkle_proof(tree: List[List[bytes]], item_index: int, tree_len: Optional[int] = None) -> List[bytes]:
    proof = []
    for i in range(tree_len if tree_len is not None else len(tree) - 1):
        subindex = (item_index // (1 << i)) ^ 1
        layer = tree[i]
        proof.append(layer[subindex] if subindex < len(layer) else ZERO_HASHES[i])
    return proof


def compute_merkle_proof_root(leaf: bytes, proof: Sequence[bytes], index: int) -> bytes:
    """Fold a branch upward; `index` is the generalized index of the leaf."""
    node = leaf
    for i, sibling in enumerate(proof):
        if (index >> i) & 1:
            node = hash_many(sibling + node)
        else:
            node = hash_many(node + sibling)
    return node
