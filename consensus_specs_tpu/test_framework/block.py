"""Block construction/signing helpers (ref: test/helpers/block.py)."""
from __future__ import annotations

from .constants import is_post_altair, is_post_bellatrix
from .keys import privkeys


def get_proposer_index_maybe(spec, state, slot, proposer_index=None):
    if proposer_index is None:
        if slot == state.slot:
            proposer_index = spec.get_beacon_proposer_index(state)
        else:
            if spec.compute_epoch_at_slot(slot) > spec.compute_epoch_at_slot(state.slot) + 1:
                print("warning: block slot far away, and no proposer index manually given."
                      " Signing block is slow due to transition for proposer index calculation.")
            # Transition a copy to compute the proposer of the future slot
            stub_state = state.copy()
            if stub_state.slot < slot:
                spec.process_slots(stub_state, slot)
            proposer_index = spec.get_beacon_proposer_index(stub_state)
    return proposer_index


def apply_randao_reveal(spec, state, block, proposer_index=None):
    assert state.slot <= block.slot
    proposer_index = get_proposer_index_maybe(spec, state, block.slot, proposer_index)
    privkey = privkeys[proposer_index]
    domain = spec.get_domain(state, spec.DOMAIN_RANDAO, spec.compute_epoch_at_slot(block.slot))
    signing_root = spec.compute_signing_root(
        spec.uint64(spec.compute_epoch_at_slot(block.slot)), domain
    )
    block.body.randao_reveal = spec.bls.Sign(privkey, signing_root)


def apply_sig(spec, state, signed_block, proposer_index=None):
    block = signed_block.message
    proposer_index = get_proposer_index_maybe(spec, state, block.slot, proposer_index)
    privkey = privkeys[proposer_index]
    domain = spec.get_domain(state, spec.DOMAIN_BEACON_PROPOSER, spec.compute_epoch_at_slot(block.slot))
    signing_root = spec.compute_signing_root(block, domain)
    signed_block.signature = spec.bls.Sign(privkey, signing_root)


def sign_block(spec, state, block, proposer_index=None):
    signed_block = spec.SignedBeaconBlock(message=block)
    apply_sig(spec, state, signed_block, proposer_index)
    return signed_block


def get_state_and_beacon_parent_root_at_slot(spec, state, slot):
    if slot < state.slot:
        raise Exception("Cannot build blocks for past slots")
    state = state.copy()
    if state.slot < slot:
        spec.process_slots(state, slot)

    previous_block_header = state.latest_block_header.copy()
    if previous_block_header.state_root == spec.Bytes32():
        previous_block_header.state_root = spec.hash_tree_root(state)
    beacon_parent_root = spec.hash_tree_root(previous_block_header)
    return state, beacon_parent_root


def build_empty_block(spec, state, slot=None):
    """Empty block at ``slot`` wired to the current chain tip
    (ref block.py:60-90)."""
    if slot is None:
        slot = state.slot
    state, parent_block_root = get_state_and_beacon_parent_root_at_slot(spec, state, slot)
    empty_block = spec.BeaconBlock()
    empty_block.slot = slot
    empty_block.proposer_index = spec.get_beacon_proposer_index(state)
    empty_block.body.eth1_data.deposit_count = state.eth1_deposit_index
    empty_block.parent_root = parent_block_root

    if is_post_altair(spec):
        empty_block.body.sync_aggregate.sync_committee_signature = spec.G2_POINT_AT_INFINITY
    if is_post_bellatrix(spec):
        # sharding+ drop is_execution_enabled: execution is always on
        # (sharding/beacon-chain.md:551-553)
        always_on = spec.fork in ("sharding", "custody_game", "das")
        if always_on or spec.is_execution_enabled(state, empty_block.body):
            from .execution_payload import build_empty_execution_payload

            empty_block.body.execution_payload = build_empty_execution_payload(spec, state)

    apply_randao_reveal(spec, state, empty_block)
    return empty_block


def build_empty_block_for_next_slot(spec, state):
    return build_empty_block(spec, state, state.slot + 1)
