"""Proposer slashing builders + runner (ref: test/helpers/
proposer_slashings.py)."""
from __future__ import annotations

from .block import sign_block  # noqa: F401  (commonly used together)
from .constants import is_post_altair
from .context import expect_assertion_error
from .keys import privkeys
from .state import get_balance


def get_min_slashing_penalty_quotient(spec):
    if hasattr(spec, "MIN_SLASHING_PENALTY_QUOTIENT_BELLATRIX") and spec.fork in ("bellatrix", "capella"):
        return spec.MIN_SLASHING_PENALTY_QUOTIENT_BELLATRIX
    if hasattr(spec, "MIN_SLASHING_PENALTY_QUOTIENT_ALTAIR") and spec.fork != "phase0":
        return spec.MIN_SLASHING_PENALTY_QUOTIENT_ALTAIR
    return spec.MIN_SLASHING_PENALTY_QUOTIENT


def check_proposer_slashing_effect(spec, pre_state, state, slashed_index, block=None):
    slashed_validator = state.validators[slashed_index]
    assert slashed_validator.slashed
    assert slashed_validator.exit_epoch < spec.FAR_FUTURE_EPOCH
    assert slashed_validator.withdrawable_epoch < spec.FAR_FUTURE_EPOCH

    proposer_index = spec.get_beacon_proposer_index(state)
    slash_penalty = state.validators[slashed_index].effective_balance // get_min_slashing_penalty_quotient(spec)
    whistleblower_reward = (
        state.validators[slashed_index].effective_balance // spec.WHISTLEBLOWER_REWARD_QUOTIENT
    )

    # Altair+: blocks also carry sync-committee reward/penalty effects
    sc_reward_for_slashed = sc_penalty_for_slashed = 0
    sc_reward_for_proposer = sc_penalty_for_proposer = 0
    if is_post_altair(spec) and block is not None:
        from .sync_committee import (
            compute_committee_indices,
            compute_sync_committee_participant_reward_and_penalty,
        )

        committee_indices = compute_committee_indices(spec, state, state.current_sync_committee)
        committee_bits = block.body.sync_aggregate.sync_committee_bits
        sc_reward_for_slashed, sc_penalty_for_slashed = (
            compute_sync_committee_participant_reward_and_penalty(
                spec, pre_state, slashed_index, committee_indices, committee_bits
            )
        )
        sc_reward_for_proposer, sc_penalty_for_proposer = (
            compute_sync_committee_participant_reward_and_penalty(
                spec, pre_state, proposer_index, committee_indices, committee_bits
            )
        )

    if proposer_index != slashed_index:
        # Slashed validator lost initial slash penalty (+- sync effects)
        assert get_balance(state, slashed_index) == (
            get_balance(pre_state, slashed_index) - slash_penalty
            + sc_reward_for_slashed - sc_penalty_for_slashed
        )
        # Proposer gained whistleblower reward (>=: may have reported more,
        # and earns sync-aggregate proposer rewards)
        assert get_balance(state, proposer_index) >= (
            get_balance(pre_state, proposer_index) + whistleblower_reward
            + sc_reward_for_proposer - sc_penalty_for_proposer
        )
    else:
        # Slashed proposer itself: whistleblower reward net of penalty (>=:
        # sync-aggregate proposer rewards come on top)
        assert get_balance(state, slashed_index) >= (
            get_balance(pre_state, slashed_index) - slash_penalty + whistleblower_reward
            + sc_reward_for_slashed - sc_penalty_for_slashed
        )


def sign_header(spec, state, header, privkey):
    domain = spec.get_domain(state, spec.DOMAIN_BEACON_PROPOSER, spec.compute_epoch_at_slot(header.slot))
    signing_root = spec.compute_signing_root(header, domain)
    return spec.bls.Sign(privkey, signing_root)


def get_valid_proposer_slashing(spec, state, random_root=b"\x99" * 32,
                                slashed_index=None, slot=None, signed_1=False, signed_2=False):
    if slashed_index is None:
        current_epoch = spec.get_current_epoch(state)
        slashed_index = spec.get_active_validator_indices(state, current_epoch)[-1]
    if slot is None:
        slot = state.slot
    privkey = privkeys[slashed_index]

    header_1 = spec.BeaconBlockHeader(
        slot=slot,
        proposer_index=slashed_index,
        parent_root=b"\x33" * 32,
        state_root=b"\x44" * 32,
        body_root=b"\x55" * 32,
    )
    header_2 = header_1.copy()
    header_2.parent_root = random_root

    signed_header_1 = spec.SignedBeaconBlockHeader(message=header_1)
    if signed_1:
        signed_header_1.signature = sign_header(spec, state, header_1, privkey)
    signed_header_2 = spec.SignedBeaconBlockHeader(message=header_2)
    if signed_2:
        signed_header_2.signature = sign_header(spec, state, header_2, privkey)

    return spec.ProposerSlashing(signed_header_1=signed_header_1, signed_header_2=signed_header_2)


def run_proposer_slashing_processing(spec, state, proposer_slashing, valid=True):
    """Yield pre/operation/post around process_proposer_slashing
    (ref proposer_slashings.py runner)."""
    pre_state = state.copy()

    yield "pre", state
    yield "proposer_slashing", proposer_slashing

    if not valid:
        expect_assertion_error(lambda: spec.process_proposer_slashing(state, proposer_slashing))
        yield "post", None
        return

    proposer_index = proposer_slashing.signed_header_1.message.proposer_index
    spec.process_proposer_slashing(state, proposer_slashing)
    yield "post", state

    check_proposer_slashing_effect(spec, pre_state, state, proposer_index)
