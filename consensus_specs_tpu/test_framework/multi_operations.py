"""Blocks carrying MANY operation kinds at once.

Single-operation suites can't catch cross-operation interactions (a
slashing invalidating a same-block exit, deposits growing the registry
while attestations index the old one, sync aggregates over a registry
mid-churn). This module builds such blocks two ways:

- `run_slash_and_exit` — the minimal adversarial pair: slash and exit
  in one block, valid when they hit different validators, invalid when
  the same one (an exit check runs against the already-slashed record);
- `build_full_house_block` / `run_full_house_test` — one deterministic
  block carrying every phase0 operation family simultaneously (plus a
  sync aggregate post-altair);
- `random_operations_block` / `run_random_operations_test` — the
  seeded-random matrix hook used by the sanity/random suites.

Scenario parity target: ref test/helpers/multi_operations.py (242 LoC)
— `run_slash_and_exit`, the per-kind random samplers, and
`run_test_full_random_operations`. The pool-partitioning design here
(disjoint validator draws per operation family, then per-family
builders) is this repo's own.
"""
from __future__ import annotations

from .attestations import get_valid_attestation
from .attester_slashings import get_valid_attester_slashing_by_indices
from .block import build_empty_block_for_next_slot
from .block_processing import state_transition_and_sign_block
from .constants import is_post_altair
from .deposits import build_deposit_data, deposit_from_context
from .keys import privkeys, pubkeys
from .proposer_slashings import get_valid_proposer_slashing
from .state import next_epoch
from .sync_committee import (
    compute_aggregate_sync_committee_signature,
    compute_committee_indices,
)
from .voluntary_exits import prepare_signed_exits


def age_for_exits(spec, state) -> None:
    """Jump the clock far enough that genesis validators pass the
    minimum-service exit check (no epoch processing — slot bump only,
    the established cheap idiom)."""
    state.slot += spec.config.SHARD_COMMITTEE_PERIOD * spec.SLOTS_PER_EPOCH


def draw_pools(spec, state, rng, sizes):
    """Partition a random sample of active validators into DISJOINT
    pools, one per requested size — so each operation family targets
    validators no other family touches in the same block."""
    active = list(spec.get_active_validator_indices(state, spec.get_current_epoch(state)))
    need = sum(sizes)
    assert need <= len(active), f"state too small: need {need} of {len(active)}"
    drawn = sorted(rng.sample(active, need))
    pools, cursor = [], 0
    for size in sizes:
        pools.append(drawn[cursor:cursor + size])
        cursor += size
    return pools


# ---------------------------------------------------------------------------
# per-family builders (each consumes its own pool)
# ---------------------------------------------------------------------------

def proposer_slashings_for(spec, state, pool):
    return [
        get_valid_proposer_slashing(
            spec, state, slashed_index=index, signed_1=True, signed_2=True
        )
        for index in pool
    ]


def attester_slashings_for(spec, state, pool, max_slashings=None):
    """Split the pool into one double-vote slashing per chunk; chunk
    sizes stay small so minimal-preset committees can host them."""
    limit = int(max_slashings if max_slashings is not None else spec.MAX_ATTESTER_SLASHINGS)
    chunks = [pool[i::limit] for i in range(limit)]
    return [
        get_valid_attester_slashing_by_indices(
            spec, state, sorted(chunk), signed_1=True, signed_2=True
        )
        for chunk in chunks
        if chunk
    ]


def attestations_for(spec, state, count, rng=None):
    """`count` distinct signed attestations over recent attestable slots
    (inclusion delay respected; slots chosen deterministically unless an
    rng is supplied)."""
    lo = max(0, int(state.slot) - int(spec.SLOTS_PER_EPOCH) + 1)
    hi = int(state.slot) - int(spec.MIN_ATTESTATION_INCLUSION_DELAY)
    assert hi >= lo, "state too young to attest"
    slots = list(range(lo, hi + 1))
    picks = (
        [slots[i % len(slots)] for i in range(count)]
        if rng is None
        else [rng.choice(slots) for _ in range(count)]
    )
    return [
        get_valid_attestation(spec, state, slot=slot, signed=True) for slot in sorted(picks)
    ]


def deposits_for(spec, state, count, first_new_index=None):
    """`count` fresh full deposits in ONE tree; points state.eth1_data at
    the final tree root so every proof verifies in block order."""
    if first_new_index is None:
        first_new_index = len(state.validators)
    data_list = []
    for i in range(count):
        idx = first_new_index + i
        withdrawal = bytes(spec.BLS_WITHDRAWAL_PREFIX) + spec.hash(pubkeys[idx])[1:]
        data_list.append(
            build_deposit_data(
                spec, pubkeys[idx], privkeys[idx], spec.MAX_EFFECTIVE_BALANCE,
                withdrawal, signed=True,
            )
        )
    # proofs must all be against the FINAL tree (the block processes them
    # under one eth1_data), so derive them after the list is complete
    deposits = []
    root = None
    for i in range(count):
        deposit, root, _ = deposit_from_context(spec, data_list, i)
        deposits.append(deposit)
    state.eth1_deposit_index = 0
    state.eth1_data.deposit_root = root
    state.eth1_data.deposit_count = count
    return deposits


def sync_aggregate_for(spec, state, block_slot, participation=1.0, rng=None):
    """A valid SyncAggregate for a block at `block_slot` with the given
    participation fraction (altair+ only)."""
    committee = compute_committee_indices(spec, state)
    seats = len(committee)
    live = int(seats * participation)
    chosen = sorted(rng.sample(range(seats), live)) if rng is not None else list(range(live))
    bits = [False] * seats
    for seat in chosen:
        bits[seat] = True
    return spec.SyncAggregate(
        sync_committee_bits=bits,
        sync_committee_signature=compute_aggregate_sync_committee_signature(
            spec, state, block_slot - 1, [committee[s] for s in chosen]
        ),
    )


# ---------------------------------------------------------------------------
# scenario drivers
# ---------------------------------------------------------------------------

def run_slash_and_exit(spec, state, slash_index, exit_index, valid=True):
    """One block: attester-slash `slash_index` AND voluntary-exit
    `exit_index`. With slash_index == exit_index the block must fail —
    initiate_validator_exit inside the slashing already set an exit
    epoch, and the exit's own processing re-checks it. Yields the
    pre/blocks/post vector parts."""
    yield "pre", state

    block = build_empty_block_for_next_slot(spec, state)
    block.body.attester_slashings.append(
        get_valid_attester_slashing_by_indices(
            spec, state, [slash_index], signed_1=True, signed_2=True
        )
    )
    block.body.voluntary_exits.append(prepare_signed_exits(spec, state, [exit_index])[0])

    signed = state_transition_and_sign_block(spec, state, block, expect_fail=not valid)
    yield "blocks", [signed]
    yield "post", state if valid else None


def build_full_house_block(spec, state, rng, deposits):
    """A next-slot block carrying: 1 proposer slashing, 1 attester
    slashing, attestations, the pre-provisioned `deposits`, and 1
    voluntary exit — every family at once, targeting disjoint
    validators. Returns (block, touched) where `touched` maps family ->
    validator indices. Deposits MUST be provisioned by the caller
    BEFORE any vector part is emitted: deposits_for re-points
    state.eth1_data, and a pre state snapshotted before that re-point
    can never validate the block's deposit proofs (emission bug caught
    by tools/replay_vectors)."""
    (ps_pool, as_pool, exit_pool) = draw_pools(spec, state, rng, [1, 1, 1])
    block = build_empty_block_for_next_slot(spec, state)
    block.body.proposer_slashings = proposer_slashings_for(spec, state, ps_pool)
    block.body.attester_slashings = attester_slashings_for(spec, state, as_pool)
    for attestation in attestations_for(spec, state, 2):
        block.body.attestations.append(attestation)
    for deposit in deposits:
        block.body.deposits.append(deposit)
    block.body.voluntary_exits = prepare_signed_exits(spec, state, exit_pool)
    if is_post_altair(spec):
        block.body.sync_aggregate = sync_aggregate_for(spec, state, block.slot)
    touched = {"proposer_slashing": ps_pool, "attester_slashing": as_pool, "exit": exit_pool}
    return block, touched


def run_full_house_test(spec, state, rng):
    """Apply a full-house block and check every family took effect."""
    age_for_exits(spec, state)
    next_epoch(spec, state)  # gives attestations a full epoch to target
    pre_validators = len(state.validators)

    # provision the deposit tree BEFORE the pre snapshot: the emitted
    # pre state must carry the eth1_data the block's proofs verify under
    deposits = deposits_for(spec, state, int(spec.MAX_DEPOSITS))
    yield "pre", state
    block, touched = build_full_house_block(spec, state, rng, deposits)
    signed = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed]
    yield "post", state

    for index in touched["proposer_slashing"] + touched["attester_slashing"]:
        assert state.validators[index].slashed
    for index in touched["exit"]:
        assert state.validators[index].exit_epoch < spec.FAR_FUTURE_EPOCH
        assert not state.validators[index].slashed
    assert len(state.validators) == pre_validators + int(spec.MAX_DEPOSITS)
    # attestations landed in the pending queue (phase0) or flipped
    # participation flags (altair+)
    if is_post_altair(spec):
        assert any(int(flag) != 0 for flag in state.current_epoch_participation) or any(
            int(flag) != 0 for flag in state.previous_epoch_participation
        )
    else:
        assert len(state.current_epoch_attestations) + len(state.previous_epoch_attestations) > 0


def random_operations_block(spec, state, rng, deposits):
    """The randomized matrix hook: sample how much of each family to
    carry (possibly zero), honoring block capacity limits. `deposits`
    must be pre-provisioned by the caller before the pre snapshot (see
    build_full_house_block)."""
    n_ps = rng.randint(0, min(2, int(spec.MAX_PROPOSER_SLASHINGS)))
    n_as_targets = rng.randint(0, 2)
    n_att = rng.randint(0, 3)
    n_exit = rng.randint(0, 1)

    ps_pool, as_pool, exit_pool = draw_pools(spec, state, rng, [n_ps, n_as_targets, n_exit])

    block = build_empty_block_for_next_slot(spec, state)
    block.body.proposer_slashings = proposer_slashings_for(spec, state, ps_pool)
    block.body.attester_slashings = attester_slashings_for(spec, state, as_pool)
    for attestation in attestations_for(spec, state, n_att, rng=rng):
        block.body.attestations.append(attestation)
    for deposit in deposits:
        block.body.deposits.append(deposit)
    block.body.voluntary_exits = prepare_signed_exits(spec, state, exit_pool)
    if is_post_altair(spec):
        block.body.sync_aggregate = sync_aggregate_for(
            spec, state, block.slot, participation=rng.random(), rng=rng
        )
    return block


def run_random_operations_test(spec, state, rng):
    """A seeded random full-mix block applied as a sanity transition."""
    age_for_exits(spec, state)
    next_epoch(spec, state)
    # deposit count drawn + tree provisioned BEFORE the pre snapshot
    n_dep = rng.randint(0, int(spec.MAX_DEPOSITS))
    deposits = deposits_for(spec, state, n_dep) if n_dep else []
    yield "pre", state
    block = random_operations_block(spec, state, rng, deposits)
    signed = state_transition_and_sign_block(spec, state, block)
    yield "blocks", [signed]
    yield "post", state
