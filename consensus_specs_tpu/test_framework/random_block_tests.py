"""Randomized-scenario building blocks + scenario expander
(ref: test/utils/randomized_block_tests.py, 377 LoC + the code-generating
tests/generators/random/generate.py — redesigned as a data-driven
scenario table instead of generated source files).

A scenario is a list of steps; each step is either a state transition
("next_slot", "next_epoch", "random_slots") or a block ("block" —
random-op block applied via the full state transition). Scenarios emit
sanity/blocks-format vectors (pre, blocks, post) so clients replay them
with their production block pipeline.
"""
from __future__ import annotations

from random import Random

from .attestations import get_valid_attestation
from .attester_slashings import get_valid_attester_slashing_by_indices
from .block import build_empty_block_for_next_slot
from .constants import is_post_altair
from .state import next_epoch, next_slot, next_slots, state_transition_and_sign_block


# -- state randomizers --------------------------------------------------------

def randomize_inactivity_scores(spec, state, rng):
    from .inactivity_scores import randomize_inactivity_scores as _randomize

    _randomize(spec, state, rng)


def randomize_balances(spec, state, rng):
    """Jitter balances around spec norms without zeroing anyone."""
    for index in range(len(state.balances)):
        jitter = rng.randrange(0, int(spec.EFFECTIVE_BALANCE_INCREMENT))
        state.balances[index] = spec.Gwei(int(state.balances[index]) + jitter)


def randomize_state(spec, state, rng):
    """Light-touch registry/balances/scores randomization that keeps the
    state transitionable (ref randomized_block_tests.py randomize_state)."""
    from .rewards import exit_random_validators, slash_random_validators_clean

    randomize_balances(spec, state, rng)
    randomize_inactivity_scores(spec, state, rng)
    exit_random_validators(spec, state, rng, fraction=0.1)
    slash_random_validators_clean(spec, state, rng, fraction=0.1)


# -- random block builder -----------------------------------------------------

def _random_attestations(spec, state, rng, max_count=2):
    """Valid attestations for the previous slot's committees."""
    atts = []
    if state.slot < spec.MIN_ATTESTATION_INCLUSION_DELAY:
        return atts
    slot = state.slot - spec.MIN_ATTESTATION_INCLUSION_DELAY
    committees = spec.get_committee_count_per_slot(state, spec.compute_epoch_at_slot(slot))
    for index in rng.sample(range(committees), min(max_count, committees)):
        atts.append(
            get_valid_attestation(spec, state, slot=slot, index=index, signed=True)
        )
    return atts


def _slashable_candidates(spec, state, slashed: set):
    return [
        i
        for i in spec.get_active_validator_indices(state, spec.get_current_epoch(state))
        if i not in slashed and not state.validators[i].slashed
    ]


def _maybe_attester_slashing(spec, state, rng, slashed: set):
    """Occasionally double-vote-slash a not-yet-slashed validator."""
    if rng.random() > 0.2:
        return None
    candidates = _slashable_candidates(spec, state, slashed)
    if not candidates:
        return None
    victim = rng.choice(candidates)
    slashing = get_valid_attester_slashing_by_indices(
        spec, state, [victim], signed_1=True, signed_2=True
    )
    slashed.add(victim)
    return slashing


def _maybe_proposer_slashing(spec, state, rng, slashed: set):
    """Occasionally double-propose-slash a not-yet-slashed validator."""
    if rng.random() > 0.2:
        return None
    candidates = _slashable_candidates(spec, state, slashed)
    if not candidates:
        return None
    from .proposer_slashings import get_valid_proposer_slashing

    victim = rng.choice(candidates)
    slashing = get_valid_proposer_slashing(
        spec, state, slashed_index=victim, signed_1=True, signed_2=True
    )
    slashed.add(victim)
    return slashing


def _maybe_voluntary_exit(spec, state, rng, slashed: set):
    """Occasionally exit a validator that has served long enough (only
    possible in scenarios whose state has aged past the minimum-service
    window — near-genesis scenarios simply never draw one)."""
    if rng.random() > 0.2:
        return None
    from .voluntary_exits import prepare_signed_exits

    current_epoch = spec.get_current_epoch(state)
    eligible = [
        i
        for i in spec.get_active_validator_indices(state, current_epoch)
        if current_epoch >= state.validators[i].activation_epoch + spec.config.SHARD_COMMITTEE_PERIOD
        and state.validators[i].exit_epoch == spec.FAR_FUTURE_EPOCH
        and i not in slashed
    ]
    if not eligible:
        return None
    return prepare_signed_exits(spec, state, [rng.choice(eligible)])[0]


def provision_scenario_deposits(spec, state, rng):
    """Occasionally provision fresh full deposits for the scenario —
    called BEFORE the pre snapshot (deposits_for re-points
    state.eth1_data; a pre state captured earlier could never validate
    the proofs — emission bug caught by tools/replay_vectors). The
    expected-deposit-count rule (process_operations) then REQUIRES the
    next block to include them all, so the first block drains the
    queue."""
    if rng.random() > 0.2:
        return []
    from .multi_operations import deposits_for

    return deposits_for(spec, state, rng.randint(1, 2))


def _advance_past_slashed_proposers(spec, state):
    """Randomization may slash the upcoming proposer; a slashed proposer
    can't produce a valid block, so skip those slots."""
    from .block import get_proposer_index_maybe

    for _ in range(int(spec.SLOTS_PER_EPOCH) * 2):
        proposer = get_proposer_index_maybe(spec, state, state.slot + 1)
        if not state.validators[proposer].slashed:
            return
        next_slot(spec, state)
    raise AssertionError("no unslashed proposer found in two epochs")


def build_random_block(spec, state, rng, slashed: set, deposit_queue: list):
    """A valid block with a random operation mix: attestations plus
    (probabilistically) attester/proposer slashings, any pending
    pre-provisioned deposits (drained in full — the expected-count rule
    demands it), a voluntary exit, and a random-participation sync
    aggregate (altair+)."""
    _advance_past_slashed_proposers(spec, state)
    deposits = list(deposit_queue)
    deposit_queue.clear()
    block = build_empty_block_for_next_slot(spec, state)
    for att in _random_attestations(spec, state, rng):
        block.body.attestations.append(att)
    att_slashing = _maybe_attester_slashing(spec, state, rng, slashed)
    if att_slashing is not None:
        block.body.attester_slashings.append(att_slashing)
    prop_slashing = _maybe_proposer_slashing(spec, state, rng, slashed)
    if prop_slashing is not None:
        block.body.proposer_slashings.append(prop_slashing)
    for deposit in deposits:
        block.body.deposits.append(deposit)
    exit_op = _maybe_voluntary_exit(spec, state, rng, slashed)
    if exit_op is not None:
        block.body.voluntary_exits.append(exit_op)
    if is_post_altair(spec) and rng.random() < 0.5:
        from .multi_operations import sync_aggregate_for

        block.body.sync_aggregate = sync_aggregate_for(
            spec, state, int(block.slot), participation=rng.random(), rng=rng
        )
    return block


# -- scenario expander --------------------------------------------------------

SCENARIOS = {
    # name -> list of steps; counts kept small: each block is a full
    # state_transition and suites run across 4 forks x presets
    "random_0": ["block", "next_slot", "block", "next_epoch", "block"],
    "random_1": ["next_epoch", "block", "block", "block"],
    "random_2": ["random_slots", "block", "next_epoch", "block", "block"],
    "random_3": ["block", "random_slots", "block", "random_slots", "block"],
    "leak_0": ["leak", "block", "next_epoch", "block"],
    "leak_1": ["leak", "random_slots", "block", "block"],
    # aged states: past the minimum-service window, so the random op mix
    # can draw voluntary exits too
    "aged_0": ["age", "next_epoch", "block", "block", "next_epoch", "block"],
    "aged_1": ["age", "next_epoch", "random_slots", "block", "block"],
}


def _expand_matrix() -> None:
    """The reference's scenario product (tests/generators/random/
    generate.py: {leak, no-leak} x epochs-to-skip x slot-offset, each
    with BLOCK_TRANSITIONS_COUNT=2 block transitions) — expanded into
    the data-driven table instead of generated source files."""
    setups = {"nl": [], "lk": ["leak"]}
    skips = {"e0": [], "e1": ["next_epoch"]}
    offsets = {
        "s0": [],
        "last": ["to_last_slot"],
        "rand": ["to_random_slot"],
        "penult": ["to_penultimate_slot"],
    }
    for sname, setup in setups.items():
        for kname, skip in skips.items():
            for oname, offset in offsets.items():
                name = f"matrix_{sname}_{kname}_{oname}"
                SCENARIOS[name] = setup + skip + offset + ["block", "next_epoch", "block"]


_expand_matrix()


def run_random_scenario(spec, state, scenario_name, seed):
    rng = Random(seed)
    randomize_state(spec, state, rng)
    steps = list(SCENARIOS[scenario_name])
    # leading "age" steps are PRE-STATE SHAPING, not replayable chain
    # history: the raw slot bump skips epoch processing, so it must
    # happen before the pre snapshot or a replaying client (which runs
    # real process_slots up to the first block) lands on a different
    # state (caught by tools/replay_vectors)
    while steps and steps[0] == "age":
        state.slot += spec.config.SHARD_COMMITTEE_PERIOD * spec.SLOTS_PER_EPOCH
        steps.pop(0)
    # any deposit tree re-point must pre-date the pre snapshot too
    deposit_queue = provision_scenario_deposits(spec, state, rng)

    yield "pre", state

    blocks = []
    slashed: set = set()
    for step in steps:
        if step == "next_slot":
            next_slot(spec, state)
        elif step == "next_epoch":
            next_epoch(spec, state)
        elif step == "random_slots":
            next_slots(spec, state, rng.randrange(1, int(spec.SLOTS_PER_EPOCH)))
        elif step == "to_last_slot":
            slots_per_epoch = int(spec.SLOTS_PER_EPOCH)
            next_slots(spec, state, slots_per_epoch - 1 - int(state.slot) % slots_per_epoch)
        elif step == "to_penultimate_slot":
            slots_per_epoch = int(spec.SLOTS_PER_EPOCH)
            next_slots(spec, state, (slots_per_epoch - 2 - int(state.slot) % slots_per_epoch) % slots_per_epoch)
        elif step == "to_random_slot":
            slots_per_epoch = int(spec.SLOTS_PER_EPOCH)
            target = rng.randrange(0, slots_per_epoch)
            delta = (target - int(state.slot)) % slots_per_epoch
            next_slots(spec, state, delta)
        elif step == "leak":
            # no attestations for > MIN_EPOCHS_TO_INACTIVITY_PENALTY epochs
            for _ in range(int(spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY) + 3):
                next_epoch(spec, state)
            assert spec.is_in_inactivity_leak(state)
        elif step == "age":  # pragma: no cover - peeled before the pre yield
            raise ValueError("'age' is only valid as a leading step (pre-state shaping)")
        elif step == "block":
            block = build_random_block(spec, state, rng, slashed, deposit_queue)
            signed = state_transition_and_sign_block(spec, state, block)
            blocks.append(signed)
        else:  # pragma: no cover
            raise ValueError(f"unknown step {step}")

    yield "blocks", blocks
    yield "post", state
