"""BLSToExecutionChange builders (ref: test/helpers/bls_to_execution_changes.py
shape in later reference versions; capella/beacon-chain.md:408)."""
from __future__ import annotations

from consensus_specs_tpu.crypto import bls

from .context import expect_assertion_error
from .keys import privkeys, pubkeys


def get_signed_address_change(
    spec,
    state,
    validator_index=None,
    withdrawal_pubkey=None,
    to_execution_address=None,
    privkey=None,
):
    if validator_index is None:
        validator_index = 0
    if withdrawal_pubkey is None:
        withdrawal_pubkey = pubkeys[validator_index]
        if privkey is None:
            privkey = privkeys[validator_index]
    if to_execution_address is None:
        to_execution_address = b"\x42" * 20

    address_change = spec.BLSToExecutionChange(
        validator_index=validator_index,
        from_bls_pubkey=withdrawal_pubkey,
        to_execution_address=to_execution_address,
    )
    domain = spec.get_domain(state, spec.DOMAIN_BLS_TO_EXECUTION_CHANGE)
    signing_root = spec.compute_signing_root(address_change, domain)
    signature = (
        bls.Sign(privkey, signing_root) if privkey is not None else b"\x00" * 96
    )
    return spec.SignedBLSToExecutionChange(message=address_change, signature=signature)


def run_bls_to_execution_change_processing(spec, state, signed_address_change, valid=True):
    """Yield pre/operation/post around process_bls_to_execution_change."""
    yield "pre", state
    yield "address_change", signed_address_change

    if not valid:
        expect_assertion_error(
            lambda: spec.process_bls_to_execution_change(state, signed_address_change)
        )
        yield "post", None
        return

    spec.process_bls_to_execution_change(state, signed_address_change)
    yield "post", state

    validator = state.validators[signed_address_change.message.validator_index]
    creds = bytes(validator.withdrawal_credentials)
    assert creds[:1] == bytes(spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX)
    assert creds[1:12] == b"\x00" * 11
    assert creds[12:] == bytes(signed_address_change.message.to_execution_address)
