"""Decorator metadata copying WITHOUT functools.wraps: wraps sets
__wrapped__, which pytest follows to the innermost function and then
misreads (spec, state) as fixture names. We copy only the display
attributes."""


def copy_meta(entry, fn):
    entry.__name__ = getattr(fn, "__name__", entry.__name__)
    entry.__qualname__ = getattr(fn, "__qualname__", entry.__qualname__)
    entry.__doc__ = getattr(fn, "__doc__", None)
    entry.__module__ = getattr(fn, "__module__", entry.__module__)
    return entry
