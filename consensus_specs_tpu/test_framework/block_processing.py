"""Block transition runners (ref: test/helpers/state.py:60-120 and
helpers/block.py signing flow)."""
from __future__ import annotations

from .block import sign_block
from .context import expect_assertion_error


def transition_unsigned_block(spec, state, block):
    """process_slots + process_block, without signature/state-root checks."""
    assert state.slot < block.slot
    spec.process_slots(state, block.slot)
    spec.process_block(state, block)
    return block


def state_transition_and_sign_block(spec, state, block, expect_fail=False):
    """Apply the block to ``state``, fill in its state root, and return the
    signed block (ref state.py:60-90). With ``expect_fail`` the transition
    must raise, state is left at the pre-block slot, and the SIGNED
    invalid block is still returned — expected-failure vectors must ship
    the block a replaying client is supposed to reject (returning None
    here emitted block-less invalid sanity vectors; caught by
    tools/replay_vectors)."""
    if expect_fail:
        expect_assertion_error(lambda: transition_unsigned_block(spec, state.copy(), block))
        return sign_block(spec, state, block)
    transition_unsigned_block(spec, state, block)
    block.state_root = spec.hash_tree_root(state)
    return sign_block(spec, state, block)


def run_block_processing_to(spec, state, block, process_name: str):
    """Advance state through the per-block sub-transitions *before*
    ``process_name``, then return — so a test can run exactly one
    sub-transition against a correctly-staged state. The order comes from
    the spec's own block_process_steps() table, so fork deltas that insert
    steps (execution payload, withdrawals, sync aggregate) stage correctly
    (ref helpers/block_processing.py)."""
    if state.slot < block.slot:
        spec.process_slots(state, block.slot)

    names = [name for name, _ in spec.block_process_steps()]
    assert process_name in names, f"{process_name} not in {names}"
    for name, apply in spec.block_process_steps():
        if name == process_name:
            break
        apply(state, block)
    return state
