"""Fork / preset registry (ref: test/helpers/constants.py)."""

PHASE0 = "phase0"
ALTAIR = "altair"
BELLATRIX = "bellatrix"
CAPELLA = "capella"

# R&D branches (ref constants.py SHARDING/CUSTODY_GAME/DAS — unstable,
# excluded from the production fork matrix and vector generation)
SHARDING = "sharding"
CUSTODY_GAME = "custody_game"
DAS = "das"
EIP4844 = "eip4844"

# In dependency order
ALL_PHASES = (PHASE0, ALTAIR, BELLATRIX, CAPELLA)
RND_PHASES = (SHARDING, CUSTODY_GAME, DAS, EIP4844)
# Forks with enabled vector generation (ref constants.py:19-22)
TESTGEN_FORKS = (PHASE0, ALTAIR, BELLATRIX)

MAINNET = "mainnet"
MINIMAL = "minimal"
ALL_PRESETS = (MAINNET, MINIMAL)

PREVIOUS_FORK_OF = {
    PHASE0: None,
    ALTAIR: PHASE0,
    BELLATRIX: ALTAIR,
    CAPELLA: BELLATRIX,
}

ALL_FORK_UPGRADES = {fr: to for to, fr in PREVIOUS_FORK_OF.items() if fr is not None}


def is_post_altair(spec) -> bool:
    return spec.fork not in (PHASE0,)


def is_post_bellatrix(spec) -> bool:
    return spec.fork not in (PHASE0, ALTAIR)


def is_post_capella(spec) -> bool:
    return spec.fork == CAPELLA
