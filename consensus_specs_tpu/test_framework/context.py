"""Decorator DSL + state factories (ref: test/context.py).

Composition mirrors the reference: ``spec_state_test = spec_test(
with_state(single_phase(fn)))``; fork matrix decorators
(`with_phases`/`with_all_phases`/...) expand a test over spec targets, and
the BLS tri-state (`always_bls`/`never_bls`/bls-switch) toggles the
facade's kill-switch around each run (ref context.py:236-334).
"""
from __future__ import annotations

import random
from typing import Any, Dict, Optional, Sequence

from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.specs import build_spec
from .constants import ALL_PHASES, MINIMAL, PHASE0, ALTAIR, BELLATRIX, CAPELLA  # noqa: F401
from .genesis import create_genesis_state
from .meta import copy_meta
from .utils import vector_test, with_meta_tags

# Set by tests/conftest.py from CLI flags (ref conftest.py:30-93)
DEFAULT_PRESET = MINIMAL
DEFAULT_BLS_ACTIVE = False
ALLOWED_FORKS = None  # --fork filter: None = all implemented forks
# --engine flag: "vectorized" = the SoA epoch engine is installed for the
# whole session (engine x fork matrix); "interpreted" = spec oracle
DEFAULT_ENGINE = "interpreted"


def get_spec(fork: str, preset: str, config_overrides: Optional[Dict[str, Any]] = None):
    return build_spec(fork, preset, config_overrides)


# ---------------------------------------------------------------------------
# State factories (ref context.py:96-220)
# ---------------------------------------------------------------------------

_state_cache: Dict[tuple, bytes] = {}


def default_activation_threshold(spec):
    return spec.MAX_EFFECTIVE_BALANCE


def zero_activation_threshold(spec):
    return 0


def default_balances(spec):
    num_validators = spec.SLOTS_PER_EPOCH * 8
    return [spec.MAX_EFFECTIVE_BALANCE] * num_validators


def scaled_churn_balances(spec):
    """Enough validators that churn limit exceeds the min
    (ref context.py:168-178)."""
    num_validators = spec.config.CHURN_LIMIT_QUOTIENT * (2 + spec.config.MIN_PER_EPOCH_CHURN_LIMIT)
    return [spec.MAX_EFFECTIVE_BALANCE] * num_validators


def low_balances(spec):
    num_validators = spec.SLOTS_PER_EPOCH * 8
    low_balance = 18 * 10**9
    return [low_balance] * num_validators


def misc_balances(spec):
    num_validators = spec.SLOTS_PER_EPOCH * 8
    balances = [spec.MAX_EFFECTIVE_BALANCE * 2 * i // num_validators for i in range(num_validators)]
    rng = random.Random(3456)
    rng.shuffle(balances)
    return balances


def misc_balances_in_default_range_with_many_validators(spec):
    num_validators = spec.SLOTS_PER_EPOCH * 8 * 2
    floor = spec.config.EJECTION_BALANCE + spec.EFFECTIVE_BALANCE_INCREMENT
    balances = [
        max(spec.MAX_EFFECTIVE_BALANCE * 2 * i // num_validators, floor) for i in range(num_validators)
    ]
    rng = random.Random(1234)
    rng.shuffle(balances)
    return balances


def low_single_balance(spec):
    return [1]


def large_validator_set(spec):
    num_validators = 2 * spec.SLOTS_PER_EPOCH * spec.MAX_COMMITTEES_PER_SLOT * spec.TARGET_COMMITTEE_SIZE
    return [spec.MAX_EFFECTIVE_BALANCE] * num_validators


def _prepare_state(balances_fn, threshold_fn, spec):
    # spec.__name__ is unique per (fork, preset) AND per config-override
    # build, so an overridden spec can never hit a default-config state.
    key = (spec.__name__, balances_fn.__name__, threshold_fn.__name__)
    serialized = _state_cache.get(key)
    if serialized is None:
        state = create_genesis_state(spec, balances_fn(spec), threshold_fn(spec))
        serialized = state.encode_bytes()
        if len(_state_cache) < 32:
            _state_cache[key] = serialized
    return spec.BeaconState.decode_bytes(serialized)


def with_custom_state(balances_fn, threshold_fn):
    def deco(fn):
        def entry(*args, spec, phases=None, **kw):
            state = _prepare_state(balances_fn, threshold_fn, spec)
            # forward `phases` unconditionally; single_phase pops it for
            # single-fork tests (ref context.py:246-255)
            return fn(*args, spec=spec, state=state, phases=phases, **kw)

        return copy_meta(entry, fn)

    return deco


def with_state(fn):
    return with_custom_state(default_balances, default_activation_threshold)(fn)


# ---------------------------------------------------------------------------
# BLS tri-state (ref context.py:236-334)
# ---------------------------------------------------------------------------

def _bls_wrap(fn, force: Optional[bool]):
    # Generator wrapper: the toggle must span the *iteration* of the wrapped
    # test (tests are generators evaluated lazily), not just its creation —
    # same shape as ref context.py:294-306.
    def entry(*args, **kw):
        setting = kw.pop("bls_active", None)
        active = force if force is not None else (
            setting if setting is not None else DEFAULT_BLS_ACTIVE
        )
        old = bls.bls_active
        bls.bls_active = active
        try:
            res = fn(*args, **kw)
            if res is not None:
                yield from res
        finally:
            bls.bls_active = old

    return copy_meta(entry, fn)


def always_bls(fn):
    """Force real BLS on (ref context.py:308)."""
    return with_meta_tags({"bls_setting": 1})(_bls_wrap(fn, True))


def never_bls(fn):
    """Force BLS off (ref context.py:317)."""
    return with_meta_tags({"bls_setting": 2})(_bls_wrap(fn, False))


def bls_switch(fn):
    return _bls_wrap(fn, None)


# ---------------------------------------------------------------------------
# Core composition (ref context.py:258-291)
# ---------------------------------------------------------------------------

def single_phase(fn):
    """Drop the `phases` kwarg for tests that only need one fork
    (ref context.py:246-255)."""

    def entry(*args, **kw):
        kw.pop("phases", None)
        return fn(*args, **kw)

    return copy_meta(entry, fn)


def spec_test(fn):
    return vector_test()(bls_switch(fn))


def spec_state_test(fn):
    return spec_test(with_state(single_phase(fn)))


def spec_configured_state_test(conf_overrides):
    """spec_state_test against a config-overridden spec copy
    (ref context.py:492-551)."""

    def deco(fn):
        return spec_test(with_config_overrides(conf_overrides)(with_state(single_phase(fn))))

    return deco


def expect_assertion_error(fn):
    """Run fn expecting a spec validation failure (ref context.py:280-291).
    ValueError covers SSZ range/limit violations that remerkleable surfaces
    differently."""
    bad = False
    try:
        fn()
        bad = True
    except (AssertionError, IndexError, ValueError):
        pass
    if bad:
        raise AssertionError("expected an assertion error, but got none.")


# ---------------------------------------------------------------------------
# Fork / preset matrix (ref context.py:355-551)
# ---------------------------------------------------------------------------

def with_phases(phases: Sequence[str], other_phases: Optional[Sequence[str]] = None):
    """Expand the test over the given forks. In pytest mode all selected
    (and implemented) forks run in sequence; generator mode pins one via
    the `phase` kwarg (ref context.py:355-456)."""

    def deco(fn):
        def entry(*args, **kw):
            from consensus_specs_tpu.specs.build import available_forks, available_rnd_forks

            implemented = set(available_forks()) | set(available_rnd_forks())
            # --fork narrows which PRIMARY phases run; auxiliary specs
            # (other_phases, e.g. a transition test's post fork) must stay
            # buildable from any implemented fork or cross-fork tests
            # break under per-fork CI slices
            have = implemented
            if ALLOWED_FORKS is not None:
                have = implemented & set(ALLOWED_FORKS)
            run_phases = [p for p in phases if p in have]
            phase = kw.pop("phase", None)
            if phase is not None:
                if phase not in phases or phase not in have:
                    return None
                run_phases = [phase]
            elif not run_phases:
                # pytest mode with no implemented fork: skip loudly rather
                # than report a vacuous pass
                import pytest

                pytest.skip(f"no implemented fork among {list(phases)}")
            preset = kw.pop("preset", DEFAULT_PRESET)
            targets = {
                f: get_spec(f, preset)
                for f in set(run_phases + [p for p in (other_phases or []) if p in implemented])
            }
            ret = None
            for p in run_phases:
                ret = fn(*args, spec=targets[p], phases=targets, **kw)
            return ret

        entry.fork_matrix = list(phases)
        return copy_meta(entry, fn)

    return deco


def with_all_phases(fn):
    return with_phases(ALL_PHASES)(fn)


def with_all_phases_except(exclusions):
    def deco(fn):
        return with_phases([p for p in ALL_PHASES if p not in exclusions])(fn)

    return deco


def with_altair_and_later(fn):
    return with_phases([p for p in ALL_PHASES if p != PHASE0])(fn)


def with_bellatrix_and_later(fn):
    return with_phases([BELLATRIX, CAPELLA])(fn)


def with_capella_and_later(fn):
    return with_phases([CAPELLA])(fn)


def with_presets(preset_names: Sequence[str], reason: Optional[str] = None):
    """Skip unless the active preset is in the set (ref context.py:459).
    Reads the preset off the already-resolved spec (the `preset` kwarg is
    consumed earlier by with_phases) and raises SkippedTest — pytest mode
    converts it to a pytest.skip, generator mode counts it as skipped."""

    def deco(fn):
        def entry(*args, **kw):
            spec = kw.get("spec")
            preset = spec.preset_base if spec is not None else DEFAULT_PRESET
            if preset not in preset_names:
                from consensus_specs_tpu.exceptions import SkippedTest

                raise SkippedTest(reason or f"preset {preset} not supported")
            return fn(*args, **kw)

        return copy_meta(entry, fn)

    return deco


def with_config_overrides(conf_overrides: Dict[str, Any]):
    """Swap in a config-overridden spec copy; in generator mode the
    modified config is emitted as part of the vectors
    (ref context.py:492-534)."""

    def deco(fn):
        def entry(*args, spec, **kw):
            spec = build_spec(spec.fork, spec.preset_base, conf_overrides)
            return fn(*args, spec=spec, **kw)

        return copy_meta(entry, fn)

    return deco


def only_generator(reason):
    def deco(fn):
        def entry(*args, **kw):
            if not kw.get("generator_mode", False):
                import pytest

                pytest.skip(reason)
            return fn(*args, **kw)

        return copy_meta(entry, fn)

    return deco


def dump_skipping_message(reason: str) -> None:
    import pytest

    pytest.skip(f"[Skipped test] {reason}")
