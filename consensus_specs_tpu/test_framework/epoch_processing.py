"""Epoch sub-transition staging/runner (ref: test/helpers/
epoch_processing.py:36-67)."""
from __future__ import annotations


def get_process_calls(spec):
    return [fn.__name__ for fn in spec.epoch_process_steps()]


def run_epoch_processing_to(spec, state, process_name: str):
    """Advance to the last slot of the epoch, then run every sub-transition
    strictly before ``process_name``."""
    slot = state.slot + (spec.SLOTS_PER_EPOCH - state.slot % spec.SLOTS_PER_EPOCH)
    if state.slot < slot - 1:
        spec.process_slots(state, slot - 1)

    names = get_process_calls(spec)
    assert process_name in names, f"{process_name} not in {names}"
    for fn in spec.epoch_process_steps():
        if fn.__name__ == process_name:
            break
        fn(state)


def run_epoch_processing_with(spec, state, process_name: str):
    """Stage, then yield pre/post around exactly one sub-transition."""
    run_epoch_processing_to(spec, state, process_name)
    yield "pre", state
    getattr(spec, process_name)(state)
    yield "post", state
