"""Genesis state factory for tests (ref: test/helpers/genesis.py)."""
from __future__ import annotations

from .constants import is_post_altair, is_post_bellatrix
from .keys import pubkeys


def mock_withdrawal_credentials(spec, validator_index: int) -> bytes:
    pubkey = pubkeys[validator_index]
    return bytes(spec.BLS_WITHDRAWAL_PREFIX) + spec.hash(pubkey)[1:]


def build_mock_validator(spec, i: int, balance: int):
    validator = spec.Validator(
        pubkey=pubkeys[i],
        withdrawal_credentials=mock_withdrawal_credentials(spec, i),
        activation_eligibility_epoch=spec.FAR_FUTURE_EPOCH,
        activation_epoch=spec.FAR_FUTURE_EPOCH,
        exit_epoch=spec.FAR_FUTURE_EPOCH,
        withdrawable_epoch=spec.FAR_FUTURE_EPOCH,
        effective_balance=min(
            balance - balance % spec.EFFECTIVE_BALANCE_INCREMENT, spec.MAX_EFFECTIVE_BALANCE
        ),
    )
    if hasattr(validator, "fully_withdrawn_epoch"):  # capella+
        validator.fully_withdrawn_epoch = spec.FAR_FUTURE_EPOCH
    return validator


def _fork_versions(spec):
    """(previous_version, current_version) the test genesis state should
    carry for the spec's fork (ref genesis.py:20-40)."""
    cfg = spec.config
    by_fork = {
        "phase0": (cfg.GENESIS_FORK_VERSION, cfg.GENESIS_FORK_VERSION),
        "altair": (cfg.GENESIS_FORK_VERSION, cfg.ALTAIR_FORK_VERSION),
        "bellatrix": (cfg.ALTAIR_FORK_VERSION, cfg.BELLATRIX_FORK_VERSION),
        "capella": (cfg.BELLATRIX_FORK_VERSION, cfg.CAPELLA_FORK_VERSION),
        # R&D branches run off bellatrix versioning (their fork configs
        # are TBD upstream; SHARDING_FORK_VERSION stands in for sharding's
        # family, bellatrix's own for eip4844)
        "sharding": (cfg.BELLATRIX_FORK_VERSION, cfg.SHARDING_FORK_VERSION),
        "custody_game": (cfg.BELLATRIX_FORK_VERSION, cfg.SHARDING_FORK_VERSION),
        "das": (cfg.BELLATRIX_FORK_VERSION, cfg.SHARDING_FORK_VERSION),
        "eip4844": (cfg.BELLATRIX_FORK_VERSION, cfg.BELLATRIX_FORK_VERSION),
    }
    return by_fork[spec.fork]


def create_genesis_state(spec, validator_balances, activation_threshold):
    deposit_root = b"\x42" * 32
    eth1_block_hash = b"\xda" * 32
    previous_version, current_version = _fork_versions(spec)

    state = spec.BeaconState(
        genesis_time=0,
        eth1_deposit_index=len(validator_balances),
        eth1_data=spec.Eth1Data(
            deposit_root=deposit_root,
            deposit_count=len(validator_balances),
            block_hash=eth1_block_hash,
        ),
        fork=spec.Fork(
            previous_version=previous_version,
            current_version=current_version,
            epoch=spec.GENESIS_EPOCH,
        ),
        latest_block_header=spec.BeaconBlockHeader(
            body_root=spec.hash_tree_root(spec.BeaconBlockBody())
        ),
        randao_mixes=[eth1_block_hash] * spec.EPOCHS_PER_HISTORICAL_VECTOR,
    )

    # Seed the registry
    for index, balance in enumerate(validator_balances):
        validator = build_mock_validator(spec, index, balance)
        state.validators.append(validator)
        state.balances.append(balance)
        if validator.effective_balance >= activation_threshold:
            validator.activation_eligibility_epoch = spec.GENESIS_EPOCH
            validator.activation_epoch = spec.GENESIS_EPOCH

    state.genesis_validators_root = spec.hash_tree_root(state.validators)

    if is_post_altair(spec):
        # Participation/inactivity tracking + initial sync committees
        state.previous_epoch_participation = [spec.ParticipationFlags(0)] * len(state.validators)
        state.current_epoch_participation = [spec.ParticipationFlags(0)] * len(state.validators)
        state.inactivity_scores = [spec.uint64(0)] * len(state.validators)
        state.current_sync_committee = spec.get_next_sync_committee(state)
        state.next_sync_committee = spec.get_next_sync_committee(state)

    if is_post_bellatrix(spec):
        state.latest_execution_payload_header = spec.ExecutionPayloadHeader()

    return state
