"""Deterministic test keypairs: privkey(i) = i + 1 (ref: test/helpers/
keys.py:1-6). Pubkeys are derived lazily and cached for the session —
the reference precomputes 8192 eagerly with native BLS; with the pure-host
scalar-mul here, laziness keeps import instant."""
from __future__ import annotations

from typing import Dict

from consensus_specs_tpu.crypto.bls import ciphersuite


class _LazyPubkeys:
    """Sequence-like view: pubkeys[i] == SkToPk(i + 1)."""

    def __init__(self):
        self._cache: Dict[int, bytes] = {}

    def __getitem__(self, i: int) -> bytes:
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(1 << 14))]
        if i < 0:
            i += 1 << 14
        pk = self._cache.get(i)
        if pk is None:
            pk = ciphersuite.SkToPk(i + 1)
            self._cache[i] = pk
            pubkey_to_privkey[pk] = i + 1
        return pk


def privkey(index: int) -> int:
    return index + 1


class _Privkeys:
    def __getitem__(self, i: int) -> int:
        if i < 0:
            i += 1 << 14
        return i + 1


privkeys = _Privkeys()
pubkeys = _LazyPubkeys()
pubkey_to_privkey: Dict[bytes, int] = {}


def aggregate_sign(sks, signing_root: bytes):
    """Aggregate signature of many keys over ONE message, computed as a
    single Sign under the summed secret key: by linearity,
    sum_i(sk_i·H(m)) == (sum_i sk_i mod r)·H(m), so the compressed bytes
    are identical to Aggregate([Sign(sk_i, m)]) at ~1/k the cost (one
    G2 scalar-mult instead of k). The reference helpers pay the per-key
    loop (test/helpers/attestations.py:83-87) because py_ecc gives them
    no cheaper algebra; the equivalence is pinned by
    tests/test_gen_pipeline.py::test_aggregate_sign_matches_per_key_path.

    Funnels through the facade's Aggregate so the bls_active=False
    behavior (G2_POINT_AT_INFINITY) is exactly the per-key path's.
    """
    from consensus_specs_tpu.crypto import bls
    from consensus_specs_tpu.crypto.bls.fields import R

    sks = list(sks)
    assert len(sks) > 0
    agg_sk = sum(int(sk) for sk in sks) % R
    return bls.Aggregate([bls.Sign(agg_sk, signing_root)])
