"""Deterministic test keypairs: privkey(i) = i + 1 (ref: test/helpers/
keys.py:1-6). Pubkeys are derived lazily and cached for the session —
the reference precomputes 8192 eagerly with native BLS; with the pure-host
scalar-mul here, laziness keeps import instant."""
from __future__ import annotations

from typing import Dict

from consensus_specs_tpu.crypto.bls import ciphersuite


class _LazyPubkeys:
    """Sequence-like view: pubkeys[i] == SkToPk(i + 1)."""

    def __init__(self):
        self._cache: Dict[int, bytes] = {}

    def __getitem__(self, i: int) -> bytes:
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(1 << 14))]
        if i < 0:
            i += 1 << 14
        pk = self._cache.get(i)
        if pk is None:
            pk = ciphersuite.SkToPk(i + 1)
            self._cache[i] = pk
            pubkey_to_privkey[pk] = i + 1
        return pk


def privkey(index: int) -> int:
    return index + 1


class _Privkeys:
    def __getitem__(self, i: int) -> int:
        if i < 0:
            i += 1 << 14
        return i + 1


privkeys = _Privkeys()
pubkeys = _LazyPubkeys()
pubkey_to_privkey: Dict[bytes, int] = {}
