"""Voluntary exit builders + runner (ref: test/helpers/voluntary_exits.py)."""
from __future__ import annotations

from .context import expect_assertion_error
from .keys import privkeys


def sign_voluntary_exit(spec, state, voluntary_exit, privkey):
    domain = spec.get_domain(state, spec.DOMAIN_VOLUNTARY_EXIT, voluntary_exit.epoch)
    signing_root = spec.compute_signing_root(voluntary_exit, domain)
    return spec.SignedVoluntaryExit(
        message=voluntary_exit, signature=spec.bls.Sign(privkey, signing_root)
    )


def prepare_signed_exits(spec, state, indices):
    def create_signed_exit(index):
        voluntary_exit = spec.VoluntaryExit(
            epoch=spec.get_current_epoch(state), validator_index=index
        )
        return sign_voluntary_exit(spec, state, voluntary_exit, privkeys[index])

    return [create_signed_exit(index) for index in indices]


def get_unslashed_exited_validators(spec, state):
    """Indices exited (at or before the current epoch) but not slashed
    (ref: test/helpers/voluntary_exits.py)."""
    epoch = spec.get_current_epoch(state)
    return [
        i
        for i, v in enumerate(state.validators)
        if not v.slashed and v.exit_epoch <= epoch
    ]


def run_voluntary_exit_processing(spec, state, signed_voluntary_exit, valid=True):
    """Yield pre/operation/post around process_voluntary_exit."""
    validator_index = signed_voluntary_exit.message.validator_index

    yield "pre", state
    yield "voluntary_exit", signed_voluntary_exit

    if not valid:
        expect_assertion_error(lambda: spec.process_voluntary_exit(state, signed_voluntary_exit))
        yield "post", None
        return

    pre_exit_epoch = state.validators[validator_index].exit_epoch
    spec.process_voluntary_exit(state, signed_voluntary_exit)
    yield "post", state

    assert pre_exit_epoch == spec.FAR_FUTURE_EPOCH
    assert state.validators[validator_index].exit_epoch < spec.FAR_FUTURE_EPOCH
