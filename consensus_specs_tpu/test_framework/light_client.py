"""Light-client sync-protocol test helpers
(ref: test/helpers/light_client.py shape; altair/sync-protocol.md)."""
from __future__ import annotations

from consensus_specs_tpu.ssz.proof import compute_merkle_proof

from .sync_committee import (
    compute_aggregate_sync_committee_signature,
    compute_committee_indices,
)


def initialize_light_client_store(spec, state):
    return spec.LightClientStore(
        finalized_header=spec.BeaconBlockHeader(),
        current_sync_committee=state.current_sync_committee,
        next_sync_committee=state.next_sync_committee,
        best_valid_update=None,
        optimistic_header=spec.BeaconBlockHeader(),
        previous_max_active_participants=0,
        current_max_active_participants=0,
    )


def signed_block_header(spec, block):
    return spec.BeaconBlockHeader(
        slot=block.slot,
        proposer_index=block.proposer_index,
        parent_root=block.parent_root,
        state_root=block.state_root,
        body_root=spec.hash_tree_root(block.body),
    )


def get_sync_aggregate_over_header(spec, state, header, participation=None):
    """SyncAggregate of the CURRENT sync committee signing `header` as the
    attested header. compute_signing_root(header, d) equals
    compute_signing_root(Root(htr(header)), d), so the sync-committee
    message signer applies directly (sync-protocol.md:159-231)."""
    committee = compute_committee_indices(spec, state)
    size = int(spec.SYNC_COMMITTEE_SIZE)
    if participation is None:
        bits = [True] * size
    else:
        bits = [i < int(size * participation) for i in range(size)]
    participants = [committee[i] for i in range(size) if bits[i]]
    signature = compute_aggregate_sync_committee_signature(
        spec, state, header.slot, participants, block_root=spec.hash_tree_root(header)
    )
    return spec.SyncAggregate(
        sync_committee_bits=bits, sync_committee_signature=signature
    ), participants


def empty_finality_branch(spec):
    return [spec.Bytes32() for _ in range(spec.floorlog2(spec.FINALIZED_ROOT_INDEX))]


def empty_next_sync_committee_branch(spec):
    return [spec.Bytes32() for _ in range(spec.floorlog2(spec.NEXT_SYNC_COMMITTEE_INDEX))]


def build_finality_branch(spec, attested_state):
    return compute_merkle_proof(attested_state, int(spec.FINALIZED_ROOT_INDEX))
