"""Cross-fork transition test machinery (ref: test/helpers/fork_transition.py,
354 LoC; emits the transition vector format: meta post_fork/fork_epoch/
fork_block + pre (old fork), blocks (mixed forks), post (new fork))."""
from __future__ import annotations

from .block import build_empty_block, build_empty_block_for_next_slot, sign_block
from .state import state_transition_and_sign_block


UPGRADE_FNS = {
    "altair": "upgrade_to_altair",
    "bellatrix": "upgrade_to_bellatrix",
    "capella": "upgrade_to_capella",
}


def _build_boundary_operation(spec, state, kind):
    """(body_field, operation) built with `spec` against `state` — used
    to plant one operation in the last pre-fork or first post-fork block."""
    if kind == "proposer_slashing":
        from .proposer_slashings import get_valid_proposer_slashing

        victim = spec.get_active_validator_indices(state, spec.get_current_epoch(state))[-1]
        return "proposer_slashings", get_valid_proposer_slashing(
            spec, state, slashed_index=victim, signed_1=True, signed_2=True
        )
    if kind == "attester_slashing":
        from .attester_slashings import get_valid_attester_slashing_by_indices

        victim = spec.get_active_validator_indices(state, spec.get_current_epoch(state))[-2]
        return "attester_slashings", get_valid_attester_slashing_by_indices(
            spec, state, [victim], signed_1=True, signed_2=True
        )
    if kind == "deposit":
        from .multi_operations import deposits_for

        return "deposits", deposits_for(spec, state, 1)[0]
    if kind == "voluntary_exit":
        from .voluntary_exits import prepare_signed_exits

        index = spec.get_active_validator_indices(state, spec.get_current_epoch(state))[-1]
        return "voluntary_exits", prepare_signed_exits(spec, state, [index])[0]
    if kind == "attestation":
        from .attestations import get_valid_attestation

        return "attestations", get_valid_attestation(
            spec, state, slot=state.slot, signed=True
        )
    raise ValueError(f"unknown boundary operation {kind!r}")


def run_fork_transition_with_operation(spec_pre, spec_post, state, kind, before_fork=False):
    """Cross a fork boundary with one operation planted AT the boundary:
    in the LAST pre-fork block (before_fork) or the FIRST post-fork block.
    The attestation kind always comes from the pre-fork context, so the
    post-fork inclusion path must handle a pre-fork vote (signature
    domain resolved against the previous fork version). Voluntary exits
    age the state first (the service-window slot-bump idiom)."""
    yield "post_fork", "meta", spec_post.fork
    if kind == "voluntary_exit":
        state.slot += spec_pre.config.SHARD_COMMITTEE_PERIOD * spec_pre.SLOTS_PER_EPOCH
    # deposits must be built NOW: deposits_for re-points state.eth1_data,
    # and the pre snapshot below must carry the tree the proof verifies
    # under (emission bug caught by tools/replay_vectors). The deposit
    # itself is boundary-independent (tree + genesis-domain signature).
    prebuilt = _build_boundary_operation(spec_pre, state, kind) if kind == "deposit" else None
    fork_epoch = int(spec_pre.get_current_epoch(state)) + 1
    yield "fork_epoch", "meta", fork_epoch
    yield "pre", state

    blocks = []
    fork_slot = fork_epoch * int(spec_pre.SLOTS_PER_EPOCH)
    assert state.slot < fork_slot

    if prebuilt is not None:
        # a pending deposit FORCES inclusion in every block (the
        # expected-count rule, process_operations), so mirror the
        # reference recipe: slide to the boundary by slot processing
        # alone and let only the op-carrying block exist pre-fork
        if int(state.slot) + 2 < fork_slot:
            spec_pre.process_slots(state, fork_slot - 2)
    else:
        # empty pre-fork chain up to (not including) the last pre-fork slot
        while int(state.slot) + 2 < fork_slot:
            block = build_empty_block_for_next_slot(spec_pre, state)
            blocks.append(state_transition_and_sign_block(spec_pre, state, block))

    # last pre-fork block — carries the op in the before_fork flavor.
    # The op is built BEFORE the block: deposits re-point state.eth1_data
    # at their tree, and the block's parent root snapshots the state root
    # at build time (a later state mutation would poison it)
    if before_fork:
        field, operation = prebuilt or _build_boundary_operation(spec_pre, state, kind)
        block = build_empty_block_for_next_slot(spec_pre, state)
        getattr(block.body, field).append(operation)
        blocks.append(state_transition_and_sign_block(spec_pre, state, block))
    elif prebuilt is None:
        block = build_empty_block_for_next_slot(spec_pre, state)
        blocks.append(state_transition_and_sign_block(spec_pre, state, block))
    # else: deposit-after-fork — a pending deposit makes ANY empty
    # pre-fork block unbuildable; the first block is the post-fork one.
    # fork_block is OPTIONAL meta (format contract: present => a
    # pre-fork block exists), so it is omitted when no block landed
    # before the boundary
    if blocks:
        yield "fork_block", "meta", len(blocks) - 1

    # a cross-fork attestation is authored in the PRE-fork context
    carried = prebuilt if not before_fork else None
    if not before_fork and kind == "attestation":
        carried = _build_boundary_operation(spec_pre, state, kind)

    spec_pre.process_slots(state, fork_slot)
    upgrade = getattr(spec_post, UPGRADE_FNS[spec_post.fork])
    state = upgrade(state)

    # first post-fork block at the fork-epoch start slot carries the op
    # in the after flavor (op built before the block — see above)
    if not before_fork and carried is None:
        carried = _build_boundary_operation(spec_post, state, kind)
    block = build_empty_block(spec_post, state, slot=state.slot)
    if carried is not None:
        field, operation = carried
        getattr(block.body, field).append(operation)
    spec_post.process_block(state, block)
    block.state_root = spec_post.hash_tree_root(state)
    blocks.append(sign_block(spec_post, state, block))

    for _ in range(2):
        block = build_empty_block_for_next_slot(spec_post, state)
        blocks.append(state_transition_and_sign_block(spec_post, state, block))

    yield "blocks", blocks
    yield "post", state


def run_fork_transition(
    spec_pre,
    spec_post,
    state,
    fork_epoch,
    blocks_before=True,
    blocks_after=2,
    attested_before=False,
    attested_after=False,
    participation_fn=None,
    skip_last_pre_fork_block=False,
):
    """Drive a chain of blocks across the fork boundary at fork_epoch.

    The last pre-fork slot gets a pre-fork block (when blocks_before,
    unless skip_last_pre_fork_block leaves that slot empty), epoch
    processing rolls into fork_epoch, the state is upgraded, and the
    first post-fork block lands at the fork-epoch start slot — matching
    the reference's transition semantics
    (test/altair/transition/test_transition.py). attested_before/_after
    fill each side's blocks with the usual cur+prev epoch attestation
    load (optionally thinned by participation_fn), so finality can keep
    advancing across the boundary."""
    from .attestations import state_transition_with_full_block
    yield "post_fork", "meta", spec_post.fork
    yield "fork_epoch", "meta", int(fork_epoch)
    yield "pre", state

    blocks = []
    fork_slot = int(fork_epoch) * int(spec_pre.SLOTS_PER_EPOCH)
    assert state.slot < fork_slot

    if blocks_before:
        last_gap = 2 if skip_last_pre_fork_block else 1
        while int(state.slot) + last_gap < fork_slot:
            if attested_before:
                blocks.append(
                    state_transition_with_full_block(
                        spec_pre, state, True, True, participation_fn
                    )
                )
            else:
                block = build_empty_block_for_next_slot(spec_pre, state)
                blocks.append(state_transition_and_sign_block(spec_pre, state, block))
    if blocks:
        yield "fork_block", "meta", len(blocks) - 1  # index of last pre-fork block

    # roll through the epoch boundary into the fork epoch, then upgrade
    spec_pre.process_slots(state, fork_slot)
    upgrade = getattr(spec_post, UPGRADE_FNS[spec_post.fork])
    state = upgrade(state)
    assert bytes(state.fork.current_version) == bytes(
        getattr(spec_post.config, f"{spec_post.fork.upper()}_FORK_VERSION")
    )

    # first post-fork block at the fork-epoch start slot: the state is
    # already at that slot, so apply process_block directly (the
    # reference's _state_transition_and_sign_block_at_slot shape)
    block = build_empty_block(spec_post, state, slot=state.slot)
    spec_post.process_block(state, block)
    block.state_root = spec_post.hash_tree_root(state)
    blocks.append(sign_block(spec_post, state, block))
    for _ in range(int(blocks_after)):
        if attested_after:
            blocks.append(
                state_transition_with_full_block(
                    spec_post, state, True, True, participation_fn
                )
            )
        else:
            block = build_empty_block_for_next_slot(spec_post, state)
            blocks.append(state_transition_and_sign_block(spec_post, state, block))

    yield "blocks", blocks
    yield "post", state
