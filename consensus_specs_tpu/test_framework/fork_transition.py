"""Cross-fork transition test machinery (ref: test/helpers/fork_transition.py,
354 LoC; emits the transition vector format: meta post_fork/fork_epoch/
fork_block + pre (old fork), blocks (mixed forks), post (new fork))."""
from __future__ import annotations

from .block import build_empty_block, build_empty_block_for_next_slot, sign_block
from .state import state_transition_and_sign_block


UPGRADE_FNS = {
    "altair": "upgrade_to_altair",
    "bellatrix": "upgrade_to_bellatrix",
    "capella": "upgrade_to_capella",
}


def run_fork_transition(
    spec_pre,
    spec_post,
    state,
    fork_epoch,
    blocks_before=True,
    blocks_after=2,
):
    """Drive a chain of blocks across the fork boundary at fork_epoch.

    The last pre-fork slot gets a pre-fork block (when blocks_before),
    epoch processing rolls into fork_epoch, the state is upgraded, and
    the first post-fork block lands at the fork-epoch start slot —
    matching the reference's transition semantics
    (test/altair/transition/test_transition.py)."""
    yield "post_fork", "meta", spec_post.fork
    yield "fork_epoch", "meta", int(fork_epoch)
    yield "pre", state

    blocks = []
    fork_slot = int(fork_epoch) * int(spec_pre.SLOTS_PER_EPOCH)
    assert state.slot < fork_slot

    if blocks_before:
        while int(state.slot) + 1 < fork_slot:
            block = build_empty_block_for_next_slot(spec_pre, state)
            blocks.append(state_transition_and_sign_block(spec_pre, state, block))
    if blocks:
        yield "fork_block", "meta", len(blocks) - 1  # index of last pre-fork block

    # roll through the epoch boundary into the fork epoch, then upgrade
    spec_pre.process_slots(state, fork_slot)
    upgrade = getattr(spec_post, UPGRADE_FNS[spec_post.fork])
    state = upgrade(state)
    assert bytes(state.fork.current_version) == bytes(
        getattr(spec_post.config, f"{spec_post.fork.upper()}_FORK_VERSION")
    )

    # first post-fork block at the fork-epoch start slot: the state is
    # already at that slot, so apply process_block directly (the
    # reference's _state_transition_and_sign_block_at_slot shape)
    block = build_empty_block(spec_post, state, slot=state.slot)
    spec_post.process_block(state, block)
    block.state_root = spec_post.hash_tree_root(state)
    blocks.append(sign_block(spec_post, state, block))
    for _ in range(int(blocks_after)):
        block = build_empty_block_for_next_slot(spec_post, state)
        blocks.append(state_transition_and_sign_block(spec_post, state, block))

    yield "blocks", blocks
    yield "post", state
