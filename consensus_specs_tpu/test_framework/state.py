"""State progression helpers (ref: test/helpers/state.py)."""
from __future__ import annotations



def get_balance(state, index):
    return state.balances[index]


def next_slot(spec, state):
    spec.process_slots(state, state.slot + 1)


def next_slots(spec, state, slots):
    if slots > 0:
        spec.process_slots(state, state.slot + slots)


def transition_to(spec, state, slot):
    assert state.slot <= slot
    for _ in range(1000):
        if state.slot < slot:
            spec.process_slots(state, slot)
        if state.slot == slot:
            return
    raise AssertionError(f"could not reach slot {slot}")


def transition_to_slot_via_block(spec, state, slot):
    """Advance using a (signed) empty block landing exactly at ``slot``."""
    from .block_processing import state_transition_and_sign_block
    from .block import build_empty_block

    assert state.slot < slot
    return state_transition_and_sign_block(spec, state, build_empty_block(spec, state, slot))


def next_epoch(spec, state):
    slot = state.slot + spec.SLOTS_PER_EPOCH - (state.slot % spec.SLOTS_PER_EPOCH)
    if slot > state.slot:
        spec.process_slots(state, slot)


def next_epoch_via_block(spec, state):
    """Advance one epoch with a block at the boundary slot."""
    from .block_processing import state_transition_and_sign_block
    from .block import build_empty_block

    slot = state.slot + spec.SLOTS_PER_EPOCH - (state.slot % spec.SLOTS_PER_EPOCH)
    block = build_empty_block(spec, state, slot)
    return state_transition_and_sign_block(spec, state, block)


def transition_to_valid_shard_slot(spec, state):
    """Advance into the first slot of epoch 1: the epoch transition's
    reset_pending_shard_work has then seeded SHARD_WORK_PENDING lists for
    the current epoch's (slot, shard) pairs, so process_shard_header
    accepts headers for slot SLOTS_PER_EPOCH (0 < header.slot <= state.slot)."""
    transition_to(spec, state, spec.SLOTS_PER_EPOCH + 1)


def state_transition_and_sign_block(spec, state, block, expect_fail=False):
    # Back-compat alias; the real implementation lives in block_processing
    from .block_processing import state_transition_and_sign_block as impl

    return impl(spec, state, block, expect_fail=expect_fail)


def has_active_balance_differential(spec, state) -> bool:
    """Active balance != total balance (ref state.py helper for randomized
    scenario sanity)."""
    active_balance = spec.get_total_active_balance(state)
    total_balance = spec.Gwei(sum(int(b) for b in state.balances))
    return active_balance // spec.EFFECTIVE_BALANCE_INCREMENT != total_balance // spec.EFFECTIVE_BALANCE_INCREMENT


def get_state_root(spec, state, slot):
    assert slot < state.slot <= slot + spec.SLOTS_PER_HISTORICAL_ROOT
    return state.state_roots[slot % spec.SLOTS_PER_HISTORICAL_ROOT]


def payload_state_transition(spec, store, block):  # bellatrix fork-choice helper hook
    pass


def cause_effective_balance_decrease_below_threshold(spec, state, index):
    """Set a validator's effective balance below the hysteresis threshold."""
    state.validators[index].effective_balance = spec.config.EJECTION_BALANCE
