"""Deposit builders + processing runner (ref: test/helpers/deposits.py)."""
from __future__ import annotations

from consensus_specs_tpu.ssz.merkle import calc_merkle_tree_from_leaves, get_merkle_proof

from .context import expect_assertion_error
from .keys import privkeys, pubkeys


def mock_deposit(spec, state, index):
    """Mock validator at ``index`` as not-yet-activated (ref deposits.py)."""
    assert spec.is_active_validator(state.validators[index], spec.get_current_epoch(state))
    state.validators[index].activation_eligibility_epoch = spec.FAR_FUTURE_EPOCH
    state.validators[index].activation_epoch = spec.FAR_FUTURE_EPOCH
    state.validators[index].effective_balance = spec.MAX_EFFECTIVE_BALANCE
    assert not spec.is_active_validator(state.validators[index], spec.get_current_epoch(state))


def build_deposit_data(spec, pubkey, privkey, amount, withdrawal_credentials, signed=False):
    deposit_data = spec.DepositData(
        pubkey=pubkey,
        withdrawal_credentials=withdrawal_credentials,
        amount=amount,
    )
    if signed:
        sign_deposit_data(spec, deposit_data, privkey)
    return deposit_data


def sign_deposit_data(spec, deposit_data, privkey):
    deposit_message = spec.DepositMessage(
        pubkey=deposit_data.pubkey,
        withdrawal_credentials=deposit_data.withdrawal_credentials,
        amount=deposit_data.amount,
    )
    domain = spec.compute_domain(spec.DOMAIN_DEPOSIT)
    signing_root = spec.compute_signing_root(deposit_message, domain)
    deposit_data.signature = spec.bls.Sign(privkey, signing_root)


def build_deposit(spec, deposit_data_list, pubkey, privkey, amount,
                  withdrawal_credentials, signed):
    deposit_data = build_deposit_data(
        spec, pubkey, privkey, amount, withdrawal_credentials, signed=signed
    )
    index = len(deposit_data_list)
    deposit_data_list.append(deposit_data)
    return deposit_from_context(spec, deposit_data_list, index)


def deposit_from_context(spec, deposit_data_list, index):
    """Deposit + root for the deposit at ``index`` given the full list:
    32-level branch + the length mix-in chunk as the 33rd proof node
    (beacon-chain.md:742,1854)."""
    deposit_data = deposit_data_list[index]
    root = spec.hash_tree_root(
        spec.List[spec.DepositData, 2**spec.DEPOSIT_CONTRACT_TREE_DEPTH](deposit_data_list)
    )
    tree = calc_merkle_tree_from_leaves(
        [spec.hash_tree_root(d) for d in deposit_data_list],
        layer_count=int(spec.DEPOSIT_CONTRACT_TREE_DEPTH),
    )
    length_chunk = len(deposit_data_list).to_bytes(32, "little")
    proof = list(get_merkle_proof(tree, item_index=index)) + [length_chunk]
    leaf = spec.hash_tree_root(deposit_data)
    assert spec.is_valid_merkle_branch(
        leaf, proof, spec.DEPOSIT_CONTRACT_TREE_DEPTH + 1, index, root
    )
    deposit = spec.Deposit(proof=proof, data=deposit_data)
    return deposit, root, deposit_data_list


def prepare_state_and_deposit(spec, state, validator_index, amount,
                              withdrawal_credentials=None, signed=False):
    """Build a deposit for ``validator_index`` and point the state's
    eth1_data at its tree (ref deposits.py:120-160)."""
    deposit_data_list = []
    pubkey = pubkeys[validator_index]
    privkey = privkeys[validator_index]

    # insecurely embedded default: hash of pubkey with BLS prefix
    if withdrawal_credentials is None:
        withdrawal_credentials = (
            bytes(spec.BLS_WITHDRAWAL_PREFIX) + spec.hash(pubkey)[1:]
        )

    deposit, root, deposit_data_list = build_deposit(
        spec, deposit_data_list, pubkey, privkey, amount, withdrawal_credentials, signed
    )

    state.eth1_deposit_index = 0
    state.eth1_data.deposit_root = root
    state.eth1_data.deposit_count = len(deposit_data_list)
    return deposit


def run_deposit_processing(spec, state, deposit, validator_index, valid=True, effective=True):
    """Yield pre/operation/post around process_deposit
    (ref deposits.py:170-230)."""
    pre_validator_count = len(state.validators)
    pre_balance = 0
    is_top_up = validator_index < pre_validator_count
    if is_top_up:
        pre_balance = int(state.balances[validator_index])
        pre_effective_balance = int(state.validators[validator_index].effective_balance)

    yield "pre", state
    yield "deposit", deposit

    if not valid:
        expect_assertion_error(lambda: spec.process_deposit(state, deposit))
        yield "post", None
        return

    spec.process_deposit(state, deposit)
    yield "post", state

    if not effective or not spec.bls.KeyValidate(deposit.data.pubkey):
        assert len(state.validators) == pre_validator_count
        assert len(state.balances) == pre_validator_count
        if is_top_up:
            assert state.balances[validator_index] == pre_balance
    else:
        if is_top_up:
            # Top-ups don't add validators
            assert len(state.validators) == pre_validator_count
            assert len(state.balances) == pre_validator_count
            # Top-ups do not change effective balance
            assert state.validators[validator_index].effective_balance == pre_effective_balance
        else:
            assert len(state.validators) == pre_validator_count + 1
            assert len(state.balances) == pre_validator_count + 1
            effective_balance = min(spec.MAX_EFFECTIVE_BALANCE, int(deposit.data.amount))
            effective_balance -= effective_balance % spec.EFFECTIVE_BALANCE_INCREMENT
            assert state.validators[validator_index].effective_balance == effective_balance
        assert state.balances[validator_index] == pre_balance + deposit.data.amount
    assert state.eth1_deposit_index == state.eth1_data.deposit_count
