"""Rewards test machinery: per-component delta runners + scenario
builders (ref: test/helpers/rewards.py, 520 LoC — redesigned around two
oracles: per-component participation properties, and an end-to-end
cross-check that the emitted deltas compose to exactly the balance
changes process_rewards_and_penalties applies)."""
from __future__ import annotations

from random import Random

from .attestations import prepare_state_with_attestations
from .constants import is_post_altair, is_post_bellatrix
from .state import next_epoch


_DELTAS_CLASSES = {}


def _deltas_class(spec):
    """SSZ container type for a (rewards, penalties) pair — the vector
    part format (ref rewards.py:19-21). Built via type() with real-type
    annotations (this module's `from __future__ import annotations`
    would stringify inline class-body annotations, hiding the fields
    from the Container metaclass)."""
    from consensus_specs_tpu.ssz import List, uint64

    limit = int(spec.VALIDATOR_REGISTRY_LIMIT)
    cls = _DELTAS_CLASSES.get(limit)
    if cls is None:
        elem = List[uint64, limit]
        cls = type(
            "Deltas",
            (spec.Container,),
            {"__annotations__": {"rewards": elem, "penalties": elem}},
        )
        _DELTAS_CLASSES[limit] = cls
    return cls


def Deltas(spec, rewards, penalties):
    return _deltas_class(spec)(rewards=rewards, penalties=penalties)


def get_inactivity_penalty_quotient(spec):
    if is_post_bellatrix(spec):
        return spec.INACTIVITY_PENALTY_QUOTIENT_BELLATRIX
    if is_post_altair(spec):
        return spec.INACTIVITY_PENALTY_QUOTIENT_ALTAIR
    return spec.INACTIVITY_PENALTY_QUOTIENT


def has_enough_for_reward(spec, state, index):
    """Positive effective balance can still round to a zero base reward
    (ref rewards.py:33-43)."""
    if is_post_altair(spec):
        increments = state.validators[index].effective_balance // spec.EFFECTIVE_BALANCE_INCREMENT
        return increments * spec.get_base_reward_per_increment(state) > 0
    return (
        state.validators[index].effective_balance * spec.BASE_REWARD_FACTOR
        > spec.integer_squareroot(spec.get_total_active_balance(state))
        // spec.BASE_REWARDS_PER_EPOCH
    )


def _eligible_indices(spec, state):
    previous_epoch = spec.get_previous_epoch(state)
    return [
        i
        for i, v in enumerate(state.validators)
        if spec.is_active_validator(v, previous_epoch)
        or (v.slashed and previous_epoch + 1 < v.withdrawable_epoch)
    ]


def _phase0_component_participants(spec, state, component):
    previous_epoch = spec.get_previous_epoch(state)
    matching = {
        "source": spec.get_matching_source_attestations,
        "target": spec.get_matching_target_attestations,
        "head": spec.get_matching_head_attestations,
    }[component](state, previous_epoch)
    return spec.get_unslashed_attesting_indices(state, matching)


def _altair_component_participants(spec, state, component):
    flag_index = {
        "source": spec.TIMELY_SOURCE_FLAG_INDEX,
        "target": spec.TIMELY_TARGET_FLAG_INDEX,
        "head": spec.TIMELY_HEAD_FLAG_INDEX,
    }[component]
    return spec.get_unslashed_participating_indices(
        state, flag_index, spec.get_previous_epoch(state)
    )


def _validate_component_deltas(spec, state, component, rewards, penalties):
    """Property oracle per component (ref rewards.py validate logic):
    participants earn (exactly, in phase0, when the base reward rounds
    positive; in altair the per-flag reward can round to zero so only a
    collective check applies), eligible non-participants are penalized
    (except altair's head flag, which carries no penalty), and everyone
    else is untouched."""
    eligible = set(_eligible_indices(spec, state))
    in_leak = spec.is_in_inactivity_leak(state)
    post_altair = is_post_altair(spec)
    if post_altair:
        participants = _altair_component_participants(spec, state, component)
        penalizing = component in ("source", "target")
    else:
        participants = _phase0_component_participants(spec, state, component)
        penalizing = True

    for index in range(len(state.validators)):
        if index not in eligible:
            assert rewards[index] == 0 and penalties[index] == 0
            continue
        if index in participants:
            assert penalties[index] == 0
            if in_leak and post_altair:
                # altair suppresses flag rewards during a leak
                assert rewards[index] == 0
            elif in_leak:
                # phase0 pays the full base reward (cancelled by the
                # inactivity deltas) — nonzero when it rounds positive
                if has_enough_for_reward(spec, state, index):
                    assert rewards[index] > 0
            elif not post_altair and has_enough_for_reward(spec, state, index):
                assert rewards[index] > 0
        else:
            assert rewards[index] == 0
            if penalizing and has_enough_for_reward(spec, state, index):
                assert penalties[index] > 0

    if post_altair and not in_leak:
        rewardable = [i for i in participants if has_enough_for_reward(spec, state, i)]
        if rewardable:
            assert any(rewards[i] > 0 for i in rewardable)


def run_deltas(spec, state):
    """Yield pre + every reward component's deltas, each validated by the
    property oracle, then cross-check composition against
    process_rewards_and_penalties (ref rewards.py:66-120)."""
    yield "pre", state

    components = []  # (rewards, penalties) per emitted part

    if is_post_altair(spec):
        flags = [
            ("source_deltas", spec.TIMELY_SOURCE_FLAG_INDEX, "source"),
            ("target_deltas", spec.TIMELY_TARGET_FLAG_INDEX, "target"),
            ("head_deltas", spec.TIMELY_HEAD_FLAG_INDEX, "head"),
        ]
        for name, flag_index, component in flags:
            rewards, penalties = spec.get_flag_index_deltas(state, flag_index)
            _validate_component_deltas(spec, state, component, rewards, penalties)
            components.append((rewards, penalties))
            yield name, Deltas(spec, rewards, penalties)
    else:
        for name, component in [
            ("source_deltas", "source"),
            ("target_deltas", "target"),
            ("head_deltas", "head"),
        ]:
            rewards, penalties = {
                "source": spec.get_source_deltas,
                "target": spec.get_target_deltas,
                "head": spec.get_head_deltas,
            }[component](state)
            _validate_component_deltas(spec, state, component, rewards, penalties)
            components.append((rewards, penalties))
            yield name, Deltas(spec, rewards, penalties)

        rewards, penalties = spec.get_inclusion_delay_deltas(state)
        # inclusion delay only rewards; recipients are source
        # participants (attester share) and block proposers (inclusion
        # share), so no per-index zero check beyond penalties
        assert all(p == 0 for p in penalties)
        components.append((rewards, penalties))
        yield "inclusion_delay_deltas", Deltas(spec, rewards, penalties)

    rewards, penalties = spec.get_inactivity_penalty_deltas(state)
    assert all(r == 0 for r in rewards)
    components.append((rewards, penalties))
    yield "inactivity_penalty_deltas", Deltas(spec, rewards, penalties)

    _cross_check_total(spec, state, components)


def _cross_check_total(spec, state, components):
    """The emitted components must compose (with the spec's saturating
    application order) to exactly what process_rewards_and_penalties
    does to balances."""
    if spec.get_current_epoch(state) == spec.GENESIS_EPOCH:
        return  # process_rewards_and_penalties is a no-op at genesis
    applied = state.copy()
    spec.process_rewards_and_penalties(applied)
    n = len(state.validators)
    totals_r = [0] * n
    totals_p = [0] * n
    for rewards, penalties in components:
        for i in range(n):
            totals_r[i] += int(rewards[i])
            totals_p[i] += int(penalties[i])
    for i in range(n):
        expected = int(state.balances[i]) + totals_r[i]
        expected = max(expected - totals_p[i], 0)
        assert int(applied.balances[i]) == expected, f"validator {i}"


# -- scenario builders (ref rewards.py run_test_* family) --------------------

def run_test_empty(spec, state):
    next_epoch(spec, state)
    next_epoch(spec, state)  # previous epoch exists, zero participation
    yield from run_deltas(spec, state)


def run_test_full_all_correct(spec, state):
    prepare_state_with_attestations(spec, state)
    yield from run_deltas(spec, state)


def run_test_full_but_partial_participation(spec, state, rng=None):
    rng = rng or Random(1010)
    prepare_state_with_attestations(spec, state)
    if is_post_altair(spec):
        for index in range(len(state.validators)):
            if rng.choice([True, False]):
                state.previous_epoch_participation[index] = spec.ParticipationFlags(0)
    else:
        atts = list(state.previous_epoch_attestations)
        state.previous_epoch_attestations = [a for a in atts if rng.choice([True, False])]
    yield from run_deltas(spec, state)


def run_test_partial_participation(spec, state, fraction):
    """Keep ~fraction of each committee attesting."""

    def participation_fn(epoch, slot, index, comm):
        comm = sorted(comm)
        return set(comm[: max(int(len(comm) * fraction), 1)])

    prepare_state_with_attestations(spec, state, participation_fn=participation_fn)
    yield from run_deltas(spec, state)


def degrade_vote_correctness(
    spec, state, rng, wrong_target_prob=0.0, wrong_head_prob=0.0, target_also_spoils_head=False
):
    """Make some previous-epoch votes INCORRECT after the fact.

    Phase0 stores PendingAttestations (no signatures), so vote quality is
    revisable in place: corrupting `target.root` drops the vote from the
    target AND head matching sets (head matching is evaluated within the
    matching-target subset); corrupting only `beacon_block_root` spoils
    just the head vote. Altair encodes correctness as participation
    flags: a wrong target strips TIMELY_TARGET|TIMELY_HEAD, a wrong head
    strips TIMELY_HEAD. Source votes stay correct (an incorrect-source
    attestation would never have been included)."""
    if is_post_altair(spec):
        target_bit = 2 ** int(spec.TIMELY_TARGET_FLAG_INDEX)
        head_bit = 2 ** int(spec.TIMELY_HEAD_FLAG_INDEX)
        for index, flags in enumerate(state.previous_epoch_participation):
            value = int(flags)
            if value & target_bit and rng.random() < wrong_target_prob:
                value &= ~(target_bit | head_bit)
            elif value & head_bit and rng.random() < wrong_head_prob:
                value &= ~head_bit
            state.previous_epoch_participation[index] = spec.ParticipationFlags(value)
    else:
        for pending in state.previous_epoch_attestations:
            if rng.random() < wrong_target_prob:
                pending.data.target.root = b"\x66" * 32
                if target_also_spoils_head:
                    pending.data.beacon_block_root = b"\x67" * 32
            elif rng.random() < wrong_head_prob:
                pending.data.beacon_block_root = b"\x67" * 32


def run_test_correct_source_incorrect_target(spec, state, rng=None):
    """Everyone attested, but half the votes picked the wrong target:
    those validators keep source rewards while paying target+head
    penalties."""
    rng = rng or Random(7700)
    prepare_state_with_attestations(spec, state)
    degrade_vote_correctness(spec, state, rng, wrong_target_prob=0.5)
    yield from run_deltas(spec, state)


def run_test_incorrect_head_only(spec, state, rng=None):
    """Everyone attested with correct source+target but half voted a
    wrong head: head component flips to penalty (phase0) / zero reward
    (altair) for them, other components unaffected."""
    rng = rng or Random(7701)
    prepare_state_with_attestations(spec, state)
    degrade_vote_correctness(spec, state, rng, wrong_head_prob=0.5)
    yield from run_deltas(spec, state)


def run_test_stretched_inclusion_delay(spec, state, rng=None):
    """Every vote correct but included LATE: phase0's inclusion-delay
    component shrinks by 1/delay (altair has no inclusion-delay deltas —
    the mutation is a no-op there and the run degenerates to
    full-correct, kept for the fork matrix's sake)."""
    rng = rng or Random(7702)
    prepare_state_with_attestations(spec, state)
    if not is_post_altair(spec):
        cap = int(spec.SLOTS_PER_EPOCH)
        for pending in state.previous_epoch_attestations:
            pending.inclusion_delay = max(
                int(pending.inclusion_delay), rng.randint(2, cap)
            )
    yield from run_deltas(spec, state)


def run_test_full_incorrect_head(spec, state, rng=None):
    """Every vote has correct source+target but a wrong head."""
    rng = rng or Random(7703)
    prepare_state_with_attestations(spec, state)
    degrade_vote_correctness(spec, state, rng, wrong_head_prob=1.0)
    yield from run_deltas(spec, state)


def run_test_half_incorrect_target_incorrect_head(spec, state, rng=None):
    """Half the votes spoil BOTH the target and head fields (distinct
    input shape from target-only corruption even though the delta effect
    coincides: head matching is scoped to the matching-target set)."""
    rng = rng or Random(7704)
    prepare_state_with_attestations(spec, state)
    degrade_vote_correctness(
        spec, state, rng, wrong_target_prob=0.5, target_also_spoils_head=True
    )
    yield from run_deltas(spec, state)


def run_test_one_attestation_one_correct(spec, state):
    """Every vote made it on chain but only one aggregate kept a correct
    target: its participants alone earn target/head credit."""
    prepare_state_with_attestations(spec, state)
    if is_post_altair(spec):
        source_only = spec.ParticipationFlags(2 ** int(spec.TIMELY_SOURCE_FLAG_INDEX))
        first_slot = spec.compute_start_slot_at_epoch(spec.get_previous_epoch(state))
        keepers = {int(i) for i in spec.get_beacon_committee(state, first_slot, 0)}
        for index in range(len(state.validators)):
            if index not in keepers and int(state.previous_epoch_participation[index]):
                state.previous_epoch_participation[index] = source_only
    else:
        for pending in list(state.previous_epoch_attestations)[1:]:
            pending.data.target.root = b"\x66" * 32
    yield from run_deltas(spec, state)


def _drop_votes_of(spec, state, indices):
    """Erase the given validators' previous-epoch votes in place (clear
    their aggregation bits per committee / zero their flags)."""
    drop = {int(i) for i in indices}
    if is_post_altair(spec):
        for index in drop:
            state.previous_epoch_participation[index] = spec.ParticipationFlags(0)
    else:
        for pending in state.previous_epoch_attestations:
            committee = spec.get_beacon_committee(
                state, pending.data.slot, pending.data.index
            )
            for pos, validator_index in enumerate(committee):
                if int(validator_index) in drop:
                    pending.aggregation_bits[pos] = False


def run_test_some_very_low_effective_balances_that_did_not_attest(spec, state):
    prepare_state_with_attestations(spec, state)
    lows = range(3)
    _drop_votes_of(spec, state, lows)
    for i in lows:
        state.validators[i].effective_balance = spec.EFFECTIVE_BALANCE_INCREMENT
    yield from run_deltas(spec, state)


def run_test_all_balances_too_low_for_reward(spec, state):
    """Every effective balance rounds to a zero base reward (the
    registry floor in get_total_active_balance keeps the denominator at
    one full increment, so 10 gwei of stake earns nothing)."""
    prepare_state_with_attestations(spec, state)
    for v in state.validators:
        v.effective_balance = 10
    yield from run_deltas(spec, state)


def run_test_full_delay_one_slot(spec, state):
    """All votes correct, all included one slot late (phase0
    inclusion-delay component halves; altair has no delay deltas)."""
    prepare_state_with_attestations(spec, state)
    if not is_post_altair(spec):
        for pending in state.previous_epoch_attestations:
            pending.inclusion_delay = int(pending.inclusion_delay) + 1
    yield from run_deltas(spec, state)


def run_test_full_delay_max_slots(spec, state):
    prepare_state_with_attestations(spec, state)
    if not is_post_altair(spec):
        for pending in state.previous_epoch_attestations:
            pending.inclusion_delay = int(spec.SLOTS_PER_EPOCH)
    yield from run_deltas(spec, state)


def run_test_proposer_not_in_attestations(spec, state):
    """The proposer who included the first aggregate did not itself
    attest: it keeps its inclusion micro-rewards while paying the
    non-participation penalties (phase0-specific shape)."""
    prepare_state_with_attestations(spec, state)
    if not is_post_altair(spec):
        proposer = int(state.previous_epoch_attestations[0].proposer_index)
        _drop_votes_of(spec, state, [proposer])
    yield from run_deltas(spec, state)


def run_test_duplicate_attestations_at_later_slots(spec, state):
    """Each aggregate also appears a second time with a larger inclusion
    delay; the delay component must credit the EARLIEST inclusion only
    (phase0-specific shape)."""
    prepare_state_with_attestations(spec, state)
    if not is_post_altair(spec):
        late = []
        for pending in state.previous_epoch_attestations:
            dup = pending.copy()
            dup.inclusion_delay = int(dup.inclusion_delay) + 2
            late.append(dup)
        for dup in late:
            state.previous_epoch_attestations.append(dup)
    yield from run_deltas(spec, state)


def run_test_with_not_yet_activated_validators(spec, state, rng=None):
    rng = rng or Random(5555)
    set_some_activations_far_future(spec, state, rng)
    prepare_state_with_attestations(spec, state)
    yield from run_deltas(spec, state)


def run_test_with_exited_validators(spec, state, rng=None):
    # exits must precede attestation prep: a retroactive exit would
    # change the historical committee shuffle the aggregation bits
    # were built against
    rng = rng or Random(1337)
    exit_random_validators(spec, state, rng)
    prepare_state_with_attestations(spec, state)
    yield from run_deltas(spec, state)


def run_test_with_slashed_validators(spec, state, rng=None):
    rng = rng or Random(3322)
    prepare_state_with_attestations(spec, state)
    slash_random_validators_clean(spec, state, rng)
    yield from run_deltas(spec, state)


def run_test_some_very_low_effective_balances_that_attested(spec, state):
    prepare_state_with_attestations(spec, state)
    for i in range(3):
        state.validators[i].effective_balance = spec.EFFECTIVE_BALANCE_INCREMENT
    yield from run_deltas(spec, state)


def transition_to_leaking(spec, state, extra_epochs=0):
    """Advance past MIN_EPOCHS_TO_INACTIVITY_PENALTY without finality so
    is_in_inactivity_leak flips on; extra_epochs deepens the leak (the
    inactivity-score / finality-delay term grows with its duration)."""
    target = spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY + 2 + extra_epochs
    for _ in range(int(target) + 1):
        next_epoch(spec, state)
    assert spec.is_in_inactivity_leak(state)


def _seed_inactivity_scores(spec, state, rng):
    if is_post_altair(spec):
        state.inactivity_scores = [
            spec.uint64(rng.randrange(0, 2 * int(spec.config.INACTIVITY_SCORE_BIAS) + 5))
            for _ in range(len(state.validators))
        ]


def run_test_full_leak(spec, state):
    transition_to_leaking(spec, state)
    _seed_inactivity_scores(spec, state, Random(77))
    prepare_state_with_attestations(spec, state)
    yield from run_deltas(spec, state)


def run_test_empty_leak(spec, state):
    transition_to_leaking(spec, state)
    _seed_inactivity_scores(spec, state, Random(78))
    next_epoch(spec, state)
    yield from run_deltas(spec, state)


def run_with_leak(spec, state, scenario_fn, extra_epochs=0, seed=79, **kw):
    """Compose any scenario builder with a leaking pre-state: enter the
    leak first (epoch advancement precedes the scenario's own registry
    mutations and attestation prep, preserving each builder's ordering
    contract), seed inactivity scores, then delegate."""
    transition_to_leaking(spec, state, extra_epochs=extra_epochs)
    _seed_inactivity_scores(spec, state, Random(seed))
    yield from scenario_fn(spec, state, **kw)


def run_test_random_leak(spec, state, rng=None):
    rng = rng or Random(9009)
    transition_to_leaking(spec, state)
    _seed_inactivity_scores(spec, state, rng)
    prepare_state_with_attestations(spec, state)
    if is_post_altair(spec):
        for index in range(len(state.validators)):
            if rng.random() < 0.4:
                state.previous_epoch_participation[index] = spec.ParticipationFlags(0)
    else:
        atts = list(state.previous_epoch_attestations)
        state.previous_epoch_attestations = [a for a in atts if rng.random() < 0.6]
    yield from run_deltas(spec, state)


# -- registry mutators (shared with the random suites) -----------------------

def set_some_activations_far_future(spec, state, rng, fraction=0.25):
    current_epoch = spec.get_current_epoch(state)
    for index in range(len(state.validators)):
        if rng.random() < fraction and index > 0:
            v = state.validators[index]
            v.activation_eligibility_epoch = spec.FAR_FUTURE_EPOCH
            v.activation_epoch = spec.FAR_FUTURE_EPOCH
            assert not spec.is_active_validator(v, current_epoch)


def exit_random_validators(spec, state, rng, fraction=0.25):
    current_epoch = spec.get_current_epoch(state)
    for index in range(len(state.validators)):
        if rng.random() < fraction:
            v = state.validators[index]
            v.exit_epoch = rng.choice(
                [max(current_epoch - 1, 0), current_epoch, current_epoch + 1]
            )
            v.withdrawable_epoch = v.exit_epoch + spec.config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY


def slash_random_validators_clean(spec, state, rng, fraction=0.25):
    """Mark slashed without the full slash_validator side effects — the
    deltas only read the flags (ref random.py slash_random_validators)."""
    current_epoch = spec.get_current_epoch(state)
    for index in range(len(state.validators)):
        if rng.random() < fraction:
            v = state.validators[index]
            v.slashed = True
            v.withdrawable_epoch = max(
                v.withdrawable_epoch, current_epoch + spec.EPOCHS_PER_SLASHINGS_VECTOR
            )
