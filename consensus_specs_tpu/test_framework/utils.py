"""pytest ↔ generator dual-mode adapter (ref: test/utils/utils.py)."""
from __future__ import annotations

from consensus_specs_tpu.ssz.types import SSZType

from .meta import copy_meta


def vector_test():
    """Wrap a yielding test so that:
    - generator mode returns [(name, kind, value), ...] parts with kinds
      inferred (SSZ view → "ssz", bytes → "ssz", else "data"; explicit
      3-tuples pass through) — ref utils.py:29-55;
    - pytest mode drains and discards the generator — ref utils.py:63-69.
    """

    def runner(fn):
        def entry(*args, **kw):
            # Parts must be captured AT YIELD TIME: helpers yield the live
            # state ("pre") and then mutate it in place, so a raw reference
            # written after the case finishes would reflect the post state.
            # SSZ values are frozen by serializing on yield and None-valued
            # parts are dropped, both exactly as the reference adapter does
            # (utils.py:29-55).
            def snapshot(kind, value):
                if kind == "ssz" and isinstance(value, SSZType):
                    return value.encode_bytes()
                if kind == "data" and isinstance(value, SSZType):
                    return value.copy()
                if isinstance(value, bytearray):
                    return bytes(value)
                return value

            def generator_mode():
                out = fn(*args, **kw)
                if out is None:
                    return
                for part in out:
                    if len(part) == 2:
                        (key, value) = part
                        if value is None:
                            continue
                        if isinstance(value, (SSZType, bytes, bytearray)):
                            yield key, "ssz", snapshot("ssz", value)
                        elif (
                            isinstance(value, (list, tuple))
                            and value
                            and all(isinstance(v, SSZType) for v in value)
                        ):
                            # an SSZ *list part* (e.g. "blocks") expands to
                            # the reference vector shape: a {key}_count meta
                            # entry plus one {key}_<i>.ssz_snappy per element
                            # (ref utils.py list handling; formats/sanity)
                            yield f"{key}_count", "meta", len(value)
                            for i, item in enumerate(value):
                                yield f"{key}_{i}", "ssz", snapshot("ssz", item)
                        else:
                            yield key, "data", snapshot("data", value)
                    else:
                        (key, kind, value) = part
                        if value is None and kind != "meta":
                            continue
                        yield key, kind, snapshot(kind, value)

            if kw.pop("generator_mode", False):
                return list(generator_mode())
            # pytest mode: drain; designed skips become pytest skips
            from consensus_specs_tpu.exceptions import SkippedTest

            try:
                out = fn(*args, **kw)
                if out is not None:
                    for _ in out:
                        continue
            except SkippedTest as e:
                import pytest

                pytest.skip(str(e))
            return None

        return copy_meta(entry, fn)

    return runner


def with_meta_tags(tags: dict):
    """Append meta key/values to the test's output parts (ref utils.py:76)."""

    def runner(fn):
        def entry(*args, **kw):
            yielded_any = False
            out = fn(*args, **kw)
            if out is not None:
                for part in out:
                    yielded_any = True
                    yield part
            if yielded_any:
                for k, v in tags.items():
                    yield k, "meta", v

        return copy_meta(entry, fn)

    return runner
