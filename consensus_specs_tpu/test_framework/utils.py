"""pytest ↔ generator dual-mode adapter (ref: test/utils/utils.py)."""
from __future__ import annotations

from consensus_specs_tpu.ssz.types import SSZType

from .meta import copy_meta


def vector_test():
    """Wrap a yielding test so that:
    - generator mode returns [(name, kind, value), ...] parts with kinds
      inferred (SSZ view → "ssz", bytes → "ssz", else "data"; explicit
      3-tuples pass through) — ref utils.py:29-55;
    - pytest mode drains and discards the generator — ref utils.py:63-69.
    """

    def runner(fn):
        def entry(*args, **kw):
            def generator_mode():
                out = fn(*args, **kw)
                if out is None:
                    return
                for part in out:
                    if len(part) == 2:
                        (key, value) = part
                        if isinstance(value, (SSZType, bytes, bytearray)):
                            yield key, "ssz", value
                        else:
                            yield key, "data", value
                    else:
                        yield part

            if kw.pop("generator_mode", False):
                return list(generator_mode())
            # pytest mode: drain; designed skips become pytest skips
            from consensus_specs_tpu.exceptions import SkippedTest

            try:
                out = fn(*args, **kw)
                if out is not None:
                    for _ in out:
                        continue
            except SkippedTest as e:
                import pytest

                pytest.skip(str(e))
            return None

        return copy_meta(entry, fn)

    return runner


def with_meta_tags(tags: dict):
    """Append meta key/values to the test's output parts (ref utils.py:76)."""

    def runner(fn):
        def entry(*args, **kw):
            yielded_any = False
            out = fn(*args, **kw)
            if out is not None:
                for part in out:
                    yielded_any = True
                    yield part
            if yielded_any:
                for k, v in tags.items():
                    yield k, "meta", v

        return copy_meta(entry, fn)

    return runner
