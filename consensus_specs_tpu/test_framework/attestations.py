"""Attestation builders + processing runners (ref: test/helpers/
attestations.py)."""
from __future__ import annotations

from .block import build_empty_block_for_next_slot
from .block_processing import state_transition_and_sign_block
from .constants import is_post_altair
from .context import expect_assertion_error
from .keys import aggregate_sign, privkeys
from .state import next_slot


def run_attestation_processing(spec, state, attestation, valid=True):
    """Yield pre/operation/post vector parts around process_attestation
    (ref attestations.py:13-50)."""
    yield "pre", state
    yield "attestation", attestation

    if not valid:
        expect_assertion_error(lambda: spec.process_attestation(state, attestation))
        yield "post", None
        return

    if not is_post_altair(spec):
        current_epoch_count = len(state.current_epoch_attestations)
        previous_epoch_count = len(state.previous_epoch_attestations)

    spec.process_attestation(state, attestation)

    if not is_post_altair(spec):
        if attestation.data.target.epoch == spec.get_current_epoch(state):
            assert len(state.current_epoch_attestations) == current_epoch_count + 1
        else:
            assert len(state.previous_epoch_attestations) == previous_epoch_count + 1

    yield "post", state


def build_attestation_data(spec, state, slot, index, beacon_block_root=None):
    assert state.slot >= slot

    if beacon_block_root is not None:
        block_root = beacon_block_root
    elif slot == state.slot:
        block_root = build_empty_block_for_next_slot(spec, state).parent_root
    else:
        block_root = spec.get_block_root_at_slot(state, slot)

    current_epoch_start_slot = spec.compute_start_slot_at_epoch(spec.get_current_epoch(state))
    if slot < current_epoch_start_slot:
        epoch_boundary_root = spec.get_block_root(state, spec.get_previous_epoch(state))
    elif slot == current_epoch_start_slot:
        epoch_boundary_root = block_root
    else:
        epoch_boundary_root = spec.get_block_root(state, spec.get_current_epoch(state))

    if slot < current_epoch_start_slot:
        source_epoch = state.previous_justified_checkpoint.epoch
        source_root = state.previous_justified_checkpoint.root
    else:
        source_epoch = state.current_justified_checkpoint.epoch
        source_root = state.current_justified_checkpoint.root

    return spec.AttestationData(
        slot=slot,
        index=index,
        beacon_block_root=block_root,
        source=spec.Checkpoint(epoch=source_epoch, root=source_root),
        target=spec.Checkpoint(epoch=spec.compute_epoch_at_slot(slot), root=epoch_boundary_root),
    )


def get_attestation_signing_root(spec, state, attestation_data):
    domain = spec.get_domain(state, spec.DOMAIN_BEACON_ATTESTER, attestation_data.target.epoch)
    return spec.compute_signing_root(attestation_data, domain)


def get_attestation_signature(spec, state, attestation_data, privkey):
    return spec.bls.Sign(privkey, get_attestation_signing_root(spec, state, attestation_data))


def sign_aggregate_attestation(spec, state, attestation_data, participants):
    # one Sign under the summed key — bit-identical to aggregating
    # per-participant signatures (see keys.aggregate_sign)
    signing_root = get_attestation_signing_root(spec, state, attestation_data)
    return aggregate_sign([privkeys[i] for i in participants], signing_root)


def sign_indexed_attestation(spec, state, indexed_attestation):
    participants = indexed_attestation.attesting_indices
    data = indexed_attestation.data
    indexed_attestation.signature = sign_aggregate_attestation(spec, state, data, participants)


def sign_attestation(spec, state, attestation):
    participants = spec.get_attesting_indices(state, attestation.data, attestation.aggregation_bits)
    attestation.signature = sign_aggregate_attestation(spec, state, attestation.data, participants)


def fill_aggregate_attestation(spec, state, attestation, signed=False, filter_participant_set=None):
    """Set all (or a filtered subset of) committee bits; optionally sign
    (ref attestations.py:130-160)."""
    beacon_committee = spec.get_beacon_committee(state, attestation.data.slot, attestation.data.index)
    participants = set(beacon_committee)
    if filter_participant_set is not None:
        participants = filter_participant_set(participants)
    for i in range(len(beacon_committee)):
        attestation.aggregation_bits[i] = beacon_committee[i] in participants
    if signed and len(participants) > 0:
        sign_attestation(spec, state, attestation)


def get_valid_attestation(spec, state, slot=None, index=None, filter_participant_set=None, signed=False):
    """A valid (optionally signed) attestation for (slot, index); committee
    bits all set unless filtered (ref attestations.py:88-128)."""
    if slot is None:
        slot = state.slot
    if index is None:
        index = 0

    attestation_data = build_attestation_data(spec, state, slot=slot, index=index)
    beacon_committee = spec.get_beacon_committee(state, attestation_data.slot, attestation_data.index)

    committee_size = len(beacon_committee)
    aggregation_bits = spec.Bitlist[spec.MAX_VALIDATORS_PER_COMMITTEE]([0] * committee_size)
    attestation = spec.Attestation(aggregation_bits=aggregation_bits, data=attestation_data)
    fill_aggregate_attestation(
        spec, state, attestation, signed=signed, filter_participant_set=filter_participant_set
    )
    return attestation


def get_valid_attestation_at_slot(state, spec, slot_to_attest, participation_fn=None):
    """One attestation per committee at the slot (generator over committee
    indices, ref attestations.py:190-230)."""
    committees_per_slot = spec.get_committee_count_per_slot(
        state, spec.compute_epoch_at_slot(slot_to_attest)
    )
    for index in range(committees_per_slot):
        def participants_filter(comm):
            if participation_fn is None:
                return comm
            return participation_fn(spec.compute_epoch_at_slot(slot_to_attest), slot_to_attest, index, comm)

        yield get_valid_attestation(
            spec,
            state,
            slot_to_attest,
            index=spec.CommitteeIndex(index),
            signed=True,
            filter_participant_set=participants_filter,
        )


def state_transition_with_full_block(spec, state, fill_cur_epoch, fill_prev_epoch,
                                     participation_fn=None, sync_aggregate=None):
    """Build + apply a block carrying a full slot's attestations
    (ref attestations.py:232-280)."""
    block = build_empty_block_for_next_slot(spec, state)
    if fill_cur_epoch and state.slot >= spec.MIN_ATTESTATION_INCLUSION_DELAY:
        slot_to_attest = state.slot - spec.MIN_ATTESTATION_INCLUSION_DELAY + 1
        if slot_to_attest >= spec.compute_start_slot_at_epoch(spec.get_current_epoch(state)):
            for attestation in get_valid_attestation_at_slot(state, spec, slot_to_attest, participation_fn):
                block.body.attestations.append(attestation)
    if fill_prev_epoch and state.slot >= spec.SLOTS_PER_EPOCH:
        slot_to_attest = state.slot - spec.SLOTS_PER_EPOCH + 1
        for attestation in get_valid_attestation_at_slot(state, spec, slot_to_attest, participation_fn):
            block.body.attestations.append(attestation)
    if sync_aggregate is not None:
        block.body.sync_aggregate = sync_aggregate
    return state_transition_and_sign_block(spec, state, block)


def state_transition_with_epoch_sweep_block(spec, state, fill_cur_epoch, fill_prev_epoch):
    """Build + apply a block sweeping attestations over the attestable
    slots of the current epoch so far (and the still-includable tail of
    the previous epoch) — the many-slot analog of
    state_transition_with_full_block, used to justify an epoch with a
    single late block. The epoch's start slot itself is left out of the
    current-epoch sweep (ref attestations.py:280-313 behavior)."""
    block = build_empty_block_for_next_slot(spec, state)
    epoch_start = spec.compute_start_slot_at_epoch(spec.get_current_epoch(state))
    if fill_cur_epoch:
        # epoch_start+1 .. the newest slot the block's inclusion delay
        # still admits
        target = int(block.slot) - int(spec.MIN_ATTESTATION_INCLUSION_DELAY)
        while target > epoch_start:
            for attestation in get_valid_attestation_at_slot(state, spec, target):
                block.body.attestations.append(attestation)
            target -= 1
    if fill_prev_epoch:
        # the previous epoch's tail still inside the inclusion window
        target = epoch_start - 1
        floor = max(int(block.slot) - int(spec.SLOTS_PER_EPOCH), 0)
        while int(target) >= floor:
            for attestation in get_valid_attestation_at_slot(state, spec, target):
                block.body.attestations.append(attestation)
            target -= 1
    return state_transition_and_sign_block(spec, state, block)


def next_slots_with_attestations(spec, state, slot_count, fill_cur_epoch, fill_prev_epoch,
                                 participation_fn=None):
    post_state = state.copy()
    signed_blocks = []
    for _ in range(slot_count):
        signed_block = state_transition_with_full_block(
            spec, post_state, fill_cur_epoch, fill_prev_epoch, participation_fn
        )
        signed_blocks.append(signed_block)
    return state, signed_blocks, post_state


def next_epoch_with_attestations(spec, state, fill_cur_epoch, fill_prev_epoch, participation_fn=None):
    assert state.slot % spec.SLOTS_PER_EPOCH == 0
    return next_slots_with_attestations(
        spec, state, spec.SLOTS_PER_EPOCH, fill_cur_epoch, fill_prev_epoch, participation_fn
    )


def prepare_state_with_attestations(spec, state, participation_fn=None):
    """Advance until previous-epoch attestations cover a full epoch; mutates
    ``state`` in place (ref attestations.py:359-374)."""
    # Go to start of next epoch to ensure attestations in current epoch count
    start_slot = state.slot
    start_epoch = spec.get_current_epoch(state)
    next_epoch_start_slot = spec.compute_start_slot_at_epoch(start_epoch + 1)
    attestations = []
    for _ in range(next_epoch_start_slot + spec.MIN_ATTESTATION_INCLUSION_DELAY - start_slot):
        if state.slot < next_epoch_start_slot:
            for index in range(spec.get_committee_count_per_slot(state, spec.get_current_epoch(state))):
                def temp_participants_filter(comm):
                    if participation_fn is None:
                        return comm
                    return participation_fn(spec.get_current_epoch(state), state.slot, index, comm)

                attestation = get_valid_attestation(
                    spec, state, index=index, signed=True, filter_participant_set=temp_participants_filter
                )
                if any(attestation.aggregation_bits):
                    attestations.append(attestation)
        next_slot(spec, state)

        # Add to state when inclusion delay has passed
        for attestation in list(attestations):
            if state.slot >= attestation.data.slot + spec.MIN_ATTESTATION_INCLUSION_DELAY:
                spec.process_attestation(state, attestation)
                attestations.remove(attestation)

    if hasattr(state, "previous_epoch_attestations"):
        # phase0: every slot of the (now previous) epoch must be attested
        attested_slots = {int(a.data.slot) for a in state.previous_epoch_attestations}
        expected = {
            int(spec.compute_start_slot_at_epoch(start_epoch) + i)
            for i in range(spec.SLOTS_PER_EPOCH)
        }
        assert attested_slots == expected, (sorted(attested_slots), sorted(expected))
    else:
        # altair+: participation flags landed for the previous epoch
        assert any(int(f) != 0 for f in state.previous_epoch_participation)
    return state
