"""Fork-choice test helpers: every on_tick/on_block/on_attestation is also
recorded as a replayable step for the fork_choice vector format
(ref: test/helpers/fork_choice.py and tests/formats/fork_choice/README.md).
"""
from __future__ import annotations

from .context import expect_assertion_error


def get_anchor_root(spec, state):
    anchor_block_header = state.latest_block_header.copy()
    if anchor_block_header.state_root == spec.Bytes32():
        anchor_block_header.state_root = spec.hash_tree_root(state)
    return spec.hash_tree_root(anchor_block_header)


def get_genesis_forkchoice_store(spec, genesis_state):
    store, _ = get_genesis_forkchoice_store_and_block(spec, genesis_state)
    return store


def get_genesis_forkchoice_store_and_block(spec, genesis_state):
    assert genesis_state.slot == spec.GENESIS_SLOT
    genesis_block = spec.BeaconBlock(state_root=spec.hash_tree_root(genesis_state))
    return spec.get_forkchoice_store(genesis_state, genesis_block), genesis_block


def tick_and_add_block(spec, store, signed_block, test_steps, valid=True,
                       merge_block=False, block_not_found=False, is_optimistic=False):
    pre_state = store.block_states[signed_block.message.parent_root]
    block_time = pre_state.genesis_time + signed_block.message.slot * spec.config.SECONDS_PER_SLOT
    if merge_block:
        assert spec.is_merge_transition_block(pre_state, signed_block.message.body)

    if store.time < block_time:
        on_tick_and_append_step(spec, store, block_time, test_steps)

    post_state = yield from add_block(
        spec, store, signed_block, test_steps, valid=valid, block_not_found=block_not_found
    )
    return post_state


def on_tick_and_append_step(spec, store, time, test_steps):
    spec.on_tick(store, time)
    test_steps.append({"tick": int(time)})


def run_on_block(spec, store, signed_block, valid=True):
    if not valid:
        expect_assertion_error(lambda: spec.on_block(store, signed_block))
        return
    spec.on_block(store, signed_block)
    assert store.blocks[spec.hash_tree_root(signed_block.message)] == signed_block.message


def add_block(spec, store, signed_block, test_steps, valid=True, block_not_found=False):
    """Run on_block and related state_transition; record the block as a step."""
    yield get_block_file_name(signed_block), signed_block

    if not valid:
        try:
            run_on_block(spec, store, signed_block, valid=True)
        except (AssertionError, KeyError, IndexError, ValueError):
            test_steps.append({
                "block": get_block_file_name(signed_block),
                "valid": False,
            })
            return None
        else:
            raise AssertionError("block with invalid signature was not rejected")

    run_on_block(spec, store, signed_block, valid=True)
    test_steps.append({"block": get_block_file_name(signed_block)})

    # An on_block step implies receiving block's attestations
    for attestation in signed_block.message.body.attestations:
        spec.on_attestation(store, attestation, is_from_block=True)
    # ...and attester slashings
    for attester_slashing in signed_block.message.body.attester_slashings:
        spec.on_attester_slashing(store, attester_slashing)

    block_root = spec.hash_tree_root(signed_block.message)
    assert store.blocks[block_root] == signed_block.message
    assert store.block_states[block_root].hash_tree_root() == signed_block.message.state_root
    test_steps.append({
        "checks": {
            "time": int(store.time),
            "head": get_formatted_head_output(spec, store),
            "justified_checkpoint": checkpoint_dict(store.justified_checkpoint),
            "finalized_checkpoint": checkpoint_dict(store.finalized_checkpoint),
            "best_justified_checkpoint": checkpoint_dict(store.best_justified_checkpoint),
            "proposer_boost_root": "0x" + bytes(store.proposer_boost_root).hex(),
        }
    })

    return store.block_states[block_root]


def add_attestation(spec, store, attestation, test_steps, is_from_block=False):
    spec.on_attestation(store, attestation, is_from_block=is_from_block)
    yield get_attestation_file_name(attestation), attestation
    test_steps.append({"attestation": get_attestation_file_name(attestation)})


def add_attestations(spec, store, attestations, test_steps, is_from_block=False):
    for attestation in attestations:
        yield from add_attestation(spec, store, attestation, test_steps, is_from_block=is_from_block)


def add_attester_slashing(spec, store, attester_slashing, test_steps, valid=True):
    slashing_file_name = get_attester_slashing_file_name(attester_slashing)
    yield slashing_file_name, attester_slashing

    if not valid:
        expect_assertion_error(lambda: spec.on_attester_slashing(store, attester_slashing))
        test_steps.append({"attester_slashing": slashing_file_name, "valid": False})
        return

    spec.on_attester_slashing(store, attester_slashing)
    test_steps.append({"attester_slashing": slashing_file_name})


def add_pow_block(spec, pow_block, test_steps):
    """Publish a PowBlock into the replay stream (bellatrix+): clients
    register it so later `get_pow_block` lookups during on_block's
    merge-transition validation can resolve it."""
    file_name = get_pow_block_file_name(pow_block)
    yield file_name, pow_block
    test_steps.append({"pow_block": file_name})


def get_pow_block_file_name(pow_block):
    return f"pow_block_{bytes(pow_block.block_hash).hex()[:16]}"


def get_block_file_name(signed_block):
    return f"block_{bytes(signed_block.message.hash_tree_root()).hex()[:16]}"


def get_attestation_file_name(attestation):
    return f"attestation_{bytes(attestation.hash_tree_root()).hex()[:16]}"


def get_attester_slashing_file_name(attester_slashing):
    return f"attester_slashing_{bytes(attester_slashing.hash_tree_root()).hex()[:16]}"


def get_formatted_head_output(spec, store):
    head = spec.get_head(store)
    slot = store.blocks[head].slot
    return {"slot": int(slot), "root": "0x" + bytes(head).hex()}


def checkpoint_dict(checkpoint):
    return {"epoch": int(checkpoint.epoch), "root": "0x" + bytes(checkpoint.root).hex()}


def apply_next_epoch_with_attestations(spec, state, store, fill_cur_epoch, fill_prev_epoch,
                                       participation_fn=None, test_steps=None):
    from .attestations import next_epoch_with_attestations

    if test_steps is None:
        test_steps = []

    _, new_signed_blocks, post_state = next_epoch_with_attestations(
        spec, state, fill_cur_epoch, fill_prev_epoch, participation_fn
    )
    for signed_block in new_signed_blocks:
        block = signed_block.message
        yield from tick_and_add_block(spec, store, signed_block, test_steps)
        block_root = spec.hash_tree_root(block)
        assert store.blocks[block_root] == block
    last_signed_block = new_signed_blocks[-1]

    assert store.block_states[spec.hash_tree_root(last_signed_block.message)].slot == post_state.slot
    return post_state, store, last_signed_block


def apply_next_slots_with_attestations(spec, state, store, slots, fill_cur_epoch,
                                       fill_prev_epoch, test_steps, participation_fn=None):
    from .attestations import next_slots_with_attestations

    _, new_signed_blocks, post_state = next_slots_with_attestations(
        spec, state, slots, fill_cur_epoch, fill_prev_epoch, participation_fn
    )
    for signed_block in new_signed_blocks:
        block = signed_block.message
        yield from tick_and_add_block(spec, store, signed_block, test_steps)
        block_root = spec.hash_tree_root(block)
        assert store.blocks[block_root] == block

    return post_state, store, new_signed_blocks[-1]
