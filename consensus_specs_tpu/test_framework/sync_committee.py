"""Sync-committee test helpers: signatures, rewards math, runner
(ref: test/helpers/sync_committee.py)."""
from __future__ import annotations

from .block_processing import run_block_processing_to
from .context import expect_assertion_error
from .keys import aggregate_sign, privkeys


def compute_committee_indices(spec, state, committee=None):
    """Validator indices of the sync committee members (with duplicates)."""
    if committee is None:
        committee = state.current_sync_committee
    all_pubkeys = [v.pubkey for v in state.validators]
    return [all_pubkeys.index(pubkey) for pubkey in committee.pubkeys]


def compute_sync_committee_signing_root(spec, state, slot, block_root=None, domain_type=None):
    domain = spec.get_domain(
        state, domain_type or spec.DOMAIN_SYNC_COMMITTEE, spec.compute_epoch_at_slot(slot)
    )
    if block_root is None:
        if slot == state.slot:
            block_root = build_empty_block_root(spec, state)
        else:
            block_root = spec.get_block_root_at_slot(state, slot)
    return spec.compute_signing_root(spec.Root(block_root), domain)


def compute_sync_committee_signature(spec, state, slot, privkey, block_root=None, domain_type=None):
    return spec.bls.Sign(
        privkey, compute_sync_committee_signing_root(spec, state, slot, block_root, domain_type)
    )


def build_empty_block_root(spec, state):
    from .block import build_empty_block_for_next_slot

    return build_empty_block_for_next_slot(spec, state).parent_root


def compute_aggregate_sync_committee_signature(spec, state, slot, participants, block_root=None,
                                               domain_type=None):
    if len(participants) == 0:
        return spec.G2_POINT_AT_INFINITY

    # all participants sign the same (block_root, domain) message: one
    # Sign under the summed key is bit-identical to the per-key loop
    # (duplicated committee members contribute their key once per seat)
    signing_root = compute_sync_committee_signing_root(spec, state, slot, block_root, domain_type)
    return aggregate_sign(
        [privkeys[validator_index] for validator_index in participants], signing_root
    )


def compute_sync_committee_inclusion_reward(spec, state):
    total_active_increments = spec.get_total_active_balance(state) // spec.EFFECTIVE_BALANCE_INCREMENT
    total_base_rewards = spec.get_base_reward_per_increment(state) * total_active_increments
    max_participant_rewards = (
        total_base_rewards * spec.SYNC_REWARD_WEIGHT // spec.WEIGHT_DENOMINATOR // spec.SLOTS_PER_EPOCH
    )
    return spec.Gwei(max_participant_rewards // spec.SYNC_COMMITTEE_SIZE)


def compute_sync_committee_participant_reward_and_penalty(spec, state, participant_index,
                                                          committee_indices, committee_bits):
    """(reward, penalty) a member accrues from one sync aggregate, counting
    multiplicity (members can appear several times)."""
    inclusion_reward = compute_sync_committee_inclusion_reward(spec, state)

    included_multiplicities = sum(
        1 for index, bit in zip(committee_indices, committee_bits)
        if index == participant_index and bit
    )
    excluded_multiplicities = sum(
        1 for index, bit in zip(committee_indices, committee_bits)
        if index == participant_index and not bit
    )
    return (
        spec.Gwei(inclusion_reward * included_multiplicities),
        spec.Gwei(inclusion_reward * excluded_multiplicities),
    )


def compute_sync_committee_proposer_reward(spec, state, committee_indices, committee_bits):
    inclusion_reward = compute_sync_committee_inclusion_reward(spec, state)
    participant_number = sum(1 for bit in committee_bits if bit)
    participant_reward = inclusion_reward * spec.PROPOSER_WEIGHT // (
        spec.WEIGHT_DENOMINATOR - spec.PROPOSER_WEIGHT
    )
    return spec.Gwei(participant_reward * participant_number)


def validate_sync_committee_rewards(spec, pre_state, post_state, committee_indices,
                                    committee_bits, proposer_index):
    for index in range(len(post_state.validators)):
        reward, penalty = compute_sync_committee_participant_reward_and_penalty(
            spec, pre_state, index, committee_indices, committee_bits
        )
        if proposer_index == index:
            reward += compute_sync_committee_proposer_reward(
                spec, pre_state, committee_indices, committee_bits
            )
        balance = pre_state.balances[index] + reward
        assert post_state.balances[index] == (0 if balance < penalty else balance - penalty)


def run_sync_committee_processing(spec, state, block, expect_exception=False):
    """Stage block processing up to the sync-aggregate step, then run
    process_sync_aggregate in isolation and yield pre/operation/post
    (ref sync_committee.py:113-146)."""
    pre_state = state.copy()
    # stage everything before process_sync_aggregate (slots, header, ops)
    run_block_processing_to(spec, state, block, "process_sync_aggregate")
    yield "pre", state
    yield "sync_aggregate", block.body.sync_aggregate
    if expect_exception:
        expect_assertion_error(
            lambda: spec.process_sync_aggregate(state, block.body.sync_aggregate)
        )
        yield "post", None
        assert pre_state.balances == state.balances
        return

    spec.process_sync_aggregate(state, block.body.sync_aggregate)
    yield "post", state

    committee_indices = compute_committee_indices(spec, state, state.current_sync_committee)
    committee_bits = block.body.sync_aggregate.sync_committee_bits
    validate_sync_committee_rewards(
        spec, pre_state, state, committee_indices, committee_bits,
        spec.get_beacon_proposer_index(state),
    )
