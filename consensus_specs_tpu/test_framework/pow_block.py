"""PoW-chain stubs for merge-transition tests (ref: test/helpers/pow_block.py).

`patch_pow_chain` swaps the spec module's `get_pow_block` stub
(specs/bellatrix.py:395-398) for a dict-backed chain view — the same
monkeypatch pattern the reference uses. Always a context manager: spec
modules are cached per (fork, preset), so a leaked patch would bleed
into other tests.
"""
from __future__ import annotations

from contextlib import contextmanager


def prepare_pow_block(spec, block_hash, parent_hash=b"\x00" * 32, total_difficulty=0):
    return spec.PowBlock(
        block_hash=block_hash,
        parent_hash=parent_hash,
        total_difficulty=total_difficulty,
    )


def prepare_terminal_pow_chain(spec, parent_hash):
    """A two-block chain whose tip is a valid terminal PoW block for the
    given execution parent_hash."""
    ttd = int(spec.config.TERMINAL_TOTAL_DIFFICULTY)
    grandparent = prepare_pow_block(
        spec, block_hash=b"\x11" * 32, total_difficulty=max(ttd - 1, 0)
    )
    tip = prepare_pow_block(
        spec,
        block_hash=parent_hash,
        parent_hash=grandparent.block_hash,
        total_difficulty=ttd,
    )
    return [grandparent, tip]


@contextmanager
def patch_pow_chain(spec, pow_chain):
    """Temporarily back spec.get_pow_block with the given blocks."""
    by_hash = {bytes(b.block_hash): b for b in pow_chain}
    original = spec.get_pow_block

    def get_pow_block(block_hash):
        return by_hash.get(bytes(block_hash))

    spec.get_pow_block = get_pow_block
    try:
        yield
    finally:
        spec.get_pow_block = original
