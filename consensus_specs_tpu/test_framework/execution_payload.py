"""Execution payload test helpers (ref: test/helpers/execution_payload.py)."""
from __future__ import annotations


def build_empty_execution_payload(spec, state, randao_mix=None):
    """Payload for an empty execution block chained on the latest header."""
    latest = state.latest_execution_payload_header
    timestamp = spec.compute_timestamp_at_slot(state, state.slot)
    empty_txs = spec.List[spec.Transaction, spec.MAX_TRANSACTIONS_PER_PAYLOAD]()

    if randao_mix is None:
        randao_mix = spec.get_randao_mix(state, spec.get_current_epoch(state))

    payload = spec.ExecutionPayload(
        parent_hash=latest.block_hash,
        fee_recipient=spec.ExecutionAddress(),
        state_root=latest.state_root,  # no change to the execution state
        receipts_root=b"no receipts here" + b"\x00" * 16,  # mock receipts
        logs_bloom=spec.ByteVector[spec.BYTES_PER_LOGS_BLOOM](),  # all zeroes
        prev_randao=randao_mix,
        block_number=latest.block_number + 1,
        gas_limit=latest.gas_limit,  # retain same limit
        gas_used=0,  # empty block, 0 gas
        timestamp=timestamp,
        extra_data=spec.ByteList[spec.MAX_EXTRA_DATA_BYTES](),
        base_fee_per_gas=latest.base_fee_per_gas,  # retain same base_fee
        transactions=empty_txs,
    )
    if hasattr(spec, "get_expected_withdrawals"):  # capella+
        # copy each withdrawal: this SSZ library assigns composites by
        # reference, and the payload must be independent of the state's
        # withdrawals_queue (a tampered payload test would otherwise
        # tamper the queue too)
        payload.withdrawals = [wd.copy() for wd in spec.get_expected_withdrawals(state)]
    payload.block_hash = compute_el_block_hash(spec, payload)
    return payload


def compute_el_block_hash(spec, payload):
    """Mock EL block hash (no RLP/keccak in scope — same convention as the
    reference test helpers)."""
    return spec.Hash32(spec.hash(payload.hash_tree_root() + b"FAKE RLP HASH"))


def get_execution_payload_header(spec, execution_payload):
    payload_header = spec.ExecutionPayloadHeader(
        parent_hash=execution_payload.parent_hash,
        fee_recipient=execution_payload.fee_recipient,
        state_root=execution_payload.state_root,
        receipts_root=execution_payload.receipts_root,
        logs_bloom=execution_payload.logs_bloom,
        prev_randao=execution_payload.prev_randao,
        block_number=execution_payload.block_number,
        gas_limit=execution_payload.gas_limit,
        gas_used=execution_payload.gas_used,
        timestamp=execution_payload.timestamp,
        extra_data=execution_payload.extra_data,
        base_fee_per_gas=execution_payload.base_fee_per_gas,
        block_hash=execution_payload.block_hash,
        transactions_root=spec.hash_tree_root(execution_payload.transactions),
    )
    if hasattr(execution_payload, "withdrawals"):  # capella+
        payload_header.withdrawals_root = spec.hash_tree_root(execution_payload.withdrawals)
    return payload_header


def build_state_with_execution_payload_header(spec, state, execution_payload_header):
    pre_state = state.copy()
    pre_state.latest_execution_payload_header = execution_payload_header
    return pre_state


def build_state_with_incomplete_transition(spec, state):
    return build_state_with_execution_payload_header(spec, state, spec.ExecutionPayloadHeader())


def build_state_with_complete_transition(spec, state):
    pre_state_payload = build_empty_execution_payload(spec, state)
    payload_header = get_execution_payload_header(spec, pre_state_payload)
    return build_state_with_execution_payload_header(spec, state, payload_header)


def run_execution_payload_processing(spec, state, execution_payload, valid=True, execution_valid=True):
    """Yield pre/operation/post around process_execution_payload
    (ref helpers/execution_payload.py runner)."""
    from .context import expect_assertion_error

    yield "pre", state
    yield "execution", {"execution_valid": execution_valid}
    yield "execution_payload", execution_payload

    class TestEngine(spec.NoopExecutionEngine):
        def notify_new_payload(self, payload) -> bool:
            return execution_valid

    if not valid:
        expect_assertion_error(
            lambda: spec.process_execution_payload(state, execution_payload, TestEngine())
        )
        yield "post", None
        return

    spec.process_execution_payload(state, execution_payload, TestEngine())
    yield "post", state

    assert state.latest_execution_payload_header.block_hash == execution_payload.block_hash
    assert state.latest_execution_payload_header.transactions_root == spec.hash_tree_root(
        execution_payload.transactions
    )
