"""Inactivity-score state fixtures (altair+), shared by the
epoch-processing, rewards, and randomized-scenario suites
(ref: test/helpers/inactivity_scores.py)."""
from __future__ import annotations

from .constants import is_post_altair


def set_uniform_inactivity_scores(spec, state, value=0):
    """Every validator at the same score (0 = the steady healthy state)."""
    if is_post_altair(spec):
        state.inactivity_scores = [spec.uint64(value)] * len(state.validators)


def randomize_inactivity_scores(spec, state, rng, minimum=0, maximum=None):
    """Scores drawn uniformly from [minimum, maximum]; the default ceiling
    spans a few leak-recovery half-lives around INACTIVITY_SCORE_BIAS so
    both the decrement and penalty branches get exercised."""
    if not is_post_altair(spec):
        return
    if maximum is None:
        maximum = 2 * int(spec.config.INACTIVITY_SCORE_BIAS) + 2
    state.inactivity_scores = [
        spec.uint64(rng.randint(minimum, maximum)) for _ in range(len(state.validators))
    ]


def saturate_inactivity_scores(spec, state, indices=None, value=None):
    """Push (selected) validators deep into leak territory — the shape
    where quadratic penalties dominate."""
    if not is_post_altair(spec):
        return
    if value is None:
        value = 100 * int(spec.config.INACTIVITY_SCORE_BIAS)
    if indices is None:
        indices = range(len(state.validators))
    for index in indices:
        state.inactivity_scores[index] = spec.uint64(value)
