"""Attester slashing builders + runner (ref: test/helpers/
attester_slashings.py)."""
from __future__ import annotations

from .attestations import get_valid_attestation, sign_attestation, sign_indexed_attestation
from .context import expect_assertion_error
from .proposer_slashings import get_min_slashing_penalty_quotient
from .state import get_balance


def get_valid_attester_slashing(spec, state, slot=None, signed_1=False, signed_2=False,
                                filter_participant_set=None):
    attestation_1 = get_valid_attestation(
        spec, state, slot=slot, signed=signed_1, filter_participant_set=filter_participant_set
    )

    attestation_2 = attestation_1.copy()
    attestation_2.data.target.root = b"\x01" * 32
    if signed_2:
        sign_attestation(spec, state, attestation_2)

    return spec.AttesterSlashing(
        attestation_1=spec.get_indexed_attestation(state, attestation_1),
        attestation_2=spec.get_indexed_attestation(state, attestation_2),
    )


def get_valid_attester_slashing_by_indices(spec, state, indices, slot=None,
                                           signed_1=False, signed_2=False):
    """Slashing whose attestations carry exactly ``indices``."""
    slashing = get_valid_attester_slashing(
        spec, state, slot=slot,
        filter_participant_set=lambda comm: comm & set(indices),
    )
    slashing.attestation_1.attesting_indices = sorted(indices)
    slashing.attestation_2.attesting_indices = sorted(indices)
    if signed_1:
        sign_indexed_attestation(spec, state, slashing.attestation_1)
    if signed_2:
        sign_indexed_attestation(spec, state, slashing.attestation_2)
    return slashing


def get_indexed_attestation_participants(spec, indexed_att):
    return list(indexed_att.attesting_indices)


def get_attestation_2_data(spec, att_slashing):
    return att_slashing.attestation_2.data


def run_attester_slashing_processing(spec, state, attester_slashing, valid=True):
    """Yield pre/operation/post around process_attester_slashing
    (ref attester_slashings.py runner)."""
    pre_state = state.copy()

    yield "pre", state
    yield "attester_slashing", attester_slashing

    if not valid:
        expect_assertion_error(lambda: spec.process_attester_slashing(state, attester_slashing))
        yield "post", None
        return

    slashed_indices = set(attester_slashing.attestation_1.attesting_indices).intersection(
        attester_slashing.attestation_2.attesting_indices
    )

    proposer_index = spec.get_beacon_proposer_index(state)
    pre_proposer_balance = get_balance(state, proposer_index)
    pre_slashed_balances = {i: get_balance(state, i) for i in slashed_indices}

    total_proposer_rewards = sum(
        int(state.validators[i].effective_balance) // spec.WHISTLEBLOWER_REWARD_QUOTIENT
        for i in slashed_indices
    )

    spec.process_attester_slashing(state, attester_slashing)

    for slashed_index in slashed_indices:
        slashed_validator = state.validators[slashed_index]
        assert slashed_validator.slashed
        assert slashed_validator.exit_epoch < spec.FAR_FUTURE_EPOCH
        assert slashed_validator.withdrawable_epoch < spec.FAR_FUTURE_EPOCH
        if slashed_index != proposer_index:
            penalty = (
                int(slashed_validator.effective_balance) // get_min_slashing_penalty_quotient(spec)
            )
            assert get_balance(state, slashed_index) == pre_slashed_balances[slashed_index] - penalty

    if proposer_index not in slashed_indices:
        assert get_balance(state, proposer_index) == pre_proposer_balance + total_proposer_rewards

    yield "post", state
