"""Test framework: decorator DSL, state factories, and per-domain helpers
(ref: tests/core/pyspec/eth2spec/test/{context.py,utils/,helpers/}).

Tests written against this DSL run in two modes:
- pytest mode: yields are drained, assertions checked (ref utils.py:63-69);
- generator mode: yielded (name, kind, value) parts become conformance
  test-vector files (ref gen_helpers/, see generators package).
"""
