"""Master generator CLI: run one or all vector runners
(the `make generate_tests` / `make gen_<name>` equivalent, ref Makefile:89,167-197).

Usage:
  python -m consensus_specs_tpu.generators.main -o out/          # all runners
  python -m consensus_specs_tpu.generators.main -o out/ --runners bls shuffling
  ... plus any gen_runner flags (-f force, -l preset filter, -c collect,
  --workers N for data-parallel sharded generation — docs/GENPIPE.md)
"""
from __future__ import annotations

import argparse
import importlib
import sys

RUNNERS = [
    "operations",
    "sanity",
    "finality",
    "epoch_processing",
    "rewards",
    "random",
    "genesis",
    "forks",
    "transition",
    "fork_choice",
    "shuffling",
    "bls",
    "ssz_static",
    "ssz_generic",
    "merkle",
]


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(prog="generate-tests")
    parser.add_argument("--runners", nargs="*", default=None,
                        help=f"runners to generate (default: all of {RUNNERS})")
    ns, rest = parser.parse_known_args(argv)

    names = ns.runners if ns.runners else RUNNERS
    unknown = [n for n in names if n not in RUNNERS]
    if unknown:
        raise SystemExit(f"unknown runner(s) {unknown}; have {RUNNERS}")
    failures = []
    for name in names:
        mod = importlib.import_module(f"consensus_specs_tpu.generators.runners.{name}")
        print(f"\n=== runner: {name} ===")
        try:
            mod.run(args=rest)
        except SystemExit as e:
            if e.code not in (0, None):
                failures.append(name)
    if failures:
        print(f"FAILED runners: {failures}")
        sys.exit(1)


if __name__ == "__main__":
    main()
