"""Generator datatypes (ref: gen_helpers/gen_base/gen_typing.py)."""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Iterable, Tuple

# (name, kind, data) where kind in {"meta", "data", "ssz"}
TestCasePart = Tuple[str, str, Any]


@dataclass
class TestCase:
    """One generated vector case.

    Re-runnability contract: ``case_fn`` MUST be deterministic and free of
    cross-case shared mutable state (seed your RNGs; no module-level
    caches that change results between invocations). Deferred-BLS mode
    (gen_runner --bls-defer) relies on this — a case whose optimistic
    signature answers were wrong is executed a SECOND time under
    ``bls.replaying`` and the replayed parts are committed; a case_fn
    that diverges between runs would silently emit different vectors.
    tests/test_gen_defer.py pins byte-identity across several handler
    families to police this.
    """

    fork_name: str
    preset_name: str
    runner_name: str
    handler_name: str
    suite_name: str
    case_name: str
    case_fn: Callable[[], Iterable[TestCasePart]]

    def dir_path(self) -> str:
        return (
            f"{self.preset_name}/{self.fork_name}/{self.runner_name}/"
            f"{self.handler_name}/{self.suite_name}/{self.case_name}"
        )


@dataclass
class TestProvider:
    # run once before making the cases (e.g. select a BLS backend)
    prepare: Callable[[], None]
    make_cases: Callable[[], Iterable[TestCase]]
