"""`bls` runner: IETF-draft-v4 style sign/verify/aggregate vectors incl.
edge cases (G2 infinity, zero privkey rejections)
(ref: tests/generators/bls/main.py)."""
from consensus_specs_tpu.crypto.bls import ciphersuite

from ..gen_runner import run_generator
from ..gen_typing import TestCase, TestProvider

Z1_PUBKEY = b"\xc0" + b"\x00" * 47
NO_SIGNATURE = b"\x00" * 96
Z2_SIGNATURE = b"\xc0" + b"\x00" * 95
ZERO_PRIVKEY = 0
ZERO_PRIVKEY_BYTES = b"\x00" * 32

PRIVKEYS = [
    0x00000000000000000000000000000000263DBD792F5B1BE47ED85F8938C0F29586AF0D3AC7B977F21C278FE1462040C3,
    0x0000000000000000000000000000000047B8192D77BF871B62E87859D653922725724A5C031AFEABC60BCEF5FF665138,
    0x00000000000000000000000000000000328388AFF0D4A5B7DC9205ABD374E7E98F3CD9F3418EDB4EAFDA5FB16473D216,
]
MESSAGES = [
    bytes(b"\x00" * 32),
    bytes(b"\x56" * 32),
    bytes(b"\xab" * 32),
]


def _hex(b: bytes) -> str:
    return "0x" + bytes(b).hex()


def case_sign():
    for i, privkey in enumerate(PRIVKEYS):
        for j, message in enumerate(MESSAGES):
            sig = ciphersuite.Sign(privkey, message)
            yield f"sign_case_{i}_{j}", {
                "input": {"privkey": _hex(privkey.to_bytes(32, "big")), "message": _hex(message)},
                "output": _hex(sig),
            }
    # Edge case: zero privkey must fail
    yield "sign_case_zero_privkey", {
        "input": {"privkey": _hex(ZERO_PRIVKEY_BYTES), "message": _hex(MESSAGES[0])},
        "output": None,
    }


def case_verify():
    for i, privkey in enumerate(PRIVKEYS):
        for j, message in enumerate(MESSAGES):
            sig = ciphersuite.Sign(privkey, message)
            pubkey = ciphersuite.SkToPk(privkey)
            yield f"verify_valid_case_{i}_{j}", {
                "input": {"pubkey": _hex(pubkey), "message": _hex(message), "signature": _hex(sig)},
                "output": True,
            }
            # tampered
            tampered = bytes(sig[:-4]) + b"\xff\xff\xff\xff"
            yield f"verify_tampered_case_{i}_{j}", {
                "input": {"pubkey": _hex(pubkey), "message": _hex(message), "signature": _hex(tampered)},
                "output": False,
            }
    # Infinity pubkey + infinity signature must NOT verify
    yield "verify_infinity_pubkey_and_infinity_signature", {
        "input": {"pubkey": _hex(Z1_PUBKEY), "message": _hex(MESSAGES[1]), "signature": _hex(Z2_SIGNATURE)},
        "output": False,
    }


def case_aggregate():
    for j, message in enumerate(MESSAGES):
        sigs = [ciphersuite.Sign(privkey, message) for privkey in PRIVKEYS]
        yield f"aggregate_0x{message.hex()}", {
            "input": [_hex(s) for s in sigs],
            "output": _hex(ciphersuite.Aggregate(sigs)),
        }
    # Edge: empty aggregate is invalid
    yield "aggregate_na_signatures", {"input": [], "output": None}
    # Edge: infinity signature aggregates to itself
    yield "aggregate_infinity_signature", {
        "input": [_hex(Z2_SIGNATURE)],
        "output": _hex(Z2_SIGNATURE),
    }


def case_fast_aggregate_verify():
    for i, message in enumerate(MESSAGES):
        privkeys = PRIVKEYS[: i + 1]
        sigs = [ciphersuite.Sign(privkey, message) for privkey in privkeys]
        aggregate_signature = ciphersuite.Aggregate(sigs)
        pubkeys = [ciphersuite.SkToPk(privkey) for privkey in privkeys]
        yield f"fast_aggregate_verify_valid_{i}", {
            "input": {"pubkeys": [_hex(pk) for pk in pubkeys], "message": _hex(message),
                      "signature": _hex(aggregate_signature)},
            "output": True,
        }
        # extra pubkey
        pubkeys_extra = pubkeys + [ciphersuite.SkToPk(PRIVKEYS[-1])]
        yield f"fast_aggregate_verify_extra_pubkey_{i}", {
            "input": {"pubkeys": [_hex(pk) for pk in pubkeys_extra], "message": _hex(message),
                      "signature": _hex(aggregate_signature)},
            "output": False,
        }
    yield "fast_aggregate_verify_na_pubkeys_and_infinity_signature", {
        "input": {"pubkeys": [], "message": _hex(MESSAGES[2]), "signature": _hex(Z2_SIGNATURE)},
        "output": False,
    }
    yield "fast_aggregate_verify_na_pubkeys_and_na_signature", {
        "input": {"pubkeys": [], "message": _hex(MESSAGES[2]), "signature": _hex(NO_SIGNATURE)},
        "output": False,
    }


def case_aggregate_verify():
    pubkeys = []
    messages = []
    sigs = []
    for privkey, message in zip(PRIVKEYS, MESSAGES):
        pubkeys.append(ciphersuite.SkToPk(privkey))
        messages.append(message)
        sigs.append(ciphersuite.Sign(privkey, message))
    aggregate_signature = ciphersuite.Aggregate(sigs)
    yield "aggregate_verify_valid", {
        "input": {"pubkeys": [_hex(pk) for pk in pubkeys], "messages": [_hex(m) for m in messages],
                  "signature": _hex(aggregate_signature)},
        "output": True,
    }
    yield "aggregate_verify_tampered_signature", {
        "input": {"pubkeys": [_hex(pk) for pk in pubkeys], "messages": [_hex(m) for m in messages],
                  "signature": _hex(bytes(aggregate_signature[:4]) + b"\xff\xff\xff\xff" + bytes(aggregate_signature[8:]))},
        "output": False,
    }
    yield "aggregate_verify_na_pubkeys_and_infinity_signature", {
        "input": {"pubkeys": [], "messages": [], "signature": _hex(Z2_SIGNATURE)},
        "output": False,
    }


HANDLERS = {
    "sign": case_sign,
    "verify": case_verify,
    "aggregate": case_aggregate,
    "fast_aggregate_verify": case_fast_aggregate_verify,
    "aggregate_verify": case_aggregate_verify,
}


def _bls_cases():
    for handler, gen in HANDLERS.items():
        for case_name, case_data in gen():
            def case_fn(case_data=case_data):
                yield "data", "data", case_data

            yield TestCase(
                fork_name="phase0",
                preset_name="general",
                runner_name="bls",
                handler_name=handler,
                suite_name="small",
                case_name=case_name,
                case_fn=case_fn,
            )


def run(args=None):
    run_generator("bls", [TestProvider(prepare=lambda: None, make_cases=_bls_cases)], args=args)


if __name__ == "__main__":
    run()
