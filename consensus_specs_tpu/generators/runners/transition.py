"""`transition` runner (ref: tests/generators/transition/main.py)."""
from ..gen_from_tests import run_state_test_generators

# Transition tests declare their own pre-fork via with_phases; register
# them under every pre-fork that has a successor.
all_mods = {
    fork: {
        "core": "tests.spec.test_transition",
        "shapes": "tests.spec.test_transition_shapes",
    }
    for fork in ("phase0", "altair", "bellatrix")
}


def run(args=None):
    run_state_test_generators(runner_name="transition", all_mods=all_mods, args=args)


if __name__ == "__main__":
    run()
