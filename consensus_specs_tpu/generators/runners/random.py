"""`random` runner (ref: tests/generators/random/main.py)."""
from ..gen_from_tests import run_state_test_generators

all_mods = {
    fork: {"random": "tests.spec.test_random"}
    for fork in ("phase0", "altair", "bellatrix", "capella")
}


def run(args=None):
    run_state_test_generators(runner_name="random", all_mods=all_mods, args=args)


if __name__ == "__main__":
    run()
