"""`forks` runner: upgrade_to_* unit vectors. The tests run against the
PRE-fork spec (phase=phase0) but the vectors are filed under the
POST-fork name (ref: tests/generators/forks/main.py)."""
import importlib

from ..gen_from_tests import generate_from_tests
from ..gen_runner import run_generator
from ..gen_typing import TestProvider

# post-fork name -> (pre-fork phase, test module)
FORK_TESTS = {
    "altair": ("phase0", "tests.spec.test_fork_upgrade_altair"),
    "bellatrix": ("altair", "tests.spec.test_fork_upgrade_bellatrix"),
    "capella": ("bellatrix", "tests.spec.test_fork_upgrade_capella"),
}


def _providers():
    for preset in ("minimal", "mainnet"):
        for post_fork, (pre_fork, mod_name) in FORK_TESTS.items():
            def make_cases(post_fork=post_fork, pre_fork=pre_fork, mod_name=mod_name, preset=preset):
                mod = importlib.import_module(mod_name)
                yield from generate_from_tests(
                    runner_name="forks",
                    handler_name="fork",
                    src=mod,
                    fork_name=post_fork,
                    preset_name=preset,
                    phase=pre_fork,
                )

            yield TestProvider(prepare=lambda: None, make_cases=make_cases)


def run(args=None):
    run_generator("forks", list(_providers()), args=args)


if __name__ == "__main__":
    run()
