"""`rewards` runner (ref: tests/generators/rewards/main.py)."""
from ..gen_from_tests import run_state_test_generators

all_mods = {
    fork: {
        "basic": "tests.spec.test_rewards_basic",
        "leak": "tests.spec.test_rewards_leak",
        "random": "tests.spec.test_rewards_random",
    }
    for fork in ("phase0", "altair", "bellatrix", "capella")
}
for _fork in ("altair", "bellatrix", "capella"):  # score-distribution cases
    all_mods[_fork] = dict(
        all_mods[_fork],
        inactivity_scores="tests.spec.test_rewards_inactivity_scores",
    )


def run(args=None):
    run_state_test_generators(runner_name="rewards", all_mods=all_mods, args=args)


if __name__ == "__main__":
    run()
