"""`fork_choice` runner (ref: tests/generators/fork_choice/main.py)."""
from ..gen_from_tests import run_state_test_generators

all_mods = {
    fork: {
        "get_head": "tests.spec.test_fork_choice",
        "ex_ante": "tests.spec.test_fork_choice_ex_ante",
        "on_block": "tests.spec.test_fork_choice_on_block",
    }
    for fork in ("phase0", "altair", "bellatrix", "capella")
}
# merge-transition store scenarios exist from bellatrix on
for _fork in ("bellatrix", "capella"):
    all_mods[_fork] = dict(
        all_mods[_fork], on_merge_block="tests.spec.test_fork_choice_on_merge_block"
    )


def run(args=None):
    run_state_test_generators(runner_name="fork_choice", all_mods=all_mods, args=args)


if __name__ == "__main__":
    run()
