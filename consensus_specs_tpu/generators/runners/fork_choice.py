"""`fork_choice` runner (ref: tests/generators/fork_choice/main.py)."""
from ..gen_from_tests import run_state_test_generators

all_mods = {
    fork: {
        "get_head": "tests.spec.test_fork_choice",
        "ex_ante": "tests.spec.test_fork_choice_ex_ante",
        "on_block": "tests.spec.test_fork_choice_on_block",
    }
    for fork in ("phase0", "altair", "bellatrix", "capella")
}


def run(args=None):
    run_state_test_generators(runner_name="fork_choice", all_mods=all_mods, args=args)


if __name__ == "__main__":
    run()
