"""`sanity` runner: `blocks` + `slots` handlers (ref:
tests/generators/sanity/main.py)."""
from ..gen_from_tests import run_state_test_generators

mods = {
    "blocks": "tests.spec.test_sanity_blocks",
    "slots": "tests.spec.test_sanity_slots",
    "multi_operations": "tests.spec.test_sanity_multi_operations",
}

all_mods = {fork: mods for fork in ("phase0", "altair", "bellatrix", "capella")}


def run(args=None):
    run_state_test_generators(runner_name="sanity", all_mods=all_mods, args=args)


if __name__ == "__main__":
    run()
