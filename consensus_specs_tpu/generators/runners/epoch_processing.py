"""`epoch_processing` runner: one handler per epoch sub-transition, matching
the reference's client-facing layout (ref: tests/generators/epoch_processing/
main.py:6-23 — vectors land under
`<preset>/<fork>/epoch_processing/<sub_transition>/`)."""
from ..gen_from_tests import combine_mods, run_state_test_generators

_EP = "tests.spec.epoch_processing.test_process_"

phase0_mods = {
    key: _EP + key
    for key in [
        "justification_and_finalization",
        "rewards_and_penalties",
        "registry_updates",
        "slashings",
        "eth1_data_reset",
        "effective_balance_updates",
        "slashings_reset",
        "randao_mixes_reset",
        "historical_roots_update",
        "participation_record_updates",
    ]
}

_new_altair_mods = {
    key: _EP + key
    for key in [
        "inactivity_updates",
        "participation_flag_updates",
        "sync_committee_updates",
    ]
}
altair_mods = combine_mods(_new_altair_mods, phase0_mods)

# no new epoch sub-transitions in bellatrix; capella adds the withdrawal sweep
bellatrix_mods = altair_mods
capella_mods = combine_mods({"full_withdrawals": _EP + "full_withdrawals"}, altair_mods)

all_mods = {
    "phase0": phase0_mods,
    "altair": altair_mods,
    "bellatrix": bellatrix_mods,
    "capella": capella_mods,
}


def run(args=None):
    run_state_test_generators(runner_name="epoch_processing", all_mods=all_mods, args=args)


if __name__ == "__main__":
    run()
