"""`epoch_processing` runner (ref: tests/generators/epoch_processing/main.py)."""
from ..gen_from_tests import run_state_test_generators

all_mods = {
    fork: {"epoch_processing": "tests.spec.test_epoch_processing"}
    for fork in ("phase0", "altair", "bellatrix", "capella")
}


def run(args=None):
    run_state_test_generators(runner_name="epoch_processing", all_mods=all_mods, args=args)


if __name__ == "__main__":
    run()
