"""`shuffling` runner: compute_shuffled_index mapping vectors for 30 seeds
x a range of counts (ref: tests/generators/shuffling/main.py)."""
from ..gen_runner import run_generator
from ..gen_typing import TestCase, TestProvider

from consensus_specs_tpu.specs import build_spec


def shuffling_case_fn(spec, seed, count):
    def case_fn():
        yield "mapping", "data", {
            "seed": "0x" + seed.hex(),
            "count": int(count),
            "mapping": [int(spec.compute_shuffled_index(spec.uint64(i), spec.uint64(count), seed))
                        for i in range(count)],
        }

    return case_fn


def shuffling_test_cases(preset_name):
    spec = build_spec("phase0", preset_name)
    for seed in [spec.hash(spec.uint_to_bytes(spec.uint64(seed_init))) for seed_init in range(30)]:
        for count in [0, 1, 2, 3, 5, 10, 33, 100, 1000, 9999]:
            yield TestCase(
                fork_name="phase0",
                preset_name=preset_name,
                runner_name="shuffling",
                handler_name="core",
                suite_name="shuffle",
                case_name=f"shuffle_0x{seed.hex()}_{count}",
                case_fn=shuffling_case_fn(spec, seed, count),
            )


def run(args=None):
    providers = [
        TestProvider(prepare=lambda: None, make_cases=lambda p=p: shuffling_test_cases(p))
        for p in ("minimal", "mainnet")
    ]
    run_generator("shuffling", providers, args=args)


if __name__ == "__main__":
    run()
