"""`finality` runner (ref: tests/generators/finality/main.py)."""
from ..gen_from_tests import run_state_test_generators

all_mods = {
    fork: {"finality": "tests.spec.test_finality"}
    for fork in ("phase0", "altair", "bellatrix", "capella")
}


def run(args=None):
    run_state_test_generators(runner_name="finality", all_mods=all_mods, args=args)


if __name__ == "__main__":
    run()
