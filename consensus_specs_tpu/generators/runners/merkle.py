"""`merkle` runner: single Merkle proof vectors against a BeaconState
(ref: tests/generators/merkle/main.py + tests/formats/merkle/README.md —
state.ssz_snappy + proof.yaml {leaf, leaf_index, branch}, verified with
is_valid_merkle_branch)."""
from __future__ import annotations

from consensus_specs_tpu.specs import build_spec
from consensus_specs_tpu.ssz.proof import compute_merkle_proof
from consensus_specs_tpu.test_framework.context import (
    _prepare_state,
    default_activation_threshold,
    default_balances,
)

from ..gen_runner import run_generator
from ..gen_typing import TestCase, TestProvider


def _proof_cases(fork: str, preset: str):
    spec = build_spec(fork, preset)
    state = _prepare_state(default_balances, default_activation_threshold, spec)

    # name -> (gindex, leaf root of the addressed subtree)
    targets = {
        "finalized_root": (
            int(spec.FINALIZED_ROOT_INDEX),
            spec.hash_tree_root(state.finalized_checkpoint.root),
        ),
        "next_sync_committee": (
            int(spec.NEXT_SYNC_COMMITTEE_INDEX),
            spec.hash_tree_root(state.next_sync_committee),
        ),
        "current_sync_committee": (
            int(spec.get_generalized_index(spec.BeaconState, "current_sync_committee")),
            spec.hash_tree_root(state.current_sync_committee),
        ),
    }

    for name, (gindex, leaf) in targets.items():
        branch = compute_merkle_proof(state, gindex)
        # self-check before emitting: the branch must verify
        assert spec.is_valid_merkle_branch(
            leaf=leaf,
            branch=branch,
            depth=spec.floorlog2(gindex),
            index=spec.get_subtree_index(gindex),
            root=spec.hash_tree_root(state),
        )

        def case_fn(state=state, gindex=gindex, branch=branch, leaf=leaf):
            yield "state", "ssz", state
            yield "proof", "data", {
                "leaf": "0x" + bytes(leaf).hex(),
                "leaf_index": gindex,
                "branch": ["0x" + bytes(b).hex() for b in branch],
            }

        yield TestCase(
            fork_name=fork,
            preset_name=preset,
            runner_name="merkle",
            handler_name="single_proof",
            suite_name="pyspec_tests",
            case_name=f"single_proof_{name}",
            case_fn=case_fn,
        )


def _cases():
    for preset in ("minimal", "mainnet"):
        yield from _proof_cases("altair", preset)


def run(args=None):
    run_generator(
        "merkle", [TestProvider(prepare=lambda: None, make_cases=_cases)], args=args
    )


if __name__ == "__main__":
    run()
