"""`operations` runner: per-fork block-operation handlers
(ref: tests/generators/operations/main.py)."""
from ..gen_from_tests import combine_mods, run_state_test_generators

_new = "tests.spec.test_operations_"

phase_0_mods = {
    "attestation": _new + "attestation",
    "attester_slashing": _new + "attester_slashing",
    "block_header": _new + "block_header",
    "deposit": _new + "deposit",
    "proposer_slashing": _new + "proposer_slashing",
    "voluntary_exit": _new + "voluntary_exit",
}

_altair_new = {
    "sync_aggregate": "tests.spec.test_altair_sync_aggregate",
}
altair_mods = combine_mods(_altair_new, phase_0_mods)

_bellatrix_new = {
    "execution_payload": _new + "execution_payload",
}
bellatrix_mods = combine_mods(_bellatrix_new, altair_mods)

_capella_new = {
    "withdrawals": _new + "withdrawals",
    "bls_to_execution_change": _new + "bls_to_execution_change",
}
capella_mods = combine_mods(_capella_new, bellatrix_mods)

all_mods = {
    "phase0": phase_0_mods,
    "altair": altair_mods,
    "bellatrix": bellatrix_mods,
    "capella": capella_mods,
}


def run(args=None):
    run_state_test_generators(runner_name="operations", all_mods=all_mods, args=args)


if __name__ == "__main__":
    run()
