"""`ssz_static` runner: randomized container round-trip vectors per
fork x preset x mode (ref: tests/generators/ssz_static/main.py)."""
from random import Random

from consensus_specs_tpu.debug.encode import encode
from consensus_specs_tpu.debug.random_value import RandomizationMode, get_random_ssz_object
from consensus_specs_tpu.specs import available_forks, build_spec
from consensus_specs_tpu.ssz.types import Container

from ..gen_runner import run_generator
from ..gen_typing import TestCase, TestProvider

MAX_BYTES_LENGTH = 1000
MAX_LIST_LENGTH = 10


def create_test_case(rng: Random, typ, mode: RandomizationMode, chaos: bool):
    value = get_random_ssz_object(rng, typ, MAX_BYTES_LENGTH, MAX_LIST_LENGTH, mode, chaos)
    yield "value", "data", encode(value)
    yield "serialized", "ssz", value.encode_bytes()
    yield "roots", "data", {"root": "0x" + bytes(value.hash_tree_root()).hex()}


def get_spec_ssz_types(spec):
    return [
        (name, value) for (name, value) in spec.__dict__.items()
        if isinstance(value, type) and issubclass(value, Container)
        and value is not Container
        and value.__module__ != "consensus_specs_tpu.ssz.types"
        and len(value.fields()) > 0
    ]


def ssz_static_cases(fork_name: str, preset_name: str, seed: int, mode: RandomizationMode,
                     chaos: bool, count: int):
    spec = build_spec(fork_name, preset_name)
    random_mode_name = mode.to_name()
    for (name, ssz_type) in get_spec_ssz_types(spec):
        for i in range(count):
            # deterministic: derive the rng from (seed, type, index) textually
            rng = Random(f"{seed}:{name}:{i}")
            yield TestCase(
                fork_name=fork_name,
                preset_name=preset_name,
                runner_name="ssz_static",
                handler_name=name,
                suite_name=f"ssz_{random_mode_name}{'_chaos' if chaos else ''}",
                case_name=f"case_{i}",
                case_fn=lambda rng=rng, t=ssz_type, m=mode, c=chaos: create_test_case(rng, t, m, c),
            )


def create_provider(fork_name, preset_name, seed, mode, chaos, count):
    return TestProvider(
        prepare=lambda: None,
        make_cases=lambda: ssz_static_cases(fork_name, preset_name, seed, mode, chaos, count),
    )


def run(args=None):
    # reference-scale matrix (ref ssz_static/main.py:74-84): every
    # randomization mode on minimal at count 30, a chaos setting at 30,
    # and a mainnet random slice at 5; non-changing modes (zero/max/
    # nil/one/lengthy-with-fixed-shapes) collapse to a single case
    settings = []
    seed = 1
    for mode in RandomizationMode:
        settings.append((seed, "minimal", mode, False, 30))
        seed += 1
    settings.append((seed, "minimal", RandomizationMode.mode_random, True, 30))
    seed += 1
    settings.append((seed, "mainnet", RandomizationMode.mode_random, False, 5))
    seed += 1

    providers = []
    for fork in available_forks():
        for (seed, preset, mode, chaos, cases_if_random) in settings:
            count = cases_if_random if chaos or mode.is_changing() else 1
            providers.append(create_provider(fork, preset, seed, mode, chaos, count))
    run_generator("ssz_static", providers, args=args)


if __name__ == "__main__":
    run()
