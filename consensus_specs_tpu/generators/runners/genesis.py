"""`genesis` runner (ref: tests/generators/genesis/main.py — two
handlers, matching the reference's initialization/validity split and
docs/formats/genesis)."""
from ..gen_from_tests import run_state_test_generators

all_mods = {
    "phase0": {
        "initialization": "tests.spec.test_genesis",
        "validity": "tests.spec.test_genesis_validity",
    },
    # bellatrix genesis adds the execution-payload-header parameter cases
    "bellatrix": {"initialization": "tests.spec.test_genesis"},
}


def run(args=None):
    run_state_test_generators(runner_name="genesis", all_mods=all_mods, args=args)


if __name__ == "__main__":
    run()
