"""`ssz_generic` runner: hand-built valid + invalid vectors for the SSZ
wire format itself (ref: tests/generators/ssz_generic/main.py and
tests/formats/ssz_generic/README.md — the deserialization robustness
contract). Handlers: uints, boolean, basic_vector, bitvector, bitlist,
containers. Valid cases carry serialized+value+root; invalid cases carry
only the malformed serialization, which clients MUST reject."""
from __future__ import annotations

from random import Random

from consensus_specs_tpu.debug.encode import encode
from consensus_specs_tpu.ssz import (
    Bitlist,
    Bitvector,
    ByteList,
    Container,
    List,
    Vector,
    boolean,
    hash_tree_root,
    serialize,
    uint8,
    uint16,
    uint32,
    uint64,
    uint128,
    uint256,
)

from ..gen_runner import run_generator
from ..gen_typing import TestCase, TestProvider


# -- canonical test containers (names are part of the vector contract) -------

def _container(name, fields):
    return type(name, (Container,), {"__annotations__": fields})


SingleFieldTestStruct = _container("SingleFieldTestStruct", {"A": uint8})
SmallTestStruct = _container("SmallTestStruct", {"A": uint16, "B": uint16})
FixedTestStruct = _container("FixedTestStruct", {"A": uint8, "B": uint64, "C": uint32})
VarTestStruct = _container(
    "VarTestStruct", {"A": uint16, "B": List[uint16, 1024], "C": uint8}
)
ComplexTestStruct = _container(
    "ComplexTestStruct",
    {
        "A": uint16,
        "B": List[uint16, 128],
        "C": uint8,
        "D": ByteList[256],
        "E": VarTestStruct,
        "F": Vector[FixedTestStruct, 4],
        "G": Vector[VarTestStruct, 2],
    },
)
BitsStruct = _container(
    "BitsStruct",
    {
        "A": Bitlist[5],
        "B": Bitvector[2],
        "C": Bitvector[1],
        "D": Bitlist[6],
        "E": Bitvector[8],
    },
)

CONTAINER_TYPES = [
    SingleFieldTestStruct,
    SmallTestStruct,
    FixedTestStruct,
    VarTestStruct,
    ComplexTestStruct,
    BitsStruct,
]

UINT_TYPES = [uint8, uint16, uint32, uint64, uint128, uint256]


def _random_value(rng: Random, typ):
    from consensus_specs_tpu.debug.random_value import RandomizationMode, get_random_ssz_object

    return get_random_ssz_object(
        rng, typ, max_bytes_length=2048, max_list_length=8,
        mode=RandomizationMode.mode_random, chaos=False,
    )


def _valid(obj):
    def case_fn(obj=obj):
        yield "serialized", "ssz", serialize(obj)
        yield "value", "data", encode(obj)
        yield "root", "meta", "0x" + bytes(hash_tree_root(obj)).hex()

    return case_fn


def _invalid(data: bytes):
    def case_fn(data=data):
        yield "serialized", "ssz", data

    return case_fn


# -- case builders ------------------------------------------------------------

def cases_uints():
    rng = Random(2001)
    for typ in UINT_TYPES:
        n = typ.type_byte_length()
        for label, value in [
            ("zero", 0),
            ("max", (1 << (8 * n)) - 1),
            ("random_0", rng.randrange(1 << (8 * n))),
            ("random_1", rng.randrange(1 << (8 * n))),
        ]:
            yield "valid", f"uint_{8 * n}_{label}", _valid(typ(value))
        yield "invalid", f"uint_{8 * n}_one_byte_short", _invalid(b"\x01" * (n - 1))
        yield "invalid", f"uint_{8 * n}_one_byte_long", _invalid(b"\x01" * (n + 1))
        yield "invalid", f"uint_{8 * n}_empty", _invalid(b"")


def cases_boolean():
    yield "valid", "true", _valid(boolean(True))
    yield "valid", "false", _valid(boolean(False))
    yield "invalid", "byte_2", _invalid(b"\x02")
    yield "invalid", "byte_ff", _invalid(b"\xff")
    yield "invalid", "empty", _invalid(b"")
    yield "invalid", "two_bytes", _invalid(b"\x01\x00")


def cases_basic_vector():
    rng = Random(2002)
    for elem, length in [(uint8, 5), (uint16, 8), (uint64, 4), (uint64, 1)]:
        typ = Vector[elem, length]
        obj = _random_value(rng, typ)
        name = f"vec_{elem.__name__}_{length}"
        yield "valid", f"{name}_random", _valid(obj)
        good = serialize(obj)
        yield "invalid", f"{name}_one_byte_short", _invalid(good[:-1])
        yield "invalid", f"{name}_one_byte_long", _invalid(good + b"\x00")
        yield "invalid", f"{name}_empty", _invalid(b"")


def cases_bitvector():
    rng = Random(2003)
    for length in [1, 2, 7, 8, 9, 16, 31, 512]:
        typ = Bitvector[length]
        obj = _random_value(rng, typ)
        yield "valid", f"bitvec_{length}_random", _valid(obj)
        good = serialize(obj)
        yield "invalid", f"bitvec_{length}_extra_byte", _invalid(good + b"\x00")
        if length % 8:
            # a bit set above the declared length in the last byte
            bad = bytearray(good)
            bad[-1] |= 1 << (length % 8)
            yield "invalid", f"bitvec_{length}_padding_bit_set", _invalid(bytes(bad))
        if len(good) > 1:
            yield "invalid", f"bitvec_{length}_short", _invalid(good[:-1])


def cases_bitlist():
    rng = Random(2004)
    for limit in [1, 2, 8, 9, 31, 512]:
        typ = Bitlist[limit]
        obj = _random_value(rng, typ)
        yield "valid", f"bitlist_{limit}_random", _valid(obj)
        yield "valid", f"bitlist_{limit}_empty", _valid(typ())
        # no delimiter bit at all
        yield "invalid", f"bitlist_{limit}_no_delimiter_zero_byte", _invalid(b"\x00")
        yield "invalid", f"bitlist_{limit}_no_delimiter_empty", _invalid(b"")
        # delimiter implies more bits than the limit allows
        full_bytes = bytearray((limit + 8) // 8 + 1)
        full_bytes[-1] = 0x01
        yield "invalid", f"bitlist_{limit}_over_limit", _invalid(bytes(full_bytes))


def cases_containers():
    rng = Random(2005)
    for typ in CONTAINER_TYPES:
        for i in range(2):
            obj = _random_value(rng, typ)
            yield "valid", f"{typ.__name__}_random_{i}", _valid(obj)
        good = serialize(_random_value(rng, typ))
        yield "invalid", f"{typ.__name__}_one_byte_short", _invalid(good[:-1] if good else b"")
        yield "invalid", f"{typ.__name__}_extra_byte", _invalid(good + b"\x00")
    # var-size container offset corruption
    var = VarTestStruct(A=1, B=List[uint16, 1024](1, 2, 3), C=2)
    good = bytearray(serialize(var))
    # fixed part: A(2) + offset(4) + C(1) = 7; corrupt the offset
    bad_low = bytearray(good)
    bad_low[2:6] = (3).to_bytes(4, "little")  # points inside the fixed part
    yield "invalid", "VarTestStruct_offset_into_fixed_part", _invalid(bytes(bad_low))
    bad_high = bytearray(good)
    bad_high[2:6] = (len(good) + 4).to_bytes(4, "little")  # past the end
    yield "invalid", "VarTestStruct_offset_past_end", _invalid(bytes(bad_high))
    bad_skew = bytearray(good)
    bad_skew[2:6] = (8).to_bytes(4, "little")  # != fixed size (7)
    yield "invalid", "VarTestStruct_first_offset_skewed", _invalid(bytes(bad_skew))


HANDLERS = {
    "uints": cases_uints,
    "boolean": cases_boolean,
    "basic_vector": cases_basic_vector,
    "bitvector": cases_bitvector,
    "bitlist": cases_bitlist,
    "containers": cases_containers,
}

# exported for the pytest-side robustness check (tests/test_ssz_generic.py)
def iter_cases():
    for handler, gen in HANDLERS.items():
        for suite, case_name, case_fn in gen():
            yield handler, suite, case_name, case_fn


def _cases():
    for handler, suite, case_name, case_fn in iter_cases():
        yield TestCase(
            fork_name="phase0",
            preset_name="general",
            runner_name="ssz_generic",
            handler_name=handler,
            suite_name=suite,
            case_name=case_name,
            case_fn=case_fn,
        )


def run(args=None):
    run_generator(
        "ssz_generic", [TestProvider(prepare=lambda: None, make_cases=_cases)], args=args
    )


if __name__ == "__main__":
    run()
