"""Vector-writing runner: CLI, case directories, INCOMPLETE sentinel
lifecycle, resume, error log (ref: gen_helpers/gen_base/gen_runner.py).
"""
from __future__ import annotations

import argparse
import contextlib
import shutil
import time
import traceback
from pathlib import Path
from typing import Iterable

import yaml

from consensus_specs_tpu.exceptions import SkippedTest
from consensus_specs_tpu.utils import profiling
from consensus_specs_tpu.ssz.types import SSZType
from consensus_specs_tpu.utils import snappy

from .gen_typing import TestCase, TestProvider

TIME_THRESHOLD_TO_PRINT = 1.0  # seconds


def validate_output_dir(path_str: str) -> Path:
    path = Path(path_str)
    if path.exists() and not path.is_dir():
        raise argparse.ArgumentTypeError(f"Output path must be a directory: {path}")
    return path


def run_generator(generator_name: str, test_providers: Iterable[TestProvider], args=None) -> None:
    """Write all providers' cases under ``<output>/<case dir>`` with the
    INCOMPLETE sentinel marking in-progress cases and skip-if-exists resume
    (ref gen_runner.py:41-218)."""
    parser = argparse.ArgumentParser(
        prog=f"gen-{generator_name}",
        description=f"Generate YAML/SSZ test-vector suites for {generator_name}",
    )
    parser.add_argument("-o", "--output-dir", dest="output_dir", required=True,
                        type=validate_output_dir, help="directory to write vectors into")
    parser.add_argument("-f", "--force", action="store_true", default=False,
                        help="overwrite existing test cases")
    parser.add_argument("-l", "--preset-list", dest="preset_list", nargs="*", default=None,
                        help="only generate the given presets")
    parser.add_argument("-c", "--collect-only", action="store_true", default=False,
                        help="list the test cases without generating")
    parser.add_argument("--profile", action="store_true", default=False,
                        help="per-handler wall-clock accounting + JAX device trace "
                             "(trace emitted when CONSENSUS_SPECS_TPU_TRACE_DIR is set)")

    ns = parser.parse_args(args=args)

    output_dir: Path = ns.output_dir
    log_file = output_dir / "testgen_error_log.txt"

    generated = skipped = failed = 0
    collected = 0

    with (profiling.trace(generator_name) if ns.profile else contextlib.nullcontext()):
      for provider in test_providers:
        provider.prepare()

        for test_case in provider.make_cases():
            if ns.preset_list is not None and test_case.preset_name not in ns.preset_list:
                continue
            collected += 1
            if ns.collect_only:
                print(test_case.dir_path())
                continue

            case_dir = output_dir / test_case.dir_path()
            incomplete_tag_file = case_dir / "INCOMPLETE"

            if case_dir.exists():
                if not ns.force and not incomplete_tag_file.exists():
                    skipped += 1
                    continue
                shutil.rmtree(case_dir)

            print(f"generating: {case_dir}")
            written_parts = 0
            profile_ctx = (
                profiling.section(f"{test_case.runner_name}/{test_case.handler_name}")
                if ns.profile
                else contextlib.nullcontext()
            )
            try:
                case_dir.mkdir(parents=True, exist_ok=True)
                start = time.time()
                # sentinel first: a crash leaves the case marked incomplete
                incomplete_tag_file.touch()

                meta = {}
                if ns.profile:
                    with profile_ctx:
                        parts = list(test_case.case_fn())
                else:
                    parts = test_case.case_fn()
                for (name, kind, data) in parts:
                    if kind == "meta":
                        meta[name] = data
                        continue
                    written_parts += 1
                    if kind == "ssz":
                        raw = bytes(data.encode_bytes()) if isinstance(data, SSZType) else bytes(data)
                        (case_dir / f"{name}.ssz_snappy").write_bytes(snappy.compress(raw))
                    elif kind == "data":
                        from consensus_specs_tpu.debug.encode import encode

                        out_data = encode(data) if isinstance(data, SSZType) else data
                        with open(case_dir / f"{name}.yaml", "w") as f:
                            yaml.safe_dump(out_data, f, default_flow_style=None)
                    else:
                        raise ValueError(f"unknown part kind {kind!r}")

                if len(meta) != 0:
                    written_parts += 1
                    with open(case_dir / "meta.yaml", "w") as f:
                        yaml.safe_dump(meta, f, default_flow_style=None)

                if written_parts == 0:
                    print(f"test case {case_dir} did not produce any parts, removing")
                    shutil.rmtree(case_dir)
                    continue

                incomplete_tag_file.unlink()
                generated += 1
                elapsed = time.time() - start
                if elapsed >= TIME_THRESHOLD_TO_PRINT:
                    print(f"  done in {elapsed:.2f}s")
            except SkippedTest as e:
                print(f"skipped: {e}")
                skipped += 1
                if case_dir.exists():
                    shutil.rmtree(case_dir)
            except Exception:
                failed += 1
                err = traceback.format_exc()
                print(f"ERROR in {case_dir}:\n{err}")
                output_dir.mkdir(parents=True, exist_ok=True)
                with open(log_file, "a") as f:
                    f.write(f"\n--- {case_dir} ---\n{err}\n")

    if ns.collect_only:
        print(f"collected {collected} test cases")
    else:
        summary = f"completed generation of {generator_name}: {generated} generated, {skipped} skipped, {failed} failed"
        print(summary)
        if ns.profile:
            profiling.print_report(header="per-handler wall clock:")
        if failed:
            raise SystemExit(1)
