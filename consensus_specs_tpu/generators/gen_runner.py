"""Vector-writing runner: CLI, case directories, INCOMPLETE sentinel
lifecycle, resume, error log (ref: gen_helpers/gen_base/gen_runner.py).

Deferred-BLS mode (--bls-defer, TPU-first addition): cases run with the
facade's DeferredVerifier installed, so every signature check records
and returns optimistically instead of dispatching; a whole provider's
checks then flush as ONE batched device call. Cases whose optimistic
answers were all genuinely True commit their buffered parts untouched;
the rest replay against the flushed truth table at zero crypto cost.
Output bytes are identical to the synchronous path by construction —
pinned by tests/test_gen_defer.py.

Resilience (consensus_specs_tpu/resilience): every case executes under
the supervisor — injected/real transient faults retry with backoff
before the case is counted failed — and committed cases are journaled
(part digests, fsync'd) so a killed run resumes from verified-complete
cases only: output that fails digest or structural verification
(truncated ``.ssz_snappy``, malformed yaml) is regenerated, never
silently shipped. Chaos points: ``gen.case``, ``sched.writer``.

Pipelining (consensus_specs_tpu/sched, docs/GENPIPE.md): deferred
checks accumulate across up to ``--flush-every`` cases before one
bucketed flush (sched.bucketing plans the canonical power-of-two
dispatch shapes), and committed cases are written by a bounded
supervised writer thread (``--serial-writes`` opts out) so yaml/part
IO + the journal append overlap the next case's compute and the next
bucket's device dispatch. Output bytes are mode-independent — pinned
by tests/test_gen_defer.py and tests/test_gen_sched.py.

Data-parallel sharding (sched/shard.py, docs/GENPIPE.md "Sharded
generation"): ``--workers N`` partitions the case stream across N
forked supervised worker processes — each rank's slice is a pure
function of (suite, N, rank), each rank runs the full pipelined path
above with its own crash-safe per-rank digest journal, and a
deterministic merge step produces a suite tree + combined journal
byte-identical to the ``--workers 1`` run regardless of completion
order, worker deaths (transients respawn and resume from the rank
journal), or chaos at the ``sched.worker`` site (deterministic faults
degrade that slice to the in-process serial path).
"""
from __future__ import annotations

import argparse
import contextlib
import shutil
import time
import traceback
from pathlib import Path
from typing import Callable, Dict, Iterable, List, Optional, Tuple

import yaml

from consensus_specs_tpu import obs
from consensus_specs_tpu.exceptions import SkippedTest
from consensus_specs_tpu.resilience import CaseJournal, RetryPolicy, chaos, supervised
from consensus_specs_tpu.resilience.journal import JOURNAL_NAME
from consensus_specs_tpu.utils import profiling
from consensus_specs_tpu.ssz.types import SSZType
from consensus_specs_tpu.utils import snappy

from .gen_typing import TestCase, TestProvider

# transient-fault budget per case (device flake, injected chaos): short
# backoff — a generator run has thousands of cases to get through
CASE_RETRY_POLICY = RetryPolicy(max_attempts=3, base_delay_s=0.05, max_delay_s=1.0)

TIME_THRESHOLD_TO_PRINT = 1.0  # seconds

# bound deferred-case buffering (parts are already-encoded bytes; this is
# a memory bound, not a dispatch bound — one flush still covers a batch);
# --flush-every / CONSENSUS_SPECS_TPU_GEN_FLUSH_EVERY override
DEFER_FLUSH_EVERY = 256


def validate_output_dir(path_str: str) -> Path:
    path = Path(path_str)
    if path.exists() and not path.is_dir():
        raise argparse.ArgumentTypeError(f"Output path must be a directory: {path}")
    return path


def _encode_parts(raw_parts) -> Tuple[List[Tuple[str, str, object]], dict]:
    """Materialize a case's yielded parts into write-ready form:
    ssz → snappy-framed bytes, data → jsonable structures, meta → dict.
    Runs INSIDE the case execution window so buffered commits are
    byte-stable regardless of later mutation or replay."""
    from consensus_specs_tpu.debug.encode import encode

    encoded: List[Tuple[str, str, object]] = []
    meta: dict = {}
    for (name, kind, data) in raw_parts:
        if kind == "meta":
            meta[name] = data
        elif kind == "ssz":
            raw = bytes(data.encode_bytes()) if isinstance(data, SSZType) else bytes(data)
            encoded.append((name, "ssz", snappy.compress(raw)))
        elif kind == "data":
            encoded.append((name, "data", encode(data) if isinstance(data, SSZType) else data))
        else:
            raise ValueError(f"unknown part kind {kind!r}")
    return encoded, meta


def _write_case(case_dir: Path, encoded: List[Tuple[str, str, object]], meta: dict) -> int:
    """Write encoded parts under the INCOMPLETE sentinel; returns the
    number of parts written (0 ⇒ caller removes the empty case dir)."""
    case_dir.mkdir(parents=True, exist_ok=True)
    incomplete_tag_file = case_dir / "INCOMPLETE"
    incomplete_tag_file.touch()

    written_parts = 0
    for (name, kind, payload) in encoded:
        written_parts += 1
        if kind == "ssz":
            (case_dir / f"{name}.ssz_snappy").write_bytes(payload)
        else:
            with open(case_dir / f"{name}.yaml", "w") as f:
                yaml.safe_dump(payload, f, default_flow_style=None)
    if len(meta) != 0:
        written_parts += 1
        with open(case_dir / "meta.yaml", "w") as f:
            yaml.safe_dump(meta, f, default_flow_style=None)

    if written_parts == 0:
        print(f"test case {case_dir} did not produce any parts, removing")
        shutil.rmtree(case_dir)
    else:
        incomplete_tag_file.unlink()
    return written_parts


class _CaseOutcome:
    """One deferred case awaiting its flush verdict."""

    __slots__ = ("test_case", "case_dir", "encoded", "meta", "error", "marks", "start")

    def __init__(self, test_case, case_dir, encoded, meta, error, marks, start):
        self.test_case = test_case
        self.case_dir = case_dir
        self.encoded = encoded
        self.meta = meta
        self.error = error
        self.marks = marks
        self.start = start


def build_parser(generator_name: str) -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog=f"gen-{generator_name}",
        description=f"Generate YAML/SSZ test-vector suites for {generator_name}",
    )
    parser.add_argument("-o", "--output-dir", dest="output_dir", required=True,
                        type=validate_output_dir, help="directory to write vectors into")
    parser.add_argument("-f", "--force", action="store_true", default=False,
                        help="overwrite existing test cases")
    parser.add_argument("-l", "--preset-list", dest="preset_list", nargs="*", default=None,
                        help="only generate the given presets")
    parser.add_argument("-c", "--collect-only", action="store_true", default=False,
                        help="list the test cases without generating")
    parser.add_argument("--profile", action="store_true", default=False,
                        help="per-handler wall-clock accounting + JAX device trace "
                             "(trace emitted when CONSENSUS_SPECS_TPU_TRACE_DIR is set)")
    parser.add_argument("--bls-defer", action="store_true",
                        default=_defer_default(),
                        help="batch signature checks across cases: run each case "
                             "optimistically, flush all checks as one device "
                             "dispatch, replay only mispredicted cases "
                             "(default: CONSENSUS_SPECS_TPU_BLS_DEFER env)")
    parser.add_argument("--no-journal", dest="journal", action="store_false",
                        default=True,
                        help="disable the crash-safe case journal (digest-"
                             "verified resume, corruption regeneration)")
    parser.add_argument("--flush-every", type=int, default=_flush_every_default(),
                        help="deferred-BLS cases to accumulate before one "
                             "bucketed cross-case flush (1 = per-case flush; "
                             "default: CONSENSUS_SPECS_TPU_GEN_FLUSH_EVERY "
                             f"env or {DEFER_FLUSH_EVERY})")
    parser.add_argument("--serial-writes", dest="overlap_writes",
                        action="store_false", default=_overlap_default(),
                        help="write committed cases inline on the main thread "
                             "instead of the bounded overlap writer queue "
                             "(default: overlapped unless "
                             "CONSENSUS_SPECS_TPU_GEN_OVERLAP=0)")
    parser.add_argument("--workers", type=int, default=_workers_default(),
                        help="shard cases across N forked supervised worker "
                             "processes with per-rank journals and a "
                             "deterministic merge (docs/GENPIPE.md; 0 = "
                             "classic in-process run; default: "
                             "CONSENSUS_SPECS_TPU_GEN_WORKERS env or 0)")
    return parser


def run_generator(generator_name: str, test_providers: Iterable[TestProvider], args=None) -> None:
    """Write all providers' cases under ``<output>/<case dir>`` with the
    INCOMPLETE sentinel marking in-progress cases and skip-if-exists resume
    (ref gen_runner.py:41-218). ``--workers N`` scales the run out across
    N supervised worker processes (sched/shard.py)."""
    ns = build_parser(generator_name).parse_args(args=args)

    if ns.workers > 0 and not ns.collect_only:
        from consensus_specs_tpu.sched import shard

        counts = shard.run_sharded(generator_name, test_providers, ns)
    else:
        counts = run_slice(generator_name, test_providers, ns)
    if ns.collect_only:
        return
    summary = (
        f"completed generation of {generator_name}: "
        f"{counts['generated']} generated, {counts['skipped']} skipped, "
        f"{counts['failed']} failed"
    )
    print(summary)
    if ns.profile and ns.workers <= 0:
        profiling.print_report(header="per-handler wall clock:")
    if counts["failed"]:
        raise SystemExit(1)


def run_slice(generator_name: str, test_providers: Iterable[TestProvider],
              ns: argparse.Namespace, *,
              journal_name: str = JOURNAL_NAME,
              absorb_journal: Optional[Path] = None,
              case_filter: Optional[Callable[[TestCase, int], bool]] = None,
              label: str = "") -> Dict[str, int]:
    """One in-process generation pass over the providers' case stream —
    the whole suite by default, or the sub-slice ``case_filter`` selects
    (sharded workers pass the rank predicate plus their per-rank
    ``journal_name``; ``absorb_journal`` pre-loads a prior merged
    journal for resume admits). Returns the generated/skipped/failed
    counts; case failures are counted and error-logged, never raised."""
    output_dir: Path = ns.output_dir
    log_file = output_dir / "testgen_error_log.txt"
    flush_every = max(1, int(ns.flush_every))

    journal = None
    if ns.journal and not ns.collect_only:
        journal = CaseJournal(output_dir, name=journal_name)
        if absorb_journal is not None:
            journal.absorb(absorb_journal)

    counts = {"generated": 0, "skipped": 0, "failed": 0}
    collected = 0
    # per-(runner, fork) stream positions: the shard function's case
    # index — identical in every worker because provider enumeration is
    # deterministic (the TestCase re-runnability contract)
    stream_pos: Dict[Tuple[str, str], int] = {}

    def record_failure(case_dir: Path, err: str) -> None:
        counts["failed"] += 1
        print(f"ERROR in {case_dir}:\n{err}")
        # leave an INCOMPLETE-marked dir so detect_generator_incomplete
        # (and a -f rerun) sees the failed case
        case_dir.mkdir(parents=True, exist_ok=True)
        (case_dir / "INCOMPLETE").touch()
        output_dir.mkdir(parents=True, exist_ok=True)
        with open(log_file, "a") as f:
            f.write(f"\n--- {case_dir} ---\n{err}\n")
        if journal is not None:
            journal.invalidate(str(case_dir.relative_to(output_dir)))

    def run_case(case_fn) -> Tuple[List[Tuple[str, str, object]], dict]:
        """One case execution under the supervisor: transient faults
        (device flake, injected chaos) retry with backoff; SkippedTest
        passes through as control flow, terminal faults re-raise into
        the caller's record_failure path."""
        def _attempt():
            chaos("gen.case")
            return _encode_parts(case_fn())

        return supervised(_attempt, domain="generator",
                          policy=CASE_RETRY_POLICY, passthrough=(SkippedTest,))

    def commit_sync(case_dir: Path, encoded, meta, start: float) -> None:
        if _write_case(case_dir, encoded, meta) == 0:
            return
        if journal is not None:
            journal.record(str(case_dir.relative_to(output_dir)), case_dir)
        counts["generated"] += 1
        elapsed = time.time() - start
        if elapsed >= TIME_THRESHOLD_TO_PRINT:
            print(f"  done in {elapsed:.2f}s")

    # overlapped serialization (sched/writer.py): part IO + the journal
    # append run on a bounded supervised thread, in submit order, so
    # serialization overlaps the next case's compute / bucket flush
    writer = None
    if ns.overlap_writes and not ns.collect_only:
        from consensus_specs_tpu.sched import CaseWriter

        writer = CaseWriter(commit_sync)

    def commit(case_dir: Path, encoded, meta, start: float) -> None:
        if writer is not None:
            writer.submit(str(case_dir), case_dir, encoded, meta, start)
        else:
            commit_sync(case_dir, encoded, meta, start)

    verifier = None
    if ns.bls_defer and not ns.collect_only:
        from consensus_specs_tpu.crypto import bls

        verifier = bls.DeferredVerifier()

    def run_case_deferred(test_case: TestCase, case_dir: Path, start: float):
        """Execute under deferral, buffering encoded parts. Commits
        immediately when the case recorded no checks; otherwise returns a
        _CaseOutcome for the flush to adjudicate."""
        from consensus_specs_tpu.crypto import bls

        assert verifier is not None
        m0 = verifier.mark()
        encoded, meta, error = None, None, None
        try:
            with bls.deferring(verifier):
                encoded, meta = run_case(test_case.case_fn)
        except SkippedTest as e:
            error = e
        except Exception:
            error = traceback.format_exc()
        m1 = verifier.mark()

        if m0 == m1:  # no signature checks: verdict already final
            finalize_case(case_dir, encoded, meta, error, start)
            return None
        return _CaseOutcome(test_case, case_dir, encoded, meta, error, (m0, m1), start)

    def finalize_case(case_dir, encoded, meta, error, start) -> None:
        if isinstance(error, SkippedTest):
            print(f"{label}skipped: {error}")
            counts["skipped"] += 1
        elif error is not None:
            record_failure(case_dir, error)
        else:
            commit(case_dir, encoded, meta, start)

    def flush_pending(pending: List[_CaseOutcome]) -> None:
        """One batched dispatch for every recorded check, then commit the
        correctly-predicted cases and replay the rest."""
        from consensus_specs_tpu.crypto import bls

        if not pending:
            return
        assert verifier is not None
        with obs.span("gen.flush", cases=len(pending),
                      checks=len(verifier.entries) - len(verifier.results)):
            verifier.flush()
        table = verifier.table()
        for p in pending:
            if p.error is None and verifier.all_true(*p.marks):
                commit(p.case_dir, p.encoded, p.meta, p.start)
                continue
            # misprediction (or an error that may stem from one): replay
            # with true answers — pure-Python re-run, no crypto
            encoded, meta, error = None, None, None
            try:
                with bls.replaying(table):
                    encoded, meta = _encode_parts(p.test_case.case_fn())
            except SkippedTest as e:
                error = e
            except Exception:
                error = traceback.format_exc()
            finalize_case(p.case_dir, encoded, meta, error, p.start)
        pending.clear()

    with (profiling.trace(generator_name) if ns.profile else contextlib.nullcontext()), \
            obs.span("gen.run", generator=generator_name):
      # ONE deferred-check population across every provider in the run:
      # providers' prepare() only selects the BLS backend (idempotent) and
      # each case_fn carries its own (fork, preset) context, so checks from
      # all handlers can share a single flush dispatch — the per-flush
      # device latency amortizes across the whole runner, not per handler
      pending: List[_CaseOutcome] = []
      for provider in test_providers:
        provider.prepare()

        for test_case in provider.make_cases():
            if ns.preset_list is not None and test_case.preset_name not in ns.preset_list:
                continue
            if case_filter is not None:
                # the per-(runner, fork) stream index advances for EVERY
                # enumerated case so rank assignment is a pure function
                # of the stream, not of what other ranks generated
                key = (test_case.runner_name, test_case.fork_name)
                idx = stream_pos.get(key, 0)
                stream_pos[key] = idx + 1
                if not case_filter(test_case, idx):
                    continue
            collected += 1
            if ns.collect_only:
                print(test_case.dir_path())
                continue

            case_dir = output_dir / test_case.dir_path()
            incomplete_tag_file = case_dir / "INCOMPLETE"

            if case_dir.exists():
                if not ns.force and not incomplete_tag_file.exists():
                    if journal is None or journal.admit(
                            str(case_dir.relative_to(output_dir)), case_dir):
                        counts["skipped"] += 1
                        if journal is not None:
                            # a case admitted on the structural pre-journal
                            # path (its journal append was lost to a kill)
                            # is backfilled so resumes verify digests and
                            # the sharded merge sees every case
                            journal.ensure_recorded(
                                str(case_dir.relative_to(output_dir)), case_dir)
                            # resume marked in the trace: digest-verified
                            # cases skipped on re-run are visible, not silent
                            obs.instant("gen.journal_admitted",
                                        case=test_case.dir_path())
                        continue
                    # journal verification failed (truncated/tampered/
                    # unverifiable output): regenerate instead of shipping
                    print(f"{label}regenerating (failed resume verification): {case_dir}")
                    obs.instant("gen.journal_regenerate",
                                case=test_case.dir_path())
                shutil.rmtree(case_dir)

            print(f"{label}generating: {case_dir}")
            start = time.time()
            profile_ctx = (
                profiling.section(f"{test_case.runner_name}/{test_case.handler_name}")
                if ns.profile
                else contextlib.nullcontext()
            )
            with profile_ctx, obs.span(
                    "gen.case", case=test_case.dir_path(),
                    fork=test_case.fork_name, preset=test_case.preset_name,
                    runner=test_case.runner_name, handler=test_case.handler_name):
                if verifier is not None:
                    outcome = run_case_deferred(test_case, case_dir, start)
                    if outcome is not None:
                        pending.append(outcome)
                        if len(pending) >= flush_every:
                            flush_pending(pending)
                else:
                    encoded, meta, error = None, None, None
                    try:
                        encoded, meta = run_case(test_case.case_fn)
                    except SkippedTest as e:
                        error = e
                    except Exception:
                        error = traceback.format_exc()
                    finalize_case(case_dir, encoded, meta, error, start)

      if verifier is not None:
          flush_pending(pending)
      if writer is not None:
          # drain inside the gen.run span so the trace shows the writer
          # tail; terminal write failures surface as failed cases, never
          # silently dropped output
          for failed_label, err in writer.close():
              record_failure(Path(failed_label), f"writer failed terminally: {err}")

    if ns.collect_only:
        print(f"collected {collected} test cases")
    return counts


def _defer_default() -> bool:
    import os

    return os.environ.get("CONSENSUS_SPECS_TPU_BLS_DEFER", "") not in ("", "0", "false")


def _workers_default() -> int:
    import os

    raw = os.environ.get("CONSENSUS_SPECS_TPU_GEN_WORKERS", "")
    try:
        return max(0, int(raw)) if raw else 0
    except ValueError:
        return 0


def _flush_every_default() -> int:
    import os

    raw = os.environ.get("CONSENSUS_SPECS_TPU_GEN_FLUSH_EVERY", "")
    try:
        return max(1, int(raw)) if raw else DEFER_FLUSH_EVERY
    except ValueError:
        return DEFER_FLUSH_EVERY


def _overlap_default() -> bool:
    import os

    return os.environ.get("CONSENSUS_SPECS_TPU_GEN_OVERLAP", "") not in ("0", "false", "off")
