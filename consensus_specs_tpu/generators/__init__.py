"""Test-vector generation (ref: tests/core/pyspec/eth2spec/gen_helpers/ and
tests/generators/): run the dual-mode tests in generator mode and write
conformance vectors in the canonical
``preset/fork/runner/handler/suite/case`` layout."""
