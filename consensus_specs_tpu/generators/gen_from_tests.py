"""Reflection bridge: re-run the dual-mode pytest tests in generator mode
and emit their yielded parts as vectors (ref: gen_helpers/gen_from_tests/
gen.py)."""
from __future__ import annotations

import importlib
import inspect
from typing import Dict, Iterable, Optional

from consensus_specs_tpu.crypto import bls
from consensus_specs_tpu.exceptions import SkippedTest

from .gen_runner import run_generator
from .gen_typing import TestCase, TestProvider


def generate_from_tests(runner_name: str, handler_name: str, src, fork_name: str,
                        preset_name: str, bls_active: bool = True,
                        phase: Optional[str] = None) -> Iterable[TestCase]:
    """One TestCase per test_* function in module ``src``
    (ref gen.py:13-56)."""
    fn_names = [
        name for (name, _) in inspect.getmembers(src, inspect.isfunction)
        if name.startswith("test_")
    ]
    if phase is None:
        phase = fork_name
    print(f"generating tests with preset '{preset_name}' for {runner_name}/{handler_name} ({len(fn_names)} tests)")
    for name in fn_names:
        case_name = name
        tfn = getattr(src, name)

        def case_fn(tfn=tfn, generator_mode=True, phase=phase, preset=preset_name, bls_active=bls_active):
            parts = tfn(generator_mode=generator_mode, phase=phase, preset=preset,
                        bls_active=bls_active)
            if parts is None:
                # fork-matrix decorator filtered this phase out: designed skip
                raise SkippedTest(f"not applicable to phase {phase}")
            return parts

        yield TestCase(
            fork_name=fork_name,
            preset_name=preset_name,
            runner_name=runner_name,
            handler_name=handler_name,
            suite_name=getattr(tfn, "suite_name", "pyspec_tests"),
            case_name=case_name if case_name.startswith("test_") is False else case_name[len("test_"):],
            case_fn=case_fn,
        )


def get_provider(create_provider_fn, fork_name: str, preset_name: str, all_mods) -> Iterable[TestProvider]:
    for handler_name, mod_name in all_mods[fork_name].items():
        yield create_provider_fn(
            fork_name=fork_name, preset_name=preset_name,
            handler_name=handler_name, tests_src_mod_name=mod_name,
        )


def get_create_provider_fn(runner_name: str):
    def prepare_fn() -> None:
        # generator mode runs real BLS; the backend is selectable the way
        # the reference's generators select milagro (gen.py:75-77) — here
        # the fast analog is the batched device backend ("jax"),
        # opted into via env so CPU-only hosts keep the pure-host path.
        import os

        bls.use_backend(os.environ.get("CONSENSUS_SPECS_TPU_BLS_BACKEND", "reference"))
        return

    def create_provider(fork_name: str, preset_name: str, handler_name: str,
                        tests_src_mod_name: str) -> TestProvider:
        def cases_fn() -> Iterable[TestCase]:
            tests_src = importlib.import_module(tests_src_mod_name)
            yield from generate_from_tests(
                runner_name=runner_name,
                handler_name=handler_name,
                src=tests_src,
                fork_name=fork_name,
                preset_name=preset_name,
            )

        return TestProvider(prepare=prepare_fn, make_cases=cases_fn)

    return create_provider


def run_state_test_generators(runner_name: str, all_mods: Dict[str, Dict[str, str]],
                              presets=("minimal", "mainnet"), args=None) -> None:
    """Loop presets × forks over the module map and write vectors
    (ref gen.py:96-132)."""
    create_provider = get_create_provider_fn(runner_name)
    providers = [
        provider
        for preset_name in presets
        for fork_name in all_mods
        for provider in get_provider(create_provider, fork_name, preset_name, all_mods)
    ]
    run_generator(runner_name, providers, args=args)


def combine_mods(dict_1: Dict[str, str], dict_2: Dict[str, str]) -> Dict[str, str]:
    """Merge a fork's handler→module delta over its parent's
    (ref gen.py:114-132)."""
    combined = dict(dict_2)
    combined.update(dict_1)
    return combined
