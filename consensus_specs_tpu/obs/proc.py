"""Zero-dependency per-process resource sampler (docs/OBSERVABILITY.md
"Long-haul telemetry plane").

One call, :func:`sample`, returns the gauges a long-lived process must
watch about itself — RSS, CPU time, open fds, thread count, GC
pressure — read from ``/proc/self`` (pure stdlib, no psutil). On a
host without procfs it degrades to ``resource.getrusage`` +
``threading`` so the series journal still carries CPU/RSS evidence,
just with coarser semantics (``ru_maxrss`` is a high-water mark, not
the live RSS).

The timeseries flusher (obs/timeseries.py) publishes every key here as
a ``proc.<key>`` gauge each sampling tick, which is what the RSS
leak-slope and stall watchdogs (obs/watchdog.py) watch.
"""
from __future__ import annotations

import gc
import os
import threading
import time
from typing import Dict

_PAGE = 4096
try:
    _PAGE = os.sysconf("SC_PAGE_SIZE")
except (AttributeError, ValueError, OSError):  # pragma: no cover
    pass

_TICK = 100.0
try:
    _TICK = float(os.sysconf("SC_CLK_TCK"))
except (AttributeError, ValueError, OSError):  # pragma: no cover
    pass


def _read(path: str) -> str:
    with open(path, "rb") as f:
        return f.read().decode("ascii", "replace")


def _procfs_sample() -> Dict[str, float]:
    out: Dict[str, float] = {}
    # /proc/self/statm: size resident shared ... (pages)
    fields = _read("/proc/self/statm").split()
    out["vm_bytes"] = float(fields[0]) * _PAGE
    out["rss_bytes"] = float(fields[1]) * _PAGE
    # /proc/self/stat: utime/stime are fields 14/15 (1-based), but the
    # comm field (2) may itself contain spaces/parens — split after the
    # LAST ')' to stay correct for any process name
    stat = _read("/proc/self/stat")
    rest = stat.rsplit(")", 1)[1].split()
    # rest[0] is field 3 (state); utime = field 14 -> rest[11]
    out["cpu_user_s"] = float(rest[11]) / _TICK
    out["cpu_sys_s"] = float(rest[12]) / _TICK
    out["cpu_s"] = out["cpu_user_s"] + out["cpu_sys_s"]
    out["threads"] = float(rest[17])
    out["fds"] = float(len(os.listdir("/proc/self/fd")))
    return out


def _fallback_sample() -> Dict[str, float]:  # pragma: no cover — non-procfs
    out: Dict[str, float] = {}
    try:
        import resource

        ru = resource.getrusage(resource.RUSAGE_SELF)
        out["rss_bytes"] = float(ru.ru_maxrss) * 1024  # linux: kB
        out["cpu_user_s"] = float(ru.ru_utime)
        out["cpu_sys_s"] = float(ru.ru_stime)
        out["cpu_s"] = out["cpu_user_s"] + out["cpu_sys_s"]
    except Exception:
        pass
    out["threads"] = float(threading.active_count())
    return out


def sample() -> Dict[str, float]:
    """Resource gauges for THIS process, plus GC counters. Never raises:
    a vanished procfs entry mid-read degrades to the rusage fallback."""
    try:
        out = _procfs_sample()
    except Exception:
        out = _fallback_sample()
    try:
        stats = gc.get_stats()
        out["gc_collections"] = float(sum(g.get("collections", 0) for g in stats))
        out["gc_collected"] = float(sum(g.get("collected", 0) for g in stats))
        out["gc_uncollectable"] = float(
            sum(g.get("uncollectable", 0) for g in stats))
    except Exception:  # pragma: no cover
        pass
    out["uptime_s"] = time.monotonic() - _T0
    return out


_T0 = time.monotonic()
