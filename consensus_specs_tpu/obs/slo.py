"""SLO plane: service-level objectives for the serving plane, computed
from the always-on ``serve.*`` aggregates and the perf ledger.

The sentinel (obs/sentinel.py) answers a *relative* question — "is this
run slower than comparable history?". An SLO answers the *absolute*
one an operator actually promises: "did we serve ≥99.9% of requests
without a 5xx, under the latency objective?" — with an **error budget**
(the tolerated failure fraction) and **burn rates** (how fast a window
is spending that budget, the multi-window SRE alerting pattern).

Objectives (env-overridable, docs/OBSERVABILITY.md):

- ``serve_availability`` — fraction of served wire requests answered
  without a 5xx. Denominator = ``serve.responses`` +
  ``serve.errors.internal``: client-side 400/404/429 rejections are
  *correct* behavior and never burn the budget, overload sheds
  (``deadline_exceeded`` 504 / ``shed`` 429 — load management, not
  faults; tracked via ``serve.shed.*`` and the flight recorder, see
  docs/RESILIENCE.md "Sheds vs faults") never enter it either, and
  introspection GETs (``/metrics`` etc.) never reach the counters at
  all (``serve/protocol.is_introspection``).
- ``serve_latency_p99`` — p99 of the always-on ``serve.request_ms``
  histogram (host objective; the histogram exists without tracing
  armed, so the SLO needs no env knob).

Ledger series (banked by ``make perfgate``'s SLO gate,
``tools/serve_canary.py`` and ``tools/slo_report.py --port``):

- ``serve_slo_availability`` — observed availability fraction (1.0 =
  no budget spent); higher is better.
- ``serve_slo_p99_budget`` — remaining latency budget as a fraction
  (``1 - p99/objective``; ≤0 = budget exhausted); higher is better.

Gate contract (``tools/perfgate.py``): FAIL iff an objective is
*burning* (availability below target / latency budget exhausted) on a
run that actually exercised the serving slice. A run that could not
(environmental skip, zero served requests) is an environment gap —
recorded, visible, never gate-failing — exactly like the sentinel's
``environmental`` verdict.
"""
from __future__ import annotations

import os
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

AVAILABILITY_TARGET_ENV = "CONSENSUS_SPECS_TPU_SLO_AVAILABILITY"
P99_OBJECTIVE_ENV = "CONSENSUS_SPECS_TPU_SLO_P99_MS"

DEFAULT_AVAILABILITY_TARGET = 0.999   # 99.9% non-5xx
DEFAULT_P99_OBJECTIVE_MS = 25.0       # host objective (loopback daemon)

# the multi-window burn-rate ladder (SRE workbook shape): a fast window
# catches a cliff, the slow window catches a slow leak
BURN_WINDOWS_S: Tuple[Tuple[str, float], ...] = (
    ("1h", 3600.0), ("6h", 21600.0), ("24h", 86400.0))

AVAILABILITY_POINT = "serve_slo_availability"
P99_BUDGET_POINT = "serve_slo_p99_budget"

# gate verdicts (mirror the sentinel's vocabulary)
OK = "ok"
BURNING = "burning"
ENV_GAP = "environmental"
NO_DATA = "no_data"


@dataclass(frozen=True)
class Objective:
    name: str
    kind: str          # "availability" | "latency_p99"
    target: float      # availability fraction / latency objective ms
    description: str


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def serve_objectives() -> Tuple[Objective, ...]:
    """The serving plane's declared objectives (env-overridable so an
    operator can tighten/loosen without a code change)."""
    return (
        Objective(
            name="serve_availability", kind="availability",
            target=min(1.0, _env_float(AVAILABILITY_TARGET_ENV,
                                       DEFAULT_AVAILABILITY_TARGET)),
            description="non-5xx fraction of served wire requests"),
        Objective(
            name="serve_latency_p99", kind="latency_p99",
            target=_env_float(P99_OBJECTIVE_ENV, DEFAULT_P99_OBJECTIVE_MS),
            description="p99 serve.request_ms (always-on histogram, host)"),
    )


# ---------------------------------------------------------------------------
# observation: the always-on aggregates -> one observed dict
# ---------------------------------------------------------------------------

def observed_from_snapshot(snap: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Availability + p99 from an ``obs.snapshot()`` (default: live).

    The denominator is served wire traffic only: ``serve.responses``
    (2xx) + ``serve.errors.internal`` (5xx). 4xx-class refusals and
    introspection scrapes are excluded by construction."""
    if snap is None:
        from . import metrics

        snap = metrics.snapshot()
    counters = snap.get("counters", {})
    ok = float(counters.get("serve.responses", 0))
    err = float(counters.get("serve.errors.internal", 0))
    total = ok + err
    hist = (snap.get("histograms") or {}).get("serve.request_ms") or {}
    return {
        "requests": int(total),
        "errors_5xx": int(err),
        "availability": (ok / total) if total else None,
        "p99_ms": hist.get("p99"),
    }


def observed_from_prometheus(text: str) -> Dict[str, Any]:
    """The same observed dict from a scraped ``/metrics`` exposition
    (the black-box path: slo_report probing a live daemon)."""
    values: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name, _, value = line.rpartition(" ")
        try:
            values[name] = float(value)
        except ValueError:
            continue
    ok = values.get("serve_responses", 0.0)
    err = values.get("serve_errors_internal", 0.0)
    total = ok + err
    return {
        "requests": int(total),
        "errors_5xx": int(err),
        "availability": (ok / total) if total else None,
        "p99_ms": values.get('serve_request_ms{quantile="0.99"}'),
    }


# ---------------------------------------------------------------------------
# evaluation: observed vs objectives
# ---------------------------------------------------------------------------

def evaluate(observed: Dict[str, Any],
             objectives: Optional[Sequence[Objective]] = None) -> List[Dict[str, Any]]:
    """Per-objective status dicts: observed value, remaining budget
    fraction, and whether the objective is *burning* right now."""
    statuses: List[Dict[str, Any]] = []
    for obj in objectives or serve_objectives():
        status: Dict[str, Any] = {
            "objective": obj.name, "kind": obj.kind, "target": obj.target,
            "description": obj.description,
        }
        if obj.kind == "availability":
            avail = observed.get("availability")
            status["observed"] = avail
            if avail is None:
                status.update(verdict=NO_DATA, burning=False)
            else:
                budget = 1.0 - obj.target
                burn = ((1.0 - avail) / budget) if budget > 0 else (
                    0.0 if avail >= 1.0 else float("inf"))
                status["burn"] = round(burn, 4)
                status["budget_remaining"] = round(1.0 - burn, 4)
                status["burning"] = avail < obj.target
                status["verdict"] = BURNING if status["burning"] else OK
        elif obj.kind == "latency_p99":
            p99 = observed.get("p99_ms")
            status["observed"] = p99
            if p99 is None:
                status.update(verdict=NO_DATA, burning=False)
            else:
                status["budget_remaining"] = round(1.0 - p99 / obj.target, 4)
                status["burning"] = p99 > obj.target
                status["verdict"] = BURNING if status["burning"] else OK
        else:  # unknown kind: visible, never gating
            status.update(observed=None, verdict=NO_DATA, burning=False)
        statuses.append(status)
    return statuses


def ledger_points(statuses: Sequence[Dict[str, Any]]) -> Dict[str, float]:
    """The SLO ledger series for one evaluated run (empty when there is
    no data — a degraded run records what it has)."""
    points: Dict[str, float] = {}
    for status in statuses:
        if status.get("verdict") == NO_DATA:
            continue
        if status["kind"] == "availability":
            points[AVAILABILITY_POINT] = round(float(status["observed"]), 6)
        elif status["kind"] == "latency_p99":
            points[P99_BUDGET_POINT] = float(status["budget_remaining"])
    return points


# ---------------------------------------------------------------------------
# burn rates: how fast recent windows spend the availability budget
# ---------------------------------------------------------------------------

def burn_rates(points: Sequence[Dict[str, Any]],
               target: Optional[float] = None,
               now: Optional[float] = None,
               windows: Sequence[Tuple[str, float]] = BURN_WINDOWS_S,
               ) -> Dict[str, Dict[str, Any]]:
    """Multi-window burn rates over ledger ``serve_slo_availability``
    points. Burn rate 1.0 = spending the budget exactly at the rate
    that exhausts it over the window; >1 = burning faster.

    ``points`` are ledger point dicts (``ts``/``value``); sources mix
    freely (perfgate runs, canary probes, slo_report scrapes) — each is
    one availability observation on the timeline."""
    if target is None:
        target = serve_objectives()[0].target
    budget = 1.0 - target
    samples = [(float(p["ts"]), float(p["value"])) for p in points
               if isinstance(p.get("value"), (int, float))
               and isinstance(p.get("ts"), (int, float))]
    if now is None:
        now = max([ts for ts, _ in samples], default=time.time())
    out: Dict[str, Dict[str, Any]] = {}
    for label, window_s in windows:
        in_window = [v for ts, v in samples if now - ts <= window_s]
        entry: Dict[str, Any] = {"window_s": window_s, "points": len(in_window)}
        if in_window:
            mean_avail = sum(in_window) / len(in_window)
            entry["mean_availability"] = round(mean_avail, 6)
            entry["burn_rate"] = (round((1.0 - mean_avail) / budget, 4)
                                  if budget > 0 else
                                  (0.0 if mean_avail >= 1.0 else float("inf")))
        out[label] = entry
    return out


# ---------------------------------------------------------------------------
# the CI gate hook (tools/perfgate.py)
# ---------------------------------------------------------------------------

def gate(snap: Optional[Dict[str, Any]] = None, *,
         skipped_environmental: bool = False,
         chaos_factor: Optional[Callable[[str], float]] = None,
         ) -> Dict[str, Any]:
    """Evaluate the serve SLOs for one just-measured run.

    ``chaos_factor`` is perfgate's ``CONSENSUS_SPECS_TPU_PERF_CHAOS``
    hook: a clause matching ``serve_slo_availability`` multiplies the
    observed availability (e.g. ``=0.5`` simulates a daemon burning its
    budget), one matching ``serve_slo_p99_ms`` multiplies the observed
    p99 — so the gate itself is drillable without a real outage.

    Returns ``{"ok", "verdict", "observed", "statuses", "points"}``:
    ``ok`` is False only for a confirmed burn; an environmental skip or
    a run with zero served requests is an environment gap that never
    fails the gate."""
    observed = observed_from_snapshot(snap)
    if chaos_factor is not None:
        if observed.get("availability") is not None:
            observed["availability"] = min(
                1.0, observed["availability"] * chaos_factor(AVAILABILITY_POINT))
        if observed.get("p99_ms") is not None:
            observed["p99_ms"] = observed["p99_ms"] * chaos_factor("serve_slo_p99_ms")
    statuses = evaluate(observed)
    if skipped_environmental or not observed["requests"]:
        return {
            "ok": True, "verdict": ENV_GAP, "observed": observed,
            "statuses": statuses, "points": {},
            "detail": "serving slice not exercised this run "
                      "(environment gap, not a burn)",
        }
    burning = [s for s in statuses if s.get("burning")]
    return {
        "ok": not burning,
        "verdict": BURNING if burning else OK,
        "observed": observed,
        "statuses": statuses,
        "points": ledger_points(statuses),
    }
