"""Long-haul time-series journals: the telemetry plane's flusher
(docs/OBSERVABILITY.md "Long-haul telemetry plane").

One env knob arms the whole plane::

    CONSENSUS_SPECS_TPU_LONGHAUL=<dir>[;<interval_s>[;<profile_hz>]]

When armed, :func:`ensure_started` launches a daemon thread that every
``interval_s`` (default 1.0):

1. samples ``/proc/self`` (obs/proc.py) and publishes the readings as
   ``proc.*`` gauges, plus any app-registered gauges
   (:func:`register_gauge` — the serve daemon registers its live queue
   depth here);
2. snapshots the metric registry (counters + gauges + histogram
   summaries) into ONE JSON line appended to a per-process
   ``series-<pid>-<token>.jsonl`` journal — fsync'd per flush, so a
   SIGKILL loses at most the in-flight line and the tail always parses
   (crash-safe exactly like the generator journal); timestamps are
   wall-anchored monotonic (``wall0 + (monotonic - mono0)``), the same
   timeline spans use, so series and trace merge onto one axis;
3. feeds the sample through the drift watchdogs (obs/watchdog.py) and
   journals any findings as ``{"type": "finding", ...}`` lines next to
   the samples (mirrored as ``obs.instant`` + ``watchdog.<kind>``
   counters).

The sampling profiler (obs/profile.py) arms into the same directory by
default (19Hz — continuous profiling is the plane's point, and the
whole armed tax is perfgate-gated under 3%); a third knob field of 0
opts out, any other value re-pins the rate.

Unarmed cost is one ``os.environ.get`` in :func:`ensure_started` — no
thread, no locks, no allocation. Fork-safety: ``obs.fork_child_reinit``
calls :func:`fork_child_reinit`, which abandons the inherited journal
(its fd belongs to the parent) and drops the dead flusher thread and
any registered gauge closures; the worker body's :func:`set_role` call
right after restarts the plane under the worker's lane label — so COW
children (fleet replicas, fuzz/gen ranks) each write their own
correctly-labelled journal with no duplicate sampler threads.

Abnormal exits leave a postmortem bundle: an uncaught exception (the
chained ``sys.excepthook``) or an explicit :func:`postmortem_bundle`
call writes ``postmortem-<pid>-<token>.json`` with the last-N samples,
all findings, and the final counter snapshot — the first thing to read
after a dead multi-hour run. ``tools/mission_report.py`` merges every
process's journals + profiles + findings into one HTML report.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, IO, List, Optional, Tuple

from . import metrics, proc, profile, watchdog

LONGHAUL_ENV = "CONSENSUS_SPECS_TPU_LONGHAUL"

_TAIL_KEEP = 180         # samples retained for the postmortem bundle
_DEFAULT_INTERVAL_S = 1.0
_DEFAULT_PROFILE_HZ = 19.0   # continuous profiling is the plane's point:
#                              armed = profiled (<3% total, perfgate-gated);
#                              a third knob field of 0 opts out
_MIN_INTERVAL_S = 0.01
_FSYNC_MIN_S = 0.5       # fsync throttle (see _write_lines)


def config_from_env() -> Optional[Tuple[str, float, float]]:
    """``(dir, interval_s, profile_hz)`` from the knob, or None."""
    raw = os.environ.get(LONGHAUL_ENV, "")
    if not raw:
        return None
    parts = raw.split(";")
    out_dir = parts[0]
    if not out_dir:
        return None
    interval = _DEFAULT_INTERVAL_S
    hz = _DEFAULT_PROFILE_HZ
    try:
        if len(parts) > 1 and parts[1]:
            interval = float(parts[1])
        if len(parts) > 2 and parts[2]:
            hz = float(parts[2])
    except ValueError:
        pass
    return out_dir, max(_MIN_INTERVAL_S, interval), max(0.0, hz)


def _default_role() -> str:
    return os.path.basename(sys.argv[0] or "python")[:48] or "python"


class SeriesFlusher(threading.Thread):
    """The background flusher. One per process, via module state."""

    def __init__(self, out_dir: str, interval_s: float,
                 role: Optional[str] = None) -> None:
        super().__init__(name="obs-timeseries", daemon=True)
        self.out_dir = out_dir
        self.interval_s = interval_s
        self.role = role or _default_role()
        self.role_explicit = role is not None
        self.pid = os.getpid()
        self.wall0 = time.time()
        self.mono0 = time.monotonic()
        self._token = os.urandom(3).hex()
        self._halt = threading.Event()
        self._io_lock = threading.Lock()
        self._fh: Optional[IO[str]] = None
        self._last_fsync = 0.0
        self.watchdog = watchdog.Watchdog()
        self._hist_cache: Dict[str, Any] = {}
        self.tail: Deque[Dict[str, Any]] = deque(maxlen=_TAIL_KEEP)
        self.findings: List[Dict[str, Any]] = []
        self.samples_written = 0

    # -- timeline ----------------------------------------------------------

    def now_us(self) -> float:
        return (self.wall0 + (time.monotonic() - self.mono0)) * 1e6

    @property
    def path(self) -> str:
        return os.path.join(self.out_dir,
                            f"series-{self.pid}-{self._token}.jsonl")

    # -- journal -----------------------------------------------------------

    def _write_lines(self, records: List[Dict[str, Any]],
                     force_fsync: bool = False) -> None:
        """Append records as JSONL, flush always, fsync THROTTLED (at
        most once per :data:`_FSYNC_MIN_S`, plus findings and the final
        sample) — a SIGKILL loses at most the last sub-second of
        samples and the tail still parses; an unthrottled fsync at
        sub-second sampling intervals was the plane's dominant armed
        overhead on a 1-CPU host (perfgate_obs_overhead_pct watches
        this)."""
        with self._io_lock:
            if self._fh is None:
                os.makedirs(self.out_dir, exist_ok=True)
                self._fh = open(self.path, "a")
                self._fh.write(json.dumps({
                    "type": "series_header",
                    "pid": self.pid,
                    "role": self.role,
                    "argv": " ".join(sys.argv[:4])[:160] or "python",
                    "interval_s": self.interval_s,
                    "ts": self.now_us(),
                }, default=repr) + "\n")
                force_fsync = True
            for rec in records:
                self._fh.write(json.dumps(rec, default=repr) + "\n")
            self._fh.flush()
            now = time.monotonic()
            if force_fsync or now - self._last_fsync >= _FSYNC_MIN_S:
                os.fsync(self._fh.fileno())
                self._last_fsync = now

    def sample_once(self, final: bool = False) -> Dict[str, Any]:
        """One sampling tick: proc gauges -> registry snapshot -> sample
        line (+ any watchdog finding lines). Findings and the final
        sample fsync unconditionally; plain samples ride the throttle."""
        for key, value in proc.sample().items():
            metrics.gauge(f"proc.{key}", value)
        for name, fn in list(_gauge_fns.items()):
            try:
                metrics.gauge(name, float(fn()))
            except Exception:
                continue
        # the CHEAP registry view: counter/gauge dict copies + cached
        # histogram summaries (only histograms that moved re-sort) —
        # a full metrics.snapshot() per sub-second tick re-sorted every
        # bounded window and dominated the armed overhead
        counters: Dict[str, float] = metrics.counters()
        gauges: Dict[str, float] = metrics.gauges()
        hists = metrics.hist_summaries(self._hist_cache)
        sample = {
            "type": "sample",
            "ts": self.now_us(),
            "role": self.role,
            "counters": counters,
            "gauges": gauges,
            "hists": hists,
        }
        records: List[Dict[str, Any]] = [sample]
        now_s = time.monotonic()
        for f in self.watchdog.check(now_s, counters, gauges):
            finding = {"type": "finding", "ts": self.now_us(),
                       "role": self.role, "pid": self.pid, **f}
            records.append(finding)
            self.findings.append(finding)
            metrics.count(f"watchdog.{f['kind']}")
            try:
                from . import core

                core.instant(f"watchdog.{f['kind']}", series=f["series"],
                             detail=f["detail"], value=f["value"])
            except Exception:
                pass
        self._write_lines(records, force_fsync=final or len(records) > 1)
        self.tail.append(sample)
        self.samples_written += 1
        return sample

    def run(self) -> None:
        try:
            self.sample_once()   # immediate first sample: short-lived
        except Exception:        # workers still land >=1 line
            pass
        while not self._halt.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:
                metrics.count("timeseries.sample_errors")
        try:
            self.sample_once(final=True)   # final sample on clean stop
        except Exception:
            pass

    def stop(self, timeout_s: float = 5.0) -> None:
        self._halt.set()
        if self.is_alive() and threading.current_thread() is not self:
            self.join(timeout_s)

    def close(self) -> None:
        with self._io_lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except Exception:
                    pass
                self._fh = None


_lock = threading.Lock()
_flusher: Optional[SeriesFlusher] = None
_gauge_fns: Dict[str, Callable[[], float]] = {}
_prev_excepthook: Optional[Callable] = None


def active() -> Optional[SeriesFlusher]:
    """The live flusher, or None (armed state test hook)."""
    return _flusher


def ensure_started(role: Optional[str] = None) -> bool:
    """Arm the plane if the env knob says so. Unarmed: ONE env check,
    returns False. Armed: starts the flusher (idempotent) + profiler
    (when hz > 0), installs the postmortem excepthook, returns True."""
    global _flusher
    cfg = config_from_env()
    if cfg is None:
        return False
    out_dir, interval_s, hz = cfg
    with _lock:
        if _flusher is not None and _flusher.is_alive():
            # an explicitly-labelled lane keeps its label (a fleet
            # replica stays "serve.r0" even though its inner daemon
            # also calls ensure_started with the generic role)
            if role and not _flusher.role_explicit:
                _flusher.role = role
                _flusher.role_explicit = True
            return True
        _flusher = SeriesFlusher(out_dir, interval_s, role)
        _flusher.start()
    if hz > 0:
        profile.arm(hz, out_dir)
    _install_excepthook()
    return True


def set_role(role: str) -> None:
    """Label this process's lane in the merged report (no-op unarmed).
    Explicit labels are sticky — later generic ``ensure_started`` calls
    never rename the lane. With the plane armed but not yet running in
    this process (a freshly forked worker after
    :func:`fork_child_reinit`), this STARTS it under ``role`` — the
    worker's very first journal line then carries the right lane label
    instead of racing the flusher's immediate first sample."""
    fl = _flusher
    if fl is not None:
        fl.role = role
        fl.role_explicit = True
        return
    ensure_started(role=role)


def register_gauge(name: str, fn: Callable[[], float]) -> None:
    """Poll ``fn`` each sampling tick and publish it as gauge ``name``
    (serve queue depth, in-flight requests, ...). Safe unarmed — the
    registry simply never gets polled. A raising fn is skipped."""
    _gauge_fns[name] = fn


def unregister_gauge(name: str) -> None:
    _gauge_fns.pop(name, None)


def stop(timeout_s: float = 5.0) -> Optional[str]:
    """Stop the flusher (writing a final sample) and the profiler.
    Returns the journal path, or None when the plane was not armed."""
    global _flusher
    with _lock:
        fl, _flusher = _flusher, None
    profile.disarm()
    if fl is None:
        return None
    fl.stop(timeout_s)
    fl.close()
    return fl.path


def fork_child_reinit() -> None:
    """Post-``os.fork`` child reset (called from obs.fork_child_reinit):
    drop the inherited flusher (its thread is dead in this process and
    its fd/journal belong to the parent), the registered gauge closures
    (they capture parent objects), and the profiler state. The child's
    OWN journal starts when the worker body calls :func:`set_role`
    (every fork site does, right after reinit) — starting here instead
    would race the first sample against the relabel and stamp worker
    lanes with the parent's argv."""
    global _flusher
    with _lock:
        _flusher = None
    _gauge_fns.clear()
    profile.fork_child_reinit()


def record_finding(finding: Dict[str, Any]) -> None:
    """Journal an externally-produced finding (the consensus watchdogs
    in obs/chain.py fire at slot boundaries, not sampling ticks) through
    the active flusher, exactly like the flusher's own watchdog
    findings: one fsync'd ``{"type": "finding", ...}`` line in the
    series journal, retained for the postmortem bundle. No-op unarmed."""
    fl = _flusher
    if fl is None:
        return
    rec = {"type": "finding", "ts": fl.now_us(), "role": fl.role,
           "pid": fl.pid, **finding}
    try:
        fl._write_lines([rec], force_fsync=True)
    except Exception:
        return
    fl.findings.append(rec)


def postmortem_bundle(reason: str) -> Optional[str]:
    """Write the postmortem bundle NOW (armed processes only): last-N
    samples, every finding, the final metric snapshot. fsync'd; returns
    the path. Callable from failure paths; also fired by the chained
    excepthook on an uncaught exception."""
    fl = _flusher
    cfg = config_from_env()
    if cfg is None:
        return None
    out_dir = cfg[0]
    token = fl._token if fl is not None else os.urandom(3).hex()
    path = os.path.join(out_dir, f"postmortem-{os.getpid()}-{token}.json")
    payload = {
        "type": "postmortem",
        "reason": str(reason)[:500],
        "pid": os.getpid(),
        "role": fl.role if fl is not None else _default_role(),
        "ts": fl.now_us() if fl is not None else time.time() * 1e6,
        "series_path": fl.path if fl is not None else None,
        "tail": list(fl.tail) if fl is not None else [],
        "findings": list(fl.findings) if fl is not None else [],
        "snapshot": metrics.snapshot(),
    }
    try:
        os.makedirs(out_dir, exist_ok=True)
        with open(path, "w") as f:
            json.dump(payload, f, default=repr)
            f.flush()
            os.fsync(f.fileno())
    except Exception:
        return None
    return path


def _install_excepthook() -> None:
    global _prev_excepthook
    if _prev_excepthook is not None:
        return
    _prev_excepthook = sys.excepthook

    def _hook(exc_type, exc, tb):  # type: ignore[no-untyped-def]
        try:
            postmortem_bundle(f"uncaught {exc_type.__name__}: {exc}")
            fl = _flusher
            if fl is not None:
                fl.sample_once()
        except Exception:
            pass
        prev = _prev_excepthook or sys.__excepthook__
        prev(exc_type, exc, tb)

    sys.excepthook = _hook
