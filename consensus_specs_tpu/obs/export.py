"""Exporters: merge per-pid span JSONL into one Chrome-trace JSON.

Every traced process appends records to its own ``spans-<pid>-*.jsonl``
under the trace dir (obs.core); the ROOT process (or any tool) merges
them here into one ``trace.json`` in the Chrome trace-event format that
Perfetto / ``chrome://tracing`` loads directly:

- spans      -> ``ph:"X"`` complete events (name, ts, dur, pid, tid)
- instants   -> ``ph:"i"`` thread-scoped instant events (resilience
  retries/quarantines/chaos hits render as ticks on the owning track)
- counters   -> ``ph:"C"`` counter events
- processes  -> ``ph:"M"`` process_name metadata
- cross-process parenthood -> ``ph:"s"``/``ph:"f"`` flow arrows from
  the parent span's track to the child process's root spans

A truncated trailing line (the writing process was SIGKILLed mid-write)
is skipped, like the generator journal's recovery contract — everything
committed before it survives.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, Iterable, List, Optional, Tuple


def read_records(trace_dir: str) -> List[Dict[str, Any]]:
    """All records from every per-pid JSONL under ``trace_dir``, in file
    order (corrupt/truncated lines skipped)."""
    records: List[Dict[str, Any]] = []
    try:
        names = sorted(os.listdir(trace_dir))
    except OSError:
        return records
    for fname in names:
        if not (fname.startswith("spans-") and fname.endswith(".jsonl")):
            continue
        try:
            with open(os.path.join(trace_dir, fname)) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue  # torn tail of a killed writer
                    if isinstance(rec, dict):
                        records.append(rec)
        except OSError:
            continue
    return records


def span_index(records: Iterable[Dict[str, Any]]) -> Dict[str, Dict[str, Any]]:
    """{span_id: span record} over the merged record stream."""
    return {r["span"]: r for r in records
            if r.get("type") == "span" and r.get("span")}


def span_children(records: Iterable[Dict[str, Any]]) -> Dict[Optional[str], List[Dict[str, Any]]]:
    """{parent span_id: [child span records]} (None = roots)."""
    out: Dict[Optional[str], List[Dict[str, Any]]] = {}
    for r in records:
        if r.get("type") == "span":
            out.setdefault(r.get("parent"), []).append(r)
    return out


def to_chrome(records: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Chrome trace-event JSON (object form) for a merged record list."""
    events: List[Dict[str, Any]] = []
    spans = span_index(records)
    flow_id = 0
    for rec in records:
        rtype = rec.get("type")
        if rtype == "process":
            events.append({
                "ph": "M", "name": "process_name", "pid": rec.get("pid", 0),
                "args": {"name": rec.get("name", "python")},
            })
        elif rtype == "span":
            args = dict(rec.get("attrs") or {})
            args["span"] = rec.get("span")
            if rec.get("parent"):
                args["parent"] = rec["parent"]
            if rec.get("links"):
                args["links"] = list(rec["links"])
            events.append({
                "ph": "X",
                "name": rec.get("name", "?"),
                "cat": str(args.get("cat", "span")),
                "ts": rec.get("ts", 0),
                "dur": rec.get("dur", 0),
                "pid": rec.get("pid", 0),
                "tid": rec.get("tid", 0),
                "args": args,
            })
            parent = spans.get(rec.get("parent") or "")
            if parent is not None and (parent.get("pid") != rec.get("pid")
                                       or (parent.get("tid") != rec.get("tid")
                                           and rec.get("remote"))):
                # parent lives in another process: draw the flow arrow
                flow_id += 1
                ts = rec.get("ts", 0)
                events.append({
                    "ph": "s", "id": flow_id, "name": "spawn", "cat": "flow",
                    "ts": max(parent.get("ts", 0), min(
                        ts, parent.get("ts", 0) + parent.get("dur", 0))),
                    "pid": parent.get("pid", 0), "tid": parent.get("tid", 0),
                })
                events.append({
                    "ph": "f", "bp": "e", "id": flow_id, "name": "spawn",
                    "cat": "flow", "ts": ts,
                    "pid": rec.get("pid", 0), "tid": rec.get("tid", 0),
                })
            # explicit causal links (a shared flush span serving many
            # requests): arrow from each linked span to this one
            for linked_id in rec.get("links") or ():
                linked = spans.get(linked_id)
                if linked is None:
                    continue
                flow_id += 1
                ts = rec.get("ts", 0)
                events.append({
                    "ph": "s", "id": flow_id, "name": "link", "cat": "flow",
                    "ts": max(linked.get("ts", 0), min(
                        ts, linked.get("ts", 0) + linked.get("dur", 0))),
                    "pid": linked.get("pid", 0), "tid": linked.get("tid", 0),
                })
                events.append({
                    "ph": "f", "bp": "e", "id": flow_id, "name": "link",
                    "cat": "flow", "ts": ts,
                    "pid": rec.get("pid", 0), "tid": rec.get("tid", 0),
                })
        elif rtype == "instant":
            events.append({
                "ph": "i", "s": "t",
                "name": rec.get("name", "?"),
                "cat": "instant",
                "ts": rec.get("ts", 0),
                "pid": rec.get("pid", 0),
                "tid": rec.get("tid", 0),
                "args": dict(rec.get("attrs") or {},
                             **({"span": rec["span"]} if rec.get("span") else {})),
            })
        elif rtype == "counter":
            values = {k: v for k, v in (rec.get("values") or {}).items()
                      if isinstance(v, (int, float))}
            if values:
                events.append({
                    "ph": "C", "name": rec.get("name", "counters"),
                    "ts": rec.get("ts", 0), "pid": rec.get("pid", 0),
                    "args": values,
                })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def export_chrome(trace_dir: str, out_path: Optional[str] = None) -> str:
    """Merge ``trace_dir``'s JSONL into a Chrome trace; returns the
    output path (default ``<trace_dir>/trace.json``). Atomic replace so
    a concurrent reader never sees a torn file."""
    records = read_records(trace_dir)
    trace = to_chrome(records)
    if out_path is None:
        out_path = os.path.join(trace_dir, "trace.json")
    tmp = f"{out_path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(trace, f)
    os.replace(tmp, out_path)
    return out_path


def records_from_chrome(trace: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Reconstruct obs records from a merged Chrome trace — the exporter
    keeps span/parent ids in ``args``, so the span tree survives the
    round trip. Shared by tools/trace_report.py and tools/trace_diff.py
    so both accept either input form."""
    records: List[Dict[str, Any]] = []
    for ev in trace.get("traceEvents", []):
        if not isinstance(ev, dict):
            continue
        ph = ev.get("ph")
        args = ev.get("args") or {}
        if ph == "X":
            rec = {
                "type": "span", "name": ev.get("name", "?"),
                "span": args.get("span"), "parent": args.get("parent"),
                "ts": ev.get("ts", 0), "dur": ev.get("dur", 0),
                "pid": ev.get("pid"), "tid": ev.get("tid"),
                "attrs": {k: v for k, v in args.items()
                          if k not in ("span", "parent", "links")},
            }
            if args.get("links"):
                rec["links"] = list(args["links"])
            records.append(rec)
        elif ph == "i":
            records.append({
                "type": "instant", "name": ev.get("name", "?"),
                "span": args.get("span"), "ts": ev.get("ts", 0),
                "pid": ev.get("pid"), "tid": ev.get("tid"),
                "attrs": {k: v for k, v in args.items() if k != "span"},
            })
    return records


def load_records(path: str) -> List[Dict[str, Any]]:
    """Records from either input form a traced run produces: a raw span
    JSONL directory, or a merged ``trace.json``. Raises ValueError on a
    file that is not a valid Chrome trace."""
    if os.path.isdir(path):
        return read_records(path)
    with open(path) as f:
        trace = json.load(f)
    ok, why = validate_chrome(trace)
    if not ok:
        raise ValueError(f"{path} is not a valid Chrome trace: {why}")
    return records_from_chrome(trace)


def validate_chrome(trace: Any) -> Tuple[bool, str]:
    """Structural validation of a Chrome trace-event object: the
    contract ``make trace`` asserts before calling a run green."""
    if not isinstance(trace, dict):
        return False, "trace is not a JSON object"
    events = trace.get("traceEvents")
    if not isinstance(events, list) or not events:
        return False, "traceEvents missing or empty"
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            return False, f"event {i} is not an object"
        ph = ev.get("ph")
        if not isinstance(ph, str) or not ph:
            return False, f"event {i} has no ph"
        if "pid" not in ev:
            return False, f"event {i} has no pid"
        if ph == "X":
            if not isinstance(ev.get("ts"), (int, float)):
                return False, f"X event {i} has non-numeric ts"
            if not isinstance(ev.get("dur"), (int, float)):
                return False, f"X event {i} has non-numeric dur"
        if ph in ("X", "i", "C", "s", "f") and not ev.get("name"):
            return False, f"{ph} event {i} has no name"
    return True, f"{len(events)} events"
