"""Online drift watchdogs over the live telemetry series
(docs/OBSERVABILITY.md "Long-haul telemetry plane").

The timeseries flusher feeds every sample through one
:class:`Watchdog`, which keeps bounded per-series windows and emits
structured findings the moment a long-haul run starts going wrong —
hours before a human would read the journal:

- ``rss_leak``          least-squares slope of ``proc.rss_bytes`` over
                        the window exceeds the configured MB/s AND the
                        absolute growth cleared the noise floor;
- ``throughput_drift``  a watched progress counter's recent rate
                        decayed below ``drift_drop_frac`` of its
                        earlier rate in the same window (slots/s,
                        execs/s, verifies/s decay detection);
- ``queue_creep``       a watched depth gauge grew near-monotonically
                        across the whole window (the metastable-failure
                        precursor the overload plane sheds against);
- ``stall``             no watched progress counter moved for
                        ``stall_s`` while the process stayed alive.

Findings are data, not exceptions: the flusher journals them as
``{"type": "finding", ...}`` lines next to the samples, mirrors each
as an ``obs.instant`` (``watchdog.<kind>``) and a
``watchdog.<kind>`` counter, and the mission report renders them as
anomaly annotations. Every threshold is overridable via
``CONSENSUS_SPECS_TPU_WATCHDOG=k=v[,k=v...]`` (keys = the
:class:`Thresholds` field names); watched series come from
``CONSENSUS_SPECS_TPU_WATCHDOG_RATES`` / ``_DEPTHS`` (comma lists).
A per-(kind, series) cooldown stops a persistent condition from
flooding the journal.
"""
from __future__ import annotations

import os
from collections import deque
from dataclasses import dataclass, fields
from typing import Any, Deque, Dict, List, Optional, Tuple

WATCHDOG_ENV = "CONSENSUS_SPECS_TPU_WATCHDOG"
RATES_ENV = "CONSENSUS_SPECS_TPU_WATCHDOG_RATES"
DEPTHS_ENV = "CONSENSUS_SPECS_TPU_WATCHDOG_DEPTHS"
CHAIN_HEALTH_ENV = "CONSENSUS_SPECS_TPU_CHAIN_HEALTH"

# progress counters watched by default: the long-running planes' hot
# loops (span.* counters are auto-maintained by obs.metrics.observe, so
# any instrumented site is watchable without new call sites)
DEFAULT_RATES = (
    "sim.blocks_proposed",
    "fuzz.execs",
    "serve.accepted",
    "span.gen.case.count",
)
DEFAULT_DEPTHS = ("serve.queue_depth",)


@dataclass
class Thresholds:
    """Watchdog knobs (env-overridable; documented thresholds in
    docs/OBSERVABILITY.md)."""

    window: int = 30               # samples per detector window
    min_samples: int = 8           # fewer -> detectors stay silent
    rss_slope_mb_per_s: float = 4.0
    rss_min_growth_mb: float = 64.0
    drift_drop_frac: float = 0.5   # recent < 50% of earlier = drift
    drift_min_rate: float = 1.0    # /s floor — idle counters never drift
    stall_s: float = 120.0
    depth_min_growth: float = 64.0
    cooldown_s: float = 60.0

    @classmethod
    def from_env(cls) -> "Thresholds":
        t = cls()
        raw = os.environ.get(WATCHDOG_ENV, "")
        valid = {f.name: f.type for f in fields(cls)}
        for clause in raw.split(","):
            clause = clause.strip()
            if not clause or "=" not in clause:
                continue
            key, _, value = clause.partition("=")
            key = key.strip()
            if key not in valid:
                continue
            try:
                setattr(t, key, int(value) if key in ("window", "min_samples")
                        else float(value))
            except ValueError:
                continue
        return t


def _env_list(env: str, default: Tuple[str, ...]) -> Tuple[str, ...]:
    raw = os.environ.get(env, "")
    if not raw:
        return default
    return tuple(s.strip() for s in raw.split(",") if s.strip())


def _slope(points: List[Tuple[float, float]]) -> float:
    """Least-squares slope of (t, v) points, units of v per second."""
    n = len(points)
    if n < 2:
        return 0.0
    mt = sum(t for t, _ in points) / n
    mv = sum(v for _, v in points) / n
    num = sum((t - mt) * (v - mv) for t, v in points)
    den = sum((t - mt) ** 2 for t, _ in points)
    return num / den if den else 0.0


# ---------------------------------------------------------------------------
# consensus watchdogs (docs/OBSERVABILITY.md "Consensus health plane")
#
# The process watchdogs above ask "is this PROCESS healthy"; these ask
# "is the CHAIN healthy" — slot-indexed, not wall-indexed, fed by the
# chain-health plane (obs/chain.py) at slot/epoch boundaries with the
# per-node consensus view. Scheduled partition windows (exported by
# sim/net.py) EXCUSE the detectors: a planned split legitimately stalls
# finality, drops participation and forks the head, and must not read as
# the chain being sick — only UNSCHEDULED versions of those symptoms do.
# ---------------------------------------------------------------------------


@dataclass
class ChainThresholds:
    """Consensus-watchdog knobs, env-overridable via
    ``CONSENSUS_SPECS_TPU_CHAIN_HEALTH=k=v[,k=v...]`` (the value
    ``off``/``0`` disarms the whole plane — obs/chain.py checks)."""

    finality_stall_epochs: int = 4    # frozen finality epochs before a finding
    genesis_grace_epochs: int = 3     # the chain cannot finalize before ~e3
    participation_floor: float = 2.0 / 3.0
    droop_epochs: int = 2             # consecutive sub-floor epochs before a
    #                                   finding (one starved epoch on a lossy
    #                                   bus is weather; a justification quorum
    #                                   problem persists)
    split_brain_slots: int = 24       # connected slots of head disagreement
    #                                   before a finding — the partitioned
    #                                   sim's own convergence bound (3
    #                                   minimal-preset epochs): honest
    #                                   connected nodes that have not
    #                                   converged by then are split
    reorg_storm_count: int = 12       # deep reorgs within reorg_storm_window
    reorg_storm_window: int = 32      # ... slots (across all nodes)
    reorg_storm_min_depth: int = 3    # calibrated against the adversarial
    #                                   bus: depth-1/2 head swaps are routine
    #                                   gossip weather on a lossy network
    #                                   (~p50 of the clean sim's depth
    #                                   histogram), not a storm
    heal_grace_slots: int = 16        # post-heal slots excused (re-justify)
    cooldown_slots: int = 64          # per-kind finding cooldown

    _INT_FIELDS = ("finality_stall_epochs", "genesis_grace_epochs",
                   "droop_epochs", "split_brain_slots", "reorg_storm_count",
                   "reorg_storm_window", "reorg_storm_min_depth",
                   "heal_grace_slots", "cooldown_slots")

    @classmethod
    def from_env(cls) -> "ChainThresholds":
        t = cls()
        raw = os.environ.get(CHAIN_HEALTH_ENV, "")
        valid = {f.name for f in fields(cls)}
        for clause in raw.split(","):
            clause = clause.strip()
            if not clause or "=" not in clause:
                continue
            key, _, value = clause.partition("=")
            key = key.strip()
            if key not in valid:
                continue
            try:
                setattr(t, key, int(value) if key in cls._INT_FIELDS
                        else float(value))
            except ValueError:
                continue
        return t


def chain_health_disarmed() -> bool:
    """True when the env knob explicitly disarms the chain-health plane
    (``CONSENSUS_SPECS_TPU_CHAIN_HEALTH=off|0|none``). Default: armed —
    the plane is cheap enough to ship on (perfgate-gated <3%)."""
    return os.environ.get(CHAIN_HEALTH_ENV, "").strip().lower() in (
        "off", "0", "none", "false")


class ChainWatchdog:
    """Slot-indexed consensus detectors over the chain-health view:

    - ``finality_stall``      no node's finalized epoch advanced for
                              ``finality_stall_epochs`` consecutive
                              non-excused epochs while head slots moved;
    - ``participation_droop`` the best (most-informed) node saw less
                              than ``participation_floor`` of the stake
                              attest target over a full non-excused
                              epoch;
    - ``split_brain``         the nodes' heads disagreed for more than
                              ``split_brain_slots`` consecutive slots
                              the schedule says are CONNECTED (scheduled
                              windows + post-heal grace are protocol,
                              not divergence);
    - ``reorg_storm``         more than ``reorg_storm_count`` reorgs
                              (across all nodes) inside a
                              ``reorg_storm_window``-slot window,
                              outside windows/grace.

    Findings are shaped exactly like the process watchdog's
    (``kind``/``series``/``detail``/``value`` + ``slot``) so they ride
    the same journal/mission-report pipeline. ``windows`` is the
    scheduled-partition export from sim/net.py: ``[(start, end), ...]``
    in slots."""

    def __init__(self, thresholds: Optional[ChainThresholds] = None,
                 windows: Tuple[Tuple[int, int], ...] = (),
                 slots_per_epoch: int = 8) -> None:
        self.t = thresholds or ChainThresholds.from_env()
        self.windows = tuple((int(a), int(b)) for a, b in windows)
        self.spe = max(1, int(slots_per_epoch))
        self._disagree_streak = 0
        self._frozen_epochs = 0
        self._droop_streak = 0
        self._last_finalized: Optional[int] = None
        self._reorg_slots: Deque[int] = deque()
        self._last_emit_slot: Dict[str, int] = {}
        self.findings_total = 0

    # -- schedule gating ----------------------------------------------------

    def set_windows(self, windows: Tuple[Tuple[int, int], ...]) -> None:
        """Replace the scheduled-partition export (drills plant an
        UNSCHEDULED split by clearing it)."""
        self.windows = tuple((int(a), int(b)) for a, b in windows)

    def excused(self, slot: int) -> bool:
        """Inside a scheduled window, or within the post-heal grace
        (nodes legitimately disagree/under-participate while the held
        mail lands and FFG re-justifies)."""
        for start, end in self.windows:
            if start <= slot <= end + self.t.heal_grace_slots:
                return True
        return False

    def _epoch_excused(self, epoch: int) -> bool:
        lo, hi = epoch * self.spe, (epoch + 1) * self.spe - 1
        return any(self.excused(s) for s in range(lo, hi + 1))

    # -- plumbing -----------------------------------------------------------

    def _cooled(self, kind: str, slot: int) -> bool:
        last = self._last_emit_slot.get(kind)
        if last is not None and slot - last < self.t.cooldown_slots:
            return False
        self._last_emit_slot[kind] = slot
        return True

    def _finding(self, kind: str, series: str, slot: int, detail: str,
                 value: float) -> Optional[Dict[str, Any]]:
        if not self._cooled(kind, slot):
            return None
        self.findings_total += 1
        return {"kind": kind, "series": series, "slot": slot,
                "detail": detail, "value": round(float(value), 3)}

    # -- slot-boundary detectors --------------------------------------------

    def on_slot(self, slot: int, heads: List[str],
                reorgs: int = 0) -> List[Dict[str, Any]]:
        """One top-of-slot observation (post-intake, pre-proposal — the
        point where connected honest nodes agree): per-node head roots
        and the number of reorgs any node recorded this slot."""
        out: List[Dict[str, Any]] = []
        excused = self.excused(slot)

        distinct = len({h for h in heads if h})
        if distinct > 1 and not excused:
            self._disagree_streak += 1
            if self._disagree_streak > self.t.split_brain_slots:
                f = self._finding(
                    "split_brain", "chain.head_slot", slot,
                    f"{distinct} distinct heads across {len(heads)} nodes "
                    f"for {self._disagree_streak} connected slots "
                    f"(> {self.t.split_brain_slots}) with no scheduled "
                    f"partition", float(self._disagree_streak))
                if f:
                    out.append(f)
        else:
            self._disagree_streak = 0

        if reorgs:
            self._reorg_slots.extend([slot] * int(reorgs))
        while self._reorg_slots and \
                self._reorg_slots[0] <= slot - self.t.reorg_storm_window:
            self._reorg_slots.popleft()
        if not excused and len(self._reorg_slots) > self.t.reorg_storm_count:
            f = self._finding(
                "reorg_storm", "chain.reorgs", slot,
                f"{len(self._reorg_slots)} reorgs of depth >= "
                f"{self.t.reorg_storm_min_depth} inside "
                f"{self.t.reorg_storm_window} slots "
                f"(> {self.t.reorg_storm_count})",
                float(len(self._reorg_slots)))
            if f:
                out.append(f)
        return out

    # -- epoch-boundary detectors -------------------------------------------

    def on_epoch(self, epoch: int, slot: int, finalized_epochs: List[int],
                 participation: Optional[float]) -> List[Dict[str, Any]]:
        """One epoch-rollover observation: per-node finalized epochs and
        the best node's previous-epoch target-participation fraction."""
        out: List[Dict[str, Any]] = []
        excused = self._epoch_excused(epoch)
        past_genesis = epoch >= self.t.genesis_grace_epochs

        best_finalized = max(finalized_epochs) if finalized_epochs else 0
        if (self._last_finalized is not None
                and best_finalized <= self._last_finalized
                and past_genesis and not excused):
            self._frozen_epochs += 1
            if self._frozen_epochs > self.t.finality_stall_epochs:
                f = self._finding(
                    "finality_stall", "chain.finalized_epoch", slot,
                    f"finalized epoch frozen at {best_finalized} for "
                    f"{self._frozen_epochs} epochs "
                    f"(> {self.t.finality_stall_epochs}) while the head "
                    f"reached slot {slot}", float(self._frozen_epochs))
                if f:
                    out.append(f)
        elif (self._last_finalized is None
                or best_finalized > self._last_finalized):
            self._frozen_epochs = 0
        self._last_finalized = max(best_finalized,
                                   self._last_finalized or 0)

        # participation reported at rollover E covers epoch E-1 (the
        # completed previous-epoch flags): a window overlapping EITHER
        # epoch excuses the droop, and the streak only counts over
        # consecutive countable epochs
        droop_excused = excused or self._epoch_excused(max(0, epoch - 1))
        if participation is None or droop_excused or not past_genesis:
            pass  # not evidence either way: the streak carries
        elif participation < self.t.participation_floor:
            self._droop_streak += 1
            if self._droop_streak >= self.t.droop_epochs:
                f = self._finding(
                    "participation_droop", "chain.participation_rate", slot,
                    f"target participation {participation:.1%} < "
                    f"{self.t.participation_floor:.1%} for "
                    f"{self._droop_streak} consecutive epochs outside any "
                    f"scheduled partition window", float(participation))
                if f:
                    out.append(f)
        else:
            self._droop_streak = 0
        return out


class Watchdog:
    """Feed every sample via :meth:`check`; returns new findings."""

    def __init__(self, thresholds: Optional[Thresholds] = None,
                 rates: Optional[Tuple[str, ...]] = None,
                 depths: Optional[Tuple[str, ...]] = None) -> None:
        self.t = thresholds or Thresholds.from_env()
        self.rates = rates if rates is not None else _env_list(
            RATES_ENV, DEFAULT_RATES)
        self.depths = depths if depths is not None else _env_list(
            DEPTHS_ENV, DEFAULT_DEPTHS)
        w = max(2, self.t.window)
        self._rss: Deque[Tuple[float, float]] = deque(maxlen=w)
        self._counter_hist: Dict[str, Deque[Tuple[float, float]]] = {}
        self._depth_hist: Dict[str, Deque[Tuple[float, float]]] = {}
        self._last_emit: Dict[Tuple[str, str], float] = {}
        self._last_progress_t: Optional[float] = None
        self._progress_seen = False
        self.findings_total = 0

    # -- helpers -----------------------------------------------------------

    def _cooled(self, kind: str, series: str, now_s: float) -> bool:
        key = (kind, series)
        last = self._last_emit.get(key)
        if last is not None and now_s - last < self.t.cooldown_s:
            return False
        self._last_emit[key] = now_s
        return True

    def _finding(self, kind: str, series: str, now_s: float,
                 detail: str, value: float) -> Optional[Dict[str, Any]]:
        if not self._cooled(kind, series, now_s):
            return None
        self.findings_total += 1
        return {"kind": kind, "series": series, "detail": detail,
                "value": round(value, 3)}

    # -- detectors ---------------------------------------------------------

    def _check_rss(self, now_s: float) -> List[Dict[str, Any]]:
        pts = list(self._rss)
        if len(pts) < self.t.min_samples:
            return []
        growth_mb = (pts[-1][1] - pts[0][1]) / (1 << 20)
        slope_mb_s = _slope(pts) / (1 << 20)
        if (slope_mb_s > self.t.rss_slope_mb_per_s
                and growth_mb > self.t.rss_min_growth_mb):
            f = self._finding(
                "rss_leak", "proc.rss_bytes", now_s,
                f"rss slope {slope_mb_s:.2f} MB/s over "
                f"{pts[-1][0] - pts[0][0]:.1f}s (+{growth_mb:.1f} MB)",
                slope_mb_s)
            return [f] if f else []
        return []

    def _check_drift(self, now_s: float) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for name, hist in self._counter_hist.items():
            pts = list(hist)
            # drift needs a FULL window (short bursts are not evidence)
            # and a recent rate that is decayed-but-nonzero — a counter
            # that stopped entirely is the stall detector's business,
            # and a workload that simply finished must not read as drift
            if len(pts) < max(self.t.min_samples, hist.maxlen or 0):
                continue
            mid = len(pts) // 2
            def _rate(seg: List[Tuple[float, float]]) -> float:
                dt = seg[-1][0] - seg[0][0]
                return (seg[-1][1] - seg[0][1]) / dt if dt > 0 else 0.0
            early, recent = _rate(pts[:mid + 1]), _rate(pts[mid:])
            if (early >= self.t.drift_min_rate
                    and 0 < recent < self.t.drift_drop_frac * early):
                f = self._finding(
                    "throughput_drift", name, now_s,
                    f"{name} {early:.2f}/s -> {recent:.2f}/s "
                    f"({recent / early:.0%} of earlier rate)",
                    recent)
                if f:
                    out.append(f)
        return out

    def _check_depth(self, now_s: float) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for name, hist in self._depth_hist.items():
            pts = list(hist)
            if len(pts) < self.t.min_samples or len(pts) < self._depth_win():
                continue
            growth = pts[-1][1] - pts[0][1]
            steps = len(pts) - 1
            rising = sum(1 for i in range(steps)
                         if pts[i + 1][1] >= pts[i][1])
            if growth >= self.t.depth_min_growth and rising >= 0.9 * steps:
                f = self._finding(
                    "queue_creep", name, now_s,
                    f"{name} {pts[0][1]:.0f} -> {pts[-1][1]:.0f} over "
                    f"{pts[-1][0] - pts[0][0]:.1f}s "
                    f"({rising}/{steps} steps non-decreasing)",
                    growth)
                if f:
                    out.append(f)
        return out

    def _depth_win(self) -> int:
        return max(2, self.t.window)

    def _check_stall(self, now_s: float) -> List[Dict[str, Any]]:
        if not self._progress_seen or self._last_progress_t is None:
            return []
        idle = now_s - self._last_progress_t
        if idle > self.t.stall_s:
            f = self._finding(
                "stall", "progress", now_s,
                f"no watched progress counter moved for {idle:.0f}s "
                f"(watching {', '.join(sorted(self._counter_hist))})",
                idle)
            return [f] if f else []
        return []

    # -- entry point -------------------------------------------------------

    def check(self, now_s: float, counters: Dict[str, float],
              gauges: Dict[str, float]) -> List[Dict[str, Any]]:
        """Absorb one sample (monotonic seconds + the metric snapshot's
        counters/gauges) and return any NEW findings."""
        rss = gauges.get("proc.rss_bytes")
        if rss is not None:
            self._rss.append((now_s, float(rss)))
        moved = False
        for name in self.rates:
            value = counters.get(name)
            if value is None:
                continue
            hist = self._counter_hist.setdefault(
                name, deque(maxlen=max(2, self.t.window)))
            if hist and float(value) > hist[-1][1]:
                moved = True
            elif not hist and float(value) > 0:
                moved = True
            hist.append((now_s, float(value)))
        if moved:
            self._last_progress_t = now_s
            self._progress_seen = True
        elif self._progress_seen and self._last_progress_t is None:
            self._last_progress_t = now_s
        for name in self.depths:
            value = gauges.get(name)
            if value is None:
                continue
            self._depth_hist.setdefault(
                name, deque(maxlen=self._depth_win())).append(
                    (now_s, float(value)))
        findings: List[Dict[str, Any]] = []
        findings += self._check_rss(now_s)
        findings += self._check_drift(now_s)
        findings += self._check_depth(now_s)
        findings += self._check_stall(now_s)
        return findings
