"""Online drift watchdogs over the live telemetry series
(docs/OBSERVABILITY.md "Long-haul telemetry plane").

The timeseries flusher feeds every sample through one
:class:`Watchdog`, which keeps bounded per-series windows and emits
structured findings the moment a long-haul run starts going wrong —
hours before a human would read the journal:

- ``rss_leak``          least-squares slope of ``proc.rss_bytes`` over
                        the window exceeds the configured MB/s AND the
                        absolute growth cleared the noise floor;
- ``throughput_drift``  a watched progress counter's recent rate
                        decayed below ``drift_drop_frac`` of its
                        earlier rate in the same window (slots/s,
                        execs/s, verifies/s decay detection);
- ``queue_creep``       a watched depth gauge grew near-monotonically
                        across the whole window (the metastable-failure
                        precursor the overload plane sheds against);
- ``stall``             no watched progress counter moved for
                        ``stall_s`` while the process stayed alive.

Findings are data, not exceptions: the flusher journals them as
``{"type": "finding", ...}`` lines next to the samples, mirrors each
as an ``obs.instant`` (``watchdog.<kind>``) and a
``watchdog.<kind>`` counter, and the mission report renders them as
anomaly annotations. Every threshold is overridable via
``CONSENSUS_SPECS_TPU_WATCHDOG=k=v[,k=v...]`` (keys = the
:class:`Thresholds` field names); watched series come from
``CONSENSUS_SPECS_TPU_WATCHDOG_RATES`` / ``_DEPTHS`` (comma lists).
A per-(kind, series) cooldown stops a persistent condition from
flooding the journal.
"""
from __future__ import annotations

import os
from collections import deque
from dataclasses import dataclass, fields
from typing import Any, Deque, Dict, List, Optional, Tuple

WATCHDOG_ENV = "CONSENSUS_SPECS_TPU_WATCHDOG"
RATES_ENV = "CONSENSUS_SPECS_TPU_WATCHDOG_RATES"
DEPTHS_ENV = "CONSENSUS_SPECS_TPU_WATCHDOG_DEPTHS"

# progress counters watched by default: the long-running planes' hot
# loops (span.* counters are auto-maintained by obs.metrics.observe, so
# any instrumented site is watchable without new call sites)
DEFAULT_RATES = (
    "sim.blocks_proposed",
    "fuzz.execs",
    "serve.accepted",
    "span.gen.case.count",
)
DEFAULT_DEPTHS = ("serve.queue_depth",)


@dataclass
class Thresholds:
    """Watchdog knobs (env-overridable; documented thresholds in
    docs/OBSERVABILITY.md)."""

    window: int = 30               # samples per detector window
    min_samples: int = 8           # fewer -> detectors stay silent
    rss_slope_mb_per_s: float = 4.0
    rss_min_growth_mb: float = 64.0
    drift_drop_frac: float = 0.5   # recent < 50% of earlier = drift
    drift_min_rate: float = 1.0    # /s floor — idle counters never drift
    stall_s: float = 120.0
    depth_min_growth: float = 64.0
    cooldown_s: float = 60.0

    @classmethod
    def from_env(cls) -> "Thresholds":
        t = cls()
        raw = os.environ.get(WATCHDOG_ENV, "")
        valid = {f.name: f.type for f in fields(cls)}
        for clause in raw.split(","):
            clause = clause.strip()
            if not clause or "=" not in clause:
                continue
            key, _, value = clause.partition("=")
            key = key.strip()
            if key not in valid:
                continue
            try:
                setattr(t, key, int(value) if key in ("window", "min_samples")
                        else float(value))
            except ValueError:
                continue
        return t


def _env_list(env: str, default: Tuple[str, ...]) -> Tuple[str, ...]:
    raw = os.environ.get(env, "")
    if not raw:
        return default
    return tuple(s.strip() for s in raw.split(",") if s.strip())


def _slope(points: List[Tuple[float, float]]) -> float:
    """Least-squares slope of (t, v) points, units of v per second."""
    n = len(points)
    if n < 2:
        return 0.0
    mt = sum(t for t, _ in points) / n
    mv = sum(v for _, v in points) / n
    num = sum((t - mt) * (v - mv) for t, v in points)
    den = sum((t - mt) ** 2 for t, _ in points)
    return num / den if den else 0.0


class Watchdog:
    """Feed every sample via :meth:`check`; returns new findings."""

    def __init__(self, thresholds: Optional[Thresholds] = None,
                 rates: Optional[Tuple[str, ...]] = None,
                 depths: Optional[Tuple[str, ...]] = None) -> None:
        self.t = thresholds or Thresholds.from_env()
        self.rates = rates if rates is not None else _env_list(
            RATES_ENV, DEFAULT_RATES)
        self.depths = depths if depths is not None else _env_list(
            DEPTHS_ENV, DEFAULT_DEPTHS)
        w = max(2, self.t.window)
        self._rss: Deque[Tuple[float, float]] = deque(maxlen=w)
        self._counter_hist: Dict[str, Deque[Tuple[float, float]]] = {}
        self._depth_hist: Dict[str, Deque[Tuple[float, float]]] = {}
        self._last_emit: Dict[Tuple[str, str], float] = {}
        self._last_progress_t: Optional[float] = None
        self._progress_seen = False
        self.findings_total = 0

    # -- helpers -----------------------------------------------------------

    def _cooled(self, kind: str, series: str, now_s: float) -> bool:
        key = (kind, series)
        last = self._last_emit.get(key)
        if last is not None and now_s - last < self.t.cooldown_s:
            return False
        self._last_emit[key] = now_s
        return True

    def _finding(self, kind: str, series: str, now_s: float,
                 detail: str, value: float) -> Optional[Dict[str, Any]]:
        if not self._cooled(kind, series, now_s):
            return None
        self.findings_total += 1
        return {"kind": kind, "series": series, "detail": detail,
                "value": round(value, 3)}

    # -- detectors ---------------------------------------------------------

    def _check_rss(self, now_s: float) -> List[Dict[str, Any]]:
        pts = list(self._rss)
        if len(pts) < self.t.min_samples:
            return []
        growth_mb = (pts[-1][1] - pts[0][1]) / (1 << 20)
        slope_mb_s = _slope(pts) / (1 << 20)
        if (slope_mb_s > self.t.rss_slope_mb_per_s
                and growth_mb > self.t.rss_min_growth_mb):
            f = self._finding(
                "rss_leak", "proc.rss_bytes", now_s,
                f"rss slope {slope_mb_s:.2f} MB/s over "
                f"{pts[-1][0] - pts[0][0]:.1f}s (+{growth_mb:.1f} MB)",
                slope_mb_s)
            return [f] if f else []
        return []

    def _check_drift(self, now_s: float) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for name, hist in self._counter_hist.items():
            pts = list(hist)
            # drift needs a FULL window (short bursts are not evidence)
            # and a recent rate that is decayed-but-nonzero — a counter
            # that stopped entirely is the stall detector's business,
            # and a workload that simply finished must not read as drift
            if len(pts) < max(self.t.min_samples, hist.maxlen or 0):
                continue
            mid = len(pts) // 2
            def _rate(seg: List[Tuple[float, float]]) -> float:
                dt = seg[-1][0] - seg[0][0]
                return (seg[-1][1] - seg[0][1]) / dt if dt > 0 else 0.0
            early, recent = _rate(pts[:mid + 1]), _rate(pts[mid:])
            if (early >= self.t.drift_min_rate
                    and 0 < recent < self.t.drift_drop_frac * early):
                f = self._finding(
                    "throughput_drift", name, now_s,
                    f"{name} {early:.2f}/s -> {recent:.2f}/s "
                    f"({recent / early:.0%} of earlier rate)",
                    recent)
                if f:
                    out.append(f)
        return out

    def _check_depth(self, now_s: float) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for name, hist in self._depth_hist.items():
            pts = list(hist)
            if len(pts) < self.t.min_samples or len(pts) < self._depth_win():
                continue
            growth = pts[-1][1] - pts[0][1]
            steps = len(pts) - 1
            rising = sum(1 for i in range(steps)
                         if pts[i + 1][1] >= pts[i][1])
            if growth >= self.t.depth_min_growth and rising >= 0.9 * steps:
                f = self._finding(
                    "queue_creep", name, now_s,
                    f"{name} {pts[0][1]:.0f} -> {pts[-1][1]:.0f} over "
                    f"{pts[-1][0] - pts[0][0]:.1f}s "
                    f"({rising}/{steps} steps non-decreasing)",
                    growth)
                if f:
                    out.append(f)
        return out

    def _depth_win(self) -> int:
        return max(2, self.t.window)

    def _check_stall(self, now_s: float) -> List[Dict[str, Any]]:
        if not self._progress_seen or self._last_progress_t is None:
            return []
        idle = now_s - self._last_progress_t
        if idle > self.t.stall_s:
            f = self._finding(
                "stall", "progress", now_s,
                f"no watched progress counter moved for {idle:.0f}s "
                f"(watching {', '.join(sorted(self._counter_hist))})",
                idle)
            return [f] if f else []
        return []

    # -- entry point -------------------------------------------------------

    def check(self, now_s: float, counters: Dict[str, float],
              gauges: Dict[str, float]) -> List[Dict[str, Any]]:
        """Absorb one sample (monotonic seconds + the metric snapshot's
        counters/gauges) and return any NEW findings."""
        rss = gauges.get("proc.rss_bytes")
        if rss is not None:
            self._rss.append((now_s, float(rss)))
        moved = False
        for name in self.rates:
            value = counters.get(name)
            if value is None:
                continue
            hist = self._counter_hist.setdefault(
                name, deque(maxlen=max(2, self.t.window)))
            if hist and float(value) > hist[-1][1]:
                moved = True
            elif not hist and float(value) > 0:
                moved = True
            hist.append((now_s, float(value)))
        if moved:
            self._last_progress_t = now_s
            self._progress_seen = True
        elif self._progress_seen and self._last_progress_t is None:
            self._last_progress_t = now_s
        for name in self.depths:
            value = gauges.get(name)
            if value is None:
                continue
            self._depth_hist.setdefault(
                name, deque(maxlen=self._depth_win())).append(
                    (now_s, float(value)))
        findings: List[Dict[str, Any]] = []
        findings += self._check_rss(now_s)
        findings += self._check_drift(now_s)
        findings += self._check_depth(now_s)
        findings += self._check_stall(now_s)
        return findings
