"""Unified tracing + metrics plane (zero-dependency, pure stdlib).

The repo's two hot primitives were accelerated (PR 1) and
fault-isolated (PR 2); this package makes the whole system *visible*:
one span API wired into every plane, one env knob, one merged
Perfetto-loadable trace per run.

- :mod:`core` — ``span()`` context manager + ``traced()`` decorator,
  ``kernel_span()`` (jit compile-vs-execute tagging), ``instant()``,
  structured ``event()`` buffering, and cross-process propagation via
  the ``CONSENSUS_SPECS_TPU_TRACE=<dir>[;trace[;parent]]`` env knob:
  subprocess children (bench sections, the dryrun child, generator
  workers) write their own span JSONL and the parent merges them into
  one tree. Disabled-by-default cost: a single env check per span.
- :mod:`metrics` — thread-safe counters + bounded histograms; span
  durations feed ``span.<name>`` histograms automatically.
- :mod:`export` — per-pid JSONL -> one Chrome trace-event JSON
  (``trace.json``) that Perfetto / ``chrome://tracing`` loads directly,
  with resilience retries/quarantines/chaos hits as instant events on
  the owning span and cross-process flow arrows.
- :mod:`ledger` — the perf evidence ledger: crash-safe append-only
  JSONL time series of every bench/perfgate datapoint (git sha,
  backend, environment fingerprint; degraded runs as first-class
  host-only datapoints).
- :mod:`sentinel` — noise-aware regression verdicts over the ledger
  (rolling median+MAD baselines; resilience-taxonomy classification so
  environment gaps never read as regressions). ``make perfgate`` gates
  CI on them.
- :mod:`timeseries` / :mod:`proc` / :mod:`profile` / :mod:`watchdog` —
  the long-haul telemetry plane (``CONSENSUS_SPECS_TPU_LONGHAUL``
  knob): fsync'd per-process time-series journals of the metric
  registry + ``/proc/self`` resource gauges, an armable collapsed-stack
  sampling profiler, and online drift watchdogs (RSS leak slope,
  throughput decay, queue creep, stalls) whose findings land in the
  journal and the trace. ``tools/mission_report.py`` merges a whole
  run into one mission-control HTML report.
- :mod:`chain` — the consensus health plane
  (``CONSENSUS_SPECS_TPU_CHAIN_HEALTH`` knob, armed by default):
  chain-level gauges (per-node head/finality/participation/forks),
  consensus watchdogs (finality_stall, participation_droop,
  split_brain, reorg_storm — excused inside scheduled partition
  windows), per-node fork-choice intake black boxes, and forensic
  bundles written the moment the chain looks sick.
  ``tools/chain_report.py`` renders a run's chain timeline.

Instrumented planes: bls facade dispatch + oracle adjudication, engine
``dispatch_delta_kernel`` + every vectorized epoch stage, the ssz
hashing backend, gen_runner per-case (journal resume marked),
replay_vectors per-case, bench.py sections, and the multichip dryrun
parent/child. ``tools/trace_report.py`` summarizes a trace; ``make
trace`` runs an instrumented smoke end-to-end.

See docs/OBSERVABILITY.md.
"""
from __future__ import annotations

from .core import (  # noqa: F401
    TRACE_ENV,
    Span,
    child_env,
    current_span,
    current_span_id,
    emit_span,
    enabled,
    event,
    events,
    events_dropped,
    fork_child_reinit,
    instant,
    is_root_process,
    kernel_span,
    mono_to_us,
    parse_traceparent,
    remote_span,
    span,
    trace_dir,
    traced,
    traceparent,
)
from .export import (  # noqa: F401
    export_chrome,
    load_records,
    read_records,
    records_from_chrome,
    to_chrome,
    validate_chrome,
)
from .metrics import (  # noqa: F401
    count,
    gauge,
    observe,
    prometheus_text,
    publish,
    snapshot,
)
from . import ledger, sentinel  # noqa: F401  (perf evidence plane)
from . import flightrec, slo  # noqa: F401  (request observability plane)
from . import proc, profile, timeseries, watchdog  # noqa: F401  (long-haul plane)
from . import chain  # noqa: F401  (consensus health plane)
