"""Regression sentinel: noise-aware verdicts over the perf ledger.

For each metric the sentinel computes a rolling baseline — median +
MAD (median absolute deviation) over the last K *comparable* runs
(same metric, same backend) — and classifies a new datapoint:

- ``improved`` / ``regressed``: the deviation from the baseline median
  exceeds the noise envelope, in the metric's good/bad direction;
- ``stable``: inside the envelope;
- ``no_baseline``: fewer than ``min_history`` comparable points exist
  (the point is recorded; the gate never fails on a cold ledger);
- ``environmental``: the run's environment explains the gap — e.g. a
  ``device_unreachable`` run cannot produce the jax-backend series, so
  the missing/host-substituted datapoint is an environment gap, not a
  regression. The verdict carries the resilience taxonomy kind
  (:data:`~consensus_specs_tpu.resilience.taxonomy.ENVIRONMENTAL`),
  exactly like a quarantined backend: recorded, visible, non-fatal.

The noise envelope is ``max(rel_threshold * |median|,
mad_k * 1.4826 * MAD)``: the MAD term adapts to each metric's observed
jitter (1.4826 scales MAD to a Gaussian sigma), the relative floor
keeps near-constant series from flagging on micro-jitter.

Directionality: metrics ending in ``_s``/``_ms``/``_us``/``_seconds``
are lower-is-better (durations); everything else (rates, MiB/s,
speedups) is higher-is-better.

Gate contract (``tools/perfgate.py``): FAIL iff any verdict is
``regressed`` — whose taxonomy kind is deterministic (same code, same
inputs, slower result = a defect). ``environmental`` and
``no_baseline`` never fail the gate.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

from ..resilience.taxonomy import DETERMINISTIC, ENVIRONMENTAL

IMPROVED = "improved"
STABLE = "stable"
REGRESSED = "regressed"
NO_BASELINE = "no_baseline"
ENV_GAP = "environmental"

_LOWER_IS_BETTER_SUFFIXES = ("_s", "_ms", "_us", "_seconds",
                             "_overhead_pct",
                             # chain-health lag series: a convergence lag
                             # of 9 slots or a finality lag of 5 epochs
                             # growing is the chain getting SICKER —
                             # lower is better (obs.ledger.infer_unit
                             # makes the same _lag_slots/_epochs
                             # carve-out)
                             "_lag_slots", "_slots", "_epochs")

# rate metrics end in "_per_s", which ALSO ends in "_s": rates are
# higher-is-better and must be carved out before the duration suffixes
# (the ledger's infer_unit makes the same distinction — a regression
# here silently inverted the gate for any *_per_s metric, first
# surfaced by perfgate_fuzz_execs_per_s's chaos drill)
_RATE_MARKERS = ("per_s", "per_sec", "_rate")

# MAD -> sigma for normally-distributed noise
_MAD_SIGMA = 1.4826


@dataclass
class Policy:
    """Sentinel thresholds (documented in docs/OBSERVABILITY.md)."""

    window: int = 8          # last K comparable points form the baseline
    min_history: int = 3     # fewer -> no_baseline
    rel_threshold: float = 0.25   # 25% relative floor on the envelope
    mad_k: float = 4.0       # envelope half-width in MAD-sigmas


DEFAULT_POLICY = Policy()


def polarity(metric: str) -> int:
    """+1 when higher is better (rates, speedups), -1 for durations."""
    if any(marker in metric for marker in _RATE_MARKERS):
        return 1
    return -1 if metric.endswith(_LOWER_IS_BETTER_SUFFIXES) else 1


def median(values: Sequence[float]) -> float:
    ordered = sorted(values)
    n = len(ordered)
    mid = n // 2
    if n % 2:
        return float(ordered[mid])
    return (ordered[mid - 1] + ordered[mid]) / 2.0


def baseline(values: Sequence[float]) -> Dict[str, float]:
    """Rolling-baseline stats: median + MAD over the given window."""
    med = median(values)
    mad = median([abs(v - med) for v in values])
    return {"median": med, "mad": mad, "n": float(len(values))}


@dataclass
class Verdict:
    metric: str
    verdict: str
    value: Optional[float] = None
    backend: Optional[str] = None
    baseline_median: Optional[float] = None
    baseline_mad: Optional[float] = None
    baseline_n: int = 0
    deviation_pct: Optional[float] = None
    kind: Optional[str] = None   # resilience taxonomy class, when at fault
    detail: str = ""

    def to_dict(self) -> Dict[str, Any]:
        out = {k: v for k, v in self.__dict__.items() if v is not None and v != ""}
        return out


def classify_point(
    metric: str,
    value: float,
    history: Sequence[float],
    policy: Policy = DEFAULT_POLICY,
) -> Verdict:
    """Verdict for one datapoint against its comparable history."""
    window = list(history)[-policy.window:]
    if len(window) < policy.min_history:
        return Verdict(metric=metric, verdict=NO_BASELINE, value=value,
                       baseline_n=len(window),
                       detail=f"{len(window)} comparable point(s), "
                              f"need {policy.min_history}")
    stats = baseline(window)
    med, mad = stats["median"], stats["mad"]
    envelope = max(policy.rel_threshold * abs(med),
                   policy.mad_k * _MAD_SIGMA * mad)
    deviation = value - med
    dev_pct = (100.0 * deviation / med) if med else None
    common = dict(value=value, baseline_median=med, baseline_mad=mad,
                  baseline_n=len(window), deviation_pct=dev_pct)
    if abs(deviation) <= envelope or envelope == 0:
        return Verdict(metric=metric, verdict=STABLE, **common)
    good = deviation * polarity(metric) > 0
    if good:
        return Verdict(metric=metric, verdict=IMPROVED, **common)
    return Verdict(
        metric=metric, verdict=REGRESSED, kind=DETERMINISTIC,
        detail=f"beyond noise envelope ±{envelope:.4g} around median {med:.4g}",
        **common)


@dataclass
class Report:
    verdicts: List[Verdict] = field(default_factory=list)
    ok: bool = True

    @property
    def regressed(self) -> List[Verdict]:
        return [v for v in self.verdicts if v.verdict == REGRESSED]

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for v in self.verdicts:
            out[v.verdict] = out.get(v.verdict, 0) + 1
        return out

    def to_dict(self) -> Dict[str, Any]:
        return {"ok": self.ok, "counts": self.counts(),
                "verdicts": [v.to_dict() for v in self.verdicts]}


def evaluate_run(
    history_points: Sequence[Dict[str, Any]],
    current_points: Sequence[Dict[str, Any]],
    *,
    run_environment: Optional[Dict[str, Any]] = None,
    policy: Policy = DEFAULT_POLICY,
) -> Report:
    """Classify every datapoint of one run against ledger history.

    ``history_points`` / ``current_points`` are ledger point dicts
    (``metric``/``value``/``backend``; see obs/ledger.py). Comparability
    = same metric AND same backend: a host-only fallback value is never
    judged against a jax-backend baseline.

    When ``run_environment`` marks the run degraded (device unreachable
    or compile failed), any metric whose established baseline lives on a
    backend this run could not exercise gets an ``environmental``
    verdict instead of silently vanishing — the r05 case, rendered as a
    first-class environment gap.
    """
    env = run_environment or {}
    degraded = bool(env.get("device_unreachable") or env.get("device_compile_failed"))
    report = Report()

    series: Dict[tuple, List[float]] = {}
    for p in history_points:
        m, b = p.get("metric"), p.get("backend")
        if m is None or not isinstance(p.get("value"), (int, float)):
            continue
        series.setdefault((m, b), []).append(float(p["value"]))

    current_by_key = {}
    for p in current_points:
        m, b = p.get("metric"), p.get("backend")
        if m is None or not isinstance(p.get("value"), (int, float)):
            continue
        current_by_key[(m, b)] = float(p["value"])

    for (m, b), value in sorted(current_by_key.items()):
        report.verdicts.append(
            classify_point(m, value, series.get((m, b), []), policy))
        report.verdicts[-1].backend = b

    if degraded:
        # baselines this run could not exercise: environment gap verdicts
        reason = ("device unreachable" if env.get("device_unreachable")
                  else "device compile failed")
        for (m, b), values in sorted(series.items()):
            if b == "host" or len(values) < policy.min_history:
                continue
            if (m, b) in current_by_key:
                continue
            report.verdicts.append(Verdict(
                metric=m, verdict=ENV_GAP, backend=b, kind=ENVIRONMENTAL,
                baseline_median=median(values[-policy.window:]),
                baseline_n=len(values[-policy.window:]),
                detail=f"{reason}: no {b}-backend datapoint this run "
                       f"(recorded as an environment gap, not a regression)"))

    report.ok = not report.regressed
    return report


def evaluate_ledger(
    ledger: Any,
    run_id: Optional[str] = None,
    policy: Policy = DEFAULT_POLICY,
) -> Report:
    """Evaluate one run already in the ledger (default: the latest run)
    against everything recorded before it."""
    runs = ledger.runs()
    if not runs:
        return Report()
    if run_id is None:
        run_id = runs[-1].get("run_id")
    run = next((r for r in runs if r.get("run_id") == run_id), None)
    points = ledger.points()
    current = [p for p in points if p.get("run_id") == run_id]
    run_ts = run.get("ts") if run else None
    history = [p for p in points if p.get("run_id") != run_id
               and (run_ts is None or (p.get("ts") or 0) <= run_ts)]
    return evaluate_run(
        history, current,
        run_environment=(run or {}).get("environment"), policy=policy)
