"""Flight recorder: a bounded in-process ring buffer of the last N
completed serve requests, so a latency spike is diagnosable *after the
fact* without having had tracing armed.

Tracing answers "what happened inside this request" but costs an env
knob armed ahead of time; the always-on metrics answer "how is the
fleet doing" but aggregate away the individual request. The recorder
is the missing middle: every completed wire request leaves one small
record (trace id, method, queue-wait / flush / total ms, cache hits,
degradation, bucket shape, outcome) in a fixed-size ring — the black
box an operator reads via ``GET /debug/requests`` / ``/debug/slowest``
on the daemon, on ``SIGUSR2``, or in the drain dump.

Threading model: the daemon's handler threads each carry at most one
in-flight request, so the recorder keeps the *open* entry in a
thread-local (:func:`begin` / :func:`note` / :func:`commit`) and only
the commit touches the shared ring (one lock, one deque append). Code
that learns something about the request mid-flight — the batcher's
submit path knows the queue wait and bucket shape after its future
resolves — calls :func:`note` from the handler thread and the fields
merge into that request's record.

The ring is process-global (like the metrics aggregates): one daemon
per process is the deployment shape, and an in-process test daemon
sharing the ring is a feature (the drill reads what the daemon wrote).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

DEFAULT_CAPACITY = 256

# fields small enough to keep per request; anything else is the trace's job
_FIELD_CAP = 200


class FlightRecorder:
    """Bounded ring of completed-request records (thread-safe)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY) -> None:
        self.capacity = max(1, int(capacity))
        self._ring: "deque[Dict[str, Any]]" = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._seq = 0
        self.recorded = 0  # total ever committed (ring only keeps the tail)

    # -- the in-flight entry (handler-thread-local) --------------------

    def begin(self, method: str, trace: Optional[str] = None,
              span: Optional[str] = None) -> Dict[str, Any]:
        """Open this thread's in-flight record. Returns the entry dict
        (callers may mutate it directly; :func:`note` is the convenience
        for code that doesn't hold a reference)."""
        entry: Dict[str, Any] = {
            "method": method,
            "trace": trace,
            "span": span,
            "t_wall": round(time.time(), 3),
            "_t0": time.monotonic(),
        }
        self._tls.entry = entry
        return entry

    def note(self, **fields: Any) -> None:
        """Merge fields into this thread's in-flight record (no-op when
        no request is open on the thread — e.g. a direct batcher user)."""
        entry = getattr(self._tls, "entry", None)
        if entry is None:
            return
        for k, v in fields.items():
            if isinstance(v, str):
                v = v[:_FIELD_CAP]
            entry[k] = v

    def commit(self, status: str = "ok",
               error: Optional[str] = None) -> Optional[Dict[str, Any]]:
        """Close this thread's in-flight record into the ring. Returns
        the committed record (None when no request was open)."""
        entry = getattr(self._tls, "entry", None)
        if entry is None:
            return None
        self._tls.entry = None
        entry["total_ms"] = round(
            (time.monotonic() - entry.pop("_t0")) * 1e3, 3)
        entry["status"] = status
        if error:
            entry["error"] = str(error)[:_FIELD_CAP]
        with self._lock:
            self._seq += 1
            entry["seq"] = self._seq
            self.recorded += 1
            self._ring.append(entry)
        return entry

    # -- reads ---------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def requests(self, n: Optional[int] = None,
                 trace: Optional[str] = None) -> List[Dict[str, Any]]:
        """The most recent completed requests, newest first, optionally
        filtered by trace id."""
        with self._lock:
            entries = list(self._ring)
        entries.reverse()
        if trace is not None:
            entries = [e for e in entries if e.get("trace") == trace]
        return entries[: n if n is not None else self.capacity]

    def slowest(self, n: int = 10) -> List[Dict[str, Any]]:
        """The slowest recorded requests by total ms, slowest first.

        Shed requests (``shed_deadline`` / ``shed_priority`` — an
        expired-deadline entry may have sat in the queue for its whole
        budget by design) are excluded: the ranking answers "which
        *served* requests were slow", not "which were load-managed".
        They remain visible in :meth:`requests` and the dump."""
        with self._lock:
            entries = [e for e in self._ring
                       if not str(e.get("status", "")).startswith("shed")]
        entries.sort(key=lambda e: e.get("total_ms") or 0.0, reverse=True)
        return entries[:max(0, n)]

    def dump(self, n: int = 32) -> Dict[str, Any]:
        """A JSON-able snapshot for the SIGUSR2 / drain dump."""
        return {
            "capacity": self.capacity,
            "recorded": self.recorded,
            "buffered": len(self),
            "slowest": self.slowest(min(n, 10)),
            "recent": self.requests(n),
        }

    def clear(self) -> None:
        """Test hook: drop the ring and any in-flight entry."""
        with self._lock:
            self._ring.clear()
            self._seq = 0
            self.recorded = 0
        self._tls.entry = None


# the process-wide recorder the serving plane writes to
RECORDER = FlightRecorder()


def begin(method: str, trace: Optional[str] = None,
          span: Optional[str] = None) -> Dict[str, Any]:
    return RECORDER.begin(method, trace=trace, span=span)


def note(**fields: Any) -> None:
    RECORDER.note(**fields)


def commit(status: str = "ok",
           error: Optional[str] = None) -> Optional[Dict[str, Any]]:
    return RECORDER.commit(status=status, error=error)


def requests(n: Optional[int] = None,
             trace: Optional[str] = None) -> List[Dict[str, Any]]:
    return RECORDER.requests(n=n, trace=trace)


def slowest(n: int = 10) -> List[Dict[str, Any]]:
    return RECORDER.slowest(n)


def dump(n: int = 32) -> Dict[str, Any]:
    return RECORDER.dump(n)
