"""Counters, gauges, and histograms: thread-safe in-process aggregates.

These are always live (no env gate — a dict update is cheaper than the
question of whether to do it), queryable via :func:`snapshot`, and
flushed into the trace as Chrome counter events by :func:`publish`
when tracing is armed. Span durations feed the ``span.<name>``
histograms automatically (obs.core.Span.__exit__), so per-site latency
distributions exist without any extra call sites.
"""
from __future__ import annotations

import math
import re
import threading
from typing import Any, Dict, List, Optional

_lock = threading.Lock()
_counters: Dict[str, float] = {}
_histograms: Dict[str, List[float]] = {}
_hist_dropped: Dict[str, int] = {}
_gauges: Dict[str, float] = {}
# metric HELP texts (prometheus exposition metadata). Registered once
# per process (metrics.describe); deliberately NOT cleared by reset() —
# descriptions are schema, not samples.
_descriptions: Dict[str, str] = {}

_HIST_CAP = 4096  # per-name sample bound (reservoir-free: drop the tail)


def count(name: str, n: float = 1) -> None:
    """Increment a monotonic counter."""
    with _lock:
        _counters[name] = _counters.get(name, 0) + n


def observe(name: str, value: float) -> None:
    """Record one sample into a histogram (bounded; extra samples still
    bump the count so rates stay truthful, and the dropped tail is
    COUNTED per histogram — long-haul runs saturate the window fast and
    a silent drop would misrepresent every later percentile)."""
    with _lock:
        hist = _histograms.setdefault(name, [])
        if len(hist) < _HIST_CAP:
            hist.append(value)
        else:
            _hist_dropped[name] = _hist_dropped.get(name, 0) + 1
        _counters[name + ".count"] = _counters.get(name + ".count", 0) + 1


def gauge(name: str, value: float) -> None:
    """Set a point-in-time gauge (last write wins). The long-haul proc
    sampler publishes ``proc.*`` through here each tick."""
    with _lock:
        _gauges[name] = float(value)


def gauges() -> Dict[str, float]:
    """Current gauge values (a copy)."""
    with _lock:
        return dict(_gauges)


def describe(name: str, help_text: str) -> None:
    """Register a HELP text for a metric (prometheus exposition
    metadata): :func:`prometheus_text` emits ``# HELP`` lines for
    described metrics so scraped/aggregated expositions stay
    self-documenting. Registration is idempotent (last write wins) and
    survives :func:`reset` — descriptions are schema, not samples."""
    with _lock:
        _descriptions[name] = str(help_text)


def describe_many(helps: Dict[str, str]) -> None:
    """Bulk :func:`describe` (the chain-health family registers ~a dozen
    series at arm time)."""
    with _lock:
        _descriptions.update({k: str(v) for k, v in helps.items()})


def description(name: str) -> Optional[str]:
    with _lock:
        return _descriptions.get(name)


def counters() -> Dict[str, float]:
    """Current counter values (a copy) — the cheap view the long-haul
    flusher reads every tick (no histogram sorting)."""
    with _lock:
        return dict(_counters)


def hist_summaries(
    cache: Dict[str, Any],
) -> Dict[str, Dict[str, Any]]:
    """``{name: {count, p50, p99, dropped}}`` per histogram, with a
    caller-held cache keyed on the unbounded ``count``: a histogram
    that saw no new observation since the caller's last call reuses its
    cached summary instead of re-copying and re-sorting the bounded
    window. The long-haul flusher samples sub-second — without the
    cache, every tick re-sorted EVERY histogram in the registry, which
    was the armed plane's dominant overhead on a loaded process
    (perfgate_obs_overhead_pct watches this)."""
    with _lock:
        counts = {
            name: int(_counters.get(name + ".count", len(vals)))
            for name, vals in _histograms.items() if vals
        }
        stale = [name for name, n in counts.items()
                 if cache.get(name, (None, None))[0] != n]
        windows = {name: list(_histograms[name]) for name in stale}
        dropped = {name: int(_hist_dropped.get(name, 0)) for name in counts}
    out: Dict[str, Dict[str, Any]] = {}
    for name, n in counts.items():
        if name not in windows:
            out[name] = cache[name][1]
            continue
        ordered = sorted(windows[name])
        summary = {"count": n, "p50": percentile(ordered, 50),
                   "p99": percentile(ordered, 99),
                   "dropped": dropped[name]}
        cache[name] = (n, summary)
        out[name] = summary
    return out


def percentile(samples: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile (q in [0, 100]) of a sample list.

    Edge contract (unit-tested directly): empty input -> None; a single
    sample is every percentile of itself; q <= 0 -> min, q >= 100 ->
    max; otherwise the classic nearest-rank definition
    ``ordered[ceil(q/100 * n) - 1]`` (the old implementation used a
    rounded linear-interpolation index, whose banker's rounding could
    pick the rank BELOW the nearest-rank answer)."""
    if not samples:
        return None
    ordered = sorted(samples)
    if q <= 0:
        return ordered[0]
    if q >= 100:
        return ordered[-1]
    rank = math.ceil(q / 100.0 * len(ordered))  # 1-based nearest rank
    return ordered[max(0, rank - 1)]


# cumulative-bucket ladder for the Prometheus histogram exposition: the
# repo's histograms are millisecond latencies (span.* / serve.*_ms), so
# a log-ish ladder from 100µs to 10s covers queue waits through cold
# compiles; observations outside land in +Inf like any prom histogram
DEFAULT_BUCKETS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
                   100.0, 250.0, 500.0, 1000.0, 2500.0, 10000.0)


def snapshot(clear: bool = False) -> Dict[str, Any]:
    """{counters: {...}, gauges: {...}, histograms: {name: {count,min,
    p50,p90,p99,max,sum,samples,dropped,buckets}}} — ``buckets`` are
    CUMULATIVE counts per ``le`` bound over the bounded sample window
    (``samples`` many; ``count`` keeps the unbounded total so rates
    stay truthful; ``dropped`` counts samples the bounded window
    refused, so long-haul percentiles are honest about their basis)."""
    with _lock:
        counters = dict(_counters)
        gauge_vals = dict(_gauges)
        hists = {name: list(vals) for name, vals in _histograms.items()}
        dropped = dict(_hist_dropped)
        if clear:
            _counters.clear()
            _histograms.clear()
            _hist_dropped.clear()
            _gauges.clear()
    out_h = {}
    for name, vals in hists.items():
        if not vals:
            continue
        ordered = sorted(vals)
        buckets = []
        i = 0
        for bound in DEFAULT_BUCKETS:
            while i < len(ordered) and ordered[i] <= bound:
                i += 1
            buckets.append((bound, i))
        out_h[name] = {
            "count": int(counters.get(name + ".count", len(vals))),
            "min": ordered[0],
            "p50": percentile(ordered, 50),
            "p90": percentile(ordered, 90),
            "p99": percentile(ordered, 99),
            "max": ordered[-1],
            "sum": sum(ordered),
            "samples": len(ordered),
            "dropped": int(dropped.get(name, 0)),
            "buckets": buckets,
        }
    return {"counters": counters, "gauges": gauge_vals, "histograms": out_h}


def publish() -> None:
    """Write current counter values into the trace as a counter record
    (rendered as a Chrome 'C' event by the exporter). No-op when
    tracing is off."""
    from . import core

    ctx = core._context()
    if ctx is None:
        return
    with _lock:
        values = {k: v for k, v in _counters.items()}
    if not values:
        return
    ctx.write({
        "type": "counter",
        "trace": ctx.trace_id,
        "name": "obs.counters",
        "ts": ctx.now_us(),
        "pid": ctx.pid,
        "values": values,
    })


_PROM_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    """A legal Prometheus metric name: invalid chars become ``_`` and a
    leading digit gets an underscore prefix."""
    out = _PROM_NAME_RE.sub("_", name)
    if not out or out[0].isdigit():
        out = "_" + out
    return out


def _help_line(pname: str, name: str) -> List[str]:
    """The ``# HELP`` line for a described metric (prometheus text
    format: backslashes and newlines escaped), or nothing."""
    with _lock:
        text = _descriptions.get(name)
    if not text:
        return []
    escaped = text.replace("\\", "\\\\").replace("\n", "\\n")
    return [f"# HELP {pname} {escaped}"]


def prometheus_text(snap: Optional[Dict[str, Any]] = None) -> str:
    """Prometheus text-format exposition of :func:`snapshot`.

    Counters render as ``counter`` series; histograms as ``summary``
    series (p50/p90/p99 quantile labels + ``_count``) plus ``_min`` /
    ``_max`` gauges. The auto-maintained ``<hist>.count`` counters are
    folded into their histogram's ``_count`` line rather than emitted
    twice under a colliding name.

    Each histogram ALSO exposes a true Prometheus histogram family
    ``<name>_hist`` — cumulative ``_bucket{le="..."}`` lines over
    :data:`DEFAULT_BUCKETS` (+Inf == ``_count``), ``_sum`` and
    ``_count`` — because quantile summaries cannot be aggregated across
    scrapes/instances while buckets can (the standard histogram_quantile
    path). A separate family name keeps promtool's one-TYPE-per-family
    rule intact next to the summary. Bucket counts cover the bounded
    sample window (the summary's ``_count`` stays unbounded).
    """
    if snap is None:
        snap = snapshot()
    counters: Dict[str, float] = snap.get("counters", {})
    gauge_vals: Dict[str, float] = snap.get("gauges", {})
    hists: Dict[str, Dict[str, Any]] = snap.get("histograms", {})
    lines: List[str] = []
    hist_count_keys = {name + ".count" for name in hists}
    for name in sorted(counters):
        if name in hist_count_keys:
            continue
        pname = _prom_name(name)
        lines.extend(_help_line(pname, name))
        lines.append(f"# TYPE {pname} counter")
        lines.append(f"{pname} {counters[name]:g}")
    for name in sorted(gauge_vals):
        pname = _prom_name(name)
        lines.extend(_help_line(pname, name))
        lines.append(f"# TYPE {pname} gauge")
        lines.append(f"{pname} {gauge_vals[name]:g}")
    for name in sorted(hists):
        h = hists[name]
        pname = _prom_name(name)
        lines.extend(_help_line(pname, name))
        lines.append(f"# TYPE {pname} summary")
        for q_label, key in (("0.5", "p50"), ("0.9", "p90"), ("0.99", "p99")):
            if h.get(key) is not None:
                lines.append(f'{pname}{{quantile="{q_label}"}} {h[key]:g}')
        lines.append(f"{pname}_count {h.get('count', 0):g}")
        # the bounded window's refusals: a scrape consumer can tell a
        # long-haul histogram's percentiles cover `samples`, not `count`
        lines.append(f"# TYPE {pname}_dropped counter")
        lines.append(f"{pname}_dropped {h.get('dropped', 0):g}")
        for suffix in ("min", "max"):
            if h.get(suffix) is not None:
                lines.append(f"# TYPE {pname}_{suffix} gauge")
                lines.append(f"{pname}_{suffix} {h[suffix]:g}")
        if h.get("buckets"):
            lines.append(f"# TYPE {pname}_hist histogram")
            for bound, cum in h["buckets"]:
                lines.append(f'{pname}_hist_bucket{{le="{bound:g}"}} {cum:g}')
            samples = h.get("samples", h.get("count", 0))
            lines.append(f'{pname}_hist_bucket{{le="+Inf"}} {samples:g}')
            lines.append(f"{pname}_hist_sum {h.get('sum', 0):.10g}")
            lines.append(f"{pname}_hist_count {samples:g}")
    return "\n".join(lines) + ("\n" if lines else "")


def parse_prometheus(text: str) -> Dict[str, float]:
    """Prometheus text exposition -> ``{series-line-key: value}``.
    The key is the full series identity (name incl. any ``{labels}``),
    so two expositions aggregate line-for-line. Comment/TYPE lines and
    unparseable values are skipped."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        key, _, value = line.rpartition(" ")
        if not key:
            continue
        try:
            out[key] = float(value)
        except ValueError:
            continue
    return out


def parse_prometheus_types(text: str) -> Dict[str, str]:
    """``{family-name: type}`` from an exposition's ``# TYPE`` lines
    (the promtool metadata :func:`aggregate_prometheus` keys its
    per-family rollup rules on). HELP and other comments are ignored."""
    out: Dict[str, str] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("# TYPE "):
            continue
        rest = line[len("# TYPE "):]
        family, _, ftype = rest.rpartition(" ")
        if family and ftype:
            out[family] = ftype
    return out


# gauge families that are LEVELS of a shared external quantity (a chain
# position, an epoch number, a rate of one chain) rather than per-replica
# load: summing them across a fleet is meaningless, the rollup wants the
# most-advanced view. Keyed by suffix; depth/level gauges like
# serve_queue_depth keep summing (total queued work IS the fleet's sum).
_LEVEL_GAUGE_SUFFIXES = ("_slot", "_epoch", "_epochs", "_rate",
                         "_lag_slots", "_partitioned")


def aggregate_prometheus(texts: List[str]) -> Dict[str, float]:
    """Fleet-level /metrics rollup (docs/SERVE.md "Fleet"): counters,
    histogram ``_bucket``/``_sum``/``_count`` series, and load gauges SUM
    across replicas; percentile/quantile summary gauges (``_p50`` etc.)
    take the MAX instead — a fleet's pessimistic tail, since summing
    per-replica percentiles is meaningless. Gauge families (per the
    exposition's own ``# TYPE`` lines) whose name marks them as a LEVEL
    of one shared chain (``*_slot``/``*_epoch(s)``/``*_rate``/
    ``*_lag_slots`` — the chain-health family) also MAX: N replicas
    observing one chain at head slot 640 roll up to 640, not 640·N."""
    out: Dict[str, float] = {}
    quantile = re.compile(r"_p\d+(\{|$)|quantile=")
    for text in texts:
        level_gauges = {family
                        for family, ftype in parse_prometheus_types(text).items()
                        if ftype == "gauge"
                        and family.endswith(_LEVEL_GAUGE_SUFFIXES)}
        for key, value in parse_prometheus(text).items():
            family = key.partition("{")[0]
            if quantile.search(key) or family in level_gauges:
                out[key] = max(out.get(key, value), value)
            else:
                out[key] = out.get(key, 0.0) + value
    return out


def reset() -> None:
    """Test hook: drop all aggregates."""
    with _lock:
        _counters.clear()
        _histograms.clear()
        _hist_dropped.clear()
        _gauges.clear()
