"""Counters and histograms: thread-safe in-process aggregates.

These are always live (no env gate — a dict update is cheaper than the
question of whether to do it), queryable via :func:`snapshot`, and
flushed into the trace as Chrome counter events by :func:`publish`
when tracing is armed. Span durations feed the ``span.<name>``
histograms automatically (obs.core.Span.__exit__), so per-site latency
distributions exist without any extra call sites.
"""
from __future__ import annotations

import math
import re
import threading
from typing import Any, Dict, List, Optional

_lock = threading.Lock()
_counters: Dict[str, float] = {}
_histograms: Dict[str, List[float]] = {}

_HIST_CAP = 4096  # per-name sample bound (reservoir-free: drop the tail)


def count(name: str, n: float = 1) -> None:
    """Increment a monotonic counter."""
    with _lock:
        _counters[name] = _counters.get(name, 0) + n


def observe(name: str, value: float) -> None:
    """Record one sample into a histogram (bounded; extra samples still
    bump the count so rates stay truthful)."""
    with _lock:
        hist = _histograms.setdefault(name, [])
        if len(hist) < _HIST_CAP:
            hist.append(value)
        _counters[name + ".count"] = _counters.get(name + ".count", 0) + 1


def percentile(samples: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile (q in [0, 100]) of a sample list.

    Edge contract (unit-tested directly): empty input -> None; a single
    sample is every percentile of itself; q <= 0 -> min, q >= 100 ->
    max; otherwise the classic nearest-rank definition
    ``ordered[ceil(q/100 * n) - 1]`` (the old implementation used a
    rounded linear-interpolation index, whose banker's rounding could
    pick the rank BELOW the nearest-rank answer)."""
    if not samples:
        return None
    ordered = sorted(samples)
    if q <= 0:
        return ordered[0]
    if q >= 100:
        return ordered[-1]
    rank = math.ceil(q / 100.0 * len(ordered))  # 1-based nearest rank
    return ordered[max(0, rank - 1)]


# cumulative-bucket ladder for the Prometheus histogram exposition: the
# repo's histograms are millisecond latencies (span.* / serve.*_ms), so
# a log-ish ladder from 100µs to 10s covers queue waits through cold
# compiles; observations outside land in +Inf like any prom histogram
DEFAULT_BUCKETS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
                   100.0, 250.0, 500.0, 1000.0, 2500.0, 10000.0)


def snapshot(clear: bool = False) -> Dict[str, Any]:
    """{counters: {...}, histograms: {name: {count,min,p50,p90,p99,max,
    sum,samples,buckets}}} — ``buckets`` are CUMULATIVE counts per
    ``le`` bound over the bounded sample window (``samples`` many;
    ``count`` keeps the unbounded total so rates stay truthful)."""
    with _lock:
        counters = dict(_counters)
        hists = {name: list(vals) for name, vals in _histograms.items()}
        if clear:
            _counters.clear()
            _histograms.clear()
    out_h = {}
    for name, vals in hists.items():
        if not vals:
            continue
        ordered = sorted(vals)
        buckets = []
        i = 0
        for bound in DEFAULT_BUCKETS:
            while i < len(ordered) and ordered[i] <= bound:
                i += 1
            buckets.append((bound, i))
        out_h[name] = {
            "count": int(counters.get(name + ".count", len(vals))),
            "min": ordered[0],
            "p50": percentile(ordered, 50),
            "p90": percentile(ordered, 90),
            "p99": percentile(ordered, 99),
            "max": ordered[-1],
            "sum": sum(ordered),
            "samples": len(ordered),
            "buckets": buckets,
        }
    return {"counters": counters, "histograms": out_h}


def publish() -> None:
    """Write current counter values into the trace as a counter record
    (rendered as a Chrome 'C' event by the exporter). No-op when
    tracing is off."""
    from . import core

    ctx = core._context()
    if ctx is None:
        return
    with _lock:
        values = {k: v for k, v in _counters.items()}
    if not values:
        return
    ctx.write({
        "type": "counter",
        "trace": ctx.trace_id,
        "name": "obs.counters",
        "ts": ctx.now_us(),
        "pid": ctx.pid,
        "values": values,
    })


_PROM_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    """A legal Prometheus metric name: invalid chars become ``_`` and a
    leading digit gets an underscore prefix."""
    out = _PROM_NAME_RE.sub("_", name)
    if not out or out[0].isdigit():
        out = "_" + out
    return out


def prometheus_text(snap: Optional[Dict[str, Any]] = None) -> str:
    """Prometheus text-format exposition of :func:`snapshot`.

    Counters render as ``counter`` series; histograms as ``summary``
    series (p50/p90/p99 quantile labels + ``_count``) plus ``_min`` /
    ``_max`` gauges. The auto-maintained ``<hist>.count`` counters are
    folded into their histogram's ``_count`` line rather than emitted
    twice under a colliding name.

    Each histogram ALSO exposes a true Prometheus histogram family
    ``<name>_hist`` — cumulative ``_bucket{le="..."}`` lines over
    :data:`DEFAULT_BUCKETS` (+Inf == ``_count``), ``_sum`` and
    ``_count`` — because quantile summaries cannot be aggregated across
    scrapes/instances while buckets can (the standard histogram_quantile
    path). A separate family name keeps promtool's one-TYPE-per-family
    rule intact next to the summary. Bucket counts cover the bounded
    sample window (the summary's ``_count`` stays unbounded).
    """
    if snap is None:
        snap = snapshot()
    counters: Dict[str, float] = snap.get("counters", {})
    hists: Dict[str, Dict[str, Any]] = snap.get("histograms", {})
    lines: List[str] = []
    hist_count_keys = {name + ".count" for name in hists}
    for name in sorted(counters):
        if name in hist_count_keys:
            continue
        pname = _prom_name(name)
        lines.append(f"# TYPE {pname} counter")
        lines.append(f"{pname} {counters[name]:g}")
    for name in sorted(hists):
        h = hists[name]
        pname = _prom_name(name)
        lines.append(f"# TYPE {pname} summary")
        for q_label, key in (("0.5", "p50"), ("0.9", "p90"), ("0.99", "p99")):
            if h.get(key) is not None:
                lines.append(f'{pname}{{quantile="{q_label}"}} {h[key]:g}')
        lines.append(f"{pname}_count {h.get('count', 0):g}")
        for suffix in ("min", "max"):
            if h.get(suffix) is not None:
                lines.append(f"# TYPE {pname}_{suffix} gauge")
                lines.append(f"{pname}_{suffix} {h[suffix]:g}")
        if h.get("buckets"):
            lines.append(f"# TYPE {pname}_hist histogram")
            for bound, cum in h["buckets"]:
                lines.append(f'{pname}_hist_bucket{{le="{bound:g}"}} {cum:g}')
            samples = h.get("samples", h.get("count", 0))
            lines.append(f'{pname}_hist_bucket{{le="+Inf"}} {samples:g}')
            lines.append(f"{pname}_hist_sum {h.get('sum', 0):.10g}")
            lines.append(f"{pname}_hist_count {samples:g}")
    return "\n".join(lines) + ("\n" if lines else "")


def parse_prometheus(text: str) -> Dict[str, float]:
    """Prometheus text exposition -> ``{series-line-key: value}``.
    The key is the full series identity (name incl. any ``{labels}``),
    so two expositions aggregate line-for-line. Comment/TYPE lines and
    unparseable values are skipped."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        key, _, value = line.rpartition(" ")
        if not key:
            continue
        try:
            out[key] = float(value)
        except ValueError:
            continue
    return out


def aggregate_prometheus(texts: List[str]) -> Dict[str, float]:
    """Fleet-level /metrics rollup (docs/SERVE.md "Fleet"): counters,
    histogram ``_bucket``/``_sum``/``_count`` series, and gauges SUM
    across replicas; percentile/quantile summary gauges (``_p50`` etc.)
    take the MAX instead — a fleet's pessimistic tail, since summing
    per-replica percentiles is meaningless."""
    out: Dict[str, float] = {}
    quantile = re.compile(r"_p\d+(\{|$)|quantile=")
    for text in texts:
        for key, value in parse_prometheus(text).items():
            if quantile.search(key):
                out[key] = max(out.get(key, value), value)
            else:
                out[key] = out.get(key, 0.0) + value
    return out


def reset() -> None:
    """Test hook: drop all aggregates."""
    with _lock:
        _counters.clear()
        _histograms.clear()
