"""Counters and histograms: thread-safe in-process aggregates.

These are always live (no env gate — a dict update is cheaper than the
question of whether to do it), queryable via :func:`snapshot`, and
flushed into the trace as Chrome counter events by :func:`publish`
when tracing is armed. Span durations feed the ``span.<name>``
histograms automatically (obs.core.Span.__exit__), so per-site latency
distributions exist without any extra call sites.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

_lock = threading.Lock()
_counters: Dict[str, float] = {}
_histograms: Dict[str, List[float]] = {}

_HIST_CAP = 4096  # per-name sample bound (reservoir-free: drop the tail)


def count(name: str, n: float = 1) -> None:
    """Increment a monotonic counter."""
    with _lock:
        _counters[name] = _counters.get(name, 0) + n


def observe(name: str, value: float) -> None:
    """Record one sample into a histogram (bounded; extra samples still
    bump the count so rates stay truthful)."""
    with _lock:
        hist = _histograms.setdefault(name, [])
        if len(hist) < _HIST_CAP:
            hist.append(value)
        _counters[name + ".count"] = _counters.get(name + ".count", 0) + 1


def percentile(samples: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile (q in [0, 100]) of a sample list."""
    if not samples:
        return None
    ordered = sorted(samples)
    idx = min(len(ordered) - 1, max(0, int(round(q / 100 * (len(ordered) - 1)))))
    return ordered[idx]


def snapshot(clear: bool = False) -> Dict[str, Any]:
    """{counters: {...}, histograms: {name: {count,min,p50,p90,p99,max}}}."""
    with _lock:
        counters = dict(_counters)
        hists = {name: list(vals) for name, vals in _histograms.items()}
        if clear:
            _counters.clear()
            _histograms.clear()
    out_h = {}
    for name, vals in hists.items():
        if not vals:
            continue
        out_h[name] = {
            "count": int(counters.get(name + ".count", len(vals))),
            "min": min(vals),
            "p50": percentile(vals, 50),
            "p90": percentile(vals, 90),
            "p99": percentile(vals, 99),
            "max": max(vals),
        }
    return {"counters": counters, "histograms": out_h}


def publish() -> None:
    """Write current counter values into the trace as a counter record
    (rendered as a Chrome 'C' event by the exporter). No-op when
    tracing is off."""
    from . import core

    ctx = core._context()
    if ctx is None:
        return
    with _lock:
        values = {k: v for k, v in _counters.items()}
    if not values:
        return
    ctx.write({
        "type": "counter",
        "trace": ctx.trace_id,
        "name": "obs.counters",
        "ts": ctx.now_us(),
        "pid": ctx.pid,
        "values": values,
    })


def reset() -> None:
    """Test hook: drop all aggregates."""
    with _lock:
        _counters.clear()
        _histograms.clear()
