"""Perf evidence ledger: a crash-safe, append-only JSONL time series of
every benchmark datapoint the system ever produces.

The round-5 lesson (VERDICT r5 "bottom line"): structure without
*evidence*. Bench runs, trace exports, and metrics snapshots were rich
but ephemeral — nothing accumulated them, so no run was ever compared
against a prior run, and a device-unreachable round recorded
``value: null`` instead of the host-side truth it actually measured.
The ledger is the accumulation point:

- one file (default ``perf-ledger/ledger.jsonl`` at the repo root,
  overridable via ``CONSENSUS_SPECS_TPU_LEDGER``; the empty string or
  ``off`` disables it), one flushed+fsync'd JSON line per record —
  the generator-journal crash contract: a SIGKILL mid-write costs
  exactly the torn last line, never the history before it;
- two record types: a ``run`` header (source, git sha, backend,
  environment fingerprint) followed by one ``point`` per metric, so a
  partially-written run still yields joinable points;
- device-unreachable runs are FIRST-CLASS host-only datapoints: the
  run's environment carries ``device_unreachable: true``, its points
  carry ``backend: "host"``, and the headline metric is populated from
  the host-path measurement instead of null;
- :func:`Ledger.ingest_bench_payload` accepts both a raw bench.py
  RESULTS dict and the driver's ``BENCH_r0N.json`` wrapper
  (``{"n", "rc", "tail", "parsed"}``), recovering metrics from the
  stderr tail when ``parsed`` is null (the r04 rc=124 case) so the
  historical rounds backfill completely.

Consumers: ``bench.py`` appends every parent run, ``tools/perfgate.py``
appends the CI micro-bench slice and gates on :mod:`.sentinel`'s
verdicts, ``tools/perf_report.py`` renders the trajectory.

See docs/OBSERVABILITY.md ("Perf evidence plane") for the schema.
"""
from __future__ import annotations

import json
import os
import re
import socket
import subprocess
import sys
import time
from typing import Any, Dict, Iterable, List, Optional

LEDGER_ENV = "CONSENSUS_SPECS_TPU_LEDGER"

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
DEFAULT_RELPATH = os.path.join("perf-ledger", "ledger.jsonl")

HEADLINE_METRIC = "bls_cold_fast_aggregate_verifies_per_sec"

# bench.py RESULTS keys that are bookkeeping, not metrics
_NON_METRIC_KEYS = {
    "n", "rc", "metric", "unit", "backend", "section_seconds",
    "section_errors", "skipped_sections", "resilience_events", "events",
    "trace_json", "trace_json_error", "ledger", "ledger_error",
}


def default_path() -> str:
    """The ledger path to append to, or "" when disabled. Env knob wins;
    the default anchors to the repo root so every tool and bench run
    shares one file regardless of cwd."""
    raw = os.environ.get(LEDGER_ENV)
    if raw is not None:
        if raw.strip().lower() in ("", "0", "off", "none"):
            return ""
        return raw
    return os.path.join(_REPO_ROOT, DEFAULT_RELPATH)


_SHA_CACHE: Optional[str] = None


def git_sha() -> Optional[str]:
    """Short git sha of the repo HEAD, or None outside a checkout."""
    global _SHA_CACHE
    if _SHA_CACHE is not None:
        return _SHA_CACHE or None
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=_REPO_ROOT,
            capture_output=True, text=True, timeout=10)
        _SHA_CACHE = out.stdout.strip() if out.returncode == 0 else ""
    except (OSError, subprocess.SubprocessError):
        _SHA_CACHE = ""
    return _SHA_CACHE or None


def environment_fingerprint(**extra: Any) -> Dict[str, Any]:
    """Where a datapoint was measured: enough to decide comparability
    (the sentinel only baselines points from comparable environments)."""
    env: Dict[str, Any] = {
        "platform": sys.platform,
        "python": ".".join(str(v) for v in sys.version_info[:3]),
        "host": socket.gethostname(),
    }
    env.update({k: v for k, v in extra.items() if v is not None})
    return env


def infer_unit(metric: str) -> Optional[str]:
    """Best-effort unit from the metric-name conventions bench.py uses."""
    if metric.endswith("_mibs") or metric.endswith("mibs"):
        return "MiB/s"
    if metric.endswith("_ms"):
        return "ms"
    if metric.endswith("_us"):
        return "us"
    # rates before the bare "_s" suffix: serve_verifies_per_s is a rate,
    # not seconds (polarity inverts on this distinction)
    if "per_sec" in metric or "per_s" in metric or metric.endswith("_rate"):
        return "/s"
    if metric.endswith("_s") or metric.endswith("_seconds"):
        return "s"
    if "speedup" in metric or "scaling" in metric or metric == "vs_baseline":
        return "x"
    if metric.endswith("_pct"):
        return "%"
    # chain-health lag series (sim_convergence_lag_slots,
    # chain_finality_lag_epochs): slot/epoch counts, lower-is-better —
    # obs.sentinel.polarity makes the same carve-out
    if metric.endswith("_lag_slots") or metric.endswith("_slots"):
        return "slots"
    if metric.endswith("_epochs"):
        return "epochs"
    return None


def metric_backend(metric: str, run_backend: str) -> str:
    """Per-point backend tag: host-path metrics stay ``host`` even in a
    device run (they are measured on host by construction), device-named
    metrics stay ``jax``; everything else inherits the run's backend."""
    name = metric.lower()
    if ("host" in name or "hashlib" in name or name.startswith("epoch_")
            or name.startswith("incremental_reroot")
            or name.startswith("perfgate_")):
        return "host"
    if "device" in name or "pallas" in name:
        return "jax"
    return run_backend


class Ledger:
    """Append-only JSONL perf ledger (see module docstring for schema)."""

    def __init__(self, path: Optional[str] = None) -> None:
        p = path if path is not None else default_path()
        if not p:
            raise ValueError("ledger disabled (empty path); check "
                             f"{LEDGER_ENV} or pass an explicit path")
        self.path = p

    # -- write ----------------------------------------------------------

    def append_raw(self, record: Dict[str, Any]) -> None:
        """One record, one flushed+fsync'd line (crash-safe append)."""
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        line = json.dumps(record, default=repr)
        with open(self.path, "a") as f:
            f.write(line + "\n")
            f.flush()
            os.fsync(f.fileno())

    def record_run(
        self,
        metrics: Dict[str, Any],
        *,
        source: str,
        backend: str = "host",
        environment: Optional[Dict[str, Any]] = None,
        sha: Optional[str] = None,
        units: Optional[Dict[str, str]] = None,
        run_id: Optional[str] = None,
        ts: Optional[float] = None,
        label: Optional[str] = None,
        extra: Optional[Dict[str, Any]] = None,
    ) -> str:
        """Append one run header + one point per numeric metric. Returns
        the run id. ``metrics`` values that are None or non-numeric are
        skipped (a degraded run records what it has)."""
        ts = time.time() if ts is None else ts
        if run_id is None:
            run_id = f"{source}-{int(ts)}-{os.urandom(3).hex()}"
        if sha is None:
            sha = git_sha()
        env = environment or environment_fingerprint()
        numeric = {k: float(v) for k, v in metrics.items()
                   if isinstance(v, (int, float)) and not isinstance(v, bool)}
        header: Dict[str, Any] = {
            "type": "run", "run_id": run_id, "ts": ts, "source": source,
            "sha": sha, "backend": backend, "environment": env,
            "metrics_count": len(numeric),
        }
        if label:
            header["label"] = label
        if extra:
            header.update(extra)
        self.append_raw(header)
        for metric, value in sorted(numeric.items()):
            unit = (units or {}).get(metric) or infer_unit(metric)
            self.append_raw({
                "type": "point", "run_id": run_id, "ts": ts,
                "metric": metric, "value": value, "unit": unit,
                "backend": metric_backend(metric, backend),
                "source": source, "sha": sha,
            })
        return run_id

    # -- read -----------------------------------------------------------

    def read(self) -> List[Dict[str, Any]]:
        """All committed records, torn trailing lines skipped."""
        records: List[Dict[str, Any]] = []
        try:
            with open(self.path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue  # torn tail of a killed writer
                    if isinstance(rec, dict):
                        records.append(rec)
        except OSError:
            return []
        return records

    def runs(self) -> List[Dict[str, Any]]:
        """Run headers, ordered by timestamp (then round label)."""
        runs = [r for r in self.read() if r.get("type") == "run"]
        runs.sort(key=lambda r: (r.get("ts") or 0, r.get("round") or 0))
        return runs

    def points(
        self,
        metric: Optional[str] = None,
        backend: Optional[str] = None,
        source: Optional[str] = None,
    ) -> List[Dict[str, Any]]:
        """Point records with the owning run's environment joined in
        (key ``environment``), filtered and ordered by timestamp."""
        records = self.read()
        envs = {r.get("run_id"): r.get("environment") or {}
                for r in records if r.get("type") == "run"}
        out = []
        for r in records:
            if r.get("type") != "point":
                continue
            if metric is not None and r.get("metric") != metric:
                continue
            if backend is not None and r.get("backend") != backend:
                continue
            if source is not None and r.get("source") != source:
                continue
            joined = dict(r)
            joined["environment"] = envs.get(r.get("run_id"), {})
            out.append(joined)
        out.sort(key=lambda r: r.get("ts") or 0)
        return out

    def series(self, metric: str, backend: Optional[str] = None) -> List[Dict[str, Any]]:
        """Time-ordered datapoints for one metric (optionally one backend)."""
        return self.points(metric=metric, backend=backend)

    def metrics(self) -> List[str]:
        """All metric names present, sorted."""
        return sorted({r.get("metric") for r in self.read()
                       if r.get("type") == "point" and r.get("metric")})

    def labels(self) -> List[str]:
        return [r["label"] for r in self.runs() if r.get("label")]

    # -- bench ingestion ------------------------------------------------

    def ingest_bench_payload(
        self,
        payload: Dict[str, Any],
        *,
        source: str = "bench",
        label: Optional[str] = None,
        ts: Optional[float] = None,
    ) -> str:
        """Ingest a bench run: either bench.py's raw RESULTS dict or the
        driver's ``BENCH_r0N.json`` wrapper. Returns the run id."""
        round_no: Optional[int] = None
        rc: Optional[int] = None
        results: Dict[str, Any] = payload
        tail = ""
        if "tail" in payload and ("parsed" in payload or "rc" in payload):
            # driver wrapper: {"n", "cmd", "rc", "tail", "parsed"}
            round_no = payload.get("n")
            rc = payload.get("rc")
            tail = payload.get("tail") or ""
            results = payload.get("parsed") or {}
            if ts is None:
                ts = _tail_timestamp(tail)
            if not results:
                # r04 shape: the run was killed before the JSON line —
                # recover what the progress tail proves was measured
                results = _recover_metrics_from_tail(tail)

        metrics = {k: v for k, v in results.items()
                   if k not in _NON_METRIC_KEYS
                   and isinstance(v, (int, float)) and not isinstance(v, bool)}
        headline = results.get("metric") or HEADLINE_METRIC
        unreachable = bool(results.get("device_unreachable"))
        degraded = bool(results.get("device_compile_failed"))
        backend = str(results.get("backend") or
                      ("host" if (unreachable or degraded or
                                  results.get("value") is None) else "jax"))

        value = results.get("value")
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            metrics[headline] = float(value)
        elif unreachable or degraded:
            # first-class host-only datapoint instead of null: the host
            # oracle rate IS the headline measurement of a degraded run
            host_rate = results.get("bls_host_oracle_cold_rate")
            if isinstance(host_rate, (int, float)):
                metrics[headline] = float(host_rate)
                backend = "host"
        metrics.pop("value", None)

        env = environment_fingerprint(
            device_unreachable=unreachable or None,
            device_compile_failed=degraded or None,
            external_timeout=(True if rc == 124 else None),
        )
        units = {headline: results.get("unit") or "/s"}
        extra: Dict[str, Any] = {}
        if round_no is not None:
            extra["round"] = round_no
        if rc is not None:
            extra["rc"] = rc
        if results.get("section_errors"):
            extra["section_errors"] = results["section_errors"]
        return self.record_run(
            metrics, source=source, backend=backend, environment=env,
            units=units, ts=ts, label=label, extra=extra)


# ---------------------------------------------------------------------------
# historical-tail recovery (the r04 rc=124 wrapper has parsed: null but a
# progress tail that proves what was measured before the kill)
# ---------------------------------------------------------------------------

_TAIL_TS_RE = re.compile(r"(\d{4})-(\d{2})-(\d{2}) (\d{2}):(\d{2}):(\d{2})")

_TAIL_PATTERNS = (
    (re.compile(r"bls done cold=([\d.]+)/s warm=([\d.]+)/s host=([\d.]+)/s"),
     ("value", "bls_warm_verifies_per_sec", "bls_host_oracle_cold_rate")),
    (re.compile(r"hashing done dev=([\d.]+) host=([\d.]+) spec=([\d.]+) "
                r"hashlib=([\d.]+)"),
     ("hash_tree_root_mibs", "hash_host_shani_mibs", "hash_spec_path_mibs",
      "hash_hashlib_ref_mibs")),
    (re.compile(r"config #3 done dev=([\d.]+)s host=([\d.]+)s"),
     ("block_128atts_mainnet_device_s", "block_128atts_mainnet_host_s")),
    (re.compile(r"config #4 done dev=([\d.]+)s host=([\d.]+)s"),
     ("sync_aggregate_512_device_s", "sync_aggregate_512_host_s")),
)


def _tail_timestamp(tail: str) -> Optional[float]:
    """Epoch seconds of the first wall-clock stamp in a driver tail (the
    jax warning lines carry one), so backfilled rounds order correctly."""
    m = _TAIL_TS_RE.search(tail)
    if not m:
        return None
    import calendar

    y, mo, d, h, mi, s = (int(g) for g in m.groups())
    return float(calendar.timegm((y, mo, d, h, mi, s, 0, 0, 0)))


def _recover_metrics_from_tail(tail: str) -> Dict[str, Any]:
    """Metrics provably measured before a kill, from the progress tail."""
    out: Dict[str, Any] = {}
    for pattern, names in _TAIL_PATTERNS:
        m = pattern.search(tail)
        if not m:
            continue
        for name, group in zip(names, m.groups()):
            out[name] = float(group)
    if "value" in out and out.get("bls_host_oracle_cold_rate"):
        out["vs_baseline"] = round(out["value"] / out["bls_host_oracle_cold_rate"], 2)
    if out.get("block_128atts_mainnet_device_s"):
        out["block_128atts_speedup"] = round(
            out["block_128atts_mainnet_host_s"] / out["block_128atts_mainnet_device_s"], 2)
    return out


def ingest_files(
    paths: Iterable[str],
    ledger: Optional[Ledger] = None,
    *,
    source: str = "ingest",
    force: bool = False,
) -> List[Dict[str, Any]]:
    """Backfill driver BENCH json files into the ledger, one run per
    file, keyed by basename so a re-ingest is a no-op unless forced.
    Returns per-file status dicts."""
    led = ledger or Ledger()
    try:
        seen = set(led.labels())
    except OSError:
        seen = set()
    out = []
    last_ts: Optional[float] = None
    for path in paths:
        label = os.path.basename(path)
        if not force and label in seen:
            out.append({"file": label, "status": "skipped", "reason": "already ingested"})
            continue
        try:
            with open(path) as f:
                payload = json.load(f)
        except (OSError, ValueError) as e:
            out.append({"file": label, "status": "error", "reason": repr(e)})
            continue
        # keep backfilled rounds in file order even when one wrapper's
        # tail carries no wall-clock stamp (BENCH_r03): order after the
        # previous round instead of "now"
        ts = _tail_timestamp(str(payload.get("tail") or ""))
        if ts is None and last_ts is not None:
            ts = last_ts + 60.0
        if ts is not None:
            last_ts = ts
        run_id = led.ingest_bench_payload(payload, source=source, label=label,
                                          ts=ts)
        n_points = sum(1 for r in led.read()
                       if r.get("type") == "point" and r.get("run_id") == run_id)
        seen.add(label)
        out.append({"file": label, "status": "ingested", "run_id": run_id,
                    "points": n_points})
    return out
