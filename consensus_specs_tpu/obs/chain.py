"""Consensus health plane: chain-level telemetry, consensus watchdogs,
and the divergence black-box recorder (docs/OBSERVABILITY.md
"Consensus health plane").

Every observability layer before this one watches *processes* — spans,
RSS, queue depths, request latencies. Nothing watched the *chain*: a
multi-hour simulated mainnet day can limp through low participation,
a finality stall, or a deepening reorg storm and only fail at the
end-of-run differential. Following the Dapper/Monarch split between
request tracing and domain-level monitoring, this module is the
domain-level monitor:

- a **chain-health metric family** registered as plain gauges/counters/
  histograms in the existing registry (so it flows into the long-haul
  time-series journals and every ``/metrics`` exposition with zero new
  plumbing): per-node head slot, justified/finalized epoch, finality
  lag, pending-queue depths, live fork count; per-epoch participation
  rate; reorg events with a depth histogram; attestation inclusion
  distance;
- **consensus watchdogs** (:class:`~.watchdog.ChainWatchdog`, knobs via
  ``CONSENSUS_SPECS_TPU_CHAIN_HEALTH``): finality_stall,
  participation_droop, split_brain, reorg_storm — slot-indexed, gated
  by the scheduled partition windows sim/net.py exports so planned
  splits and their heals never false-positive;
- a **black-box recorder**: each node keeps a bounded ring of recent
  fork-choice intake (message id, arrival slot/phase, accept/reject
  class). Any watchdog finding — or an explicit convergence/differential
  failure — triggers a forensic bundle: per-node Store dumps + intake
  rings + the seeded bus schedule slice + the config (seed included),
  enough to replay the divergence without rerunning the day;
- a **chain journal** (``chain-<pid>-<token>.jsonl`` next to the
  long-haul series journals): one line per slot/epoch/reorg/finding,
  rendered by ``tools/chain_report.py`` and the mission report's
  "Chain health" section.

Armed by default (a handful of dict writes per *slot*, not per
operation — ``perfgate_chain_health_overhead_pct`` holds the armed sim
under the same <3% ceiling as the process plane);
``CONSENSUS_SPECS_TPU_CHAIN_HEALTH=off`` disarms it entirely. The
plane is strictly observational: an armed and an unarmed run of the
same config produce bit-identical chains (asserted inside the perfgate
measurement and the chain-health smoke).
"""
from __future__ import annotations

import json
import os
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from . import metrics
from .watchdog import (  # noqa: F401  (re-exported knobs)
    CHAIN_HEALTH_ENV,
    ChainThresholds,
    ChainWatchdog,
    chain_health_disarmed,
)

# intake outcome classes the black-box ring records (the spec's real
# rejection ladder, as the sims exercise it)
INTAKE_ACCEPTED = "accepted"
INTAKE_REJECTED = "rejected"
INTAKE_PARKED = "parked"
INTAKE_DUPLICATE = "duplicate"

_RING_DEFAULT = 512
_JOURNAL_FLUSH_EVERY = 64      # buffered lines between flushes
_TAIL_KEEP = 256               # slot rows retained for the bundle

GAUGE_HELP: Dict[str, str] = {
    "chain.head_slot": "Best head slot across nodes (chain position)",
    "chain.finalized_epoch": "Best finalized epoch across nodes",
    "chain.finality_lag_epochs":
        "Worst current-epoch minus finalized-epoch gap across nodes "
        "(lower is better)",
    "chain.participation_rate":
        "Best previous-epoch target-participation fraction across nodes "
        "(the FFG justification input)",
    "chain.fork_count": "Most live branch tips any node's Store holds",
    "chain.net_partitioned":
        "1 while a scheduled partition window covers the current slot "
        "(sim/net.py export; watchdogs are excused inside)",
    "chain.reorgs": "Reorg events observed (head moved to a non-ancestor)",
    "chain.reorg_depth": "Reorg depth in slots (old head to common ancestor)",
    "chain.inclusion_distance_slots":
        "Attestation inclusion distance (block slot minus attestation slot)",
}


def node_gauge_help(nodes: int) -> Dict[str, str]:
    """HELP texts for the per-node series of an ``nodes``-node run."""
    out: Dict[str, str] = {}
    for i in range(nodes):
        out.update({
            f"chain.n{i}.head_slot": f"Node {i} fork-choice head slot",
            f"chain.n{i}.justified_epoch": f"Node {i} justified epoch",
            f"chain.n{i}.finalized_epoch": f"Node {i} finalized epoch",
            f"chain.n{i}.finality_lag_epochs":
                f"Node {i} current-epoch minus finalized-epoch gap",
            f"chain.n{i}.pending_blocks":
                f"Node {i} blocks parked awaiting a parent (sync queue)",
            f"chain.n{i}.pending_atts":
                f"Node {i} attestations parked awaiting their block",
            f"chain.n{i}.fork_count":
                f"Node {i} live branch tips (Store leaves above finality)",
            f"chain.n{i}.participation_rate":
                f"Node {i} previous-epoch target-participation fraction",
        })
    return out


def register_descriptions(nodes: int = 1) -> None:
    """Register the family's HELP texts (prometheus exposition
    metadata) — the serve daemon calls this on its startup path so a
    fleet's ``/metrics`` rollup carries self-documenting chain gauges."""
    metrics.describe_many(GAUGE_HELP)
    metrics.describe_many(node_gauge_help(nodes))


# ---------------------------------------------------------------------------
# metric math (unit-tested directly in tests/test_chain_health.py)
# ---------------------------------------------------------------------------

def participation_rate(spec, state) -> Optional[float]:
    """Previous-epoch target-participation fraction of ``state`` —
    EXACTLY the balance ratio the interpreted epoch transition feeds
    into FFG justification (``weigh_justification_and_finalization``):

    - altair+: unslashed TIMELY_TARGET participants of the previous
      epoch (``get_unslashed_participating_indices``) total balance over
      total active balance;
    - phase0: ``get_attesting_balance`` of the matching-target previous-
      epoch attestations over total active balance.

    Returns None when the state cannot answer (mid-genesis shapes)."""
    try:
        total = int(spec.get_total_active_balance(state))
        if not total:
            return None
        prev = spec.get_previous_epoch(state)
        if hasattr(state, "previous_epoch_participation"):
            indices = spec.get_unslashed_participating_indices(
                state, spec.TIMELY_TARGET_FLAG_INDEX, prev)
            part = int(spec.get_total_balance(state, indices))
        else:
            atts = spec.get_matching_target_attestations(state, prev)
            part = int(spec.get_attesting_balance(state, atts))
        return part / total
    except Exception:
        return None


def reorg_depth(store, old_head, new_head) -> int:
    """Depth of a reorg in slots: the old head's slot minus the slot of
    the deepest common ancestor of old and new head (>= 1 for any real
    reorg). When the old branch was already pruned out of the Store the
    fallback is the old head's slot minus the finalized slot — the
    deepest a surviving reorg can reach."""
    blocks = {bytes(root): block for root, block in store.blocks.items()}
    new_ancestry = set()
    cursor = bytes(new_head)
    while cursor in blocks:
        new_ancestry.add(cursor)
        parent = bytes(blocks[cursor].parent_root)
        if parent == cursor:
            break
        cursor = parent
    old = blocks.get(bytes(old_head))
    if old is None:
        return 0
    old_slot = int(old.slot)
    cursor = bytes(old_head)
    while cursor in blocks and cursor not in new_ancestry:
        cursor = bytes(blocks[cursor].parent_root)
    if cursor in new_ancestry:
        return max(0, old_slot - int(blocks[cursor].slot))
    # old branch severed (pruned): bound by finality
    try:
        fin_root = bytes(store.finalized_checkpoint.root)
        fin_slot = int(blocks[fin_root].slot) if fin_root in blocks else 0
        return max(0, old_slot - fin_slot)
    except Exception:
        return max(0, old_slot)


def fork_count(store, cap: int = 4096) -> int:
    """Live branch tips: Store blocks that are nobody's parent. 1 on a
    clean chain; every competing branch adds a tip. Skipped (returns -1)
    past ``cap`` blocks — an unpruned pathological Store must not turn
    the health plane into the hot path."""
    blocks = store.blocks
    if len(blocks) > cap:
        return -1
    parents = {bytes(b.parent_root) for b in blocks.values()}
    return sum(1 for root in blocks if bytes(root) not in parents)


# ---------------------------------------------------------------------------
# black-box recorder
# ---------------------------------------------------------------------------

class BlackBox:
    """One node's bounded ring of recent fork-choice intake: what
    arrived, when (slot + phase), and what the spec's rejection ladder
    did with it. This is the flight recorder a divergence post-mortem
    reads first: two nodes' rings pin the exact message whose differing
    fate forked their views."""

    __slots__ = ("node", "ring")

    def __init__(self, node: int, capacity: int = _RING_DEFAULT) -> None:
        self.node = node
        self.ring: Deque[Tuple[int, str, str, str, str]] = deque(
            maxlen=max(16, int(capacity)))

    def record(self, slot: int, phase: str, kind: str, msg_id: str,
               outcome: str) -> None:
        self.ring.append((int(slot), phase, kind, msg_id, outcome))

    def entries(self) -> List[Dict[str, Any]]:
        return [{"slot": s, "phase": p, "kind": k, "id": m, "outcome": o}
                for s, p, k, m, o in self.ring]


# ---------------------------------------------------------------------------
# the plane
# ---------------------------------------------------------------------------

class ChainHealth:
    """One instance per sim run (single- or multi-node). The owning
    driver feeds it at slot/epoch boundaries; it publishes the metric
    family, runs the consensus watchdogs, journals the chain timeline,
    and writes forensic bundles the moment something is wrong.

    ``bundle_cb`` is the owning sim's zero-arg callable returning the
    heavyweight forensic payload (per-node Store dumps + bus state);
    the plane itself adds findings, rings, and the timeline tail.
    ``out_dir`` defaults to the long-haul telemetry directory when that
    plane is armed, else no journal is written (metrics/watchdogs still
    run)."""

    def __init__(
        self,
        nodes: int,
        slots_per_epoch: int,
        windows: Tuple[Tuple[int, int], ...] = (),
        thresholds: Optional[ChainThresholds] = None,
        out_dir: Optional[str] = None,
        label: str = "chain",
        bundle_cb: Optional[Callable[[], Dict[str, Any]]] = None,
        max_bundles: int = 2,
        ring_capacity: int = _RING_DEFAULT,
    ) -> None:
        self.nodes = int(nodes)
        self.spe = int(slots_per_epoch)
        self.label = label
        self.bundle_cb = bundle_cb
        self.max_bundles = int(max_bundles)
        self.watchdog = ChainWatchdog(thresholds, windows=windows,
                                      slots_per_epoch=slots_per_epoch)
        self.rings = [BlackBox(i, ring_capacity) for i in range(self.nodes)]
        self.findings: List[Dict[str, Any]] = []
        self.bundles: List[str] = []
        self.tail: Deque[Dict[str, Any]] = deque(maxlen=_TAIL_KEEP)
        self._reorgs_pending = 0
        self._token = os.urandom(3).hex()
        self._pid = os.getpid()
        self._buffer: List[str] = []
        self._fh = None
        if out_dir is None:
            from . import timeseries

            cfg = timeseries.config_from_env()
            out_dir = cfg[0] if cfg is not None else None
        self.out_dir = out_dir
        register_descriptions(self.nodes)
        self._header = {"type": "chain_header", "label": label,
                        "nodes": self.nodes, "spe": self.spe,
                        "pid": self._pid,
                        "windows": [list(w) for w in self.watchdog.windows]}
        self._journal(dict(self._header))

    def set_out_dir(self, out_dir: Optional[str]) -> None:
        """Re-point (or arm) the journal directory after construction —
        drills arm an explicit directory without the long-haul knob. The
        header is re-emitted so the new journal is self-describing."""
        self.out_dir = out_dir
        self._buffer = []
        if self._fh is not None:
            try:
                self._fh.close()
            except Exception:
                pass
            self._fh = None
        if out_dir is not None:
            self._journal(dict(self._header))

    # -- scheduled-window plumbing (drills re-point it) --------------------

    def set_windows(self, windows: Tuple[Tuple[int, int], ...]) -> None:
        self.watchdog.set_windows(windows)

    # -- journal -----------------------------------------------------------

    @property
    def journal_path(self) -> Optional[str]:
        if self.out_dir is None:
            return None
        return os.path.join(self.out_dir,
                            f"chain-{self._pid}-{self._token}.jsonl")

    def _journal(self, record: Dict[str, Any], flush: bool = False,
                 fsync: bool = False) -> None:
        if self.out_dir is None:
            return
        self._buffer.append(json.dumps(record, default=repr))
        if not (flush or fsync
                or len(self._buffer) >= _JOURNAL_FLUSH_EVERY):
            return
        try:
            if self._fh is None:
                os.makedirs(self.out_dir, exist_ok=True)
                self._fh = open(self.journal_path, "a")
            self._fh.write("\n".join(self._buffer) + "\n")
            self._buffer = []
            self._fh.flush()
            if fsync:
                os.fsync(self._fh.fileno())
        except OSError:
            self._buffer = []

    def close(self) -> None:
        """Flush the journal tail (end of run)."""
        self._journal({"type": "chain_close"}, fsync=True)
        if self._fh is not None:
            try:
                self._fh.close()
            except Exception:
                pass
            self._fh = None

    # -- intake / event recorders ------------------------------------------

    def record_intake(self, node: int, slot: int, phase: str, kind: str,
                      msg_id: str, outcome: str) -> None:
        """One fork-choice intake decision into the node's black box."""
        if 0 <= node < self.nodes:
            self.rings[node].record(slot, phase, kind, msg_id, outcome)
        metrics.count(f"chain.intake.{outcome}")

    def record_reorg(self, node: int, slot: int, depth: int) -> None:
        metrics.count("chain.reorgs")
        metrics.observe("chain.reorg_depth", float(depth))
        # only DEEP reorgs feed the storm detector: depth-1 head swaps
        # are ordinary gossip weather on a lossy network
        if depth >= self.watchdog.t.reorg_storm_min_depth:
            self._reorgs_pending += 1
        self._journal({"type": "chain_reorg", "slot": int(slot),
                       "node": int(node), "depth": int(depth)})

    def record_inclusion(self, block_slot: int, att_slot: int) -> None:
        """Attestation rode a block: distance = block slot − attestation
        slot (spec bounds: [MIN_ATTESTATION_INCLUSION_DELAY,
        SLOTS_PER_EPOCH])."""
        metrics.observe("chain.inclusion_distance_slots",
                        float(int(block_slot) - int(att_slot)))

    # -- slot/epoch boundaries ---------------------------------------------

    def on_slot(self, slot: int, views: List[Dict[str, Any]],
                partitioned: bool = False) -> List[Dict[str, Any]]:
        """Top-of-slot observation (post-intake, pre-proposal). Each
        view: ``{head, head_slot, justified_epoch, finalized_epoch,
        pending_blocks, pending_atts, fork_count}`` (``head`` = root
        hex). Publishes the gauge family, runs the slot watchdogs,
        journals the row; returns new findings."""
        epoch = slot // self.spe
        heads: List[str] = []
        row: List[List[int]] = []
        for i, view in enumerate(views):
            lag = max(0, epoch - int(view["finalized_epoch"]))
            metrics.gauge(f"chain.n{i}.head_slot", view["head_slot"])
            metrics.gauge(f"chain.n{i}.justified_epoch",
                          view["justified_epoch"])
            metrics.gauge(f"chain.n{i}.finalized_epoch",
                          view["finalized_epoch"])
            metrics.gauge(f"chain.n{i}.finality_lag_epochs", lag)
            metrics.gauge(f"chain.n{i}.pending_blocks",
                          view.get("pending_blocks", 0))
            metrics.gauge(f"chain.n{i}.pending_atts",
                          view.get("pending_atts", 0))
            if view.get("fork_count") is not None:
                metrics.gauge(f"chain.n{i}.fork_count", view["fork_count"])
            heads.append(str(view.get("head", "")))
            row.append([int(view["head_slot"]), int(view["justified_epoch"]),
                        int(view["finalized_epoch"]), int(lag),
                        int(view.get("pending_blocks", 0)),
                        int(view.get("pending_atts", 0)),
                        int(view.get("fork_count") or 0)])
        if views:
            metrics.gauge("chain.head_slot",
                          max(v["head_slot"] for v in views))
            metrics.gauge("chain.finalized_epoch",
                          max(v["finalized_epoch"] for v in views))
            metrics.gauge("chain.finality_lag_epochs",
                          max(0, epoch - min(int(v["finalized_epoch"])
                                             for v in views)))
            forks = [v["fork_count"] for v in views
                     if v.get("fork_count") is not None]
            if forks:
                metrics.gauge("chain.fork_count", max(forks))
        metrics.gauge("chain.net_partitioned", 1.0 if partitioned else 0.0)

        reorgs, self._reorgs_pending = self._reorgs_pending, 0
        findings = self.watchdog.on_slot(slot, heads, reorgs=reorgs)
        slot_row = {"type": "chain_slot", "slot": int(slot),
                    "part": 1 if partitioned else 0, "nodes": row,
                    "heads": [h[:16] for h in heads]}
        self.tail.append(slot_row)
        self._journal(slot_row)
        if findings:
            self._absorb(findings)
        return findings

    def on_epoch(self, epoch: int, slot: int,
                 participations: List[Optional[float]],
                 finalized_epochs: List[int]) -> List[Dict[str, Any]]:
        """Epoch-rollover observation: per-node previous-epoch
        participation + finalized epochs. Returns new findings."""
        rates = [p for p in participations if p is not None]
        best = max(rates) if rates else None
        if best is not None:
            metrics.gauge("chain.participation_rate", best)
        for i, p in enumerate(participations):
            if p is not None:
                metrics.gauge(f"chain.n{i}.participation_rate", p)
        findings = self.watchdog.on_epoch(epoch, slot,
                                          [int(f) for f in finalized_epochs],
                                          best)
        self._journal({"type": "chain_epoch", "epoch": int(epoch),
                       "slot": int(slot),
                       "participation": [None if p is None else round(p, 6)
                                         for p in participations],
                       "finalized": [int(f) for f in finalized_epochs]},
                      flush=True)
        if findings:
            self._absorb(findings)
        return findings

    # -- findings + forensics ----------------------------------------------

    def _absorb(self, findings: List[Dict[str, Any]]) -> None:
        """Route findings into every sink the process plane uses: the
        metric registry, the trace, the long-haul series journal, the
        chain journal — and trigger the forensic bundle."""
        from . import core, timeseries

        for f in findings:
            self.findings.append(f)
            metrics.count(f"watchdog.{f['kind']}")
            try:
                core.instant(f"watchdog.{f['kind']}", series=f["series"],
                             detail=f["detail"], value=f["value"],
                             slot=f.get("slot"))
            except Exception:
                pass
            try:
                timeseries.record_finding(dict(f))
            except Exception:
                pass
            self._journal({"type": "finding", **f}, fsync=True)
        self.write_bundle("watchdog: " + ", ".join(
            sorted({f["kind"] for f in findings})))

    def write_bundle(self, reason: str,
                     extra: Optional[Dict[str, Any]] = None) -> Optional[str]:
        """Write the forensic bundle NOW (bounded: at most
        ``max_bundles`` per run). Contents: reason, findings so far, the
        timeline tail, every node's intake ring, plus the owning sim's
        heavyweight payload (Store dumps, bus schedule slice, config —
        the replay-without-rerunning-the-day material)."""
        if self.out_dir is None or len(self.bundles) >= self.max_bundles:
            return None
        payload: Dict[str, Any] = {
            "type": "chain_forensics",
            "label": self.label,
            "reason": str(reason)[:500],
            "pid": self._pid,
            "findings": list(self.findings),
            "tail": list(self.tail),
            "intake_rings": [r.entries() for r in self.rings],
            "windows": [list(w) for w in self.watchdog.windows],
        }
        if extra:
            payload.update(extra)
        if self.bundle_cb is not None:
            try:
                payload.update(self.bundle_cb())
            except Exception as e:
                payload["bundle_cb_error"] = repr(e)
        path = os.path.join(
            self.out_dir,
            f"chain-forensics-{self._pid}-{self._token}"
            f"-{len(self.bundles)}.json")
        try:
            os.makedirs(self.out_dir, exist_ok=True)
            with open(path, "w") as f:
                json.dump(payload, f, default=repr)
                f.flush()
                os.fsync(f.fileno())
        except OSError:
            return None
        self.bundles.append(path)
        return path


def build(nodes: int, slots_per_epoch: int, **kwargs: Any) -> Optional[ChainHealth]:
    """The arming decision in one place: a :class:`ChainHealth` unless
    ``CONSENSUS_SPECS_TPU_CHAIN_HEALTH`` disarms the plane."""
    if chain_health_disarmed():
        return None
    return ChainHealth(nodes, slots_per_epoch, **kwargs)


__all__ = [
    "BlackBox", "CHAIN_HEALTH_ENV", "ChainHealth", "ChainThresholds",
    "ChainWatchdog", "GAUGE_HELP", "INTAKE_ACCEPTED", "INTAKE_DUPLICATE",
    "INTAKE_PARKED", "INTAKE_REJECTED", "build", "chain_health_disarmed",
    "fork_count", "node_gauge_help", "participation_rate",
    "reorg_depth", "register_descriptions",
]
