"""Armable in-process sampling profiler (docs/OBSERVABILITY.md
"Long-haul telemetry plane").

A daemon thread wakes ``hz`` times per second, grabs every live
thread's frame via ``sys._current_frames()`` (one C-level dict copy —
no tracing hooks, no sys.settrace overhead on the profiled code), and
folds each stack into a collapsed-stack counter
(``file:func;file:func;... count`` — the flamegraph.pl /
speedscope-compatible format Parca-style continuous profilers emit).
Output lands as ``profile-<pid>-<token>.collapsed`` in the long-haul
directory, rewritten atomically (tmp + rename) every few seconds so a
SIGKILL'd process still leaves its last flush behind.

Arming is explicit (:func:`arm`) — the timeseries plane arms it when
the ``CONSENSUS_SPECS_TPU_LONGHAUL`` knob carries a nonzero hz field —
and unarmed cost is zero: no thread exists, no hooks are installed.
``fork_child_reinit`` (obs/core.py) drops the inherited (dead) sampler
thread and its counts so a COW child never double-reports its parent's
stacks.
"""
from __future__ import annotations

import atexit
import os
import sys
import threading
import time
from typing import Dict, List, Optional

_MAX_DEPTH = 64          # frames per stack
_FLUSH_EVERY_S = 2.0     # periodic atomic rewrite (crash visibility —
#                          forked workers exit via os._exit, so their
#                          profiles only survive through these flushes)


def _frame_label(code) -> str:
    return f"{os.path.basename(code.co_filename)}:{code.co_name}"


class SamplingProfiler(threading.Thread):
    """The sampler thread. Use via :func:`arm`/:func:`disarm`."""

    def __init__(self, hz: float, out_dir: str) -> None:
        super().__init__(name="obs-profiler", daemon=True)
        self.hz = max(0.5, float(hz))
        self.out_dir = out_dir
        self.pid = os.getpid()
        self._token = os.urandom(3).hex()
        self._halt = threading.Event()
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}
        self.samples = 0

    @property
    def path(self) -> str:
        return os.path.join(self.out_dir,
                            f"profile-{self.pid}-{self._token}.collapsed")

    def _sample(self) -> None:
        me = self.ident
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue
            stack: List[str] = []
            plane_internal = False
            f = frame
            while f is not None and len(stack) < _MAX_DEPTH:
                code = f.f_code
                label = _frame_label(code)
                # the telemetry plane profiling its own sampler loops
                # is noise that drowns the busy stacks in mostly-idle
                # processes; other idle threads (an app thread blocked
                # on a queue) are real evidence and stay in
                if label == "timeseries.py:run":
                    plane_internal = True
                    break
                stack.append(label)
                f = f.f_back
            if plane_internal or not stack:
                continue
            key = ";".join(reversed(stack))
            with self._lock:
                self._counts[key] = self._counts.get(key, 0) + 1
                self.samples += 1

    def run(self) -> None:
        interval = 1.0 / self.hz
        last_flush = time.monotonic()
        while not self._halt.wait(interval):
            try:
                self._sample()
            except Exception:
                continue
            now = time.monotonic()
            if now - last_flush >= _FLUSH_EVERY_S:
                last_flush = now
                self.flush()

    def flush(self) -> Optional[str]:
        """Atomic rewrite of the collapsed-stack file (sorted, so the
        bytes are a pure function of the accumulated counts)."""
        with self._lock:
            if not self._counts:
                return None
            lines = [f"{stack} {n}" for stack, n in sorted(self._counts.items())]
        os.makedirs(self.out_dir, exist_ok=True)
        tmp = self.path + f".tmp.{self.pid}"
        with open(tmp, "w") as f:
            f.write("\n".join(lines) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        return self.path

    def stop(self, timeout_s: float = 2.0) -> Optional[str]:
        self._halt.set()
        if self.is_alive() and threading.current_thread() is not self:
            self.join(timeout_s)
        return self.flush()


_lock = threading.Lock()
_profiler: Optional[SamplingProfiler] = None
_atexit_installed = False


def arm(hz: float, out_dir: str) -> bool:
    """Start sampling at ``hz`` into ``out_dir`` (idempotent; a live
    sampler is left running). Returns True when a sampler is armed."""
    global _profiler, _atexit_installed
    if hz <= 0:
        return False
    with _lock:
        if _profiler is not None and _profiler.is_alive():
            return True
        _profiler = SamplingProfiler(hz, out_dir)
        _profiler.start()
        if not _atexit_installed:
            _atexit_installed = True
            atexit.register(disarm)
        return True


def disarm() -> Optional[str]:
    """Stop sampling and write the final collapsed output. Idempotent;
    returns the output path (None when nothing was sampled)."""
    global _profiler
    with _lock:
        prof, _profiler = _profiler, None
    if prof is None:
        return None
    return prof.stop()


def armed() -> bool:
    prof = _profiler
    return prof is not None and prof.is_alive()


def active() -> Optional[SamplingProfiler]:
    return _profiler


def fork_child_reinit() -> None:
    """Post-``os.fork`` child reset: the sampler thread did not survive
    the fork, and its counts/file belong to the parent — drop both. The
    timeseries plane re-arms from the env knob afterwards."""
    global _profiler
    with _lock:
        _profiler = None
