"""Three-tier constants model (ref: presets/*/*.yaml, configs/*.yaml,
setup.py:218-247,782-806 and eth2spec/config/config_util.py).

- *constants*: never change; baked into the fork spec sources.
- *presets*: compile-time bundles ("mainnet"/"minimal") that size SSZ
  containers; a spec module is built per (fork, preset).
- *configs*: runtime-swappable variables exposed as attributes of a
  mutable ``Config`` object inside each built spec module.
"""
from .presets import PRESETS, preset_for
from .runtime import CONFIGS, Config, config_for, load_config_file, parse_config_var
from .yaml_io import (
    load_network,
    load_preset_dir,
    load_yaml_vars,
    register_config,
    register_preset,
)

__all__ = [
    "PRESETS",
    "preset_for",
    "CONFIGS",
    "Config",
    "config_for",
    "load_config_file",
    "parse_config_var",
    "load_yaml_vars",
    "load_preset_dir",
    "register_preset",
    "register_config",
    "load_network",
]
