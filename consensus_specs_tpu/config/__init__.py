"""Three-tier constants model (ref: presets/*/*.yaml, configs/*.yaml,
setup.py:218-247,782-806 and eth2spec/config/config_util.py).

- *constants*: never change; baked into the fork spec sources.
- *presets*: compile-time bundles ("mainnet"/"minimal") that size SSZ
  containers; a spec module is built per (fork, preset).
- *configs*: runtime-swappable variables exposed as attributes of a
  mutable ``Config`` object inside each built spec module.
"""
from .presets import PRESETS, preset_for
from .runtime import CONFIGS, Config, config_for, load_config_file, parse_config_var

__all__ = [
    "PRESETS",
    "preset_for",
    "CONFIGS",
    "Config",
    "config_for",
    "load_config_file",
    "parse_config_var",
]
