"""YAML preset/config loading — the reference's file-driven configuration
tier (ref: presets/{mainnet,minimal}/*.yaml, configs/*.yaml,
setup.py:782-806, eth2spec/config/config_util.py:25-63).

Clients re-point the framework at custom networks by loading their YAML
files and registering them under a name; `build_spec(fork, name)` then
builds against them like any built-in bundle. The reference's own preset
and config files load verbatim (see tests/test_config_yaml.py, which
checks them against the hardcoded bundles key by key).
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional

from .presets import PRESETS
from .runtime import CONFIGS

def load_yaml_vars(path: str) -> Dict[str, Any]:
    """Flat `KEY: value` YAML file → parsed dict.

    Deliberately NOT yaml.safe_load: YAML 1.1 reads `0x...` as an integer,
    destroying the hex-bytes-vs-number distinction these files rely on
    (the reference keeps values as strings via ruamel round-trip mode,
    config_util.py:25-35). The flat line parser preserves it."""
    from .runtime import load_config_file

    return load_config_file(path)


def load_preset_dir(path: str) -> Dict[str, Dict[str, Any]]:
    """A reference-layout preset directory (one YAML per fork) → per-fork
    variable dicts (ref setup.py:782-792). Every ``*.yaml`` file loads
    (stem = fork name), so fork files beyond the built-in set are kept,
    not silently dropped; missing fork files simply have an empty delta."""
    out: Dict[str, Dict[str, Any]] = {}
    for fn in sorted(os.listdir(path)):
        if fn.endswith(".yaml"):
            out[fn[: -len(".yaml")]] = load_yaml_vars(os.path.join(path, fn))
    return out


def register_preset(name: str, per_fork: Dict[str, Dict[str, Any]], base: Optional[str] = None) -> None:
    """Make a preset bundle available to `build_spec` under `name`.

    With `base`, the new bundle starts from a copy of an existing preset
    and the given per-fork vars override it (a customized-minimal network
    only states its deltas)."""
    if base is not None:
        bundle = {f: dict(v) for f, v in PRESETS[base].items()}
    else:
        bundle = {}
    for fork, vars_ in per_fork.items():
        bundle.setdefault(fork, {}).update(vars_)
    PRESETS[name] = bundle


def register_config(name: str, values: Dict[str, Any], base: Optional[str] = None) -> None:
    """Make a runtime config available to `build_spec` under `name`
    (ref config_util.py:25-63's load-into-globals, done by registration
    instead of module mutation). CONFIG_NAME becomes `name` unless the
    values themselves set one — a base's name never leaks through."""
    merged = dict(CONFIGS[base]) if base is not None else {}
    merged.update(values)
    if "CONFIG_NAME" not in values:
        merged["CONFIG_NAME"] = name
    CONFIGS[name] = merged


def load_network(name: str, preset_dir: str, config_file: str, base_preset: Optional[str] = None) -> str:
    """One-call client entry: load a network's preset directory + config
    file and register both under `name`. Returns the name (use it as the
    `preset_name` for `build_spec`; the config registers under the same
    key). The config's PRESET_BASE is the default base for BOTH tiers;
    `base_preset` overrides it for both."""
    cfg = load_yaml_vars(config_file)
    base = base_preset or cfg.get("PRESET_BASE")
    if base is not None and base not in PRESETS:
        # fail at the root cause: a silent None base would surface much
        # later as a missing-variable NameError inside build_spec
        raise KeyError(
            f"unknown base preset {base!r} (registered: {sorted(PRESETS)})"
        )
    register_preset(name, load_preset_dir(preset_dir), base=base)
    register_config(name, cfg, base=base if base in CONFIGS else None)
    return name
