"""Runtime configuration (ref: configs/{mainnet,minimal}.yaml and
eth2spec/config/config_util.py:6-63).

A built spec module carries a mutable ``Config`` instance named ``config``;
spec functions read fork epochs/versions etc. through it, so a client (or a
test, via with_config_overrides) can re-point a compiled spec at a custom
config without rebuilding.
"""
from __future__ import annotations

from typing import Any, Dict

UINT64_MAX = 2**64 - 1

MAINNET_CONFIG: Dict[str, Any] = dict(
    PRESET_BASE="mainnet",
    CONFIG_NAME="mainnet",
    # Transition (configs/mainnet.yaml:9-14)
    TERMINAL_TOTAL_DIFFICULTY=2**256 - 2**10,
    TERMINAL_BLOCK_HASH=bytes(32),
    TERMINAL_BLOCK_HASH_ACTIVATION_EPOCH=UINT64_MAX,
    # Genesis
    MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=2**14,
    MIN_GENESIS_TIME=1606824000,
    GENESIS_FORK_VERSION=bytes.fromhex("00000000"),
    GENESIS_DELAY=604800,
    # Forking
    ALTAIR_FORK_VERSION=bytes.fromhex("01000000"),
    ALTAIR_FORK_EPOCH=74240,
    BELLATRIX_FORK_VERSION=bytes.fromhex("02000000"),
    BELLATRIX_FORK_EPOCH=UINT64_MAX,
    CAPELLA_FORK_VERSION=bytes.fromhex("03000000"),
    CAPELLA_FORK_EPOCH=UINT64_MAX,
    SHARDING_FORK_VERSION=bytes.fromhex("04000000"),
    SHARDING_FORK_EPOCH=UINT64_MAX,
    # Time parameters
    SECONDS_PER_SLOT=12,
    SECONDS_PER_ETH1_BLOCK=14,
    MIN_VALIDATOR_WITHDRAWABILITY_DELAY=2**8,
    SHARD_COMMITTEE_PERIOD=2**8,
    ETH1_FOLLOW_DISTANCE=2**11,
    # Validator cycling
    INACTIVITY_SCORE_BIAS=4,
    INACTIVITY_SCORE_RECOVERY_RATE=16,
    EJECTION_BALANCE=16 * 10**9,
    MIN_PER_EPOCH_CHURN_LIMIT=4,
    CHURN_LIMIT_QUOTIENT=2**16,
    # Fork choice
    PROPOSER_SCORE_BOOST=40,
    # Deposit contract
    DEPOSIT_CHAIN_ID=1,
    DEPOSIT_NETWORK_ID=1,
    DEPOSIT_CONTRACT_ADDRESS=bytes.fromhex("00000000219ab540356cbb839cbe05303d7705fa"),
)

MINIMAL_CONFIG: Dict[str, Any] = dict(
    MAINNET_CONFIG,
    PRESET_BASE="minimal",
    CONFIG_NAME="minimal",
    MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=64,
    MIN_GENESIS_TIME=1578009600,
    GENESIS_FORK_VERSION=bytes.fromhex("00000001"),
    GENESIS_DELAY=300,
    ALTAIR_FORK_VERSION=bytes.fromhex("01000001"),
    ALTAIR_FORK_EPOCH=UINT64_MAX,
    BELLATRIX_FORK_VERSION=bytes.fromhex("02000001"),
    CAPELLA_FORK_VERSION=bytes.fromhex("03000001"),
    SHARDING_FORK_VERSION=bytes.fromhex("04000001"),
    SECONDS_PER_SLOT=6,
    SHARD_COMMITTEE_PERIOD=64,
    ETH1_FOLLOW_DISTANCE=16,
    CHURN_LIMIT_QUOTIENT=32,
    DEPOSIT_CHAIN_ID=5,
    DEPOSIT_NETWORK_ID=5,
    DEPOSIT_CONTRACT_ADDRESS=bytes.fromhex("1234567890123456789012345678901234567890"),
)

CONFIGS: Dict[str, Dict[str, Any]] = {
    "mainnet": MAINNET_CONFIG,
    "minimal": MINIMAL_CONFIG,
}


class Config:
    """Mutable attribute bag a spec module reads runtime vars through
    (the reference's regenerated `config` NamedTuple, setup.py:632-639,
    made mutable so overrides don't require module re-import)."""

    def __init__(self, values: Dict[str, Any]):
        self.__dict__.update(values)

    def asdict(self) -> Dict[str, Any]:
        return dict(self.__dict__)

    def update(self, overrides: Dict[str, Any]) -> None:
        for k, v in overrides.items():
            if k not in self.__dict__:
                raise KeyError(f"unknown config var {k!r}")
            self.__dict__[k] = v

    def copy(self) -> "Config":
        return Config(self.asdict())

    def __repr__(self):
        return f"Config({self.__dict__.get('CONFIG_NAME', '?')})"


def config_for(name: str) -> Config:
    return Config(CONFIGS[name])


def parse_config_var(value: str) -> Any:
    """Parse one textual config value (config_util.py:14-24): 0x-hex →
    bytes, decimal → int, else kept as string."""
    value = value.strip().strip("'\"")
    if value.startswith("0x"):
        return bytes.fromhex(value[2:])
    try:
        return int(value)
    except ValueError:
        return value


def load_config_file(path) -> Dict[str, Any]:
    """Load a client-style YAML config of flat `KEY: value` pairs
    (config_util.py:25-35). A tiny line parser keeps this dependency-free;
    comments and blank lines are ignored."""
    out: Dict[str, Any] = {}
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line or ":" not in line:
                continue
            key, value = line.split(":", 1)
            out[key.strip()] = parse_config_var(value)
    return out
