"""Compile-time preset bundles (ref: presets/{mainnet,minimal}/*.yaml).

Values are the normative eth2 constants at spec v1.1.10. Stored as Python
dicts (keyed per fork so fork deltas stay deltas, mirroring the one-YAML-
file-per-fork layout) rather than YAML — the builder consumes them
directly and no YAML dependency is needed at import time.
"""
from __future__ import annotations

from typing import Dict

# -- mainnet -----------------------------------------------------------------

_MAINNET_PHASE0 = dict(
    # Misc (presets/mainnet/phase0.yaml:6-17)
    MAX_COMMITTEES_PER_SLOT=2**6,
    TARGET_COMMITTEE_SIZE=2**7,
    MAX_VALIDATORS_PER_COMMITTEE=2**11,
    SHUFFLE_ROUND_COUNT=90,
    HYSTERESIS_QUOTIENT=4,
    HYSTERESIS_DOWNWARD_MULTIPLIER=1,
    HYSTERESIS_UPWARD_MULTIPLIER=5,
    # Fork choice
    SAFE_SLOTS_TO_UPDATE_JUSTIFIED=2**3,
    # Gwei values
    MIN_DEPOSIT_AMOUNT=10**9,
    MAX_EFFECTIVE_BALANCE=32 * 10**9,
    EFFECTIVE_BALANCE_INCREMENT=10**9,
    # Time parameters
    MIN_ATTESTATION_INCLUSION_DELAY=1,
    SLOTS_PER_EPOCH=2**5,
    MIN_SEED_LOOKAHEAD=1,
    MAX_SEED_LOOKAHEAD=2**2,
    EPOCHS_PER_ETH1_VOTING_PERIOD=2**6,
    SLOTS_PER_HISTORICAL_ROOT=2**13,
    MIN_EPOCHS_TO_INACTIVITY_PENALTY=2**2,
    # State list lengths
    EPOCHS_PER_HISTORICAL_VECTOR=2**16,
    EPOCHS_PER_SLASHINGS_VECTOR=2**13,
    HISTORICAL_ROOTS_LIMIT=2**24,
    VALIDATOR_REGISTRY_LIMIT=2**40,
    # Reward and penalty quotients
    BASE_REWARD_FACTOR=2**6,
    WHISTLEBLOWER_REWARD_QUOTIENT=2**9,
    PROPOSER_REWARD_QUOTIENT=2**3,
    INACTIVITY_PENALTY_QUOTIENT=2**26,
    MIN_SLASHING_PENALTY_QUOTIENT=2**7,
    PROPORTIONAL_SLASHING_MULTIPLIER=1,
    # Max operations per block
    MAX_PROPOSER_SLASHINGS=2**4,
    MAX_ATTESTER_SLASHINGS=2**1,
    MAX_ATTESTATIONS=2**7,
    MAX_DEPOSITS=2**4,
    MAX_VOLUNTARY_EXITS=2**4,
)

_MAINNET_ALTAIR = dict(
    # Updated penalties (presets/mainnet/altair.yaml:5-11)
    INACTIVITY_PENALTY_QUOTIENT_ALTAIR=3 * 2**24,
    MIN_SLASHING_PENALTY_QUOTIENT_ALTAIR=2**6,
    PROPORTIONAL_SLASHING_MULTIPLIER_ALTAIR=2,
    # Sync committee
    SYNC_COMMITTEE_SIZE=2**9,
    EPOCHS_PER_SYNC_COMMITTEE_PERIOD=2**8,
    # Sync protocol
    MIN_SYNC_COMMITTEE_PARTICIPANTS=1,
    UPDATE_TIMEOUT=2**5 * 2**8,  # SLOTS_PER_EPOCH * EPOCHS_PER_SYNC_COMMITTEE_PERIOD
)

_MAINNET_BELLATRIX = dict(
    # Updated penalties (presets/mainnet/bellatrix.yaml:5-11)
    INACTIVITY_PENALTY_QUOTIENT_BELLATRIX=2**24,
    MIN_SLASHING_PENALTY_QUOTIENT_BELLATRIX=2**5,
    PROPORTIONAL_SLASHING_MULTIPLIER_BELLATRIX=3,
    # Execution
    MAX_BYTES_PER_TRANSACTION=2**30,
    MAX_TRANSACTIONS_PER_PAYLOAD=2**20,
    BYTES_PER_LOGS_BLOOM=2**8,
    MAX_EXTRA_DATA_BYTES=2**5,
)

# Capella preset file is empty at v1.1.10 (presets/mainnet/capella.yaml);
# the withdrawal-related sizes live in the capella spec draft itself and are
# supplied here so containers can be sized.
_MAINNET_CAPELLA = dict(
    MAX_BLS_TO_EXECUTION_CHANGES=2**4,
    MAX_WITHDRAWALS_PER_PAYLOAD=2**4,
    WITHDRAWALS_QUEUE_LIMIT=2**40,
)

_MAINNET_CUSTODY = dict(
    # presets/mainnet/custody_game.yaml
    RANDAO_PENALTY_EPOCHS=2**1,
    EARLY_DERIVED_SECRET_PENALTY_MAX_FUTURE_EPOCHS=2**15,
    EPOCHS_PER_CUSTODY_PERIOD=2**14,
    CUSTODY_PERIOD_TO_RANDAO_PADDING=2**11,
    MAX_CHUNK_CHALLENGE_DELAY=2**15,
    MAX_CUSTODY_KEY_REVEALS=2**8,
    MAX_EARLY_DERIVED_SECRET_REVEALS=1,
    MAX_CUSTODY_CHUNK_CHALLENGES=2**2,
    MAX_CUSTODY_CHUNK_CHALLENGE_RESP=2**4,
    MAX_CUSTODY_SLASHINGS=1,
    EARLY_DERIVED_SECRET_REVEAL_SLOT_REWARD_MULTIPLE=2,
    MINOR_REWARD_QUOTIENT=2**8,
)

_MAINNET_SHARDING = dict(
    # presets/mainnet/sharding.yaml
    MAX_SHARDS=2**10,
    INITIAL_ACTIVE_SHARDS=2**6,
    SAMPLE_PRICE_ADJUSTMENT_COEFFICIENT=2**3,
    MAX_SHARD_PROPOSER_SLASHINGS=2**4,
    MAX_SHARD_HEADERS_PER_SHARD=4,
    SHARD_STATE_MEMORY_SLOTS=2**8,
    BLOB_BUILDER_REGISTRY_LIMIT=2**40,
    MAX_SAMPLES_PER_BLOCK=2**11,
    TARGET_SAMPLES_PER_BLOCK=2**10,
    MAX_SAMPLE_PRICE=2**33,
    MIN_SAMPLE_PRICE=2**3,
    # development KZG setup size = MAX_DEGREE+1 (the reference leaves the
    # setup undefined, sharding/beacon-chain.md:170-173); mainnet covers
    # the full MAX_SAMPLES_PER_BLOCK * POINTS_PER_SAMPLE degree bound
    KZG_SETUP_SIZE=2**14,
)

_MAINNET_EIP4844 = dict(
    # eip4844/beacon-chain.md:54 + p2p-interface.md:40
    FIELD_ELEMENTS_PER_BLOB=4096,
    MAX_BLOBS_PER_BLOCK=2**4,
)

# -- minimal (only keys that differ from mainnet) ----------------------------

_MINIMAL_PHASE0 = dict(
    _MAINNET_PHASE0,
    MAX_COMMITTEES_PER_SLOT=4,
    TARGET_COMMITTEE_SIZE=4,
    SHUFFLE_ROUND_COUNT=10,
    SAFE_SLOTS_TO_UPDATE_JUSTIFIED=2,
    SLOTS_PER_EPOCH=8,
    EPOCHS_PER_ETH1_VOTING_PERIOD=4,
    SLOTS_PER_HISTORICAL_ROOT=64,
    EPOCHS_PER_HISTORICAL_VECTOR=64,
    EPOCHS_PER_SLASHINGS_VECTOR=64,
    INACTIVITY_PENALTY_QUOTIENT=2**25,
    MIN_SLASHING_PENALTY_QUOTIENT=64,
    PROPORTIONAL_SLASHING_MULTIPLIER=2,
)

_MINIMAL_ALTAIR = dict(
    _MAINNET_ALTAIR,
    SYNC_COMMITTEE_SIZE=32,
    EPOCHS_PER_SYNC_COMMITTEE_PERIOD=8,
    UPDATE_TIMEOUT=64,
)

_MINIMAL_BELLATRIX = dict(_MAINNET_BELLATRIX)

_MINIMAL_CAPELLA = dict(_MAINNET_CAPELLA)

_MINIMAL_CUSTODY = dict(
    _MAINNET_CUSTODY,
    EARLY_DERIVED_SECRET_PENALTY_MAX_FUTURE_EPOCHS=64,
    EPOCHS_PER_CUSTODY_PERIOD=32,
    CUSTODY_PERIOD_TO_RANDAO_PADDING=8,
    MAX_CHUNK_CHALLENGE_DELAY=64,
    MAX_CUSTODY_CHUNK_CHALLENGES=2,
    MAX_CUSTODY_CHUNK_CHALLENGE_RESP=8,
)

_MINIMAL_SHARDING = dict(
    _MAINNET_SHARDING,
    MAX_SHARDS=8,
    INITIAL_ACTIVE_SHARDS=2,
    MAX_SHARD_PROPOSER_SLASHINGS=4,
    KZG_SETUP_SIZE=64,  # fast dev setup; degree bound 64 points
)

_MINIMAL_EIP4844 = dict(
    _MAINNET_EIP4844,
    FIELD_ELEMENTS_PER_BLOB=4,  # tiny blobs for fast minimal-preset tests
)

PRESETS: Dict[str, Dict[str, Dict[str, int]]] = {
    "mainnet": {
        "phase0": _MAINNET_PHASE0,
        "altair": _MAINNET_ALTAIR,
        "bellatrix": _MAINNET_BELLATRIX,
        "capella": _MAINNET_CAPELLA,
        "custody_game": _MAINNET_CUSTODY,
        "sharding": _MAINNET_SHARDING,
        "eip4844": _MAINNET_EIP4844,
    },
    "minimal": {
        "phase0": _MINIMAL_PHASE0,
        "altair": _MINIMAL_ALTAIR,
        "bellatrix": _MINIMAL_BELLATRIX,
        "capella": _MINIMAL_CAPELLA,
        "custody_game": _MINIMAL_CUSTODY,
        "sharding": _MINIMAL_SHARDING,
        "eip4844": _MINIMAL_EIP4844,
    },
}


def preset_for(preset_name: str, forks) -> Dict[str, int]:
    """Merged preset-variable dict for the given fork chain (a list like
    ["phase0", "altair"]), mirroring setup.py:782-792's per-fork YAML load."""
    bundle = PRESETS[preset_name]
    out: Dict[str, int] = {}
    for fork in forks:
        out.update(bundle.get(fork, {}))
    return out
