"""Minimal stdlib client for the serve wire contract — the consumer
side used by tests, tools/serve_bench.py and tools/serve_smoke.py (and
a reasonable starting point for real callers; the contract itself is
documented in docs/SERVE.md, this is just http.client plumbing).

One :class:`ServeClient` holds ONE keep-alive connection and is NOT
thread-safe — each concurrent client thread owns its own instance,
which is exactly the N-clients shape the daemon's micro-batcher
amortizes across.
"""
from __future__ import annotations

import http.client
import json
import socket
from typing import Any, Dict, List, Optional, Sequence

from .. import obs
from . import protocol


class ServeError(Exception):
    """A structured error response from the daemon."""

    def __init__(self, status: int, code: str, message: str) -> None:
        super().__init__(f"[{status}/{code}] {message}")
        self.status = status
        self.code = code
        self.message = message


class ServeClient:
    def __init__(self, port: int, host: str = "127.0.0.1",
                 timeout_s: float = 120.0) -> None:
        self.host = host
        self.port = port
        self.timeout_s = timeout_s
        self._conn: Optional[http.client.HTTPConnection] = None

    # -- plumbing ------------------------------------------------------

    def _connection(self) -> http.client.HTTPConnection:
        if self._conn is None:
            self._conn = http.client.HTTPConnection(
                self.host, self.port, timeout=self.timeout_s)
            self._conn.connect()
            # see daemon._Handler.disable_nagle_algorithm: without this a
            # loopback round-trip stalls ~40ms in delayed-ACK territory
            self._conn.sock.setsockopt(
                socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return self._conn

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None

    def __enter__(self) -> "ServeClient":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    def _roundtrip(self, method: str, path: str,
                   body: Optional[Dict[str, Any]] = None) -> Any:
        conn = self._connection()
        payload = protocol.dumps(body) if body is not None else None
        headers = {"Content-Type": "application/json"} if payload else {}
        try:
            conn.request(method, path, body=payload, headers=headers)
            resp = conn.getresponse()
            raw = resp.read()
        except Exception:
            self.close()  # a torn connection must not poison the next call
            raise
        if path == "/metrics":
            if resp.status != 200:
                raise ServeError(resp.status, protocol.INTERNAL,
                                 raw.decode(errors="replace")[:200])
            return raw.decode()
        try:
            obj = json.loads(raw.decode())
        except ValueError:
            raise ServeError(resp.status, protocol.INTERNAL,
                             f"non-JSON response: {raw[:200]!r}")
        if isinstance(obj, dict) and obj.get("ok") is False:
            err = obj.get("error") or {}
            raise ServeError(resp.status, err.get("code", protocol.INTERNAL),
                             err.get("message", ""))
        return obj

    def call(self, method: str, params: Dict[str, Any]) -> Dict[str, Any]:
        """One wire method round trip. With tracing armed, the call runs
        under a ``serve.client`` span and injects its trace context as
        the optional ``trace`` wire field, so the daemon-side request
        span files under THIS span in the merged trace (docs/SERVE.md).
        Disabled cost: one env check."""
        if not obs.enabled():
            return self._roundtrip("POST", protocol.route_for(method), params)
        with obs.span("serve.client", method=method,
                      host=self.host, port=self.port):
            tp = obs.traceparent()
            if tp is not None and protocol.TRACE_FIELD not in params:
                params = dict(params)
                params[protocol.TRACE_FIELD] = tp
            return self._roundtrip("POST", protocol.route_for(method), params)

    # -- the wire methods ----------------------------------------------

    def verify(self, *, pubkeys: Optional[Sequence[bytes]] = None,
               pubkey: Optional[bytes] = None,
               message: Optional[bytes] = None,
               messages: Optional[Sequence[bytes]] = None,
               signature: bytes) -> bool:
        params: Dict[str, Any] = {"signature": protocol.to_hex(signature)}
        if pubkey is not None:
            params["pubkey"] = protocol.to_hex(pubkey)
        if pubkeys is not None:
            params["pubkeys"] = [protocol.to_hex(p) for p in pubkeys]
        if message is not None:
            params["message"] = protocol.to_hex(message)
        if messages is not None:
            params["messages"] = [protocol.to_hex(m) for m in messages]
        return bool(self.call("verify", params)["valid"])

    def verify_batch(self, checks: List[Dict[str, Any]]) -> List[bool]:
        return list(self.call("verify_batch", {"checks": checks})["results"])

    def hash_tree_root(self, fork: str, preset: str, type_name: str,
                       ssz_bytes: bytes) -> bytes:
        out = self.call("hash_tree_root", {
            "fork": fork, "preset": preset, "type": type_name,
            "ssz": protocol.to_hex(ssz_bytes)})
        return protocol.from_hex(out["root"], "root")

    def process_block(self, fork: str, preset: str, pre_ssz: bytes,
                      block_ssz: bytes) -> Dict[str, bytes]:
        out = self.call("process_block", {
            "fork": fork, "preset": preset,
            "pre": protocol.to_hex(pre_ssz),
            "block": protocol.to_hex(block_ssz)})
        return {"post": protocol.from_hex(out["post"], "post"),
                "root": protocol.from_hex(out["root"], "root")}

    # -- observability -------------------------------------------------

    def metrics(self) -> str:
        return self._roundtrip("GET", "/metrics")

    def health(self) -> Dict[str, Any]:
        return self._roundtrip("GET", "/healthz")

    def ready(self) -> bool:
        try:
            return bool(self._roundtrip("GET", "/readyz").get("ready"))
        except (ServeError, OSError):
            return False
