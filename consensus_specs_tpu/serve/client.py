"""Minimal stdlib client for the serve wire contract — the consumer
side used by tests, tools/serve_bench.py and tools/serve_smoke.py (and
a reasonable starting point for real callers; the contract itself is
documented in docs/SERVE.md, this is just http.client plumbing).

One :class:`ServeClient` holds ONE keep-alive connection and is NOT
thread-safe — each concurrent client thread owns its own instance,
which is exactly the N-clients shape the daemon's micro-batcher
amortizes across.

Retry discipline (docs/SERVE.md "Overload control"): retryable
refusals (``queue_full`` 429, ``draining`` 503) and torn connections
retry with **jittered exponential backoff**, but only while the
client-wide **token-bucket retry budget** holds tokens — each original
request deposits ``retry_ratio`` tokens (default 0.1 = at most ~10%
retry amplification in steady state), each retry spends one. An empty
bucket means the fleet is already overloaded and retrying would
multiply the offered load — the classic retry-storm / metastable-
failure amplifier — so the original error surfaces instead (counted
``serve.client.retry_budget_exhausted`` and committed to the flight
recorder). ``shed`` and ``deadline_exceeded`` responses are NEVER
retried: the daemon is explicitly telling the caller to back off / the
budget is spent. A client-level ``deadline_ms`` propagates on the wire
(minus elapsed time, re-computed per attempt) so the daemon can shed
work the caller has already given up on.
"""
from __future__ import annotations

import http.client
import json
import random
import socket
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .. import obs
from ..obs import flightrec
from . import protocol
from .ring import HashRing


class ServeError(Exception):
    """A structured error response from the daemon."""

    def __init__(self, status: int, code: str, message: str) -> None:
        super().__init__(f"[{status}/{code}] {message}")
        self.status = status
        self.code = code
        self.message = message


# refusals worth retrying (transient queue states); sheds and deadline
# expiries are the daemon telling the caller NOT to add load
RETRYABLE_CODES = (protocol.QUEUE_FULL, protocol.DRAINING)


class RetryBudget:
    """SRE-style token-bucket retry budget: ``capacity`` tokens to
    start, ``ratio`` deposited per original request, one spent per
    retry. Thread-safe (one budget may be shared by a fleet of
    per-thread clients to bound GLOBAL retry amplification)."""

    def __init__(self, capacity: float = 10.0, ratio: float = 0.1) -> None:
        self.capacity = max(0.0, float(capacity))
        self.ratio = max(0.0, float(ratio))
        self._tokens = self.capacity
        self._lock = threading.Lock()

    def deposit(self) -> None:
        with self._lock:
            self._tokens = min(self.capacity, self._tokens + self.ratio)

    def try_spend(self) -> bool:
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    @property
    def tokens(self) -> float:
        with self._lock:
            return self._tokens


class _WireCalls:
    """The typed wire-method surface, defined over ``self.call`` so the
    single-daemon client and the fleet router share one implementation."""

    def call(self, method: str, params: Dict[str, Any],
             deadline_ms: Optional[float] = None,
             priority: Optional[str] = None) -> Dict[str, Any]:
        raise NotImplementedError

    def verify(self, *, pubkeys: Optional[Sequence[bytes]] = None,
               pubkey: Optional[bytes] = None,
               message: Optional[bytes] = None,
               messages: Optional[Sequence[bytes]] = None,
               signature: bytes,
               deadline_ms: Optional[float] = None,
               priority: Optional[str] = None) -> bool:
        params: Dict[str, Any] = {"signature": protocol.to_hex(signature)}
        if pubkey is not None:
            params["pubkey"] = protocol.to_hex(pubkey)
        if pubkeys is not None:
            params["pubkeys"] = [protocol.to_hex(p) for p in pubkeys]
        if message is not None:
            params["message"] = protocol.to_hex(message)
        if messages is not None:
            params["messages"] = [protocol.to_hex(m) for m in messages]
        return bool(self.call("verify", params, deadline_ms=deadline_ms,
                              priority=priority)["valid"])

    def verify_batch(self, checks: List[Dict[str, Any]],
                     deadline_ms: Optional[float] = None,
                     priority: Optional[str] = None) -> List[bool]:
        return list(self.call("verify_batch", {"checks": checks},
                              deadline_ms=deadline_ms,
                              priority=priority)["results"])

    def hash_tree_root(self, fork: str, preset: str, type_name: str,
                       ssz_bytes: bytes) -> bytes:
        out = self.call("hash_tree_root", {
            "fork": fork, "preset": preset, "type": type_name,
            "ssz": protocol.to_hex(ssz_bytes)})
        return protocol.from_hex(out["root"], "root")

    def process_block(self, fork: str, preset: str, pre_ssz: bytes,
                      block_ssz: bytes) -> Dict[str, bytes]:
        out = self.call("process_block", {
            "fork": fork, "preset": preset,
            "pre": protocol.to_hex(pre_ssz),
            "block": protocol.to_hex(block_ssz)})
        return {"post": protocol.from_hex(out["post"], "post"),
                "root": protocol.from_hex(out["root"], "root")}


class ServeClient(_WireCalls):
    def __init__(self, port: int, host: str = "127.0.0.1",
                 timeout_s: float = 120.0,
                 *,
                 max_retries: int = 2,
                 retry_budget: Optional[RetryBudget] = None,
                 backoff_base_ms: float = 25.0,
                 backoff_cap_ms: float = 1000.0,
                 deadline_ms: Optional[float] = None,
                 priority: Optional[str] = None,
                 rng: Optional[random.Random] = None) -> None:
        self.host = host
        self.port = port
        self.timeout_s = timeout_s
        self.max_retries = max(0, int(max_retries))
        self.retry_budget = retry_budget if retry_budget is not None \
            else RetryBudget()
        self.backoff_base_ms = backoff_base_ms
        self.backoff_cap_ms = backoff_cap_ms
        self.deadline_ms = deadline_ms      # client-wide default budget
        self.priority = priority            # client-wide default class
        self._rng = rng or random.Random()
        self.retries = 0                    # spent on this client
        self._conn: Optional[http.client.HTTPConnection] = None

    # -- plumbing ------------------------------------------------------

    def _connection(self) -> http.client.HTTPConnection:
        if self._conn is None:
            self._conn = http.client.HTTPConnection(
                self.host, self.port, timeout=self.timeout_s)
            self._conn.connect()
            # see daemon._Handler.disable_nagle_algorithm: without this a
            # loopback round-trip stalls ~40ms in delayed-ACK territory
            self._conn.sock.setsockopt(
                socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return self._conn

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None

    def __enter__(self) -> "ServeClient":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    def _roundtrip(self, method: str, path: str,
                   body: Optional[Dict[str, Any]] = None) -> Any:
        conn = self._connection()
        payload = protocol.dumps(body) if body is not None else None
        headers = {"Content-Type": "application/json"} if payload else {}
        try:
            conn.request(method, path, body=payload, headers=headers)
            resp = conn.getresponse()
            raw = resp.read()
        except Exception:
            self.close()  # a torn connection must not poison the next call
            raise
        if path == "/metrics":
            if resp.status != 200:
                raise ServeError(resp.status, protocol.INTERNAL,
                                 raw.decode(errors="replace")[:200])
            return raw.decode()
        try:
            obj = json.loads(raw.decode())
        except ValueError:
            raise ServeError(resp.status, protocol.INTERNAL,
                             f"non-JSON response: {raw[:200]!r}")
        if isinstance(obj, dict) and obj.get("ok") is False:
            err = obj.get("error") or {}
            raise ServeError(resp.status, err.get("code", protocol.INTERNAL),
                             err.get("message", ""))
        return obj

    def call(self, method: str, params: Dict[str, Any],
             deadline_ms: Optional[float] = None,
             priority: Optional[str] = None) -> Dict[str, Any]:
        """One wire method call with retry discipline. With tracing
        armed, each attempt runs under a ``serve.client`` span and
        injects its trace context as the optional ``trace`` wire field,
        so the daemon-side request span files under THIS span in the
        merged trace (docs/SERVE.md). Disabled cost: one env check.

        ``deadline_ms`` (or the client-wide default) is the TOTAL
        budget across attempts: each attempt propagates the remaining
        budget on the wire, and an expired budget surfaces as a
        client-side ``deadline_exceeded`` ServeError without another
        round trip."""
        deadline_ms = deadline_ms if deadline_ms is not None else self.deadline_ms
        priority = priority if priority is not None else self.priority
        t_start = time.monotonic()
        self.retry_budget.deposit()
        attempt = 0
        while True:
            send = params
            remaining: Optional[float] = None
            if deadline_ms is not None:
                remaining = deadline_ms - (time.monotonic() - t_start) * 1e3
                if remaining <= 0:
                    obs.count("serve.client.deadline_expired")
                    raise ServeError(
                        protocol.HTTP_STATUS[protocol.DEADLINE_EXCEEDED],
                        protocol.DEADLINE_EXCEEDED,
                        f"client budget ({deadline_ms:.0f}ms) expired "
                        f"before attempt {attempt + 1}")
            if remaining is not None or priority is not None:
                send = dict(params)
                if remaining is not None:
                    send.setdefault(protocol.DEADLINE_FIELD, round(remaining, 3))
                if priority is not None:
                    send.setdefault(protocol.PRIORITY_FIELD, priority)
            try:
                return self._call_once(method, send)
            except (ServeError, OSError) as e:
                if not self._retryable(e) or attempt >= self.max_retries:
                    raise
                if not self.retry_budget.try_spend():
                    # retrying now would amplify offered load with no
                    # budget to pay for it — the retry-storm guard
                    obs.count("serve.client.retry_budget_exhausted")
                    flightrec.begin(method)
                    flightrec.commit(status="retry_budget_exhausted",
                                     error=str(e))
                    raise
                delay_s = self._backoff_s(attempt, remaining)
                obs.count("serve.client.retries")
                self.retries += 1
                if delay_s > 0:
                    time.sleep(delay_s)
                attempt += 1

    @staticmethod
    def _retryable(e: BaseException) -> bool:
        if isinstance(e, ServeError):
            return e.code in RETRYABLE_CODES
        return isinstance(e, OSError)  # torn/refused connection

    def _backoff_s(self, attempt: int, remaining_ms: Optional[float]) -> float:
        """Full-jitter exponential backoff, capped, and never sleeping
        past the remaining deadline budget."""
        cap_ms = min(self.backoff_cap_ms,
                     self.backoff_base_ms * (2 ** attempt))
        delay_ms = self._rng.uniform(0, cap_ms)
        if remaining_ms is not None:
            delay_ms = min(delay_ms, max(0.0, remaining_ms))
        return delay_ms / 1e3

    def _call_once(self, method: str, params: Dict[str, Any]) -> Dict[str, Any]:
        if not obs.enabled():
            return self._roundtrip("POST", protocol.route_for(method), params)
        with obs.span("serve.client", method=method,
                      host=self.host, port=self.port):
            tp = obs.traceparent()
            if tp is not None and protocol.TRACE_FIELD not in params:
                params = dict(params)
                params[protocol.TRACE_FIELD] = tp
            return self._roundtrip("POST", protocol.route_for(method), params)

    # -- observability -------------------------------------------------

    def metrics(self) -> str:
        return self._roundtrip("GET", "/metrics")

    def health(self) -> Dict[str, Any]:
        return self._roundtrip("GET", "/healthz")

    def ready(self) -> bool:
        try:
            return bool(self._roundtrip("GET", "/readyz").get("ready"))
        except (ServeError, OSError, http.client.HTTPException):
            return False


# ---------------------------------------------------------------------------
# the fleet router (docs/SERVE.md "Fleet")
# ---------------------------------------------------------------------------

# errors that justify re-sending the SAME request to the NEXT ring
# replica: the replica is gone/going (torn socket, refused connect,
# timeout, draining) or full (queue_full spills to a sibling with
# capacity). Sheds/deadlines/bad requests NEVER fail over — the fleet
# is saying "stop", or the request itself is wrong on every replica.
FAILOVER_CODES = (protocol.DRAINING, protocol.QUEUE_FULL)


class _ReplicaState:
    """Router-side view of one replica: its keep-alive client, the
    down-mark backoff, and the TTL-cached /readyz verdict."""

    __slots__ = ("name", "port", "client", "down_until",
                 "ready_checked", "ready")

    def __init__(self, name: str, port: int, client: ServeClient) -> None:
        self.name = name
        self.port = port
        self.client = client
        self.down_until = 0.0
        self.ready_checked = float("-inf")  # first use always probes
        self.ready = True


class FleetClient(_WireCalls):
    """Shard-aware failover router over a fleet of daemon replicas.

    Routing: each request's *identity* (``protocol.affinity_key`` — the
    params minus volatile fields) hashes onto a consistent-hash ring of
    replica names, so repeat traffic for one key lands on one replica
    (its LRU result cache and warm BLS bucket shapes stay hot) and a
    membership change moves only ~K/N keys. Health/drain awareness:
    replicas are dispatched optimistically, but each replica's
    ``/readyz`` is re-probed at most every ``health_ttl_s`` — a draining
    or heartbeat-stale replica answers 503 there and is routed around —
    and a replica that fails a request transport-wise is marked down for
    ``down_backoff_s`` before being re-probed.

    Failover exactly-once: every logical request carries ONE idempotency
    key across all its sends. An unanswered request (torn socket,
    timeout, refused connect, ``draining``/``queue_full`` refusal)
    re-sends to the next replica in the key's ring chain under the same
    key; a replica that already answered it replays its stored response
    from the idempotency cache instead of executing twice, and replicas
    that never saw it compute the same answer by purity — the caller
    receives exactly one answer, never a dropped request, never double
    work on one replica. Re-sends spend the **fleet-shared**
    :class:`RetryBudget` (pass one budget to every router in a client
    fleet): when the bucket is empty the error surfaces instead of
    joining a retry storm — the metastable-failure guard, fleet-wide.

    Tracing: every logical request runs under ONE ``serve.route`` span
    (attrs: chosen replica, failover count); each send is a
    ``serve.client`` child injecting the SAME trace context, so failover
    re-sends stay linked to the original trace id across processes.

    Like ServeClient, one FleetClient is NOT thread-safe — one per
    thread, sharing a membership callable and a RetryBudget.
    """

    def __init__(self, members: Any, *,
                 timeout_s: float = 30.0,
                 retry_budget: Optional[RetryBudget] = None,
                 health_ttl_s: float = 0.5,
                 down_backoff_s: float = 1.0,
                 deadline_ms: Optional[float] = None,
                 priority: Optional[str] = None,
                 host: str = "127.0.0.1",
                 rng: Optional[random.Random] = None) -> None:
        # members: a callable returning [(name, port), ...] (live view —
        # e.g. FleetSupervisor.members) or a static sequence of pairs
        self._members_fn = members if callable(members) else (lambda: members)
        self.timeout_s = timeout_s
        self.retry_budget = retry_budget if retry_budget is not None \
            else RetryBudget()
        self.health_ttl_s = health_ttl_s
        self.down_backoff_s = down_backoff_s
        self.deadline_ms = deadline_ms
        self.priority = priority
        self.host = host
        self._rng = rng or random.Random()
        self._ring = HashRing()
        self._replicas: Dict[str, _ReplicaState] = {}
        self._membership: Tuple = ()
        self.failovers = 0

    # -- membership ----------------------------------------------------

    def _refresh(self) -> None:
        snapshot = tuple(sorted((str(n), int(p))
                                for n, p in self._members_fn()))
        if snapshot == self._membership:
            return
        self._membership = snapshot
        live = {name: port for name, port in snapshot}
        for name in list(self._replicas):
            state = self._replicas[name]
            if name not in live:
                state.client.close()
                del self._replicas[name]
                self._ring.remove(name)
            elif state.port != live[name]:
                # respawned on a new port: same ring slot, fresh socket
                state.client.close()
                del self._replicas[name]
                self._ring.remove(name)
        for name, port in snapshot:
            if name not in self._replicas:
                self._replicas[name] = _ReplicaState(
                    name, port, ServeClient(port, host=self.host,
                                            timeout_s=self.timeout_s,
                                            max_retries=0))
                self._ring.add(name)

    def close(self) -> None:
        for state in self._replicas.values():
            state.client.close()

    def __enter__(self) -> "FleetClient":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    # -- health gating -------------------------------------------------

    def _usable(self, state: _ReplicaState, now: float) -> bool:
        if now < state.down_until:
            return False
        if now - state.ready_checked > self.health_ttl_s:
            state.ready = state.client.ready()
            state.ready_checked = now
            if not state.ready:
                # draining / heartbeat-stale / dead: routed around until
                # the next TTL probe says otherwise
                state.down_until = now + self.down_backoff_s
        return state.ready

    def _mark_down(self, state: _ReplicaState) -> None:
        state.ready = False
        state.ready_checked = time.monotonic()
        state.down_until = state.ready_checked + self.down_backoff_s

    # -- routing -------------------------------------------------------

    @staticmethod
    def _failover_worthy(e: BaseException) -> bool:
        if isinstance(e, ServeError):
            return e.code in FAILOVER_CODES
        return isinstance(e, (OSError, http.client.HTTPException,
                              TimeoutError))

    def call(self, method: str, params: Dict[str, Any],
             deadline_ms: Optional[float] = None,
             priority: Optional[str] = None) -> Dict[str, Any]:
        """Route one wire method call: affinity replica first, then the
        ring chain with the same idempotency key, spending the shared
        retry budget per re-send."""
        deadline_ms = deadline_ms if deadline_ms is not None else self.deadline_ms
        priority = priority if priority is not None else self.priority
        self._refresh()
        send = dict(params)
        send.setdefault(protocol.IDEM_FIELD,
                        f"{self._rng.getrandbits(64):016x}")
        key = protocol.affinity_key(method, params)
        chain = self._ring.chain(key)
        if not chain:
            raise ServeError(503, protocol.DRAINING,
                             "fleet has no routable members")
        obs.count("serve.route.requests")
        self.retry_budget.deposit()
        with obs.span("serve.route", method=method,
                      owner=chain[0]) as route_sp:
            now = time.monotonic()
            candidates = [self._replicas[n] for n in chain
                          if self._usable(self._replicas[n], now)]
            if not candidates:
                # everything marked down: dispatch the raw chain anyway
                # (a request must never be stranded by stale marks)
                candidates = [self._replicas[n] for n in chain]
            last_err: Optional[BaseException] = None
            for attempt, state in enumerate(candidates):
                if attempt > 0:
                    if not self.retry_budget.try_spend():
                        # re-sending without budget would turn one
                        # replica failure into a fleet-wide retry storm
                        obs.count("serve.route.budget_exhausted")
                        assert last_err is not None
                        raise last_err
                    obs.count("serve.route.failover")
                    self.failovers += 1
                try:
                    result = state.client.call(method, send,
                                               deadline_ms=deadline_ms,
                                               priority=priority)
                except (ServeError, OSError, http.client.HTTPException,
                        TimeoutError) as e:
                    if not self._failover_worthy(e):
                        raise
                    if not (isinstance(e, ServeError)
                            and e.code == protocol.QUEUE_FULL):
                        self._mark_down(state)  # full != unhealthy
                    last_err = e
                    self._refresh()  # a respawn may already have landed
                    continue
                if route_sp.span_id is not None:
                    route_sp.attrs["replica"] = state.name
                    route_sp.attrs["port"] = state.port
                    route_sp.attrs["failovers"] = attempt
                return result
            assert last_err is not None
            raise last_err
