"""The serve wire contract, version 1 (docs/SERVE.md).

JSON bodies over localhost HTTP. Every response carries ``v`` (the wire
version) and ``ok``; successful responses carry the method result,
failures an ``error`` object::

    {"v": 1, "ok": true,  ...result fields...}
    {"v": 1, "ok": false, "error": {"code": "...", "message": "..."}}

Error codes map onto HTTP statuses (and, for faults, onto the
resilience taxonomy so a client can tell a bad request from a degraded
backend):

    bad_request        400  malformed params / undecodable SSZ / unknown type
    not_found          404  unknown route or method
    queue_full         429  admission control: the bounded verify queue is full
    shed               429  overload control: a `sheddable`-priority request
                            was shed to protect higher-priority work (do NOT
                            blind-retry; the daemon is telling you it is
                            overloaded)
    deadline_exceeded  504  the request's `deadline_ms` budget expired (in
                            queue, or predicted to at admission) before any
                            flush work was spent on it
    draining           503  daemon is shutting down; request was NOT accepted
    internal           500  a fault the service could not degrade around

Overload-control wire fields (docs/SERVE.md "Overload control"; both
optional — v1 clients that omit them are unaffected):

    deadline_ms   number  the caller's REMAINING latency budget, relative
                          to request arrival (relative, because client and
                          daemon clocks need not agree). Admission
                          timestamps arrival; a request whose estimated
                          queue wait already exceeds the budget — or whose
                          budget expires while queued — is answered
                          `deadline_exceeded` instead of burning flush work.
    priority      string  `critical` | `default` | `sheddable`. Under
                          overload the queue sheds `sheddable` first;
                          `critical` bypasses the adaptive limit (never the
                          hard bound).

This module is pure stdlib and imported by both sides of the socket
(daemon and client) plus the bench/smoke tools — the contract lives in
exactly one place.
"""
from __future__ import annotations

import binascii
import json
from typing import Any, Dict, List, Optional, Tuple

WIRE_VERSION = 1

# route prefix for versioned methods; bumping WIRE_VERSION bumps this
API_PREFIX = f"/v{WIRE_VERSION}"

# method name -> route (POST). GET routes: /metrics /healthz /readyz
# /debug/requests /debug/slowest
METHODS = ("verify", "verify_batch", "hash_tree_root",
           "hash_tree_root_batch", "process_block",
           "fork_choice_attestation")

# introspection surface: scraped by monitors, never served traffic —
# excluded from serve.request_ms accounting, the flight recorder, and
# SLO denominators so a tight scrape loop cannot skew the histograms
INTROSPECTION_ROUTES = ("/metrics", "/healthz", "/readyz")
DEBUG_PREFIX = "/debug/"

# every request body MAY carry a trace context field (v1 clients that
# omit it are unaffected): a W3C-traceparent-shaped string
# ``00-<trace-id>-<parent-span-id>-01`` linking the daemon-side spans
# under the client's request span (obs.traceparent / obs.remote_span)
TRACE_FIELD = "trace"

# overload-control fields (optional on every POST body; see module
# docstring): a relative latency budget and a criticality class
DEADLINE_FIELD = "deadline_ms"
PRIORITY_FIELD = "priority"

# fleet-routing fields (optional; docs/SERVE.md "Fleet"): an
# idempotency key a failover router attaches so a request re-sent to
# another replica — or re-sent to the SAME replica after a torn
# connection — is answered from the daemon's bounded idempotency cache
# instead of executed twice. Volatile per logical request, stable
# across its attempts.
IDEM_FIELD = "idem"
IDEM_MAX_LEN = 128

# request fields that vary per attempt / per caller without changing
# the request's *identity* — stripped before computing affinity keys
VOLATILE_FIELDS = (TRACE_FIELD, DEADLINE_FIELD, PRIORITY_FIELD,
                   IDEM_FIELD, "v")

PRIORITY_CRITICAL = "critical"
PRIORITY_DEFAULT = "default"
PRIORITY_SHEDDABLE = "sheddable"
PRIORITIES = (PRIORITY_CRITICAL, PRIORITY_DEFAULT, PRIORITY_SHEDDABLE)

BAD_REQUEST = "bad_request"
NOT_FOUND = "not_found"
QUEUE_FULL = "queue_full"
SHED = "shed"
DEADLINE_EXCEEDED = "deadline_exceeded"
DRAINING = "draining"
INTERNAL = "internal"

HTTP_STATUS = {
    BAD_REQUEST: 400,
    NOT_FOUND: 404,
    QUEUE_FULL: 429,
    SHED: 429,
    DEADLINE_EXCEEDED: 504,
    DRAINING: 503,
    INTERNAL: 500,
}


class RequestError(Exception):
    """A request the service rejects — carries the wire error code."""

    def __init__(self, code: str, message: str) -> None:
        super().__init__(message)
        self.code = code
        self.message = message

    @property
    def http_status(self) -> int:
        return HTTP_STATUS.get(self.code, 500)


def bad_request(message: str) -> RequestError:
    return RequestError(BAD_REQUEST, message)


# ---------------------------------------------------------------------------
# encoding helpers (hex on the wire, bytes in the service)
# ---------------------------------------------------------------------------

def to_hex(data: bytes) -> str:
    return "0x" + bytes(data).hex()


def from_hex(value: Any, field: str) -> bytes:
    if not isinstance(value, str):
        raise bad_request(f"{field}: expected a hex string")
    raw = value[2:] if value.startswith("0x") else value
    try:
        return binascii.unhexlify(raw)
    except (binascii.Error, ValueError) as e:
        raise bad_request(f"{field}: invalid hex ({e})")


def hex_list(value: Any, field: str) -> List[bytes]:
    if not isinstance(value, (list, tuple)):
        raise bad_request(f"{field}: expected a list of hex strings")
    return [from_hex(v, f"{field}[{i}]") for i, v in enumerate(value)]


# ---------------------------------------------------------------------------
# verify-check parsing: wire params -> the facade's deferred-check key
# (the same key shape crypto.bls.DeferredVerifier records, so the served
# path and the direct path dedup/bucket/dispatch identically)
# ---------------------------------------------------------------------------

def parse_check(params: Dict[str, Any], field: str = "params") -> Tuple:
    """One verify check -> a DeferredVerifier key:

    - ``{"pubkey", "message", "signature"}``              -> ``("v", ...)``
    - ``{"pubkeys", "message", "signature"}``             -> ``("fav", ...)``
    - ``{"pubkeys", "messages", "signature"}``            -> ``("av", ...)``
    """
    if not isinstance(params, dict):
        raise bad_request(f"{field}: expected an object")
    sig = from_hex(params.get("signature"), f"{field}.signature")
    if "pubkey" in params:
        return ("v", from_hex(params["pubkey"], f"{field}.pubkey"),
                from_hex(params.get("message"), f"{field}.message"), sig)
    if "pubkeys" not in params:
        raise bad_request(f"{field}: needs 'pubkey' or 'pubkeys'")
    pks = tuple(hex_list(params["pubkeys"], f"{field}.pubkeys"))
    if "messages" in params:
        msgs = tuple(hex_list(params["messages"], f"{field}.messages"))
        if len(msgs) != len(pks):
            raise bad_request(f"{field}: len(messages) != len(pubkeys)")
        return ("av", pks, msgs, sig)
    if not pks:
        raise bad_request(f"{field}.pubkeys: must be non-empty")
    return ("fav", pks, from_hex(params.get("message"), f"{field}.message"), sig)


def require_str(params: Dict[str, Any], field: str) -> str:
    value = params.get(field)
    if not isinstance(value, str) or not value:
        raise bad_request(f"{field}: expected a non-empty string")
    return value


# ---------------------------------------------------------------------------
# response envelopes
# ---------------------------------------------------------------------------

def ok_response(result: Dict[str, Any]) -> Dict[str, Any]:
    out: Dict[str, Any] = {"v": WIRE_VERSION, "ok": True}
    out.update(result)
    return out


def error_response(code: str, message: str) -> Dict[str, Any]:
    return {"v": WIRE_VERSION, "ok": False,
            "error": {"code": code, "message": message[:800]}}


def dumps(obj: Dict[str, Any]) -> bytes:
    return json.dumps(obj, sort_keys=True).encode()


def loads(body: bytes) -> Dict[str, Any]:
    try:
        obj = json.loads(body.decode())
    except (ValueError, UnicodeDecodeError) as e:
        raise bad_request(f"body is not valid JSON ({e})")
    if not isinstance(obj, dict):
        raise bad_request("body must be a JSON object")
    return obj


def check_version(obj: Dict[str, Any]) -> None:
    """Bodies MAY pin ``v``; a mismatched pin is a bad request (the route
    prefix is the primary version channel)."""
    v = obj.get("v")
    if v is not None and v != WIRE_VERSION:
        raise bad_request(f"wire version {v} not supported (have {WIRE_VERSION})")


def is_introspection(path: str) -> bool:
    """True for the scrape/debug surface (never served traffic)."""
    return path in INTROSPECTION_ROUTES or path.startswith(DEBUG_PREFIX)


def trace_context(params: Dict[str, Any]) -> Optional[str]:
    """The optional wire trace field. Present-but-not-a-string is a bad
    request (a typed contract violation); an unparseable traceparent
    STRING is the W3C restart-the-trace case and is handled downstream
    (obs.remote_span degrades to a fresh span)."""
    value = params.get(TRACE_FIELD)
    if value is None:
        return None
    if not isinstance(value, str):
        raise bad_request(f"{TRACE_FIELD}: expected a traceparent string")
    return value


def request_deadline_ms(params: Dict[str, Any]) -> Optional[float]:
    """The optional ``deadline_ms`` budget: a positive-or-zero number.
    Absent -> None (no deadline). A non-number, bool, NaN, or negative
    value is a typed contract violation (bad request)."""
    value = params.get(DEADLINE_FIELD)
    if value is None:
        return None
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        raise bad_request(f"{DEADLINE_FIELD}: expected a number of ms")
    ms = float(value)
    if ms != ms or ms < 0:  # NaN or negative
        raise bad_request(f"{DEADLINE_FIELD}: must be a finite ms budget >= 0")
    return ms


def request_priority(params: Dict[str, Any]) -> str:
    """The optional ``priority`` class; absent -> ``default``."""
    value = params.get(PRIORITY_FIELD)
    if value is None:
        return PRIORITY_DEFAULT
    if not isinstance(value, str) or value not in PRIORITIES:
        raise bad_request(
            f"{PRIORITY_FIELD}: expected one of {'/'.join(PRIORITIES)}")
    return value


def request_idem(params: Dict[str, Any]) -> Optional[str]:
    """The optional idempotency key; absent -> None. A non-string,
    empty, or oversized key is a typed contract violation."""
    value = params.get(IDEM_FIELD)
    if value is None:
        return None
    if not isinstance(value, str) or not value or len(value) > IDEM_MAX_LEN:
        raise bad_request(
            f"{IDEM_FIELD}: expected a non-empty string of at most "
            f"{IDEM_MAX_LEN} chars")
    return value


def affinity_key(method: str, params: Dict[str, Any]) -> bytes:
    """The fleet router's key→replica affinity identity: a canonical
    encoding of (method, params minus the volatile per-attempt fields),
    so the SAME logical check routes to the SAME replica every time —
    its per-replica LRU result cache entry and warm BLS bucket shapes
    stay hot — while deadlines/priorities/trace contexts/idempotency
    keys never scatter repeats across the ring (docs/SERVE.md "Fleet")."""
    stable = {k: v for k, v in params.items() if k not in VOLATILE_FIELDS}
    return f"{method}\x00".encode() + json.dumps(
        stable, sort_keys=True, default=repr).encode()


def route_for(method: str) -> str:
    return f"{API_PREFIX}/{method}"


def method_for(path: str) -> Optional[str]:
    """The method a POST path names, or None."""
    if not path.startswith(API_PREFIX + "/"):
        return None
    name = path[len(API_PREFIX) + 1:].strip("/")
    return name if name in METHODS else None
