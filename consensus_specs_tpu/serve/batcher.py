"""Bounded request queue + micro-batcher: the continuous-batching core
of the resident verification service.

Concurrent clients submit individual signature checks; a single flusher
thread accumulates them (up to ``max_batch`` rows or a ``linger_ms``
window, whichever fills first) and dispatches the whole accumulation as
ONE cross-client flush through the facade's ``DeferredVerifier`` — the
same dedup + ``sched.bucketing.plan_flush`` canonical-bucket pipeline
the offline generator uses, so a request mix of 1-key exits and 512-key
sync aggregates compiles O(#buckets) programs and pads nothing to the
widest row (docs/GENPIPE.md). Per-request futures resolve when their
flush lands.

Admission control: the queue is bounded (``max_queue``); a submit
against a full queue raises :class:`QueueFull` immediately (the daemon
maps it to a 429) instead of queueing unbounded work — counted under
``serve.rejected`` so backpressure is visible in /metrics.

Result cache: a verify check is a pure function of its key (the same
rationale that lets the flush dedup rows), so resolved answers populate
a bounded LRU keyed by check key. Repeat traffic — the validator
registry repeats across a workload — is answered at queue-free latency
and counted under ``serve.cache_hits``.

Degradation: the flush body runs under ``resilience.supervised`` with
the per-row host oracle as fallback — a chaos-injected or real backend
fault mid-flight (site ``serve.flush``) degrades THAT batch to the
always-correct reference path; concurrent clients still get bit-exact
answers, and the event lands in the trace. Faults inside a single row's
oracle evaluation answer that row ``False`` (the facade's invalid-input
contract) without poisoning the batch.

Drain: ``drain()`` closes intake (later submits raise
:class:`Draining`), flushes every accepted entry, resolves every
future, and joins the flusher thread — no accepted check is ever
dropped or dispatched twice (each entry is popped exactly once).
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Deque, Dict, List, Optional, Tuple

from .. import obs
from ..obs import flightrec
from ..resilience import chaos, record_event, supervised

DEFAULT_MAX_QUEUE = 1024
DEFAULT_MAX_BATCH = 256
DEFAULT_LINGER_MS = 5.0
DEFAULT_CACHE_SIZE = 4096


class QueueFull(Exception):
    """Admission control: the bounded queue is at capacity."""


class Draining(Exception):
    """Intake is closed: the daemon is shutting down."""


class _Pending:
    """One accepted check: resolved exactly once by the flusher.

    ``origin`` carries the submitting request's identity — (trace id,
    span id, thread id), captured only when tracing is armed — so the
    flusher can attribute the queue wait and the shared flush back to
    the request span. ``stats`` is filled at flush time (queue-wait /
    flush ms, bucket shape, degradation) and read back on the handler
    thread for the flight recorder."""

    __slots__ = ("key", "done", "result", "error", "t_submit",
                 "origin", "stats")

    def __init__(self, key: Tuple,
                 origin: Optional[Tuple[Optional[str], str, int]] = None) -> None:
        self.key = key
        self.done = threading.Event()
        self.result: Optional[bool] = None
        self.error: Optional[BaseException] = None
        self.t_submit = time.monotonic()
        self.origin = origin
        self.stats: Optional[Dict[str, object]] = None

    def resolve(self, result: bool) -> None:
        self.result = result
        self.done.set()

    def fail(self, error: BaseException) -> None:
        self.error = error
        self.done.set()


class VerifyBatcher:
    """The bounded queue + flusher thread. One instance per daemon."""

    def __init__(
        self,
        *,
        max_queue: int = DEFAULT_MAX_QUEUE,
        max_batch: int = DEFAULT_MAX_BATCH,
        linger_ms: float = DEFAULT_LINGER_MS,
        cache_size: int = DEFAULT_CACHE_SIZE,
    ) -> None:
        self.max_queue = max(1, int(max_queue))
        self.max_batch = max(1, int(max_batch))
        self.linger_s = max(0.0, float(linger_ms)) / 1e3
        self.cache_size = max(0, int(cache_size))
        self._q: Deque[_Pending] = deque()
        self._cond = threading.Condition()
        self._cache: "OrderedDict[Tuple, bool]" = OrderedDict()
        self._closing = False
        self._thread: Optional[threading.Thread] = None
        self.stats_lock = threading.Lock()
        self.accepted = 0
        self.rejected = 0
        self.cache_hits = 0
        self.flushes = 0
        self.flushed_rows = 0

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "VerifyBatcher":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="serve-flusher", daemon=True)
            self._thread.start()
        return self

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Close intake, flush everything accepted, join the flusher.
        Returns True when the queue fully drained within the timeout."""
        with self._cond:
            self._closing = True
            self._cond.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout_s)
        with self._cond:
            return not self._q and (t is None or not t.is_alive())

    @property
    def draining(self) -> bool:
        return self._closing

    def depth(self) -> int:
        with self._cond:
            return len(self._q)

    def cache_stats(self) -> Dict[str, int]:
        with self.stats_lock:
            return {"size": len(self._cache), "hits": self.cache_hits,
                    "capacity": self.cache_size}

    # -- intake --------------------------------------------------------

    def submit(self, key: Tuple, timeout_s: Optional[float] = None) -> bool:
        """Submit one check key (the DeferredVerifier key shape) and
        block until its flush resolves. Raises :class:`QueueFull` /
        :class:`Draining` at admission time, TimeoutError if the result
        does not land within ``timeout_s``."""
        if self.cache_size:
            with self.stats_lock:
                cached = self._cache.get(key)
                if cached is not None:
                    self._cache.move_to_end(key)
                    self.cache_hits += 1
            if cached is not None:
                obs.count("serve.cache_hits")
                flightrec.note(cache_hit=True)
                return cached
        pending = self._enqueue([key])[0]
        result = self._await(pending, timeout_s)
        if pending.stats is not None:
            flightrec.note(**pending.stats)
        return result

    def submit_many(self, keys: List[Tuple],
                    timeout_s: Optional[float] = None) -> List[bool]:
        """Batched submit: all-or-nothing admission (a 429 must never
        leave half a client batch queued), one future per key."""
        results: Dict[int, bool] = {}
        misses: List[Tuple[int, Tuple]] = []
        if self.cache_size:
            with self.stats_lock:
                for i, key in enumerate(keys):
                    cached = self._cache.get(key)
                    if cached is None:
                        misses.append((i, key))
                    else:
                        self._cache.move_to_end(key)
                        self.cache_hits += 1
                        results[i] = cached
        else:
            misses = list(enumerate(keys))
        if results:
            obs.count("serve.cache_hits", len(results))
            flightrec.note(cache_hits=len(results))
        if misses:
            pendings = self._enqueue([k for _, k in misses])
            for (i, _), pending in zip(misses, pendings):
                results[i] = self._await(pending, timeout_s)
            if pendings[0].stats is not None:
                flightrec.note(**pendings[0].stats)
        return [results[i] for i in range(len(keys))]

    def _enqueue(self, keys: List[Tuple]) -> List[_Pending]:
        origin: Optional[Tuple[Optional[str], str, int]] = None
        if obs.enabled():
            sp = obs.current_span()
            if sp is not None:
                origin = (sp.remote_trace, sp.span_id,
                          threading.get_ident() & 0xFFFFFFFF)
        with self._cond:
            if self._closing:
                raise Draining("serve batcher is draining")
            if len(self._q) + len(keys) > self.max_queue:
                with self.stats_lock:
                    self.rejected += len(keys)
                obs.count("serve.rejected", len(keys))
                raise QueueFull(
                    f"verify queue full ({len(self._q)}/{self.max_queue})")
            pendings = [_Pending(k, origin) for k in keys]
            self._q.extend(pendings)
            with self.stats_lock:
                self.accepted += len(keys)
            obs.count("serve.accepted", len(keys))
            self._cond.notify_all()
        return pendings

    @staticmethod
    def _await(pending: _Pending, timeout_s: Optional[float]) -> bool:
        if not pending.done.wait(timeout_s):
            raise TimeoutError("verify result did not land in time")
        if pending.error is not None:
            raise pending.error
        assert pending.result is not None
        return pending.result

    # -- the flusher thread --------------------------------------------

    def _run(self) -> None:
        while True:
            batch = self._collect()
            if not batch:
                return  # closing and empty: done
            self._flush(batch)

    def _collect(self) -> List[_Pending]:
        """Block for the first entry, then linger up to ``linger_s`` for
        the batch to fill (skipped when closing: drain flushes at full
        speed). Pops at most ``max_batch`` entries — each exactly once."""
        with self._cond:
            while not self._q and not self._closing:
                self._cond.wait()
            if self._q and not self._closing and self.linger_s > 0:
                deadline = time.monotonic() + self.linger_s
                while len(self._q) < self.max_batch and not self._closing:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
            batch = [self._q.popleft()
                     for _ in range(min(len(self._q), self.max_batch))]
        return batch

    def _flush(self, batch: List[_Pending]) -> None:
        t0 = time.monotonic()
        for p in batch:
            obs.observe("serve.queue_wait_ms", (t0 - p.t_submit) * 1e3)

        # request-scoped attribution (tracing armed): a synthesized
        # serve.queue_wait child under each member's request span, and
        # the shared flush span linked to EVERY member — the merged
        # trace shows which other clients' checks shared this bucket
        member_spans: List[str] = []
        member_traces: List[str] = []
        if obs.enabled():
            for p in batch:
                if p.origin is None:
                    continue
                trace_id, span_id, tid = p.origin
                member_spans.append(span_id)
                if trace_id and trace_id not in member_traces:
                    member_traces.append(trace_id)
                ts = obs.mono_to_us(p.t_submit)
                if ts is not None:
                    obs.emit_span("serve.queue_wait", ts,
                                  (t0 - p.t_submit) * 1e6, parent=span_id,
                                  trace=trace_id, tid=tid)

        degraded = {"hit": False}

        def dispatch() -> Dict[Tuple, bool]:
            chaos("serve.flush")
            from ..crypto import bls

            verifier = bls.DeferredVerifier()
            for p in batch:
                verifier.record(p.key)
            verifier.flush()
            return verifier.table()

        def fallback() -> Dict[Tuple, bool]:
            degraded["hit"] = True
            return self._oracle_flush(batch)

        with obs.span("serve.flush", rows=len(batch),
                      members=len(member_spans),
                      client_traces=",".join(member_traces) or None) as fsp:
            fsp.link(*member_spans)
            try:
                table = supervised(
                    dispatch, domain="serve.flush", fallback=fallback)
            except BaseException as e:  # a fallback that itself failed
                for p in batch:
                    p.fail(e)
                return
        with self.stats_lock:
            self.flushes += 1
            self.flushed_rows += len(batch)
        obs.count("serve.flushes")
        obs.count("serve.flush_rows", len(batch))
        flush_ms = (time.monotonic() - t0) * 1e3
        obs.observe("serve.flush_ms", flush_ms)
        if self.cache_size:
            with self.stats_lock:
                for key, result in table.items():
                    self._cache[key] = result
                    self._cache.move_to_end(key)
                while len(self._cache) > self.cache_size:
                    self._cache.popitem(last=False)
        for p in batch:
            p.stats = {
                "queue_wait_ms": round((t0 - p.t_submit) * 1e3, 3),
                "flush_ms": round(flush_ms, 3),
                "batch_rows": len(batch),
            }
            if degraded["hit"]:
                p.stats["degraded"] = True
            p.resolve(bool(table[p.key]))

    @staticmethod
    def _oracle_flush(batch: List[_Pending]) -> Dict[Tuple, bool]:
        """Per-row host-oracle degradation: answer every check straight
        from the reference ciphersuite (never the installed backend — it
        just faulted). A row the oracle rejects-by-raising is False, the
        facade's invalid-input contract."""
        from ..crypto.bls import ciphersuite as oracle

        ops = {"v": oracle.Verify, "fav": oracle.FastAggregateVerify,
               "av": oracle.AggregateVerify}
        record_event("fallback", domain="serve.flush", capability="serve.flush",
                     detail=f"batch of {len(batch)} degraded to the host oracle")
        obs.count("serve.flush_degraded")
        table: Dict[Tuple, bool] = {}
        for p in batch:
            if p.key in table:
                continue
            kind, a, b, sig = p.key
            try:
                table[p.key] = bool(ops[kind](
                    list(a) if isinstance(a, tuple) else a,
                    list(b) if isinstance(b, tuple) else b, sig))
            except Exception:
                table[p.key] = False
        return table
