"""Bounded request queue + micro-batcher: the continuous-batching core
of the resident verification service.

Concurrent clients submit individual signature checks; a single flusher
thread accumulates them (up to ``max_batch`` rows or a ``linger_ms``
window, whichever fills first) and dispatches the whole accumulation as
ONE cross-client flush through the facade's ``DeferredVerifier`` — the
same dedup + ``sched.bucketing.plan_flush`` canonical-bucket pipeline
the offline generator uses, so a request mix of 1-key exits and 512-key
sync aggregates compiles O(#buckets) programs and pads nothing to the
widest row (docs/GENPIPE.md). Per-request futures resolve when their
flush lands.

Admission control (docs/SERVE.md "Overload control"): the queue is
hard-bounded (``max_queue``) and, by default, *adaptively* bounded
below that by an :class:`~.admission.AdmissionController` — an AIMD
limit driven by the observed queue-wait p99 against a latency target,
so queue depth tracks what the flush pipeline can actually absorb. A
submit against the hard bound raises :class:`QueueFull` (429,
``serve.rejected``); over the adaptive limit the queue sheds by
criticality class: an incoming ``sheddable`` request is refused with
:class:`Shed`, queued ``sheddable`` entries are evicted (answered with
:class:`Shed`) to make room for ``default`` traffic, and ``critical``
bypasses the adaptive limit entirely (never the hard bound). A request
carrying a ``deadline_ms`` budget is rejected with
:class:`DeadlineExceeded` at admission when the estimated completion
time (queue wait from live ``serve.queue_wait_ms`` evidence + drain
rate, plus the EWMA flush service time) already exceeds it, and entries
whose deadline expires while queued are shed — answered
``deadline_exceeded``, never dropped — *before* any flush work is spent
on them. Under sustained pressure the controller enters brownout and
the linger window collapses to zero. All sheds are counted per class
(``serve.shed.*``) and land in the flight recorder; after a drain,
``accepted == flushed_rows + shed_rows`` — exactly-once, with sheds
accounted separately.

Result cache: a verify check is a pure function of its key (the same
rationale that lets the flush dedup rows), so resolved answers populate
a bounded LRU keyed by check key. Repeat traffic — the validator
registry repeats across a workload — is answered at queue-free latency
and counted under ``serve.cache_hits``.

Degradation: the flush body runs under ``resilience.supervised`` with
the per-row host oracle as fallback — a chaos-injected or real backend
fault mid-flight (site ``serve.flush``) degrades THAT batch to the
always-correct reference path; concurrent clients still get bit-exact
answers, and the event lands in the trace. Faults inside a single row's
oracle evaluation answer that row ``False`` (the facade's invalid-input
contract) without poisoning the batch.

Drain: ``drain()`` closes intake (later submits raise
:class:`Draining`), flushes every accepted entry, resolves every
future, and joins the flusher thread — no accepted check is ever
dropped or dispatched twice (each entry is popped exactly once).
"""
from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict, deque
from typing import Deque, Dict, List, Optional, Tuple

from .. import obs
from ..obs import flightrec
from ..resilience import chaos, record_event, supervised
from . import protocol
from .admission import AdmissionController

DEFAULT_MAX_QUEUE = 1024
DEFAULT_MAX_BATCH = 256
DEFAULT_LINGER_MS = 5.0
DEFAULT_CACHE_SIZE = 4096

# drill knob (docs/SERVE.md "Overload control"): a deterministic
# simulated service time per flush, so overload drills / the perfgate
# slice can create real queueing pressure jax-free and crypto-free
ENV_FLUSH_DELAY = "CONSENSUS_SPECS_TPU_SERVE_FLUSH_DELAY_MS"


class QueueFull(Exception):
    """Admission control: the bounded queue is at capacity."""


class Draining(Exception):
    """Intake is closed: the daemon is shutting down."""


class DeadlineExceeded(Exception):
    """Overload control: the request's ``deadline_ms`` budget expired
    while queued, or the estimated queue wait already exceeds it at
    admission — answered structured (wire code ``deadline_exceeded``),
    never silently dropped."""


class Shed(Exception):
    """Overload control: a ``sheddable``-priority request was refused
    (or evicted from the queue) to protect higher-priority work."""


class _Pending:
    """One accepted check: resolved exactly once by the flusher.

    ``origin`` carries the submitting request's identity — (trace id,
    span id, thread id), captured only when tracing is armed — so the
    flusher can attribute the queue wait and the shared flush back to
    the request span. ``stats`` is filled at flush time (queue-wait /
    flush ms, bucket shape, degradation) and read back on the handler
    thread for the flight recorder."""

    __slots__ = ("key", "done", "result", "error", "t_submit",
                 "origin", "stats", "priority", "deadline_at")

    def __init__(self, key: Tuple,
                 origin: Optional[Tuple[Optional[str], str, int]] = None,
                 priority: str = protocol.PRIORITY_DEFAULT,
                 deadline_ms: Optional[float] = None) -> None:
        self.key = key
        self.done = threading.Event()
        self.result: Optional[bool] = None
        self.error: Optional[BaseException] = None
        self.t_submit = time.monotonic()
        self.origin = origin
        self.priority = priority
        # absolute monotonic deadline: admission timestamps arrival, the
        # wire budget is relative (client and daemon clocks may disagree)
        self.deadline_at = (self.t_submit + deadline_ms / 1e3
                            if deadline_ms is not None else None)
        self.stats: Optional[Dict[str, object]] = None

    def expired(self, now: float) -> bool:
        return self.deadline_at is not None and now >= self.deadline_at

    def resolve(self, result: bool) -> None:
        self.result = result
        self.done.set()

    def fail(self, error: BaseException) -> None:
        self.error = error
        self.done.set()


class VerifyBatcher:
    """The bounded queue + flusher thread. One instance per daemon."""

    def __init__(
        self,
        *,
        max_queue: int = DEFAULT_MAX_QUEUE,
        max_batch: int = DEFAULT_MAX_BATCH,
        linger_ms: float = DEFAULT_LINGER_MS,
        cache_size: int = DEFAULT_CACHE_SIZE,
        admission: Optional[AdmissionController] = None,
        flush_delay_ms: Optional[float] = None,
    ) -> None:
        self.max_queue = max(1, int(max_queue))
        self.max_batch = max(1, int(max_batch))
        self.linger_s = max(0.0, float(linger_ms)) / 1e3
        self.cache_size = max(0, int(cache_size))
        self.admission = admission or AdmissionController(self.max_queue)
        if flush_delay_ms is None:
            try:
                flush_delay_ms = float(os.environ.get(ENV_FLUSH_DELAY, "") or 0)
            except ValueError:
                flush_delay_ms = 0.0
        self.flush_delay_s = max(0.0, flush_delay_ms) / 1e3
        self._q: Deque[_Pending] = deque()
        self._cond = threading.Condition()
        self._cache: "OrderedDict[Tuple, bool]" = OrderedDict()
        self._closing = False
        self._thread: Optional[threading.Thread] = None
        self.stats_lock = threading.Lock()
        self.accepted = 0
        self.rejected = 0
        self.cache_hits = 0
        self.flushes = 0
        self.flushed_rows = 0
        # sheds are accepted-then-answered-structured, never dropped:
        # after a drain, accepted == flushed_rows + shed_rows
        self.shed_rows = 0
        self.shed_by_class: Dict[str, int] = {"deadline": 0, "priority": 0,
                                              "admission_deadline": 0}

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "VerifyBatcher":
        if self._thread is None:
            self.admission.start()
            self._thread = threading.Thread(
                target=self._run, name="serve-flusher", daemon=True)
            self._thread.start()
        return self

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Close intake, flush everything accepted, join the flusher.
        Returns True when the queue fully drained within the timeout."""
        with self._cond:
            self._closing = True
            self._cond.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout_s)
        self.admission.stop()
        with self._cond:
            return not self._q and (t is None or not t.is_alive())

    @property
    def draining(self) -> bool:
        return self._closing

    def depth(self) -> int:
        with self._cond:
            return len(self._q)

    def cache_stats(self) -> Dict[str, int]:
        with self.stats_lock:
            return {"size": len(self._cache), "hits": self.cache_hits,
                    "capacity": self.cache_size}

    def overload_snapshot(self) -> Dict[str, object]:
        """The /debug/overload surface: admission state + shed tallies."""
        with self.stats_lock:
            sheds = dict(self.shed_by_class)
            shed_rows = self.shed_rows
        snap = self.admission.snapshot()
        snap.update({
            "depth": self.depth(),
            "linger_ms_effective": round(self._effective_linger_s() * 1e3, 3),
            "linger_ms_configured": round(self.linger_s * 1e3, 3),
            "shed": sheds,
            "shed_rows": shed_rows,
            "flush_delay_ms": round(self.flush_delay_s * 1e3, 3),
        })
        return snap

    # -- intake --------------------------------------------------------

    def submit(self, key: Tuple, timeout_s: Optional[float] = None,
               priority: str = protocol.PRIORITY_DEFAULT,
               deadline_ms: Optional[float] = None) -> bool:
        """Submit one check key (the DeferredVerifier key shape) and
        block until its flush resolves. Raises :class:`QueueFull` /
        :class:`Shed` / :class:`DeadlineExceeded` / :class:`Draining`
        at admission time, TimeoutError if the result does not land
        within ``timeout_s``."""
        if self.cache_size:
            with self.stats_lock:
                cached = self._cache.get(key)
                if cached is not None:
                    self._cache.move_to_end(key)
                    self.cache_hits += 1
            if cached is not None:
                obs.count("serve.cache_hits")
                flightrec.note(cache_hit=True)
                return cached
        pending = self._enqueue([key], priority, deadline_ms)[0]
        result = self._await(pending, timeout_s)
        if pending.stats is not None:
            flightrec.note(**pending.stats)
        return result

    def submit_many(self, keys: List[Tuple],
                    timeout_s: Optional[float] = None,
                    priority: str = protocol.PRIORITY_DEFAULT,
                    deadline_ms: Optional[float] = None) -> List[bool]:
        """Batched submit: all-or-nothing admission (a 429 must never
        leave half a client batch queued), one future per key. The
        priority/deadline apply to the whole wire request."""
        results: Dict[int, bool] = {}
        misses: List[Tuple[int, Tuple]] = []
        if self.cache_size:
            with self.stats_lock:
                for i, key in enumerate(keys):
                    cached = self._cache.get(key)
                    if cached is None:
                        misses.append((i, key))
                    else:
                        self._cache.move_to_end(key)
                        self.cache_hits += 1
                        results[i] = cached
        else:
            misses = list(enumerate(keys))
        if results:
            obs.count("serve.cache_hits", len(results))
            flightrec.note(cache_hits=len(results))
        if misses:
            pendings = self._enqueue([k for _, k in misses],
                                     priority, deadline_ms)
            for (i, _), pending in zip(misses, pendings):
                results[i] = self._await(pending, timeout_s)
            if pendings[0].stats is not None:
                flightrec.note(**pendings[0].stats)
        return [results[i] for i in range(len(keys))]

    def _enqueue(self, keys: List[Tuple],
                 priority: str = protocol.PRIORITY_DEFAULT,
                 deadline_ms: Optional[float] = None) -> List[_Pending]:
        origin: Optional[Tuple[Optional[str], str, int]] = None
        if obs.enabled():
            sp = obs.current_span()
            if sp is not None:
                origin = (sp.remote_trace, sp.span_id,
                          threading.get_ident() & 0xFFFFFFFF)
        k = len(keys)
        with self._cond:
            if self._closing:
                raise Draining("serve batcher is draining")
            # 1) the hard bound (the fixed PR-6 knob) always applies
            if len(self._q) + k > self.max_queue:
                with self.stats_lock:
                    self.rejected += k
                obs.count("serve.rejected", k)
                raise QueueFull(
                    f"verify queue full ({len(self._q)}/{self.max_queue})")
            # 2) deadline admission: reject a request whose estimated
            #    COMPLETION time (queue wait + flush service, from live
            #    evidence) already exceeds its remaining budget — the
            #    cheapest shed, before the queue ever holds the row
            if deadline_ms is not None:
                est = self.admission.estimator.completion_estimate_ms(
                    len(self._q))
                if est >= deadline_ms:
                    self._count_shed("admission_deadline", k, queued=False)
                    raise DeadlineExceeded(
                        f"estimated completion {est:.0f}ms exceeds the "
                        f"{deadline_ms:.0f}ms deadline budget")
            # 3) the adaptive limit, with priority shedding: sheddable
            #    is refused, queued sheddable is evicted for default
            #    traffic, critical bypasses (never past the hard bound)
            limit = self.admission.limit()
            if (len(self._q) + k > limit
                    and priority != protocol.PRIORITY_CRITICAL):
                if priority == protocol.PRIORITY_SHEDDABLE:
                    self._count_shed("priority", k, queued=False)
                    raise Shed(
                        f"queue over adaptive limit ({len(self._q)}/{limit}): "
                        "sheddable request refused")
                self._evict_sheddable(len(self._q) + k - limit)
                if len(self._q) + k > limit:
                    with self.stats_lock:
                        self.rejected += k
                    obs.count("serve.rejected", k)
                    raise QueueFull(
                        f"verify queue over adaptive limit "
                        f"({len(self._q)}/{limit}, hard {self.max_queue})")
            pendings = [_Pending(key, origin, priority, deadline_ms)
                        for key in keys]
            self._q.extend(pendings)
            with self.stats_lock:
                self.accepted += k
            obs.count("serve.accepted", k)
            self._cond.notify_all()
        return pendings

    def _evict_sheddable(self, need: int) -> None:
        """Shed up to ``need`` queued ``sheddable`` entries (oldest
        first — they are nearest their deadlines anyway), answering each
        with :class:`Shed`. Caller holds ``_cond``."""
        if need <= 0:
            return
        kept: List[_Pending] = []
        evicted: List[_Pending] = []
        for p in self._q:
            if len(evicted) < need and (
                    p.priority == protocol.PRIORITY_SHEDDABLE):
                evicted.append(p)
            else:
                kept.append(p)
        if not evicted:
            return
        self._q.clear()
        self._q.extend(kept)
        self._count_shed("priority", len(evicted), queued=True)
        for p in evicted:
            p.fail(Shed("evicted from the queue under overload "
                        "(sheddable priority)"))

    def _count_shed(self, klass: str, n: int, *, queued: bool) -> None:
        """Tally one shed decision: per-class counters always; the
        exactly-once ``shed_rows`` only for entries that were accepted
        (admission-time refusals were never queued)."""
        with self.stats_lock:
            self.shed_by_class[klass] = self.shed_by_class.get(klass, 0) + n
            if queued:
                self.shed_rows += n
        obs.count(f"serve.shed.{klass}", n)
        obs.count("serve.shed.total", n)

    @staticmethod
    def _await(pending: _Pending, timeout_s: Optional[float]) -> bool:
        if not pending.done.wait(timeout_s):
            raise TimeoutError("verify result did not land in time")
        if pending.error is not None:
            raise pending.error
        assert pending.result is not None
        return pending.result

    # -- the flusher thread --------------------------------------------

    def _run(self) -> None:
        while True:
            batch = self._collect()
            if not batch:
                return  # closing and empty: done
            self._flush(batch)

    def _effective_linger_s(self) -> float:
        """Brownout shrinks the linger window to zero: under sustained
        pressure a batch never waits for company — the queue already
        guarantees full batches, and every linger ms is pure added
        latency against the deadlines."""
        return 0.0 if self.admission.brownout() else self.linger_s

    def _collect(self) -> List[_Pending]:
        """Block for the first entry, then linger up to the effective
        linger window for the batch to fill (skipped when closing: drain
        flushes at full speed). Pops at most ``max_batch`` entries —
        each exactly once."""
        with self._cond:
            while not self._q and not self._closing:
                self._cond.wait()
            linger_s = self._effective_linger_s()
            if self._q and not self._closing and linger_s > 0:
                deadline = time.monotonic() + linger_s
                while len(self._q) < self.max_batch and not self._closing:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
            batch = [self._q.popleft()
                     for _ in range(min(len(self._q), self.max_batch))]
        return batch

    def _shed_expired(self, batch: List[_Pending]) -> List[_Pending]:
        """Answer every expired — or doomed — entry with
        ``deadline_exceeded`` BEFORE any flush work is spent on it (the
        anti-congestion-collapse move: never burn pairings for callers
        that gave up). Doomed = the remaining budget cannot even cover
        the flush's own estimated service time, so dispatching it could
        only produce a late answer. Returns the still-live remainder."""
        now = time.monotonic()
        horizon = now + self.admission.estimator.service_estimate_ms() / 1e3
        live: List[_Pending] = []
        shed: List[_Pending] = []
        for p in batch:
            (shed if p.expired(horizon) else live).append(p)
        if shed:
            self._count_shed("deadline", len(shed), queued=True)
            for p in shed:
                waited_ms = (now - p.t_submit) * 1e3
                p.stats = {"queue_wait_ms": round(waited_ms, 3),
                           "shed": "deadline"}
                verb = ("expired" if p.expired(now)
                        else "cannot complete within its budget")
                p.fail(DeadlineExceeded(
                    f"deadline {verb} after {waited_ms:.0f}ms in queue "
                    "(shed before flush)"))
        return live

    def _flush(self, batch: List[_Pending]) -> None:
        batch = self._shed_expired(batch)
        if not batch:
            return
        t0 = time.monotonic()
        for p in batch:
            wait_ms = (t0 - p.t_submit) * 1e3
            obs.observe("serve.queue_wait_ms", wait_ms)
            self.admission.estimator.observe_wait(wait_ms)

        # request-scoped attribution (tracing armed): a synthesized
        # serve.queue_wait child under each member's request span, and
        # the shared flush span linked to EVERY member — the merged
        # trace shows which other clients' checks shared this bucket
        member_spans: List[str] = []
        member_traces: List[str] = []
        if obs.enabled():
            for p in batch:
                if p.origin is None:
                    continue
                trace_id, span_id, tid = p.origin
                member_spans.append(span_id)
                if trace_id and trace_id not in member_traces:
                    member_traces.append(trace_id)
                ts = obs.mono_to_us(p.t_submit)
                if ts is not None:
                    obs.emit_span("serve.queue_wait", ts,
                                  (t0 - p.t_submit) * 1e6, parent=span_id,
                                  trace=trace_id, tid=tid)

        degraded = {"hit": False}

        def dispatch() -> Dict[Tuple, bool]:
            chaos("serve.flush")
            if self.flush_delay_s:
                time.sleep(self.flush_delay_s)  # drill-knob service time
            from ..crypto import bls

            verifier = bls.DeferredVerifier()
            for p in batch:
                verifier.record(p.key)
            verifier.flush()
            return verifier.table()

        def fallback() -> Dict[Tuple, bool]:
            degraded["hit"] = True
            return self._oracle_flush(batch)

        with obs.span("serve.flush", rows=len(batch),
                      members=len(member_spans),
                      client_traces=",".join(member_traces) or None) as fsp:
            fsp.link(*member_spans)
            try:
                table = supervised(
                    dispatch, domain="serve.flush", fallback=fallback)
            except BaseException as e:  # a fallback that itself failed
                for p in batch:
                    p.fail(e)
                return
        with self.stats_lock:
            self.flushes += 1
            self.flushed_rows += len(batch)
        obs.count("serve.flushes")
        obs.count("serve.flush_rows", len(batch))
        flush_ms = (time.monotonic() - t0) * 1e3
        obs.observe("serve.flush_ms", flush_ms)
        self.admission.estimator.note_flush(len(batch), flush_ms / 1e3)
        if self.cache_size:
            with self.stats_lock:
                for key, result in table.items():
                    self._cache[key] = result
                    self._cache.move_to_end(key)
                while len(self._cache) > self.cache_size:
                    self._cache.popitem(last=False)
        for p in batch:
            p.stats = {
                "queue_wait_ms": round((t0 - p.t_submit) * 1e3, 3),
                "flush_ms": round(flush_ms, 3),
                "batch_rows": len(batch),
            }
            if degraded["hit"]:
                p.stats["degraded"] = True
            p.resolve(bool(table[p.key]))

    @staticmethod
    def _oracle_flush(batch: List[_Pending]) -> Dict[Tuple, bool]:
        """Per-row host-oracle degradation: answer every check straight
        from the reference ciphersuite (never the installed backend — it
        just faulted). A row the oracle rejects-by-raising is False, the
        facade's invalid-input contract."""
        from ..crypto.bls import ciphersuite as oracle

        ops = {"v": oracle.Verify, "fav": oracle.FastAggregateVerify,
               "av": oracle.AggregateVerify}
        record_event("fallback", domain="serve.flush", capability="serve.flush",
                     detail=f"batch of {len(batch)} degraded to the host oracle")
        obs.count("serve.flush_degraded")
        table: Dict[Tuple, bool] = {}
        for p in batch:
            if p.key in table:
                continue
            kind, a, b, sig = p.key
            try:
                table[p.key] = bool(ops[kind](
                    list(a) if isinstance(a, tuple) else a,
                    list(b) if isinstance(b, tuple) else b, sig))
            except Exception:
                table[p.key] = False
        return table
