"""Warm start: everything a resident daemon (or CI) pays ONCE so no
request ever does — shared by the daemon's startup and the standalone
``make warm-cache`` (tools/warm_cache.py).

Three stages, each skippable and each reported:

1. **compile cache** — point jax's persistent compilation cache at the
   shared directory (sched/compile_cache.py) BEFORE any backend builds
   its jits, so executables compiled by any prior process load instead
   of compile.
2. **spec matrix** — ``specs.build.prebuild`` of the served fork×preset
   slice (each build lands a ``spec.build`` span).
3. **jit probe** (opt-in) — run a small representative kernel per
   accelerated plane (the ssz device hasher, the engine delta kernel)
   so their XLA programs land in the persistent cache while nobody is
   waiting. The big BLS pairing graphs are deliberately NOT compiled
   here by default: minutes of cold compile belong to an explicit
   ``--bls-shapes`` opt-in, not to every daemon start on a laptop.
"""
from __future__ import annotations

import time
from typing import Any, Dict, Optional, Sequence

from .. import obs


def warm_start(
    forks: Optional[Sequence[str]] = None,
    presets: Sequence[str] = ("minimal",),
    *,
    compile_cache: bool = True,
    jit_probe: bool = False,
    bls_shapes: bool = False,
) -> Dict[str, Any]:
    """Prime caches; return a report of what got warm. Never raises for
    an optional stage — a cold cache is a slower first request, not a
    startup failure."""
    from ..specs import build

    report: Dict[str, Any] = {}
    if compile_cache:
        from ..sched import compile_cache as cc

        cache_dir = cc.configure_compile_cache(enable_by_default=True)
        report["compile_cache_dir"] = cache_dir or None

    t0 = time.perf_counter()
    forks = list(forks) if forks is not None else build.available_forks()
    built = build.prebuild(forks=forks, presets=presets)
    report["spec_modules"] = built
    report["spec_matrix_s"] = round(time.perf_counter() - t0, 3)

    if jit_probe:
        report["jit_probe"] = _jit_probe(bls_shapes=bls_shapes)
    return report


def _jit_probe(bls_shapes: bool = False) -> Dict[str, Any]:
    """Compile one small kernel per accelerated plane into the (already
    configured) persistent cache. Returns per-plane status strings."""
    out: Dict[str, Any] = {}
    with obs.span("serve.warm.jit_probe"):
        try:
            import jax.numpy as jnp

            (jnp.arange(8) * 2).block_until_ready()
            out["jax"] = "ok"
        except Exception as e:
            out["jax"] = f"unavailable: {e!r}"
            return out
        try:
            import numpy as np

            from ..ops import sha256 as dev_hash

            dev_hash.hash_many_device(np.zeros((8, 64), dtype=np.uint8).tobytes())
            out["hash"] = "ok"
        except Exception as e:
            out["hash"] = f"skipped: {e!r}"
        try:
            import numpy as np

            from ..engine import stages

            n = 1 << 8
            stages._flag_deltas(
                np.full(n, 32, dtype=np.uint64),
                np.zeros(n, dtype=bool), np.ones(n, dtype=bool),
                25_000, 14, 0, n * 32, 64, False, True)
            out["engine"] = "ok"
        except Exception as e:
            out["engine"] = f"skipped: {e!r}"
        if bls_shapes:
            out["bls"] = _warm_bls_shapes()
    return out


def _warm_bls_shapes() -> str:
    """Opt-in: compile the smallest canonical BLS bucket shape (rows and
    keys at the planner floors) so a device daemon's first flush loads
    the pairing executable from cache. Minutes cold; seconds warm."""
    try:
        from ..crypto import bls
        from ..crypto.bls import ciphersuite as oracle

        prev = bls.backend_name()
        bls.use_jax()
        try:
            if bls.backend_name() != "jax":
                return "jax backend unavailable (quarantined or unimportable)"
            sks = [1, 2]
            pks = [oracle.SkToPk(sk) for sk in sks]
            msg = b"\x42" * 32
            from ..crypto.bls.fields import R as _R

            sig = oracle.Sign(sum(sks) % _R, msg)
            verifier = bls.DeferredVerifier()
            verifier.record(("fav", tuple(pks), msg, sig))
            verifier.flush()
            return "ok" if all(verifier.results) else "verify returned False"
        finally:
            if prev == "reference":
                bls.use_reference()
    except Exception as e:
        return f"failed: {e!r}"
