"""Serve fleet: N replicated verification daemons under one supervisor
(docs/SERVE.md "Fleet", ROADMAP #1).

One hardened daemon (PRs 6-10) survives faults *inside* itself —
degraded flushes, quarantined controllers, shed overload. The fleet
layer survives the loss of the daemon itself: N replicas forked like
``sched/shard.py`` workers (COW — the parent prebuilds the spec matrix
so every child inherits it instantly; the persistent XLA compile cache
is shared by path), each with its own ephemeral port, scratch dir
(ready file + drain report — the replica's journal), and flight
recorder, supervised with the resilience taxonomy:

- **transient death** (SIGKILL, ``EX_TEMPFAIL``, injected chaos) —
  respawn the slot and rejoin once the fresh process answers
  ``/readyz`` green (``serve.fleet.respawns`` /
  ``serve.fleet.rejoined``); the ring slot keeps its NAME, so the keys
  the dead replica owned come home to the respawn and its sibling's
  cache churn is transient;
- **deterministic fault** (``EX_CONFIG``/``EX_SOFTWARE`` exits, or a
  respawn budget exhausted — a slot that never stops dying is an
  environment problem) — quarantine the slot and shrink the ring
  (``serve.fleet.quarantined``): the router's consistent hash moves
  only that slot's keys to the survivors;
- **hang** — the replica's supervise loop stops beating its daemon
  heartbeat, ``/readyz`` flips to 503 ``stale``, and routers steer
  around it via health staleness without the supervisor killing
  anything (the process may recover).

Drain handoff: :meth:`FleetSupervisor.drain_replica` removes the slot
from the membership FIRST (routers steer new traffic to survivors on
their next refresh), then SIGTERMs it — the replica answers everything
it accepted (``accepted == flushed + shed``, the PR 6/10 exactly-once
drain contract) and its report is collected from its journal dir.

Membership is served programmatically (:meth:`members` — the callable
a :class:`~.client.FleetClient` routes over) and the fleet's aggregate
observability rolls up the per-replica surfaces:
:meth:`fleet_health` (every ``/healthz`` + supervisor state) and
:meth:`fleet_metrics` (every ``/metrics`` summed via
``obs.metrics.aggregate_prometheus``, plus the fleet-wide SLO
availability burn over the summed response counters).

Chaos site ``serve.replica`` fires in each replica's supervise loop
(cross-process hit state makes "kill one replica" mean exactly one
across the fleet); all three kinds are drilled in
``tests/test_serve_fleet.py`` and ``make fleet-smoke``.

Pure stdlib + os.fork; jax-free unless a replica's config asks to warm
device kernels.
"""
from __future__ import annotations

import json
import os
import signal
import sys
import tempfile
import threading
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .. import obs
from ..obs import metrics as obs_metrics
from ..resilience import chaos, record_event
from ..resilience import taxonomy
from .client import ServeClient

READY_FMT = "ready.{epoch}.json"
DRAIN_FMT = "drain.{epoch}.json"


@dataclass
class FleetConfig:
    """One replica recipe, applied to every slot."""

    replicas: int = 2
    forks: Sequence[str] = ("phase0",)
    presets: Sequence[str] = ("minimal",)
    max_queue: int = 1024
    max_batch: int = 64
    linger_ms: float = 2.0
    cache_size: int = 4096
    flush_delay_ms: float = 0.0       # drill knob (docs/SERVE.md)
    admission_mode: Optional[str] = None
    target_p99_ms: Optional[float] = None
    min_limit: Optional[int] = None
    warm: bool = False                # jax-free by default
    heartbeat_stale_s: float = 1.0    # /readyz goes stale past this
    tick_s: float = 0.02              # replica supervise-loop cadence
    drain_timeout_s: float = 15.0
    ready_timeout_s: float = 120.0
    max_respawns: int = 3             # per slot; beyond = quarantine
    base_dir: Optional[str] = None    # scratch root (default: mkdtemp)


class Replica:
    """Parent-side handle for one fleet slot."""

    __slots__ = ("name", "slot", "pid", "port", "epoch", "status",
                 "respawns", "rc", "dir")

    def __init__(self, name: str, slot: int, pid: int, epoch: int,
                 rdir: Path) -> None:
        self.name = name
        self.slot = slot
        self.pid = pid
        self.port: Optional[int] = None
        self.epoch = epoch
        self.status = "starting"   # starting/ready/draining/drained/
        #                            exited/quarantined
        self.respawns = 0
        self.rc: Optional[int] = None
        self.dir = rdir

    def snapshot(self) -> Dict[str, Any]:
        return {"name": self.name, "pid": self.pid, "port": self.port,
                "epoch": self.epoch, "status": self.status,
                "respawns": self.respawns}


def _fsync_write(path: Path, payload: Dict[str, Any]) -> None:
    tmp = path.with_name(f"{path.name}.tmp.{os.getpid()}")
    with open(tmp, "w") as f:
        json.dump(payload, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _replica_child(cfg: FleetConfig, name: str, epoch: int, rdir: Path,
                   trace_env: Optional[str]) -> None:
    """The forked replica body: build one full daemon (admission,
    batcher, service, HTTP front-end), report ready, then supervise-loop
    (heartbeat + the ``serve.replica`` chaos site) until SIGTERM drains
    it. Exits via the sysexits taxonomy so the parent can classify."""
    code = taxonomy.EX_SOFTWARE
    try:
        obs.fork_child_reinit(trace_env)
        from ..obs import timeseries

        timeseries.set_role(f"serve.{name}")
        stop = threading.Event()

        def _on_term(signum: int, frame: Any) -> None:
            stop.set()

        try:
            signal.signal(signal.SIGTERM, _on_term)
            signal.signal(signal.SIGINT, _on_term)
        except ValueError:  # pragma: no cover — non-main-thread fork
            pass

        from .admission import AdmissionController
        from .batcher import VerifyBatcher
        from .daemon import ServeDaemon
        from .service import SpecService

        admission = AdmissionController(
            cfg.max_queue, mode=cfg.admission_mode,
            min_limit=cfg.min_limit, target_p99_ms=cfg.target_p99_ms)
        batcher = VerifyBatcher(
            max_queue=cfg.max_queue, max_batch=cfg.max_batch,
            linger_ms=cfg.linger_ms, cache_size=cfg.cache_size,
            admission=admission, flush_delay_ms=cfg.flush_delay_ms)
        service = SpecService(forks=tuple(cfg.forks),
                              presets=tuple(cfg.presets), batcher=batcher)
        daemon = ServeDaemon(service, port=0,
                             heartbeat_stale_s=cfg.heartbeat_stale_s)
        daemon.start(warm=cfg.warm)
        _fsync_write(rdir / READY_FMT.format(epoch=epoch),
                     {"port": daemon.port, "pid": os.getpid(),
                      "replica": name, "epoch": epoch})
        with obs.span("serve.replica", replica=name, epoch=epoch,
                      port=daemon.port):
            while not stop.is_set():
                chaos("serve.replica")
                daemon.heartbeat()
                stop.wait(cfg.tick_s)
            report = daemon.drain(cfg.drain_timeout_s)
        _fsync_write(rdir / DRAIN_FMT.format(epoch=epoch), report)
        code = 0 if (report.get("queue_drained")
                     and report.get("inflight_answered")) \
            else taxonomy.EX_SOFTWARE
    except BaseException as e:
        kind = taxonomy.classify(e)
        try:
            sys.stderr.write(f"[{name}] replica failed ({kind}): "
                             f"{type(e).__name__}: {e}\n")
        except Exception:
            pass
        code = taxonomy.exit_code_for(kind)
    finally:
        try:
            sys.stdout.flush()
            sys.stderr.flush()
        except Exception:
            pass
        os._exit(code)


class FleetSupervisor:
    """Spawn, watch, respawn/quarantine, and drain a replica fleet."""

    def __init__(self, config: Optional[FleetConfig] = None) -> None:
        self.cfg = config or FleetConfig()
        self.base_dir = Path(self.cfg.base_dir
                             or tempfile.mkdtemp(prefix="serve_fleet_"))
        self._replicas: Dict[str, Replica] = {}
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self.drain_reports: Dict[str, Dict[str, Any]] = {}

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "FleetSupervisor":
        """Prebuild the spec matrix in the parent (children inherit it
        COW — SpecService.start in every replica is then cache-hits
        only), fork every slot, wait for the fleet to go ready, start
        the monitor."""
        from ..obs import timeseries
        from ..specs import build

        timeseries.ensure_started(role="serve.fleet")
        with obs.span("serve.fleet.start", replicas=self.cfg.replicas):
            build.prebuild(forks=list(self.cfg.forks),
                           presets=tuple(self.cfg.presets))
            for slot in range(self.cfg.replicas):
                self._spawn(f"r{slot}", slot, epoch=0)
            deadline = time.monotonic() + self.cfg.ready_timeout_s
            while time.monotonic() < deadline:
                self._poll_once()
                states = {r.status for r in self._replicas.values()}
                if states <= {"ready", "quarantined"} and "ready" in states:
                    break
                time.sleep(0.02)
            else:
                raise TimeoutError(
                    f"fleet not ready within {self.cfg.ready_timeout_s}s: "
                    f"{[r.snapshot() for r in self._replicas.values()]}")
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         name="fleet-monitor", daemon=True)
        self._monitor.start()
        obs.count("serve.fleet.started")
        return self

    def _spawn(self, name: str, slot: int, epoch: int) -> Replica:
        rdir = self.base_dir / name
        rdir.mkdir(parents=True, exist_ok=True)
        trace_env = obs.child_env().get(obs.TRACE_ENV)
        sys.stdout.flush()
        sys.stderr.flush()
        pid = os.fork()
        if pid == 0:
            _replica_child(self.cfg, name, epoch, rdir, trace_env)
            raise AssertionError("unreachable")  # pragma: no cover
        rep = Replica(name, slot, pid, epoch, rdir)
        with self._lock:
            old = self._replicas.get(name)
            if old is not None:
                rep.respawns = old.respawns
            self._replicas[name] = rep
        return rep

    # -- supervision ---------------------------------------------------

    def _try_reap(self, rep: Replica) -> Optional[int]:
        """Non-blocking reap; idempotent per incarnation."""
        with self._lock:
            if rep.rc is not None:
                return rep.rc
            try:
                pid, status = os.waitpid(rep.pid, os.WNOHANG)
            except ChildProcessError:
                rep.rc = taxonomy.EX_SOFTWARE
                return rep.rc
            if pid == 0:
                return None
            rep.rc = (-os.WTERMSIG(status) if os.WIFSIGNALED(status)
                      else os.WEXITSTATUS(status))
            return rep.rc

    def _poll_once(self) -> None:
        for rep in list(self._replicas.values()):
            if rep.status in ("drained", "exited", "quarantined"):
                continue
            rc = self._try_reap(rep)
            if rc is not None:
                self._handle_death(rep, rc)
                continue
            if rep.status == "starting":
                self._progress_startup(rep)

    def _progress_startup(self, rep: Replica) -> None:
        ready_path = rep.dir / READY_FMT.format(epoch=rep.epoch)
        if rep.port is None:
            if not ready_path.exists():
                return
            try:
                rep.port = int(json.loads(ready_path.read_text())["port"])
            except (OSError, ValueError, KeyError):
                return
        # rejoin gate: membership only once the replica answers green
        probe = ServeClient(rep.port, timeout_s=2.0, max_retries=0)
        try:
            if probe.ready():
                with self._lock:
                    if rep.status == "starting":
                        rep.status = "ready"
                if rep.epoch > 0:
                    obs.count("serve.fleet.rejoined")
                    record_event("probe", domain="serve.fleet",
                                 capability=f"serve.replica.{rep.name}",
                                 detail=f"respawn epoch {rep.epoch} rejoined "
                                        f"on :{rep.port}")
        finally:
            probe.close()

    def _handle_death(self, rep: Replica, rc: int) -> None:
        if rep.status == "draining":
            # an operator-initiated drain: collect the report, done
            self._collect_drain(rep)
            return
        kind = taxonomy.classify_exit(rc)
        with self._lock:
            rep.status = "dead"
        if kind is None:
            # clean exit nobody asked for: treat as a voluntary leave
            with self._lock:
                rep.status = "exited"
            obs.count("serve.fleet.exited")
            return
        detail = f"replica {rep.name} (epoch {rep.epoch}) died rc={rc}"
        if kind == taxonomy.TRANSIENT and rep.respawns < self.cfg.max_respawns:
            rep.respawns += 1
            obs.count("serve.fleet.respawns")
            record_event("retry", domain="serve.fleet",
                         capability=f"serve.replica.{rep.name}",
                         kind=kind, detail=f"{detail}: respawning "
                                           f"(attempt {rep.respawns})")
            self._spawn(rep.name, rep.slot, epoch=rep.epoch + 1)
            return
        if kind == taxonomy.TRANSIENT:
            kind = taxonomy.ENVIRONMENTAL  # a slot that never stops dying
            detail += f" with the respawn budget ({self.cfg.max_respawns}) spent"
        with self._lock:
            rep.status = "quarantined"
        obs.count("serve.fleet.quarantined")
        record_event("quarantine", domain="serve.fleet",
                     capability=f"serve.replica.{rep.name}", kind=kind,
                     detail=f"{detail}: slot quarantined, ring shrinks")

    def _monitor_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._poll_once()
            except Exception:  # supervision must never die silently
                pass
            self._stop.wait(0.05)

    # -- membership (the router's view) --------------------------------

    def members(self) -> List[Tuple[str, int]]:
        """Live routable replicas as (name, port) — the callable handed
        to :class:`~.client.FleetClient`."""
        with self._lock:
            return [(r.name, r.port) for r in self._replicas.values()
                    if r.status == "ready" and r.port is not None]

    def replicas(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [r.snapshot() for r in self._replicas.values()]

    def replica(self, name: str) -> Replica:
        with self._lock:
            return self._replicas[name]

    # -- chaos / drain handoff -----------------------------------------

    def kill_replica(self, name: str) -> int:
        """SIGKILL one replica (the kill-one drill); the monitor will
        classify the signal death transient and respawn the slot."""
        rep = self.replica(name)
        os.kill(rep.pid, signal.SIGKILL)
        obs.count("serve.fleet.killed")
        return rep.pid

    def drain_replica(self, name: str,
                      timeout_s: Optional[float] = None) -> Dict[str, Any]:
        """Drain handoff: pull the slot out of the membership FIRST (new
        traffic steers to survivors on the routers' next refresh), then
        SIGTERM it and collect its exactly-once drain report."""
        rep = self.replica(name)
        with self._lock:
            rep.status = "draining"
        obs.count("serve.fleet.drained")
        try:
            os.kill(rep.pid, signal.SIGTERM)
        except OSError:
            pass
        deadline = time.monotonic() + (timeout_s
                                       or self.cfg.drain_timeout_s + 15)
        while time.monotonic() < deadline:
            if self._try_reap(rep) is not None:
                break
            time.sleep(0.02)
        return self._collect_drain(rep)

    def _collect_drain(self, rep: Replica) -> Dict[str, Any]:
        report: Dict[str, Any] = {"rc": rep.rc}
        drain_path = rep.dir / DRAIN_FMT.format(epoch=rep.epoch)
        if drain_path.exists():
            try:
                report.update(json.loads(drain_path.read_text()))
            except (OSError, ValueError):
                pass
        with self._lock:
            rep.status = "drained"
            self.drain_reports[f"{rep.name}.{rep.epoch}"] = report
        return report

    def stop(self) -> Dict[str, Dict[str, Any]]:
        """Drain the whole fleet (monitor stopped first so this thread
        owns every reap), returning per-replica drain reports."""
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(5)
            self._monitor = None
        for rep in list(self._replicas.values()):
            if rep.status in ("ready", "starting"):
                self.drain_replica(rep.name)
        return dict(self.drain_reports)

    # -- aggregate observability ---------------------------------------

    def fleet_health(self) -> Dict[str, Any]:
        """Every live replica's /healthz plus supervisor state — the
        fleet-level health surface."""
        per: Dict[str, Any] = {}
        totals = {"accepted": 0, "flushes": 0, "rejected": 0,
                  "shed_rows": 0, "depth": 0}
        for name, port in self.members():
            client = ServeClient(port, timeout_s=2.0, max_retries=0)
            try:
                h = client.health()
            except Exception as e:
                per[name] = {"error": f"{type(e).__name__}: {e}"}
                continue
            finally:
                client.close()
            per[name] = {"status": h.get("status"), "port": port,
                         "queue": h.get("queue"),
                         "backend": h.get("backend"),
                         "idem_cache": h.get("idem_cache")}
            q = h.get("queue") or {}
            for key in ("accepted", "rejected", "shed_rows", "depth",
                        "flushes"):
                totals[key] += int(q.get(key) or 0)
        return {
            "replicas": self.replicas(),
            "members": len(self.members()),
            "per_replica": per,
            "totals": totals,
            "respawns": sum(r["respawns"] for r in self.replicas()),
            "quarantined": [r["name"] for r in self.replicas()
                            if r["status"] == "quarantined"],
        }

    def fleet_metrics(self) -> Dict[str, Any]:
        """Aggregate /metrics across the fleet: counters summed,
        quantile gauges taken pessimistically (max), plus the fleet-wide
        SLO availability burn over the summed response counters."""
        texts: Dict[str, str] = {}
        for name, port in self.members():
            client = ServeClient(port, timeout_s=2.0, max_retries=0)
            try:
                texts[name] = client.metrics()
            except Exception:
                continue
            finally:
                client.close()
        aggregate = obs_metrics.aggregate_prometheus(list(texts.values()))
        responses = aggregate.get("serve_responses", 0.0)
        internal = aggregate.get("serve_errors_internal", 0.0)
        denom = responses + internal
        return {
            "replicas_scraped": len(texts),
            "aggregate": aggregate,
            "slo": {
                "availability": (responses / denom) if denom else None,
                "responses": responses,
                "errors_internal": internal,
            },
        }
