"""``python -m consensus_specs_tpu.serve`` — run the resident daemon."""
from __future__ import annotations

import sys

from .daemon import main

if __name__ == "__main__":
    sys.exit(main())
