"""Adaptive admission control for the serving plane (docs/SERVE.md
"Overload control").

The PR-6 batcher admitted work against one fixed bound (1024 queue
slots, all-or-nothing 429s). That shape collapses under sustained
overload: the queue fills with requests whose callers have already
given up, every flush burns real pairing work on them, and goodput
(answers that still matter) falls toward zero while the daemon looks
"busy" — the metastable-failure mode. This module replaces the fixed
bound with three cooperating pieces:

- :class:`WaitEstimator` — a live model of how long a newly admitted
  row will wait: recent ``serve.queue_wait_ms`` samples (the same
  values the always-on histogram receives) plus an EWMA of the flush
  pipeline's observed drain rate, so the estimate is
  ``depth / drain_rate`` with the recent-wait percentile as a floor.
  Admission uses it to reject a request whose estimated wait already
  exceeds its remaining ``deadline_ms`` budget — the cheapest possible
  shed, before the queue ever holds the row.

- :class:`AimdLimit` — the adaptive queue bound: additive increase
  while the observed queue-wait p99 sits under the latency target,
  multiplicative decrease when it overshoots (the TCP-congestion /
  gradient concurrency-limit shape). The limit floats in
  ``[min_limit, hard_limit]``; the old fixed bound is the hard
  ceiling and the fallback.

- :class:`AdmissionController` — a resident controller thread that
  re-evaluates the limit every ``tick_s`` under
  ``resilience.supervised`` (chaos site ``serve.admission``). The
  accept path never computes anything: it reads the last *published*
  limit, so a hung controller cannot wedge admission — staleness past
  ``stale_s`` trips the supervisor instead (quarantine
  ``serve.admission``, recorded event, degrade to the fixed bound).
  Sustained pressure (p99 over target for ``brownout_ticks``
  consecutive ticks) enters **brownout**: the batcher's linger window
  collapses to zero so batches stop waiting for company they no longer
  need, restoring latency headroom; calm ticks exit it.

Everything here is pure stdlib and jax-free; the knobs are
env-overridable (docs/SERVE.md "Knobs").
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, Optional

from .. import obs
from ..resilience import chaos, quarantine, supervised

ENV_MODE = "CONSENSUS_SPECS_TPU_SERVE_ADMISSION"          # adaptive|fixed
ENV_TARGET_P99 = "CONSENSUS_SPECS_TPU_SERVE_TARGET_P99_MS"
ENV_MIN_LIMIT = "CONSENSUS_SPECS_TPU_SERVE_MIN_LIMIT"
ENV_TICK_S = "CONSENSUS_SPECS_TPU_SERVE_ADMISSION_TICK_S"
ENV_STALE_S = "CONSENSUS_SPECS_TPU_SERVE_ADMISSION_STALE_S"
ENV_BROWNOUT_TICKS = "CONSENSUS_SPECS_TPU_SERVE_BROWNOUT_TICKS"

MODE_ADAPTIVE = "adaptive"
MODE_FIXED = "fixed"

DEFAULT_TARGET_P99_MS = 50.0
DEFAULT_MIN_LIMIT = 16
DEFAULT_TICK_S = 0.05
DEFAULT_STALE_S = 2.0
DEFAULT_BROWNOUT_TICKS = 3

# AIMD shape: gentle additive probe upward, decisive multiplicative
# back-off — the asymmetry is what keeps the loop stable
INCREASE_STEP = 8
DECREASE_FACTOR = 0.65


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_str(name: str, default: str) -> str:
    return (os.environ.get(name, "") or default).strip().lower()


class WaitEstimator:
    """Live queue-wait model: recent waits + EWMA drain rate.

    Fed by the flusher (one ``observe_wait`` per flushed row, one
    ``note_flush`` per dispatch); read by admission. Thread-safe; with
    no evidence yet it estimates 0 (optimistic — admission never
    rejects on a cold start)."""

    def __init__(self, window: int = 512, alpha: float = 0.3) -> None:
        self._waits: Deque[float] = deque(maxlen=max(8, int(window)))
        self._alpha = alpha
        self._rate_rows_s: Optional[float] = None  # EWMA service rate
        self._service_ms: Optional[float] = None   # EWMA per-flush time
        self._lock = threading.Lock()

    def observe_wait(self, wait_ms: float) -> None:
        with self._lock:
            self._waits.append(float(wait_ms))

    def note_flush(self, rows: int, service_s: float) -> None:
        """One dispatch: ``rows`` answered in ``service_s`` of flusher
        time. Under overload the flusher is always busy, so the service
        rate IS the drain rate — exactly the regime where the estimate
        matters."""
        if rows <= 0 or service_s <= 0:
            return
        sample = rows / service_s
        with self._lock:
            if self._rate_rows_s is None:
                self._rate_rows_s = sample
                self._service_ms = service_s * 1e3
            else:
                self._rate_rows_s += self._alpha * (sample - self._rate_rows_s)
                self._service_ms += self._alpha * (  # type: ignore[operator]
                    service_s * 1e3 - self._service_ms)

    def wait_percentile(self, q: float) -> Optional[float]:
        from ..obs.metrics import percentile

        with self._lock:
            samples = list(self._waits)
        return percentile(samples, q)

    def drain_rate(self) -> Optional[float]:
        with self._lock:
            return self._rate_rows_s

    def service_estimate_ms(self) -> float:
        """EWMA of one flush's service time — the part of a request's
        latency its ``deadline_ms`` budget must cover AFTER the queue
        wait. 0 until evidence exists (optimistic cold start)."""
        with self._lock:
            return self._service_ms or 0.0

    def estimate_ms(self, depth: int) -> float:
        """Estimated queue wait for a row admitted behind ``depth``
        already-queued rows: the forward-looking ``depth / drain_rate``
        with the recent p90 wait as a floor (a burst grows depth before
        new wait samples land; a draining lull does the opposite)."""
        rate = self.drain_rate()
        forward = (depth / rate) * 1e3 if (rate and depth > 0) else None
        recent = self.wait_percentile(90) if depth > 0 else None
        candidates = [v for v in (forward, recent) if v is not None]
        return max(candidates) if candidates else 0.0

    def completion_estimate_ms(self, depth: int) -> float:
        """What a budget must actually cover: the queue wait, the row's
        OWN flush, and up to one more service period for the flush that
        may already be in flight when the row lands (the drain-rate
        model cannot see intra-flush phase, and quantized 1-2s flushes
        make that error material). A request admitted with
        ``deadline_ms`` under this number would clear the queue only to
        finish late — burning a flush on an answer nobody is waiting
        for — so admission sheds it up front."""
        return self.estimate_ms(depth) + 2.0 * self.service_estimate_ms()

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            n = len(self._waits)
        return {
            "wait_samples": n,
            "wait_p50_ms": self.wait_percentile(50),
            "wait_p90_ms": self.wait_percentile(90),
            "wait_p99_ms": self.wait_percentile(99),
            "drain_rate_rows_s": self.drain_rate(),
            "service_ms": self.service_estimate_ms(),
        }


class AimdLimit:
    """The adaptive queue bound: +``INCREASE_STEP`` per calm tick,
    ×``DECREASE_FACTOR`` per overshooting tick, clamped to
    ``[min_limit, hard_limit]``. Starts at the hard limit (optimistic:
    only observed pressure shrinks it)."""

    def __init__(self, hard_limit: int, min_limit: int,
                 target_p99_ms: float) -> None:
        self.hard_limit = max(1, int(hard_limit))
        self.min_limit = max(1, min(int(min_limit), self.hard_limit))
        self.target_p99_ms = float(target_p99_ms)
        self._limit = float(self.hard_limit)

    @property
    def limit(self) -> int:
        return int(self._limit)

    def update(self, wait_p99_ms: Optional[float]) -> int:
        """One control step against the observed queue-wait p99. No
        evidence (None) reads as no pressure."""
        if wait_p99_ms is not None and wait_p99_ms > self.target_p99_ms:
            self._limit = max(float(self.min_limit),
                              self._limit * DECREASE_FACTOR)
        else:
            self._limit = min(float(self.hard_limit),
                              self._limit + INCREASE_STEP)
        return self.limit


class AdmissionController:
    """The resident control loop + the published admission state.

    The accept path calls :meth:`limit` / :meth:`brownout` only — both
    are lock-free reads of published values plus one staleness check,
    so nothing on the accept path can hang even when the controller
    thread does (chaos kind ``hang`` at site ``serve.admission``): the
    staleness watchdog quarantines the capability and degrades to the
    fixed bound instead."""

    CAPABILITY = "serve.admission"

    def __init__(
        self,
        hard_limit: int,
        *,
        mode: Optional[str] = None,
        min_limit: Optional[int] = None,
        target_p99_ms: Optional[float] = None,
        tick_s: Optional[float] = None,
        stale_s: Optional[float] = None,
        brownout_ticks: Optional[int] = None,
    ) -> None:
        self.mode = (mode or _env_str(ENV_MODE, MODE_ADAPTIVE))
        if self.mode not in (MODE_ADAPTIVE, MODE_FIXED):
            raise ValueError(f"unknown admission mode {self.mode!r} "
                             f"(have {MODE_ADAPTIVE!r}/{MODE_FIXED!r})")
        self.hard_limit = max(1, int(hard_limit))
        self.target_p99_ms = (target_p99_ms if target_p99_ms is not None
                              else _env_float(ENV_TARGET_P99,
                                              DEFAULT_TARGET_P99_MS))
        self.tick_s = max(0.005, tick_s if tick_s is not None
                          else _env_float(ENV_TICK_S, DEFAULT_TICK_S))
        self.stale_s = max(0.05, stale_s if stale_s is not None
                           else _env_float(ENV_STALE_S, DEFAULT_STALE_S))
        self.brownout_ticks = max(1, int(
            brownout_ticks if brownout_ticks is not None
            else _env_float(ENV_BROWNOUT_TICKS, DEFAULT_BROWNOUT_TICKS)))
        self.estimator = WaitEstimator()
        self._aimd = AimdLimit(
            self.hard_limit,
            int(min_limit if min_limit is not None
                else _env_float(ENV_MIN_LIMIT, DEFAULT_MIN_LIMIT)),
            self.target_p99_ms)
        self._published_limit = self.hard_limit
        self._brownout = False
        self._over_ticks = 0
        self._calm_ticks = 0
        self._ticks = 0
        self._degraded: Optional[str] = None
        self._last_tick = time.monotonic()
        self._closing = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "AdmissionController":
        if self.mode == MODE_ADAPTIVE and self._thread is None:
            self._last_tick = time.monotonic()
            self._thread = threading.Thread(
                target=self._run, name="serve-admission", daemon=True)
            self._thread.start()
        return self

    def stop(self, timeout_s: float = 2.0) -> None:
        self._closing.set()
        t = self._thread
        if t is not None:
            t.join(timeout_s)  # a hung tick is abandoned (daemon thread)

    # -- the accept-path reads (never compute, never block) ------------

    @property
    def adaptive(self) -> bool:
        return (self.mode == MODE_ADAPTIVE and self._degraded is None
                and self._thread is not None)

    def limit(self) -> int:
        """The queue bound admission enforces right now. Fixed mode, a
        degraded controller, or a controller that has not started all
        publish the hard (fixed) bound."""
        if not self.adaptive:
            return self.hard_limit
        if time.monotonic() - self._last_tick > self.stale_s:
            self._degrade(f"controller stale: no tick for >{self.stale_s}s "
                          "(hung admission check)")
            return self.hard_limit
        return self._published_limit

    def brownout(self) -> bool:
        return self._brownout if self.adaptive else False

    # -- the control loop ----------------------------------------------

    def _run(self) -> None:
        while not self._closing.wait(self.tick_s):
            try:
                supervised(self._tick, domain="serve.admission",
                           capability=self.CAPABILITY)
            except BaseException as e:
                # deterministic/exhausted fault: supervised() already
                # quarantined the capability; publish the degradation
                # and leave the fixed bound in charge
                self._degrade(f"{type(e).__name__}: {e}", quarantined=True)
                return

    def _tick(self) -> None:
        chaos("serve.admission")
        p99 = self.estimator.wait_percentile(99)
        self._published_limit = self._aimd.update(p99)
        over = p99 is not None and p99 > self.target_p99_ms
        self._over_ticks = self._over_ticks + 1 if over else 0
        self._calm_ticks = 0 if over else self._calm_ticks + 1
        if not self._brownout and self._over_ticks >= self.brownout_ticks:
            self._brownout = True
            obs.count("serve.brownout.entered")
        elif self._brownout and self._calm_ticks >= self.brownout_ticks:
            self._brownout = False
        self._ticks += 1
        self._last_tick = time.monotonic()

    def _degrade(self, reason: str, quarantined: bool = False) -> None:
        if self._degraded is not None:
            return
        self._degraded = reason
        if not quarantined:
            quarantine(self.CAPABILITY, reason, domain="serve.admission")
        obs.count("serve.admission.degraded")

    # -- introspection (/debug/overload, /healthz) ---------------------

    def snapshot(self) -> Dict[str, Any]:
        return {
            "mode": self.mode,
            "adaptive": self.adaptive,
            "limit": self.limit(),
            "hard_limit": self.hard_limit,
            "target_p99_ms": self.target_p99_ms,
            "brownout": self.brownout(),
            "ticks": self._ticks,
            "degraded": self._degraded,
            "estimator": self.estimator.snapshot(),
        }
