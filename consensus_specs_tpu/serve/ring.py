"""Consistent-hash ring: key → replica affinity for the serve fleet
(docs/SERVE.md "Fleet").

Why consistent hashing instead of round-robin: each daemon replica owns
a bounded LRU result cache and a set of warm BLS bucket shapes, both
keyed by the check population it has seen. Routing a check by a stable
hash of its *identity* keeps repeat traffic for one key landing on one
replica — the caches stay hot — and a membership change (one replica
dies, drains, or joins) moves only ~K/N of K keys instead of reshuffling
everything (`tests/test_serve_fleet.py` pins the remap bound).

Implementation: the classic virtual-node ring. Each node name is hashed
onto ``vnodes`` points of a 64-bit circle (sha256, so placement is
stable across processes and Python hash randomization); a key routes to
the first node clockwise from its own hash. Removing a node removes
only its points, so exactly the keys it owned remap — the ≤K/N
guarantee is structural, not statistical. Nodes are *names* (replica
slot labels like ``r0``), not (host, port) pairs: a replica that dies
and is respawned on a new port rejoins under the same name, so its keys
come home and its successor's cache churn is transient.

``chain(key)`` returns every node in ring preference order (distinct,
starting at the owner) — the failover walk: an unanswered request
re-sends to the next replica in ITS OWN chain, so two routers always
agree on the failover order without coordination.

Pure stdlib; imported by the router (serve/client.py) and the fleet
supervisor (serve/fleet.py).
"""
from __future__ import annotations

import bisect
import hashlib
from typing import Dict, Iterable, List, Sequence, Tuple

DEFAULT_VNODES = 96


def _point(label: str) -> int:
    return int.from_bytes(
        hashlib.sha256(label.encode()).digest()[:8], "big")


def key_point(key: bytes) -> int:
    """A key's position on the circle (stable across processes)."""
    return int.from_bytes(hashlib.sha256(key).digest()[:8], "big")


class HashRing:
    """A consistent-hash ring over node names. Not thread-safe: the
    owner (one router / one supervisor) rebuilds or mutates it from a
    single thread and hands out lookups."""

    def __init__(self, nodes: Iterable[str] = (),
                 vnodes: int = DEFAULT_VNODES) -> None:
        self.vnodes = max(1, int(vnodes))
        self._points: List[int] = []       # sorted circle positions
        self._owner: Dict[int, str] = {}   # position -> node name
        self._nodes: List[str] = []
        for node in nodes:
            self.add(node)

    def __len__(self) -> int:
        return len(self._nodes)

    def nodes(self) -> List[str]:
        return list(self._nodes)

    def add(self, node: str) -> None:
        if node in self._nodes:
            return
        self._nodes.append(node)
        for i in range(self.vnodes):
            p = _point(f"{node}#{i}")
            if p in self._owner:   # 64-bit collision: first owner keeps it
                continue
            bisect.insort(self._points, p)
            self._owner[p] = node

    def remove(self, node: str) -> None:
        if node not in self._nodes:
            return
        self._nodes.remove(node)
        dead = [p for p, n in self._owner.items() if n == node]
        for p in dead:
            del self._owner[p]
            idx = bisect.bisect_left(self._points, p)
            if idx < len(self._points) and self._points[idx] == p:
                del self._points[idx]

    def lookup(self, key: bytes) -> str:
        """The owning node for ``key`` (raises LookupError on an empty
        ring)."""
        if not self._points:
            raise LookupError("hash ring is empty")
        idx = bisect.bisect_right(self._points, key_point(key))
        if idx == len(self._points):
            idx = 0
        return self._owner[self._points[idx]]

    def chain(self, key: bytes) -> List[str]:
        """Every node in preference order for ``key``: the owner first,
        then each DISTINCT node met walking clockwise — the failover
        order every router derives identically with no coordination."""
        if not self._points:
            return []
        out: List[str] = []
        start = bisect.bisect_right(self._points, key_point(key))
        n = len(self._points)
        for step in range(n):
            node = self._owner[self._points[(start + step) % n]]
            if node not in out:
                out.append(node)
                if len(out) == len(self._nodes):
                    break
        return out


def remap_fraction(before: HashRing, after: HashRing,
                   keys: Sequence[bytes]) -> Tuple[int, float]:
    """(moved, fraction) of ``keys`` whose owner differs between two
    rings — the stability measurement the ring tests pin."""
    moved = sum(1 for k in keys if before.lookup(k) != after.lookup(k))
    return moved, moved / max(1, len(keys))
