"""Resident verification service — "specs as a service" (docs/SERVE.md).

The sched/ plane (PR 5) gave the repo cross-request shape-bucketed BLS
batching, a persistent compile cache, and overlapped serialization —
but its only client was the offline suite generator. This package
promotes it to a long-lived daemon: the spec matrix stays built, the
XLA cache stays warm, and a bounded request queue feeds the SAME
bucketed flush across *concurrent clients* — the continuous-batching
shape inference stacks use to amortize compilation and dispatch.

- :mod:`protocol` — the versioned JSON wire contract (v1), shared by
  daemon, client, and tools.
- :mod:`batcher` — bounded queue + micro-batcher: per-request futures,
  cross-client accumulation into ``DeferredVerifier`` /
  ``sched.bucketing.plan_flush`` dispatches, admission-control 429s,
  a bounded pure-function result cache, host-oracle degradation for a
  faulted batch (chaos site ``serve.flush``).
- :mod:`service` — wire methods → spec paths (verify / hash_tree_root /
  process_block + batched variants), bit-identical to the direct path
  by construction; chaos site ``serve.request``.
- :mod:`daemon` — localhost HTTP front-end, ``/metrics`` +
  ``/healthz`` + ``/readyz``, SIGTERM drain that answers every accepted
  request; ``python -m consensus_specs_tpu.serve`` CLI.
- :mod:`lifecycle` — warm start (compile cache + spec matrix + opt-in
  jit probes), shared with ``make warm-cache``.
- :mod:`admission` — overload control (ISSUE 10): the AIMD adaptive
  queue limit driven by observed queue-wait p99 vs a latency target,
  the live wait estimator behind deadline admission, brownout, and the
  supervised controller loop (chaos site ``serve.admission``).
- :mod:`client` — stdlib client used by tests and the bench/smoke
  tools (``tools/serve_bench.py``, ``tools/serve_smoke.py``); carries
  the client-side overload discipline (token-bucket retry budget,
  jittered backoff, deadline propagation).
- :mod:`drill` — open-loop / closed-loop load drivers + the overload
  drill harness shared by ``tools/overload_drill.py``,
  ``tools/serve_bench.py --open-loop`` and perfgate's
  ``perfgate_overload_goodput_ratio`` slice.
- :mod:`ring` — the consistent-hash ring (key→replica affinity, ≤K/N
  remap on membership change, the coordination-free failover chain).
- :mod:`fleet` — the replica fleet (ISSUE 11, ROADMAP #1):
  ``FleetSupervisor`` forks N daemon replicas (COW spec matrix, shared
  compile cache, per-replica ports + ready/drain journals), supervises
  them with the resilience taxonomy (transient death → respawn-and-
  rejoin via ``/readyz``, deterministic → quarantine + ring shrink,
  hang → heartbeat-stale ``/readyz`` routed around), aggregates fleet
  ``/metrics``+``/healthz``+SLO burn, and hands off drains; chaos site
  ``serve.replica``. ``FleetClient`` (in :mod:`client`) is the
  shard-aware router: affinity routing, health/drain-aware dispatch,
  idempotency-keyed failover (exactly-once), fleet-shared RetryBudget.

Request observability (ISSUE 7): every wire body MAY carry an optional
W3C-shaped ``trace`` field — ``ServeClient`` injects it from the active
obs span, the daemon adopts it, and one merged Perfetto trace links
client → daemon request → synthesized queue-wait → the shared flush
(with the other clients that shared the bucket). The flight recorder
(``obs/flightrec.py``) keeps the last N completed requests for
``/debug/requests`` / ``/debug/slowest`` / SIGUSR2 / drain dumps, and
``obs/slo.py`` declares the availability + latency objectives gated by
``make perfgate`` and probed by ``tools/serve_canary.py``.

Perf evidence: ``make serve-bench`` banks ``serve_p50_ms`` /
``serve_p99_ms`` / ``serve_verifies_per_s`` in the ledger (and, with
``--open-loop RATE``, the ``serve_ol_*`` open-loop series);
``make overload-drill`` banks ``serve_goodput_per_s`` /
``serve_shed_ratio`` under 3x open-loop overload; ``make perfgate``
gates ``perfgate_serve_rtt_ms`` on the sentinel, the serve SLOs
(``serve_slo_availability`` / ``serve_slo_p99_budget``) on their
absolute objectives, and ``perfgate_overload_goodput_ratio`` on the
absolute no-collapse floor.
"""
from __future__ import annotations

from .admission import AdmissionController, AimdLimit, WaitEstimator  # noqa: F401
from .batcher import (  # noqa: F401
    DeadlineExceeded,
    Draining,
    QueueFull,
    Shed,
    VerifyBatcher,
)
from .client import FleetClient, RetryBudget, ServeClient, ServeError  # noqa: F401
from .daemon import IdemCache, ServeDaemon  # noqa: F401
from .fleet import FleetConfig, FleetSupervisor  # noqa: F401
from .lifecycle import warm_start  # noqa: F401
from .protocol import WIRE_VERSION, RequestError  # noqa: F401
from .ring import HashRing  # noqa: F401
from .service import SpecService  # noqa: F401
