"""The resident daemon: localhost HTTP front-end over
:class:`~.service.SpecService`, lifecycle management, clean drain.

Endpoints (wire contract v1 — docs/SERVE.md):

- ``POST /v1/<method>`` — verify / verify_batch / hash_tree_root /
  hash_tree_root_batch / process_block (JSON bodies, protocol.py).
- ``GET /metrics`` — ``obs.metrics.prometheus_text()``: every
  ``serve.*`` counter plus the auto-maintained ``span.*`` latency
  histograms (p50/p90/p99 summaries).
- ``GET /healthz`` — health JSON: backend, quarantine state, queue
  depth/capacity, result+compile cache stats, served matrix, uptime.
- ``GET /readyz`` — 200 once the matrix is prebuilt and the flusher
  runs; 503 while starting or draining (load-balancer semantics).
- ``GET /debug/requests[?trace=<id>&n=<k>]`` / ``GET /debug/slowest`` —
  the flight recorder (obs/flightrec.py): the last N completed wire
  requests with queue-wait/flush/total ms, cache hits, degradation and
  bucket shape; also dumped to stderr on SIGUSR2 and at drain.
  ``/debug/slowest`` excludes shed requests from the ranking.
- ``GET /debug/overload`` — the overload-control surface (docs/SERVE.md
  "Overload control"): admission mode, published adaptive limit vs the
  hard bound, brownout, the wait estimator, per-class shed tallies.

Introspection routes (``/metrics`` ``/healthz`` ``/readyz``
``/debug/*``) are excluded from ``serve.request_ms`` and the SLO
denominators (``protocol.is_introspection``): scrapers cannot skew the
served-traffic histograms.

Drain: SIGTERM/SIGINT flips the daemon to ``draining`` — new POSTs get
a structured 503, requests already accepted (including every check
sitting in the verify queue) complete and are answered, the batcher
flushes to empty, and the process exits 0. The drill in
tests/test_serve_drain.py SIGTERMs a daemon with a deliberately full
queue and asserts every accepted request got its answer — none
dropped, none double-dispatched.

A request handler thread is tracked while a request is in flight so the
drain can wait for the tail; an idle keep-alive connection holds no
in-flight slot and never blocks shutdown.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

from .. import obs
from ..obs import flightrec
from . import protocol
from .admission import AdmissionController
from .batcher import DeadlineExceeded, Draining, QueueFull, Shed, VerifyBatcher
from .service import DEFAULT_FORKS, DEFAULT_PRESETS, SpecService

MAX_BODY_BYTES = 64 << 20  # a mainnet BeaconState is ~tens of MiB

ENV_MAX_QUEUE = "CONSENSUS_SPECS_TPU_SERVE_MAX_QUEUE"
ENV_MAX_BATCH = "CONSENSUS_SPECS_TPU_SERVE_MAX_BATCH"
ENV_LINGER_MS = "CONSENSUS_SPECS_TPU_SERVE_LINGER_MS"
ENV_CACHE = "CONSENSUS_SPECS_TPU_SERVE_RESULT_CACHE"


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


class IdemCache:
    """Bounded LRU of completed (status, payload) responses keyed by the
    wire ``idem`` field (docs/SERVE.md "Fleet"): a failover router
    re-sends an unanswered request — to the next ring replica, or to the
    SAME replica after a torn connection — under one idempotency key, and
    a replica that already answered it replays the stored response
    instead of executing twice. Only *settled* outcomes are stored
    (200s and deterministic 400/404s); transient refusals
    (queue_full/shed/deadline/draining/internal) are not, because a
    re-send SHOULD re-attempt those. Thread-safe (handler threads)."""

    def __init__(self, capacity: int = 2048) -> None:
        self.capacity = max(0, int(capacity))
        self._entries: "OrderedDict[str, Tuple[int, Dict[str, Any]]]" = \
            OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.stored = 0

    def get(self, key: str) -> Optional[Tuple[int, Dict[str, Any]]]:
        with self._lock:
            hit = self._entries.get(key)
            if hit is not None:
                self._entries.move_to_end(key)
                self.hits += 1
            return hit

    def put(self, key: str, status: int, payload: Dict[str, Any]) -> None:
        if not self.capacity:
            return
        with self._lock:
            self._entries[key] = (status, payload)
            self._entries.move_to_end(key)
            self.stored += 1
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"size": len(self._entries), "hits": self.hits,
                    "capacity": self.capacity}


class _Handler(BaseHTTPRequestHandler):
    """One instance per request (http.server contract); the daemon hangs
    off the server object."""

    protocol_version = "HTTP/1.1"
    # loopback request/response ping-pong: Nagle + delayed ACK adds ~40ms
    # per round-trip; the payloads are single writes, so just disable it
    disable_nagle_algorithm = True

    # -- plumbing ------------------------------------------------------

    def log_message(self, fmt: str, *args: Any) -> None:
        if self.server.daemon_ref.verbose:  # type: ignore[attr-defined]
            sys.stderr.write("serve: %s\n" % (fmt % args))

    def _send_json(self, status: int, payload: Dict[str, Any]) -> None:
        body = protocol.dumps(payload)
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_text(self, status: int, text: str,
                   content_type: str = "text/plain; charset=utf-8") -> None:
        body = text.encode()
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    # -- routes --------------------------------------------------------

    def do_GET(self) -> None:  # noqa: N802 (http.server API)
        daemon = self.server.daemon_ref  # type: ignore[attr-defined]
        path, _, query = self.path.partition("?")
        if protocol.is_introspection(path):
            # scrape/debug traffic: counted on its own, NEVER in
            # serve.request_ms or the SLO denominators — a tight scrape
            # loop must not skew the served-traffic histograms
            obs.count("serve.introspection")
            obs.count(f"serve.introspection.{path.strip('/').replace('/', '_')}")
        if path == "/metrics":
            self._send_text(200, obs.prometheus_text(),
                            "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/healthz":
            health = daemon.service.health(draining=daemon.draining)
            health["idem_cache"] = daemon.idem_cache.stats()
            self._send_json(200, health)
        elif path == "/readyz":
            stale = daemon.heartbeat_stale
            ready = daemon.service.ready and not daemon.draining and not stale
            self._send_json(200 if ready else 503,
                            {"ready": ready,
                             "status": "draining" if daemon.draining
                             else "stale" if stale
                             else "ready" if daemon.service.ready
                             else "starting"})
        elif path == "/debug/requests":
            params = self._query_params(query)
            self._send_json(200, {
                "requests": flightrec.requests(
                    n=params.get("n"), trace=params.get("trace")),
                "recorded": flightrec.RECORDER.recorded,
                "capacity": flightrec.RECORDER.capacity,
            })
        elif path == "/debug/slowest":
            params = self._query_params(query)
            self._send_json(200, {
                "requests": flightrec.slowest(params.get("n") or 10),
                "recorded": flightrec.RECORDER.recorded,
            })
        elif path == "/debug/overload":
            # the overload-control surface: adaptive limit, brownout,
            # wait estimator, per-class shed tallies (docs/SERVE.md)
            self._send_json(200, daemon.service.batcher.overload_snapshot())
        else:
            self._send_json(404, protocol.error_response(
                protocol.NOT_FOUND, f"no route {path!r}"))

    @staticmethod
    def _query_params(query: str) -> Dict[str, Any]:
        """``n`` (int) and ``trace`` (str) from a query string."""
        from urllib.parse import parse_qs

        out: Dict[str, Any] = {}
        parsed = parse_qs(query)
        if parsed.get("trace"):
            out["trace"] = parsed["trace"][0]
        if parsed.get("n"):
            try:
                out["n"] = max(1, int(parsed["n"][0]))
            except ValueError:
                pass
        return out

    def do_POST(self) -> None:  # noqa: N802 (http.server API)
        daemon = self.server.daemon_ref  # type: ignore[attr-defined]
        path = self.path.split("?", 1)[0]
        method = protocol.method_for(path)
        if method is None:
            self._send_json(404, protocol.error_response(
                protocol.NOT_FOUND, f"no method at {path!r}"))
            return
        if daemon.draining:
            obs.count("serve.rejected_draining")
            self._send_json(503, protocol.error_response(
                protocol.DRAINING, "daemon is draining; request not accepted"))
            return
        with daemon.track_request():
            flightrec.begin(method)
            idem: Optional[str] = None
            settled = False  # settled outcomes enter the idem cache
            try:
                length = int(self.headers.get("Content-Length") or 0)
                if length > MAX_BODY_BYTES:
                    raise protocol.bad_request(
                        f"body too large ({length} > {MAX_BODY_BYTES})")
                params = protocol.loads(self.rfile.read(length))
                protocol.check_version(params)
                idem = protocol.request_idem(params)
                if idem is not None:
                    replay = daemon.idem_cache.get(idem)
                    if replay is not None:
                        # a failover router re-sent a request this
                        # replica already answered: replay the stored
                        # response — exactly-once execution per replica
                        obs.count("serve.idem_hits")
                        flightrec.commit(status="idem_replay")
                        self._send_json(replay[0], replay[1])
                        return
                wire_trace = obs.parse_traceparent(
                    params.get(protocol.TRACE_FIELD))
                if wire_trace is not None:
                    flightrec.note(trace=wire_trace["trace_id"])
                result = daemon.service.handle(method, params)
            except protocol.RequestError as e:
                obs.count("serve.errors.bad_request")
                flightrec.commit(status=e.code, error=e.message)
                status, payload = e.http_status, protocol.error_response(
                    e.code, e.message)
                settled = True  # a malformed request stays malformed
            except QueueFull as e:
                flightrec.commit(status=protocol.QUEUE_FULL, error=str(e))
                status, payload = 429, protocol.error_response(
                    protocol.QUEUE_FULL, str(e))
            except DeadlineExceeded as e:
                # a shed, not a fault: answered structured (504), never
                # counted against availability, excluded from /debug/slowest
                flightrec.commit(status="shed_deadline", error=str(e))
                status = protocol.HTTP_STATUS[protocol.DEADLINE_EXCEEDED]
                payload = protocol.error_response(
                    protocol.DEADLINE_EXCEEDED, str(e))
            except Shed as e:
                flightrec.commit(status="shed_priority", error=str(e))
                status = protocol.HTTP_STATUS[protocol.SHED]
                payload = protocol.error_response(protocol.SHED, str(e))
            except Draining as e:
                flightrec.commit(status=protocol.DRAINING, error=str(e))
                status, payload = 503, protocol.error_response(
                    protocol.DRAINING, str(e))
            except Exception as e:
                from ..resilience import classify, record_event

                kind = classify(e)
                record_event("gave_up", domain="serve.request", kind=kind,
                             detail=f"{type(e).__name__}: {e}")
                obs.count("serve.errors.internal")
                flightrec.commit(status=protocol.INTERNAL,
                                 error=f"[{kind}] {type(e).__name__}: {e}")
                status, payload = 500, protocol.error_response(
                    protocol.INTERNAL,
                    f"[{kind}] {type(e).__name__}: {e}")
            else:
                obs.count("serve.responses")
                flightrec.commit(status="ok")
                status, payload = 200, protocol.ok_response(result)
                settled = True
            if idem is not None and settled:
                daemon.idem_cache.put(idem, status, payload)
            self._send_json(status, payload)


class _Server(ThreadingHTTPServer):
    daemon_threads = True
    # socketserver's default listen backlog is 5: a burst of N concurrent
    # clients connecting at once gets RSTs on some boxes (observed: 16
    # simultaneous connects -> 3 ECONNRESET). A serving daemon wants a
    # real accept queue.
    request_queue_size = 128
    daemon_ref: "ServeDaemon"


class ServeDaemon:
    """Owns the HTTP server + service lifecycle. Usable in-process (tests,
    perfgate) or as the __main__ CLI process."""

    def __init__(
        self,
        service: Optional[SpecService] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        verbose: bool = False,
        idem_cache_size: int = 2048,
        heartbeat_stale_s: Optional[float] = None,
    ) -> None:
        self.service = service or SpecService()
        self.host = host
        self.requested_port = port
        self.verbose = verbose
        self.draining = False
        self.idem_cache = IdemCache(idem_cache_size)
        # fleet replicas run a supervise loop that beats this; /readyz
        # goes 503 "stale" when the loop stops beating (a hung replica
        # must advertise itself un-routable — docs/SERVE.md "Fleet").
        # None (the default, non-fleet daemon) disables the gate.
        self.heartbeat_stale_s = heartbeat_stale_s
        self._last_heartbeat = time.monotonic()
        self._server: Optional[_Server] = None
        self._serve_thread: Optional[threading.Thread] = None
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._inflight_zero = threading.Event()
        self._inflight_zero.set()

    # -- liveness heartbeat (fleet replicas) ---------------------------

    def heartbeat(self) -> None:
        self._last_heartbeat = time.monotonic()

    @property
    def heartbeat_stale(self) -> bool:
        return (self.heartbeat_stale_s is not None
                and time.monotonic() - self._last_heartbeat
                > self.heartbeat_stale_s)

    # -- in-flight accounting ------------------------------------------

    def track_request(self) -> "_Tracked":
        return _Tracked(self)

    def _enter(self) -> None:
        with self._inflight_lock:
            self._inflight += 1
            self._inflight_zero.clear()

    def _leave(self) -> None:
        with self._inflight_lock:
            self._inflight -= 1
            if self._inflight == 0:
                self._inflight_zero.set()

    @property
    def inflight(self) -> int:
        with self._inflight_lock:
            return self._inflight

    # -- lifecycle -----------------------------------------------------

    @property
    def port(self) -> int:
        assert self._server is not None, "start() first"
        return self._server.server_address[1]

    def start(self, warm: bool = True) -> "ServeDaemon":
        """Bind, warm, prebuild, serve. Returns self once /readyz is
        green."""
        self._server = _Server((self.host, self.requested_port), _Handler)
        self._server.daemon_ref = self
        # long-haul telemetry (docs/OBSERVABILITY.md): when the knob is
        # armed, this daemon writes a series journal and exposes its
        # live queue pressure as gauges the queue-creep watchdog reads;
        # unarmed cost is one env check + two dict writes
        from ..obs import timeseries

        if timeseries.ensure_started(role="serve.daemon"):
            timeseries.register_gauge("serve.queue_depth",
                                      self.service.batcher.depth)
            timeseries.register_gauge("serve.inflight", lambda: self.inflight)
        # the consensus health plane's exposition metadata (obs/chain.py):
        # a daemon ingesting a chain (the sim as a client, the
        # fork_choice_attestation wire path) publishes chain.* gauges
        # into the same registry; registering the family's HELP/TYPE
        # descriptions here makes every /metrics scrape — and the
        # fleet's aggregate_prometheus rollup, which MAXes level gauges
        # by their TYPE — self-describing
        from ..obs import chain as obs_chain

        obs_chain.register_descriptions()
        if warm:
            from .lifecycle import warm_start

            report = warm_start(self.service.forks, self.service.presets,
                                jit_probe=False)
            if self.verbose:
                sys.stderr.write(f"serve: warm start {report}\n")
        self.service.start()
        self._serve_thread = threading.Thread(
            target=self._server.serve_forever, name="serve-http", daemon=True)
        self._serve_thread.start()
        obs.count("serve.started")
        return self

    def drain(self, timeout_s: float = 30.0) -> Dict[str, Any]:
        """Stop intake, answer the tail, flush the queue, stop serving.
        Idempotent. Returns a drain report."""
        if self.draining and self._server is None:
            return {"already": True}
        self.draining = True
        self.service.stop()
        t0 = time.monotonic()
        # order matters: verify handlers block on futures the batcher
        # resolves — flush the queue FIRST, then wait for the tail of
        # in-flight handler threads to write their responses
        queue_drained = self.service.batcher.drain(timeout_s)
        tail_done = self._inflight_zero.wait(
            max(0.1, timeout_s - (time.monotonic() - t0)))
        server, self._server = self._server, None
        if server is not None:
            server.shutdown()
            server.server_close()
        if self._serve_thread is not None:
            self._serve_thread.join(5)
        report = {
            "inflight_answered": tail_done,
            "queue_drained": queue_drained,
            "drain_s": round(time.monotonic() - t0, 3),
            "accepted": self.service.batcher.accepted,
            # flushed_rows + shed_rows == accepted iff every accepted
            # check was answered exactly once — flushed OR shed with a
            # structured deadline_exceeded/shed response, never dropped
            # (the drain drill reads this; sheds counted separately)
            "flushed_rows": self.service.batcher.flushed_rows,
            "shed_rows": self.service.batcher.shed_rows,
            "shed": dict(self.service.batcher.shed_by_class),
            "rejected": self.service.batcher.rejected,
            "flightrec_recorded": flightrec.RECORDER.recorded,
        }
        obs.count("serve.drained")
        return report


class _Tracked:
    __slots__ = ("_daemon",)

    def __init__(self, daemon: ServeDaemon) -> None:
        self._daemon = daemon

    def __enter__(self) -> None:
        self._daemon._enter()

    def __exit__(self, *exc: Any) -> None:
        self._daemon._leave()


# ---------------------------------------------------------------------------
# CLI: python -m consensus_specs_tpu.serve
# ---------------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m consensus_specs_tpu.serve",
        description="resident spec verification daemon (docs/SERVE.md)")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0,
                        help="0 = ephemeral (printed on the READY line)")
    parser.add_argument("--forks", default=",".join(DEFAULT_FORKS),
                        help="comma-separated served forks")
    parser.add_argument("--presets", default=",".join(DEFAULT_PRESETS),
                        help="comma-separated served presets")
    parser.add_argument("--backend", default="reference",
                        choices=("reference", "jax"),
                        help="BLS backend (jax degrades to reference when "
                             "unavailable, with a recorded event)")
    parser.add_argument("--max-queue", type=int,
                        default=int(_env_float(ENV_MAX_QUEUE, 1024)))
    parser.add_argument("--max-batch", type=int,
                        default=int(_env_float(ENV_MAX_BATCH, 256)))
    parser.add_argument("--linger-ms", type=float,
                        default=_env_float(ENV_LINGER_MS, 5.0))
    parser.add_argument("--result-cache", type=int,
                        default=int(_env_float(ENV_CACHE, 4096)))
    parser.add_argument("--admission", default=None,
                        choices=("adaptive", "fixed"),
                        help="queue admission mode (default: adaptive, or "
                             "CONSENSUS_SPECS_TPU_SERVE_ADMISSION); fixed = "
                             "the PR-6 hard bound only")
    parser.add_argument("--target-p99-ms", type=float, default=None,
                        help="adaptive admission latency target (queue-wait "
                             "p99; default 50 or "
                             "CONSENSUS_SPECS_TPU_SERVE_TARGET_P99_MS)")
    parser.add_argument("--min-limit", type=int, default=None,
                        help="adaptive admission floor (default 16)")
    parser.add_argument("--flush-delay-ms", type=float, default=None,
                        help="drill knob: simulated service time per flush "
                             "(overload drills; default 0 or "
                             "CONSENSUS_SPECS_TPU_SERVE_FLUSH_DELAY_MS)")
    parser.add_argument("--no-warm", action="store_true",
                        help="skip the compile-cache/jit warm start")
    parser.add_argument("--jit-probe", action="store_true",
                        help="also prime small per-plane kernels at startup")
    parser.add_argument("--ready-file", default=None,
                        help="write {port,pid} JSON here once ready")
    parser.add_argument("--drain-timeout-s", type=float, default=30.0)
    parser.add_argument("--verbose", action="store_true")
    ns = parser.parse_args(argv)

    from ..crypto import bls

    admission = AdmissionController(
        ns.max_queue, mode=ns.admission, min_limit=ns.min_limit,
        target_p99_ms=ns.target_p99_ms)
    batcher = VerifyBatcher(max_queue=ns.max_queue, max_batch=ns.max_batch,
                            linger_ms=ns.linger_ms, cache_size=ns.result_cache,
                            admission=admission,
                            flush_delay_ms=ns.flush_delay_ms)
    service = SpecService(
        forks=tuple(f for f in ns.forks.split(",") if f),
        presets=tuple(p for p in ns.presets.split(",") if p),
        batcher=batcher)
    daemon = ServeDaemon(service, host=ns.host, port=ns.port,
                         verbose=ns.verbose)

    if ns.backend == "jax":
        bls.use_jax()  # degrades to reference + recorded event if broken
    if ns.jit_probe and not ns.no_warm:
        from .lifecycle import warm_start

        warm_start(service.forks, service.presets, jit_probe=True)
        daemon.start(warm=False)
    else:
        daemon.start(warm=not ns.no_warm)

    stop = threading.Event()

    def _on_signal(signum: int, frame: Any) -> None:
        sys.stderr.write(f"serve: signal {signum} -> draining\n")
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    # operator escape hatch: SIGUSR2 dumps every thread's stack AND the
    # flight recorder's last-N-requests ring to stderr (a resident
    # process should be debuggable without gdb, and a p99 spike should
    # be diagnosable without having had tracing armed)
    import faulthandler

    def _on_usr2(signum: int, frame: Any) -> None:
        faulthandler.dump_traceback(all_threads=True)
        sys.stderr.write("SERVE FLIGHTREC "
                         + json.dumps(flightrec.dump(), sort_keys=True) + "\n")
        sys.stderr.flush()

    signal.signal(signal.SIGUSR2, _on_usr2)

    ready_line = (f"SERVE READY port={daemon.port} pid={os.getpid()} "
                  f"backend={bls.backend_name()} "
                  f"matrix={','.join(service.matrix_labels())}")
    print(ready_line, flush=True)
    if ns.ready_file:
        tmp = f"{ns.ready_file}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"port": daemon.port, "pid": os.getpid(),
                       "backend": bls.backend_name()}, f)
        os.replace(tmp, ns.ready_file)

    # NOT a bare stop.wait(): the kernel may deliver SIGTERM to any
    # non-blocking thread, and Python-level handlers only ever run on
    # the MAIN thread — which a bare Event.wait() parks in an
    # uninterruptible lock acquire (observed: a daemon with busy
    # handler threads ignored SIGTERM forever). Waking every 200ms
    # guarantees pending handlers run within one tick.
    while not stop.is_set():
        stop.wait(0.2)
    report = daemon.drain(ns.drain_timeout_s)
    # the drain dump: the flight recorder's tail survives to stderr so a
    # post-mortem has the last requests even without /debug access
    sys.stderr.write("SERVE FLIGHTREC "
                     + json.dumps(flightrec.dump(), sort_keys=True) + "\n")
    sys.stderr.flush()
    print(f"SERVE DRAINED {json.dumps(report, sort_keys=True)}", flush=True)
    return 0 if (report.get("queue_drained", True)
                 and report.get("inflight_answered", True)) else 1
