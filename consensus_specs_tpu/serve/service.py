"""Request execution for the resident verification service: wire params
in, spec-path results out — through exactly the same facade machinery
the direct (non-served) path uses, so served answers are bit-identical
by construction.

- ``verify`` / ``verify_batch`` — checks parse to the facade's deferred
  keys and ride the :class:`~.batcher.VerifyBatcher` (cross-client
  micro-batching, admission control, host-oracle degradation). The rare
  ``AggregateVerify`` form resolves scalar, like the flush path does.
- ``hash_tree_root`` (+ batch) — decode the SSZ payload as the named
  container of a (fork, preset) spec module and return its root; the
  hashing backend (SHA-NI host / device) is whatever the process has
  installed, faults degrade inside the ssz plane itself.
- ``process_block`` — decode pre-state + block, run the spec module's
  ``process_block`` on a copy, return the post-state SSZ + root.

The (fork, preset) matrix is prebuilt at startup (``spec.build`` spans)
so no request pays a spec compile; requests for pairs outside the
served matrix are 400s, not lazy builds — the daemon's memory footprint
is an operator decision, not a client side effect.

Every request runs under a ``serve.request`` span (method/fork attrs →
``span.serve.request`` latency histograms feed /metrics) and passes the
``serve.request`` chaos site, so a fault injected here proves the error
surface: the request fails structured, the daemon lives on.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .. import obs
from ..obs import flightrec
from ..resilience import chaos
from . import protocol
from .batcher import DeadlineExceeded, VerifyBatcher

DEFAULT_FORKS = ("phase0", "altair")
DEFAULT_PRESETS = ("minimal",)

# spec-module attributes a client may name as an SSZ type: any public
# SSZType subclass in the built namespace (BeaconState, BeaconBlock,
# Attestation, ...). Resolved per request against the matrix module.
_TYPE_BLOCKLIST_PREFIX = "_"

# the spec's invalid-block rejection ladder (the exception classes
# process_block uses as control flow). Shared contract with the fuzz
# farm's differential executor (fuzz/executor.py REJECTED): the served
# path must classify exactly the same set as rejections, or a fuzz case
# diverges on error surface alone.
PROCESS_BLOCK_REJECTED = (AssertionError, IndexError, ValueError, KeyError,
                          OverflowError)


class SpecService:
    """The method surface one daemon serves. Thread-safe: handler
    threads call :meth:`handle` concurrently."""

    def __init__(
        self,
        forks: Sequence[str] = DEFAULT_FORKS,
        presets: Sequence[str] = DEFAULT_PRESETS,
        batcher: Optional[VerifyBatcher] = None,
        request_timeout_s: float = 120.0,
    ) -> None:
        self.forks = tuple(forks)
        self.presets = tuple(presets)
        self.batcher = batcher or VerifyBatcher()
        self.request_timeout_s = request_timeout_s
        self._matrix: Dict[Tuple[str, str], Any] = {}
        # fork-choice anchor stores for fork_choice_attestation, keyed
        # (fork, preset, seed) — built lazily, shared read-only across
        # requests via fresh per-request views
        self._fc_anchors: Dict[Tuple, Any] = {}
        self._build_lock = threading.Lock()
        self.started_at = time.time()
        self.ready = False

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "SpecService":
        """Prebuild the served spec matrix and start the flusher. The
        compile cache is configured by the daemon's warm start (see
        serve.lifecycle) before any backend import."""
        from ..specs import build

        with obs.span("serve.startup", forks=",".join(self.forks),
                      presets=",".join(self.presets)):
            for preset in self.presets:
                for fork in self.forks:
                    self._matrix[(fork, preset)] = build.build_spec(fork, preset)
        self.batcher.start()
        self.ready = True
        return self

    def stop(self) -> None:
        self.ready = False

    def matrix_labels(self) -> List[str]:
        return [f"{fork}/{preset}" for fork, preset in self._matrix]

    def _spec(self, params: Dict[str, Any]) -> Any:
        fork = protocol.require_str(params, "fork")
        preset = protocol.require_str(params, "preset")
        spec = self._matrix.get((fork, preset))
        if spec is None:
            raise protocol.bad_request(
                f"({fork}, {preset}) is not in the served matrix "
                f"{self.matrix_labels()}")
        return spec

    # -- dispatch ------------------------------------------------------

    def handle(self, method: str, params: Dict[str, Any]) -> Dict[str, Any]:
        """Execute one wire method. Raises protocol.RequestError for
        client-side errors; batcher admission errors propagate for the
        daemon to map (QueueFull -> 429, Draining -> 503)."""
        fn = getattr(self, f"_do_{method}", None)
        if fn is None:
            raise protocol.RequestError(protocol.NOT_FOUND,
                                        f"unknown method {method!r}")
        t0 = time.monotonic()
        try:
            # an optional wire trace field adopts the CLIENT's context:
            # this request span parents under the client's span id and
            # carries its trace id, so the merged trace links
            # client -> daemon -> shared flush with flow arrows
            with obs.remote_span("serve.request", protocol.trace_context(params),
                                 method=method, fork=params.get("fork"),
                                 preset=params.get("preset")) as sp:
                flightrec.note(span=sp.span_id)
                chaos("serve.request")
                obs.count(f"serve.requests.{method}")
                # overload-control fields validate for EVERY method; a
                # request that arrives with its budget already spent is
                # shed before any work (docs/SERVE.md "Overload control")
                deadline_ms = protocol.request_deadline_ms(params)
                priority = protocol.request_priority(params)
                if priority != protocol.PRIORITY_DEFAULT:
                    flightrec.note(priority=priority)
                if deadline_ms is not None and deadline_ms <= 0:
                    self.batcher._count_shed("admission_deadline", 1,
                                             queued=False)
                    raise DeadlineExceeded(
                        "deadline_ms budget already expired at arrival")
                return fn(params)
        finally:
            # span histograms only feed when tracing is armed; /metrics
            # must expose request latency unconditionally. Introspection
            # endpoints never reach handle(), so scrapers cannot skew
            # this histogram (protocol.is_introspection).
            obs.observe("serve.request_ms", (time.monotonic() - t0) * 1e3)

    # -- methods -------------------------------------------------------

    def _resolve_check(self, key: Tuple, priority: str,
                       deadline_ms: Optional[float]) -> bool:
        if key[0] == "av":
            # never appears in spec-level state-transition code; resolve
            # scalar through the facade, same as DeferredVerifier.flush
            from ..crypto import bls

            try:
                return bool(bls.AggregateVerify(list(key[1]), list(key[2]),
                                                key[3]))
            except Exception:
                return False
        return self.batcher.submit(key, timeout_s=self.request_timeout_s,
                                   priority=priority, deadline_ms=deadline_ms)

    def _do_verify(self, params: Dict[str, Any]) -> Dict[str, Any]:
        key = protocol.parse_check(params)
        return {"valid": self._resolve_check(
            key, protocol.request_priority(params),
            protocol.request_deadline_ms(params))}

    def _do_verify_batch(self, params: Dict[str, Any]) -> Dict[str, Any]:
        checks = params.get("checks")
        if not isinstance(checks, list) or not checks:
            raise protocol.bad_request("checks: expected a non-empty list")
        priority = protocol.request_priority(params)
        deadline_ms = protocol.request_deadline_ms(params)
        keys = [protocol.parse_check(c, f"checks[{i}]")
                for i, c in enumerate(checks)]
        scalar = {i: self._resolve_check(k, priority, deadline_ms)
                  for i, k in enumerate(keys) if k[0] == "av"}
        batched = [(i, k) for i, k in enumerate(keys) if k[0] != "av"]
        if batched:
            answers = self.batcher.submit_many(
                [k for _, k in batched], timeout_s=self.request_timeout_s,
                priority=priority, deadline_ms=deadline_ms)
            scalar.update({i: a for (i, _), a in zip(batched, answers)})
        return {"results": [scalar[i] for i in range(len(keys))]}

    def _resolve_type(self, spec: Any, name: str) -> Any:
        from ..ssz import SSZType

        if name.startswith(_TYPE_BLOCKLIST_PREFIX):
            raise protocol.bad_request(f"type: {name!r} is not servable")
        obj = getattr(spec, name, None)
        if not (isinstance(obj, type) and issubclass(obj, SSZType)):
            raise protocol.bad_request(
                f"type: {name!r} is not an SSZ type of {spec.fork}")
        return obj

    def _do_hash_tree_root(self, params: Dict[str, Any]) -> Dict[str, Any]:
        spec = self._spec(params)
        ssz_type = self._resolve_type(spec, protocol.require_str(params, "type"))
        data = protocol.from_hex(params.get("ssz"), "ssz")
        try:
            obj = ssz_type.decode_bytes(data)
        except Exception as e:
            raise protocol.bad_request(f"ssz: does not decode as "
                                       f"{params['type']} ({e})")
        return {"root": protocol.to_hex(obj.hash_tree_root())}

    def _do_hash_tree_root_batch(self, params: Dict[str, Any]) -> Dict[str, Any]:
        items = params.get("items")
        if not isinstance(items, list) or not items:
            raise protocol.bad_request("items: expected a non-empty list")
        roots = []
        for i, item in enumerate(items):
            if not isinstance(item, dict):
                raise protocol.bad_request(f"items[{i}]: expected an object")
            merged = dict(params)
            merged.update(item)
            merged.pop("items", None)
            roots.append(self._do_hash_tree_root(merged)["root"])
        return {"roots": roots}

    def _do_process_block(self, params: Dict[str, Any]) -> Dict[str, Any]:
        spec = self._spec(params)
        pre_bytes = protocol.from_hex(params.get("pre"), "pre")
        block_bytes = protocol.from_hex(params.get("block"), "block")
        try:
            state = spec.BeaconState.decode_bytes(pre_bytes)
        except Exception as e:
            raise protocol.bad_request(f"pre: does not decode as BeaconState ({e})")
        try:
            block = spec.BeaconBlock.decode_bytes(block_bytes)
        except Exception as e:
            raise protocol.bad_request(f"block: does not decode as BeaconBlock ({e})")
        try:
            spec.process_block(state, block)
        except PROCESS_BLOCK_REJECTED as e:
            # the spec's invalid-block surface: a structured rejection,
            # not a daemon fault (mirrors how the generators classify it
            # and the sim's intake paths — adversarial blocks from the
            # fuzz corpus reach KeyError/OverflowError rungs too, and
            # those are rejections, not 500s)
            raise protocol.bad_request(f"block rejected by {spec.fork} "
                                       f"process_block: {e!r}")
        return {"post": protocol.to_hex(state.encode_bytes()),
                "root": protocol.to_hex(state.hash_tree_root())}

    def _do_fork_choice_attestation(self, params: Dict[str, Any]) -> Dict[str, Any]:
        """Fork-choice intake as a served method (docs/FUZZ.md
        "Fork-choice intake"): run ``on_attestation`` against the seeded
        anchor store context — the same pure function of
        ``(fork, preset, seed)`` the fuzz executor's direct paths build —
        and answer the normalized latest-message digest. Wire params:
        ``fork``/``preset``/``seed``/``attestation`` (hex). Rejections
        classify on exactly the shared ladder so the served path can
        never diverge from the oracle on error surface alone."""
        from ..fuzz.corpus import build_fc_store
        from ..fuzz.executor import fresh_store_view, latest_messages_digest

        spec = self._spec(params)
        seed = params.get("seed", 1)
        if not isinstance(seed, int) or isinstance(seed, bool):
            raise protocol.bad_request("seed: expected an integer")
        att_bytes = protocol.from_hex(params.get("attestation"), "attestation")
        try:
            att = spec.Attestation.decode_bytes(att_bytes)
        except Exception as e:
            raise protocol.bad_request(
                f"attestation: does not decode as Attestation ({e})")
        key = (spec.fork, params.get("preset"), seed)
        with self._build_lock:
            anchor = self._fc_anchors.get(key)
            if anchor is None:
                anchor = build_fc_store(spec, seed)
                self._fc_anchors[key] = anchor
        store = fresh_store_view(spec, anchor)
        try:
            spec.on_attestation(store, att, is_from_block=False)
        except PROCESS_BLOCK_REJECTED as e:
            raise protocol.bad_request(
                f"attestation rejected by {spec.fork} "
                f"on_attestation: {e!r}")
        return {"accepted": True,
                "latest": latest_messages_digest(store)}

    # -- health --------------------------------------------------------

    def health(self, draining: bool = False) -> Dict[str, Any]:
        from ..crypto import bls
        from ..resilience import quarantined
        from ..sched import compile_cache_stats

        snap = obs.snapshot()
        counters = {k: v for k, v in snap["counters"].items()
                    if k.startswith("serve.")}
        status = ("draining" if draining
                  else "ready" if self.ready else "starting")
        return {
            "status": status,
            "wire_version": protocol.WIRE_VERSION,
            "pid": os.getpid(),
            "uptime_s": round(time.time() - self.started_at, 3),
            "backend": bls.backend_name(),
            "quarantined": quarantined(),
            "matrix": self.matrix_labels(),
            "queue": {"depth": self.batcher.depth(),
                      "capacity": self.batcher.max_queue,
                      "accepted": self.batcher.accepted,
                      "rejected": self.batcher.rejected,
                      "shed_rows": self.batcher.shed_rows,
                      "flushes": self.batcher.flushes},
            "overload": self.batcher.overload_snapshot(),
            "result_cache": self.batcher.cache_stats(),
            "compile_cache": compile_cache_stats(),
            "counters": counters,
        }
