"""Overload drill harness: open-loop / closed-loop load drivers and
the goodput-under-overload measurement (docs/SERVE.md "Overload
control").

The existing bench harness (tools/serve_bench.py) is *closed-loop*: N
threads each wait for an answer before sending the next request, so
offered load can never exceed capacity — the harness itself backs off,
and congestion collapse is unobservable by construction. This module
adds the missing half:

- :func:`closed_loop` — the saturation measurement: N clients at full
  tilt over distinct (dedup-proof, cache-proof) checks. Its answered/s
  IS the serving capacity on this box.
- :func:`open_loop` — fixed *arrival rate*, independent of completions
  (arrivals that find every sender busy are sent late and counted
  ``lagged``, never dropped): offered load CAN exceed capacity, which
  is the only regime where overload control does anything.
- :func:`run_overload_drill` — the full phase sequence against an
  already-running daemon: saturation -> 3x open-loop overload with
  deadlines + a priority mix -> recovery probe. Returns one report
  dict with **goodput** (answered within deadline / s), per-outcome
  tallies, the shed ratio, and the recovery latency — the numbers
  ``make overload-drill`` banks as ``serve_goodput_per_s`` /
  ``serve_shed_ratio``.
- :func:`mini_drill` — a scaled-down, jax-free, crypto-free instance
  (in-process daemon, simulated flush service time via the
  ``flush_delay_ms`` drill knob, invalid-pubkey checks the oracle
  answers instantly) used by ``make overload-smoke`` and perfgate's
  ``perfgate_overload_goodput_ratio`` slice.

Check populations: "cheap" checks are well-formed-but-invalid (the
oracle rejects the pubkey without a pairing) — they exercise every
queue/batch/shed mechanism at zero crypto cost. "Expensive" checks
reuse ONE valid signature against distinct messages, so every check is
a distinct key (no dedup, no cache hit) that costs a full pairing —
the honest capacity workload for the real drill.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..obs.metrics import percentile
from . import protocol
from .client import ServeClient, ServeError

OUTCOMES = ("ok_in_deadline", "ok_late", "shed_deadline", "shed_priority",
            "queue_full", "draining", "error")


# ---------------------------------------------------------------------------
# check populations
# ---------------------------------------------------------------------------

def cheap_check(i: int, tag: str = "drill") -> Dict[str, Any]:
    """A distinct well-formed-but-invalid check: the oracle rejects the
    pubkey without pairing work, answers False — free of crypto cost,
    distinct key (no dedup/cache short-circuit)."""
    seed = (i * 2654435761) & 0xFFFFFFFF
    return {
        "pubkeys": [protocol.to_hex(bytes([seed % 251 + 1]) * 48)],
        "message": protocol.to_hex(
            tag.encode()[:8].ljust(8, b".") + seed.to_bytes(4, "little")
            + b"\x00" * 20),
        "signature": protocol.to_hex(b"\x02" * 96),
    }


def expensive_check_factory() -> Callable[[int], Dict[str, Any]]:
    """Checks that each cost a FULL pairing: one valid (pk, sig) pair is
    built once (one SkToPk + one Sign), then reused against distinct
    messages — every check is a distinct key, deserializes cleanly, and
    the pairing evaluates before answering False."""
    from ..crypto.bls import ciphersuite as oracle

    pk = protocol.to_hex(oracle.SkToPk(7))
    sig = protocol.to_hex(oracle.Sign(7, b"overload-drill-anchor" + b"\x00" * 11))

    def make(i: int) -> Dict[str, Any]:
        return {"pubkey": pk,
                "message": protocol.to_hex(
                    b"overload." + i.to_bytes(4, "little") + b"\x00" * 19),
                "signature": sig}

    return make


def default_priority_mix(i: int) -> str:
    """The drill's deterministic criticality mix: 10% critical, 20%
    sheddable, 70% default."""
    if i % 10 == 0:
        return protocol.PRIORITY_CRITICAL
    if i % 5 == 1:
        return protocol.PRIORITY_SHEDDABLE
    return protocol.PRIORITY_DEFAULT


# ---------------------------------------------------------------------------
# load drivers
# ---------------------------------------------------------------------------

def _make_client(port: Optional[int], timeout_s: float,
                 client_factory: Optional[Callable[[], Any]]) -> Any:
    """One per-thread wire client: the default single-daemon
    ``ServeClient``, or whatever ``client_factory`` builds (the fleet
    drills pass a :class:`~.client.FleetClient` factory so the SAME
    load drivers exercise the routed path)."""
    if client_factory is not None:
        return client_factory()
    assert port is not None, "need a port or a client_factory"
    return ServeClient(port, timeout_s=timeout_s, max_retries=0)


def closed_loop(port: Optional[int], *, clients: int,
                requests_per_client: int,
                make_check: Callable[[int], Dict[str, Any]],
                timeout_s: float = 120.0,
                priority: Optional[str] = None,
                client_factory: Optional[Callable[[], Any]] = None,
                ) -> Dict[str, Any]:
    """Saturation measurement: every thread always has exactly one
    request outstanding. Distinct checks per request, no retries (the
    harness must never amplify its own load). The drill runs this at
    ``critical`` priority so the capacity number can never be clipped
    by the adaptive limiter it is calibrating."""
    lat: List[List[float]] = [[] for _ in range(clients)]
    answered = [0] * clients
    errors = [0] * clients
    barrier = threading.Barrier(clients + 1)

    def worker(idx: int) -> None:
        with _make_client(port, timeout_s, client_factory) as c:
            barrier.wait()
            for r in range(requests_per_client):
                i = idx * requests_per_client + r
                t0 = time.perf_counter()
                try:
                    c.call("verify", make_check(i), priority=priority)
                    answered[idx] += 1
                except Exception:
                    errors[idx] += 1
                lat[idx].append((time.perf_counter() - t0) * 1e3)

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(clients)]
    for t in threads:
        t.start()
    barrier.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    flat = sorted(x for ls in lat for x in ls)
    total = sum(answered)
    return {
        "clients": clients,
        "requests": clients * requests_per_client,
        "answered": total,
        "errors": sum(errors),
        "wall_s": round(wall, 3),
        "rate_per_s": round(total / wall, 3) if wall > 0 else None,
        "p50_ms": percentile(flat, 50),
        "p99_ms": percentile(flat, 99),
    }


def open_loop(port: Optional[int], *, rate_per_s: float, duration_s: float,
              make_check: Callable[[int], Dict[str, Any]],
              deadline_ms: Optional[float] = None,
              priority_for: Optional[Callable[[int], str]] = None,
              max_threads: int = 64,
              timeout_s: Optional[float] = None,
              client_factory: Optional[Callable[[], Any]] = None,
              ) -> Dict[str, Any]:
    """Fixed-arrival-rate driver. Arrival i is due at ``t0 + i/rate``;
    a free sender sleeps until then and fires. When every sender is
    busy the arrival goes out late (counted ``lagged``) — arrivals are
    never dropped, so offered load is honest even past capacity.

    Senders use ``max_retries=0``: the drill measures the DAEMON's
    overload behavior; client retry discipline is drilled separately.
    """
    n_arrivals = max(1, int(rate_per_s * duration_s))
    if timeout_s is None:
        timeout_s = max(10.0, (deadline_ms or 0) / 1e3 * 4 + 10.0)
    # enough senders to keep arrivals on schedule at the expected
    # latency, bounded so the driver cannot melt the box
    threads_n = min(max(8, int(rate_per_s * (timeout_s if deadline_ms is None
                                             else deadline_ms / 1e3) * 1.5)),
                    max_threads)
    counter = {"next": 0}
    counter_lock = threading.Lock()
    outcomes = {k: 0 for k in OUTCOMES}
    ok_lat: List[float] = []
    stats_lock = threading.Lock()
    lagged = [0]
    t_start = [0.0]
    barrier = threading.Barrier(threads_n + 1)

    def classify(code: str) -> str:
        return {protocol.DEADLINE_EXCEEDED: "shed_deadline",
                protocol.SHED: "shed_priority",
                protocol.QUEUE_FULL: "queue_full",
                protocol.DRAINING: "draining"}.get(code, "error")

    def worker() -> None:
        with _make_client(port, timeout_s, client_factory) as c:
            barrier.wait()
            while True:
                with counter_lock:
                    i = counter["next"]
                    if i >= n_arrivals:
                        return
                    counter["next"] = i + 1
                due = t_start[0] + i / rate_per_s
                now = time.perf_counter()
                if now < due:
                    time.sleep(due - now)
                elif now - due > 0.05:
                    with stats_lock:
                        lagged[0] += 1
                check = make_check(i)
                prio = priority_for(i) if priority_for else None
                t0 = time.perf_counter()
                try:
                    c.call("verify", check, deadline_ms=deadline_ms,
                           priority=prio)
                    ms = (time.perf_counter() - t0) * 1e3
                    key = ("ok_late" if deadline_ms is not None
                           and ms > deadline_ms else "ok_in_deadline")
                    with stats_lock:
                        outcomes[key] += 1
                        if key == "ok_in_deadline":
                            ok_lat.append(ms)
                except ServeError as e:
                    with stats_lock:
                        outcomes[classify(e.code)] += 1
                except Exception:
                    with stats_lock:
                        outcomes["error"] += 1

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(threads_n)]
    for t in threads:
        t.start()
    t_start[0] = time.perf_counter()
    barrier.wait()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start[0]
    sheds = outcomes["shed_deadline"] + outcomes["shed_priority"]
    return {
        "offered": n_arrivals,
        "offered_rate_per_s": round(rate_per_s, 3),
        "achieved_rate_per_s": round(n_arrivals / wall, 3) if wall else None,
        "duration_s": round(wall, 3),
        "senders": threads_n,
        "lagged": lagged[0],
        "outcomes": dict(outcomes),
        "goodput_per_s": (round(outcomes["ok_in_deadline"] / wall, 3)
                          if wall else None),
        "shed_ratio": round(sheds / n_arrivals, 4),
        "rejected_ratio": round(
            (sheds + outcomes["queue_full"]) / n_arrivals, 4),
        "answered": sum(outcomes.values()),
        "ok_p50_ms": percentile(sorted(ok_lat), 50),
        "ok_p99_ms": percentile(sorted(ok_lat), 99),
    }


def recovery_probe(port: int, *, make_check: Callable[[int], Dict[str, Any]],
                   probes: int = 20, settle_timeout_s: float = 30.0,
                   ) -> Dict[str, Any]:
    """After the overload stops: wait for the queue to drain, then
    measure a clean probe window — the daemon must return to baseline
    latency, not stay wedged behind a backlog of dead work."""
    t0 = time.perf_counter()
    with ServeClient(port, timeout_s=60, max_retries=0) as c:
        depth: Optional[int] = None
        while time.perf_counter() - t0 < settle_timeout_s:
            depth = c.health()["queue"]["depth"]
            if depth == 0:
                break
            time.sleep(0.05)
        settle_s = time.perf_counter() - t0
        lat: List[float] = []
        errors = 0
        for i in range(probes):
            t1 = time.perf_counter()
            try:
                c.call("verify", make_check(10_000_000 + i))
            except Exception:
                errors += 1
            lat.append((time.perf_counter() - t1) * 1e3)
    return {
        "settle_s": round(settle_s, 3),
        "settled": depth == 0,
        "probes": probes,
        "errors": errors,
        "p50_ms": percentile(sorted(lat), 50),
        "p99_ms": percentile(sorted(lat), 99),
    }


# ---------------------------------------------------------------------------
# the drill sequence
# ---------------------------------------------------------------------------

def run_overload_drill(
    port: int,
    *,
    make_check: Callable[[int], Dict[str, Any]],
    sat_clients: int = 4,
    sat_requests_per_client: int = 12,
    overload_multiplier: float = 3.0,
    overload_duration_s: float = 10.0,
    deadline_ms: float = 2000.0,
    priority_for: Optional[Callable[[int], str]] = default_priority_mix,
    recovery_probes: int = 20,
    max_threads: int = 64,
) -> Dict[str, Any]:
    """Saturation -> overload -> recovery against a running daemon.

    Goodput contract (the no-collapse criterion the drill asserts):
    open-loop offered load at ``overload_multiplier``x the measured
    saturation rate must keep goodput (answered within deadline / s)
    within 20% of the saturation rate — shed the excess, serve the
    rest — and the post-load probe must sit back at baseline latency.
    """
    saturation = closed_loop(port, clients=sat_clients,
                             requests_per_client=sat_requests_per_client,
                             make_check=make_check,
                             priority=protocol.PRIORITY_CRITICAL)
    sat_rate = saturation["rate_per_s"] or 1.0
    offered_rate = max(1.0, sat_rate * overload_multiplier)
    overload = open_loop(
        port, rate_per_s=offered_rate, duration_s=overload_duration_s,
        make_check=lambda i: make_check(1_000_000 + i),
        deadline_ms=deadline_ms, priority_for=priority_for,
        max_threads=max_threads)
    recovery = recovery_probe(port, make_check=make_check,
                              probes=recovery_probes)
    goodput = overload["goodput_per_s"] or 0.0
    return {
        "saturation": saturation,
        "overload": overload,
        "recovery": recovery,
        "deadline_ms": deadline_ms,
        "overload_multiplier": overload_multiplier,
        "goodput_per_s": goodput,
        "goodput_ratio": round(goodput / sat_rate, 4) if sat_rate else None,
        "shed_ratio": overload["shed_ratio"],
    }


# ---------------------------------------------------------------------------
# the scaled-down in-process instance (overload-smoke + perfgate)
# ---------------------------------------------------------------------------

def mini_drill(
    *,
    flush_delay_ms: float = 80.0,
    max_batch: int = 2,
    sat_clients: int = 4,
    sat_requests_per_client: int = 10,
    overload_multiplier: float = 3.0,
    overload_duration_s: float = 2.5,
    deadline_ms: float = 500.0,
    target_p99_ms: float = 250.0,
    min_limit: int = 2,
    recovery_probes: int = 20,
    probe: Optional[Callable[[int], Any]] = None,
) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """The deterministic, jax-free, crypto-free drill: an in-process
    daemon whose flush pipeline has a SIMULATED service time
    (``flush_delay_ms`` per dispatch, ``max_batch`` rows each), driven
    with invalid-pubkey checks the oracle answers instantly. Capacity
    is therefore ``max_batch / flush_delay`` rows/s by construction —
    small enough that a Python thread pool can offer 3x it — and every
    shed/admission mechanism runs for real.

    Returns ``(report, drain_report)``; the daemon is always drained.
    """
    from .admission import AdmissionController
    from .batcher import VerifyBatcher
    from .daemon import ServeDaemon
    from .service import SpecService

    admission = AdmissionController(
        1024, mode="adaptive", min_limit=min_limit,
        target_p99_ms=target_p99_ms, tick_s=0.02, brownout_ticks=2)
    batcher = VerifyBatcher(
        max_queue=1024, max_batch=max_batch, linger_ms=2.0, cache_size=0,
        admission=admission, flush_delay_ms=flush_delay_ms)
    service = SpecService(forks=("phase0",), presets=("minimal",),
                          batcher=batcher, request_timeout_s=30.0)
    daemon = ServeDaemon(service).start(warm=False)
    try:
        report = run_overload_drill(
            daemon.port, make_check=cheap_check,
            sat_clients=sat_clients,
            sat_requests_per_client=sat_requests_per_client,
            overload_multiplier=overload_multiplier,
            overload_duration_s=overload_duration_s,
            deadline_ms=deadline_ms,
            recovery_probes=recovery_probes,
            max_threads=48)
        report["overload_state"] = batcher.overload_snapshot()
        if probe is not None:
            report["probe"] = probe(daemon.port)
    finally:
        drain_report = daemon.drain(15)
    return report, drain_report


# ---------------------------------------------------------------------------
# fleet drills (docs/SERVE.md "Fleet"): the same load drivers routed
# through a FleetClient over a real forked replica fleet
# ---------------------------------------------------------------------------

def fleet_client_factory(supervisor: Any, *,
                         retry_budget: Optional[Any] = None,
                         timeout_s: float = 30.0,
                         health_ttl_s: float = 0.25) -> Callable[[], Any]:
    """A per-thread :class:`~.client.FleetClient` factory over a live
    supervisor's membership, all sharing ONE fleet-wide retry budget —
    the drill shape the load drivers accept as ``client_factory``."""
    from .client import FleetClient, RetryBudget

    budget = retry_budget if retry_budget is not None \
        else RetryBudget(capacity=64.0, ratio=0.25)

    def make() -> Any:
        return FleetClient(supervisor.members, retry_budget=budget,
                           timeout_s=timeout_s, health_ttl_s=health_ttl_s)

    return make


def victim_check(supervisor: Any, victim: str,
                 make_check: Callable[[int], Dict[str, Any]],
                 start: int = 0) -> Tuple[int, Dict[str, Any]]:
    """The first check index >= ``start`` whose affinity key routes to
    ``victim`` on the CURRENT membership ring — how the kill drills aim
    traffic at the replica about to die."""
    from .ring import HashRing

    ring = HashRing([name for name, _ in supervisor.members()])
    i = start
    while True:
        check = make_check(i)
        if ring.lookup(protocol.affinity_key("verify", check)) == victim:
            return i, check
        i += 1


def kill_one_drill(supervisor: Any, *,
                   make_check: Callable[[int], Dict[str, Any]],
                   client_factory: Callable[[], Any],
                   clients: int = 3,
                   requests_per_client: int = 30,
                   kill_at_fraction: float = 0.35,
                   victim: Optional[str] = None,
                   rejoin_timeout_s: float = 60.0) -> Dict[str, Any]:
    """The kill-one-replica chaos drill: a closed-loop fleet workload
    with a killer thread SIGKILLing one replica once ``kill_at_fraction``
    of the requests have completed. The acceptance the callers assert:
    **zero dropped** — every request is answered (failover re-sends the
    unanswered ones under their idempotency keys), zero transport errors
    surface, and the slot respawns and rejoins before the drill ends."""
    total = clients * requests_per_client
    completed = [0]
    lock = threading.Lock()
    errors: List[str] = []
    answers: Dict[int, Any] = {}
    factories_failovers = [0]
    victim_name = victim or supervisor.members()[0][0]
    kill_info: Dict[str, Any] = {}
    kill_trigger = threading.Event()
    barrier = threading.Barrier(clients + 1)

    def worker(idx: int) -> None:
        c = client_factory()
        try:
            barrier.wait()
            for r in range(requests_per_client):
                i = idx * requests_per_client + r
                try:
                    out = c.call("verify", make_check(i))
                    with lock:
                        answers[i] = bool(out["valid"])
                except Exception as e:
                    with lock:
                        errors.append(f"req {i}: {type(e).__name__}: {e}")
                with lock:
                    completed[0] += 1
                    if completed[0] >= kill_at_fraction * total:
                        kill_trigger.set()
        finally:
            with lock:
                factories_failovers[0] += getattr(c, "failovers", 0)
            c.close()

    def killer() -> None:
        kill_trigger.wait(120)
        kill_info["t_kill"] = time.perf_counter()
        kill_info["victim"] = victim_name
        kill_info["pid"] = supervisor.kill_replica(victim_name)

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(clients)]
    kill_thread = threading.Thread(target=killer, daemon=True)
    for t in threads:
        t.start()
    kill_thread.start()
    barrier.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join(300)
    kill_thread.join(10)
    wall = time.perf_counter() - t0

    # the respawn-and-rejoin half: the slot must come back ready
    rejoined = False
    deadline = time.perf_counter() + rejoin_timeout_s
    expect = {r["name"] for r in supervisor.replicas()
              if r["status"] in ("ready", "starting")}
    while time.perf_counter() < deadline:
        names = {name for name, _ in supervisor.members()}
        if victim_name in names:
            rejoined = True
            break
        time.sleep(0.05)
    return {
        "requests": total,
        "answered": len(answers),
        "dropped": total - len(answers) - len(errors),
        "errors": errors,
        "failovers": factories_failovers[0],
        "victim": victim_name,
        "rejoined": rejoined,
        "expected_members": sorted(expect),
        "wall_s": round(wall, 3),
        "answers": answers,
    }


def failover_probe(supervisor: Any, *,
                   make_check: Callable[[int], Dict[str, Any]],
                   timeout_s: float = 10.0) -> Dict[str, Any]:
    """One measured failover: aim a request at a replica, SIGKILL it,
    then time the FIRST victim-affine request through a router that
    still believes the victim is alive — the membership snapshot is
    FROZEN before the kill and ``health_ttl_s`` is huge, the realistic
    stale-router view, so the latency always includes dead-replica
    detection + the re-send to the next ring replica (the supervisor's
    monitor may quarantine the victim concurrently; a live-membership
    router would sometimes learn first and skip the failover, making
    the measurement race-dependent). The perfgate slice medians this
    over a couple of victims — ``perfgate_fleet_failover_ms``."""
    from .client import FleetClient, RetryBudget

    frozen = supervisor.members()  # the stale view the failover drills
    victim = frozen[0][0]
    idx, check = victim_check(supervisor, victim, make_check)
    # a SECOND victim-affine key, computed before the kill: the answer
    # must be computed by the failover target, not replayed from a cache
    _, check2 = victim_check(supervisor, victim, make_check, start=idx + 1)
    client = FleetClient(frozen, retry_budget=RetryBudget(capacity=16.0),
                         timeout_s=timeout_s, health_ttl_s=3600.0)
    try:
        warm = client.call("verify", check)  # connection + route warm
        assert "valid" in warm
        supervisor.kill_replica(victim)
        t0 = time.perf_counter()
        out = client.call("verify", check2)
        failover_ms = (time.perf_counter() - t0) * 1e3
        assert "valid" in out
        failovers = client.failovers
    finally:
        client.close()
    return {"victim": victim, "failover_ms": round(failover_ms, 3),
            "failovers": failovers}


def mini_fleet_drill(
    *,
    replicas: int = 2,
    flush_delay_ms: float = 10.0,
    clients: int = 3,
    requests_per_client: int = 20,
    probe: Optional[Callable[[Callable[[], Any]], Any]] = None,
) -> Tuple[Dict[str, Any], Dict[str, Dict[str, Any]]]:
    """The deterministic, jax-free fleet drill (``make fleet-smoke`` +
    the perfgate failover slice): a real forked 2-replica fleet driven
    with invalid-pubkey checks (zero crypto cost), SIGKILL one replica
    mid-workload, and assert the fleet contract — zero dropped requests,
    correct answers throughout, the slot respawns and rejoins, and every
    replica's drain report holds ``accepted == flushed + shed``.

    Returns ``(report, drain_reports)``; the fleet is always stopped."""
    from .fleet import FleetConfig, FleetSupervisor

    cfg = FleetConfig(replicas=replicas, linger_ms=1.0, cache_size=0,
                      flush_delay_ms=flush_delay_ms, max_batch=8,
                      heartbeat_stale_s=1.0)
    sup = FleetSupervisor(cfg).start()
    try:
        factory = fleet_client_factory(sup, timeout_s=15.0,
                                       health_ttl_s=0.25)
        baseline = closed_loop(None, clients=clients,
                               requests_per_client=6,
                               make_check=lambda i: cheap_check(i, "base"),
                               client_factory=factory)
        kill = kill_one_drill(sup, make_check=lambda i: cheap_check(i, "kill"),
                              client_factory=factory, clients=clients,
                              requests_per_client=requests_per_client)
        # every cheap check is invalid-by-construction: the answers the
        # fleet computed — including the failed-over ones — must all be
        # False, bit-identical to the direct oracle path
        wrong = [i for i, v in kill["answers"].items() if v is not False]
        kill["wrong_answers"] = wrong
        kill.pop("answers")
        report = {
            "replicas": replicas,
            "baseline": baseline,
            "kill": kill,
            "fleet_health": sup.fleet_health(),
            "fleet_slo": sup.fleet_metrics()["slo"],
        }
        if probe is not None:
            report["probe"] = probe(factory)
    finally:
        drain_reports = sup.stop()
    return report, drain_reports
