"""Overload drill harness: open-loop / closed-loop load drivers and
the goodput-under-overload measurement (docs/SERVE.md "Overload
control").

The existing bench harness (tools/serve_bench.py) is *closed-loop*: N
threads each wait for an answer before sending the next request, so
offered load can never exceed capacity — the harness itself backs off,
and congestion collapse is unobservable by construction. This module
adds the missing half:

- :func:`closed_loop` — the saturation measurement: N clients at full
  tilt over distinct (dedup-proof, cache-proof) checks. Its answered/s
  IS the serving capacity on this box.
- :func:`open_loop` — fixed *arrival rate*, independent of completions
  (arrivals that find every sender busy are sent late and counted
  ``lagged``, never dropped): offered load CAN exceed capacity, which
  is the only regime where overload control does anything.
- :func:`run_overload_drill` — the full phase sequence against an
  already-running daemon: saturation -> 3x open-loop overload with
  deadlines + a priority mix -> recovery probe. Returns one report
  dict with **goodput** (answered within deadline / s), per-outcome
  tallies, the shed ratio, and the recovery latency — the numbers
  ``make overload-drill`` banks as ``serve_goodput_per_s`` /
  ``serve_shed_ratio``.
- :func:`mini_drill` — a scaled-down, jax-free, crypto-free instance
  (in-process daemon, simulated flush service time via the
  ``flush_delay_ms`` drill knob, invalid-pubkey checks the oracle
  answers instantly) used by ``make overload-smoke`` and perfgate's
  ``perfgate_overload_goodput_ratio`` slice.

Check populations: "cheap" checks are well-formed-but-invalid (the
oracle rejects the pubkey without a pairing) — they exercise every
queue/batch/shed mechanism at zero crypto cost. "Expensive" checks
reuse ONE valid signature against distinct messages, so every check is
a distinct key (no dedup, no cache hit) that costs a full pairing —
the honest capacity workload for the real drill.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..obs.metrics import percentile
from . import protocol
from .client import ServeClient, ServeError

OUTCOMES = ("ok_in_deadline", "ok_late", "shed_deadline", "shed_priority",
            "queue_full", "draining", "error")


# ---------------------------------------------------------------------------
# check populations
# ---------------------------------------------------------------------------

def cheap_check(i: int, tag: str = "drill") -> Dict[str, Any]:
    """A distinct well-formed-but-invalid check: the oracle rejects the
    pubkey without pairing work, answers False — free of crypto cost,
    distinct key (no dedup/cache short-circuit)."""
    seed = (i * 2654435761) & 0xFFFFFFFF
    return {
        "pubkeys": [protocol.to_hex(bytes([seed % 251 + 1]) * 48)],
        "message": protocol.to_hex(
            tag.encode()[:8].ljust(8, b".") + seed.to_bytes(4, "little")
            + b"\x00" * 20),
        "signature": protocol.to_hex(b"\x02" * 96),
    }


def expensive_check_factory() -> Callable[[int], Dict[str, Any]]:
    """Checks that each cost a FULL pairing: one valid (pk, sig) pair is
    built once (one SkToPk + one Sign), then reused against distinct
    messages — every check is a distinct key, deserializes cleanly, and
    the pairing evaluates before answering False."""
    from ..crypto.bls import ciphersuite as oracle

    pk = protocol.to_hex(oracle.SkToPk(7))
    sig = protocol.to_hex(oracle.Sign(7, b"overload-drill-anchor" + b"\x00" * 11))

    def make(i: int) -> Dict[str, Any]:
        return {"pubkey": pk,
                "message": protocol.to_hex(
                    b"overload." + i.to_bytes(4, "little") + b"\x00" * 19),
                "signature": sig}

    return make


def default_priority_mix(i: int) -> str:
    """The drill's deterministic criticality mix: 10% critical, 20%
    sheddable, 70% default."""
    if i % 10 == 0:
        return protocol.PRIORITY_CRITICAL
    if i % 5 == 1:
        return protocol.PRIORITY_SHEDDABLE
    return protocol.PRIORITY_DEFAULT


# ---------------------------------------------------------------------------
# load drivers
# ---------------------------------------------------------------------------

def closed_loop(port: int, *, clients: int, requests_per_client: int,
                make_check: Callable[[int], Dict[str, Any]],
                timeout_s: float = 120.0,
                priority: Optional[str] = None) -> Dict[str, Any]:
    """Saturation measurement: every thread always has exactly one
    request outstanding. Distinct checks per request, no retries (the
    harness must never amplify its own load). The drill runs this at
    ``critical`` priority so the capacity number can never be clipped
    by the adaptive limiter it is calibrating."""
    lat: List[List[float]] = [[] for _ in range(clients)]
    answered = [0] * clients
    errors = [0] * clients
    barrier = threading.Barrier(clients + 1)

    def worker(idx: int) -> None:
        with ServeClient(port, timeout_s=timeout_s, max_retries=0) as c:
            barrier.wait()
            for r in range(requests_per_client):
                i = idx * requests_per_client + r
                t0 = time.perf_counter()
                try:
                    c.call("verify", make_check(i), priority=priority)
                    answered[idx] += 1
                except Exception:
                    errors[idx] += 1
                lat[idx].append((time.perf_counter() - t0) * 1e3)

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(clients)]
    for t in threads:
        t.start()
    barrier.wait()
    t0 = time.perf_counter()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    flat = sorted(x for ls in lat for x in ls)
    total = sum(answered)
    return {
        "clients": clients,
        "requests": clients * requests_per_client,
        "answered": total,
        "errors": sum(errors),
        "wall_s": round(wall, 3),
        "rate_per_s": round(total / wall, 3) if wall > 0 else None,
        "p50_ms": percentile(flat, 50),
        "p99_ms": percentile(flat, 99),
    }


def open_loop(port: int, *, rate_per_s: float, duration_s: float,
              make_check: Callable[[int], Dict[str, Any]],
              deadline_ms: Optional[float] = None,
              priority_for: Optional[Callable[[int], str]] = None,
              max_threads: int = 64,
              timeout_s: Optional[float] = None) -> Dict[str, Any]:
    """Fixed-arrival-rate driver. Arrival i is due at ``t0 + i/rate``;
    a free sender sleeps until then and fires. When every sender is
    busy the arrival goes out late (counted ``lagged``) — arrivals are
    never dropped, so offered load is honest even past capacity.

    Senders use ``max_retries=0``: the drill measures the DAEMON's
    overload behavior; client retry discipline is drilled separately.
    """
    n_arrivals = max(1, int(rate_per_s * duration_s))
    if timeout_s is None:
        timeout_s = max(10.0, (deadline_ms or 0) / 1e3 * 4 + 10.0)
    # enough senders to keep arrivals on schedule at the expected
    # latency, bounded so the driver cannot melt the box
    threads_n = min(max(8, int(rate_per_s * (timeout_s if deadline_ms is None
                                             else deadline_ms / 1e3) * 1.5)),
                    max_threads)
    counter = {"next": 0}
    counter_lock = threading.Lock()
    outcomes = {k: 0 for k in OUTCOMES}
    ok_lat: List[float] = []
    stats_lock = threading.Lock()
    lagged = [0]
    t_start = [0.0]
    barrier = threading.Barrier(threads_n + 1)

    def classify(code: str) -> str:
        return {protocol.DEADLINE_EXCEEDED: "shed_deadline",
                protocol.SHED: "shed_priority",
                protocol.QUEUE_FULL: "queue_full",
                protocol.DRAINING: "draining"}.get(code, "error")

    def worker() -> None:
        with ServeClient(port, timeout_s=timeout_s, max_retries=0) as c:
            barrier.wait()
            while True:
                with counter_lock:
                    i = counter["next"]
                    if i >= n_arrivals:
                        return
                    counter["next"] = i + 1
                due = t_start[0] + i / rate_per_s
                now = time.perf_counter()
                if now < due:
                    time.sleep(due - now)
                elif now - due > 0.05:
                    with stats_lock:
                        lagged[0] += 1
                check = make_check(i)
                prio = priority_for(i) if priority_for else None
                t0 = time.perf_counter()
                try:
                    c.call("verify", check, deadline_ms=deadline_ms,
                           priority=prio)
                    ms = (time.perf_counter() - t0) * 1e3
                    key = ("ok_late" if deadline_ms is not None
                           and ms > deadline_ms else "ok_in_deadline")
                    with stats_lock:
                        outcomes[key] += 1
                        if key == "ok_in_deadline":
                            ok_lat.append(ms)
                except ServeError as e:
                    with stats_lock:
                        outcomes[classify(e.code)] += 1
                except Exception:
                    with stats_lock:
                        outcomes["error"] += 1

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(threads_n)]
    for t in threads:
        t.start()
    t_start[0] = time.perf_counter()
    barrier.wait()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start[0]
    sheds = outcomes["shed_deadline"] + outcomes["shed_priority"]
    return {
        "offered": n_arrivals,
        "offered_rate_per_s": round(rate_per_s, 3),
        "achieved_rate_per_s": round(n_arrivals / wall, 3) if wall else None,
        "duration_s": round(wall, 3),
        "senders": threads_n,
        "lagged": lagged[0],
        "outcomes": dict(outcomes),
        "goodput_per_s": (round(outcomes["ok_in_deadline"] / wall, 3)
                          if wall else None),
        "shed_ratio": round(sheds / n_arrivals, 4),
        "rejected_ratio": round(
            (sheds + outcomes["queue_full"]) / n_arrivals, 4),
        "answered": sum(outcomes.values()),
        "ok_p50_ms": percentile(sorted(ok_lat), 50),
        "ok_p99_ms": percentile(sorted(ok_lat), 99),
    }


def recovery_probe(port: int, *, make_check: Callable[[int], Dict[str, Any]],
                   probes: int = 20, settle_timeout_s: float = 30.0,
                   ) -> Dict[str, Any]:
    """After the overload stops: wait for the queue to drain, then
    measure a clean probe window — the daemon must return to baseline
    latency, not stay wedged behind a backlog of dead work."""
    t0 = time.perf_counter()
    with ServeClient(port, timeout_s=60, max_retries=0) as c:
        depth = None
        while time.perf_counter() - t0 < settle_timeout_s:
            depth = c.health()["queue"]["depth"]
            if depth == 0:
                break
            time.sleep(0.05)
        settle_s = time.perf_counter() - t0
        lat: List[float] = []
        errors = 0
        for i in range(probes):
            t1 = time.perf_counter()
            try:
                c.call("verify", make_check(10_000_000 + i))
            except Exception:
                errors += 1
            lat.append((time.perf_counter() - t1) * 1e3)
    return {
        "settle_s": round(settle_s, 3),
        "settled": depth == 0,
        "probes": probes,
        "errors": errors,
        "p50_ms": percentile(sorted(lat), 50),
        "p99_ms": percentile(sorted(lat), 99),
    }


# ---------------------------------------------------------------------------
# the drill sequence
# ---------------------------------------------------------------------------

def run_overload_drill(
    port: int,
    *,
    make_check: Callable[[int], Dict[str, Any]],
    sat_clients: int = 4,
    sat_requests_per_client: int = 12,
    overload_multiplier: float = 3.0,
    overload_duration_s: float = 10.0,
    deadline_ms: float = 2000.0,
    priority_for: Optional[Callable[[int], str]] = default_priority_mix,
    recovery_probes: int = 20,
    max_threads: int = 64,
) -> Dict[str, Any]:
    """Saturation -> overload -> recovery against a running daemon.

    Goodput contract (the no-collapse criterion the drill asserts):
    open-loop offered load at ``overload_multiplier``x the measured
    saturation rate must keep goodput (answered within deadline / s)
    within 20% of the saturation rate — shed the excess, serve the
    rest — and the post-load probe must sit back at baseline latency.
    """
    saturation = closed_loop(port, clients=sat_clients,
                             requests_per_client=sat_requests_per_client,
                             make_check=make_check,
                             priority=protocol.PRIORITY_CRITICAL)
    sat_rate = saturation["rate_per_s"] or 1.0
    offered_rate = max(1.0, sat_rate * overload_multiplier)
    overload = open_loop(
        port, rate_per_s=offered_rate, duration_s=overload_duration_s,
        make_check=lambda i: make_check(1_000_000 + i),
        deadline_ms=deadline_ms, priority_for=priority_for,
        max_threads=max_threads)
    recovery = recovery_probe(port, make_check=make_check,
                              probes=recovery_probes)
    goodput = overload["goodput_per_s"] or 0.0
    return {
        "saturation": saturation,
        "overload": overload,
        "recovery": recovery,
        "deadline_ms": deadline_ms,
        "overload_multiplier": overload_multiplier,
        "goodput_per_s": goodput,
        "goodput_ratio": round(goodput / sat_rate, 4) if sat_rate else None,
        "shed_ratio": overload["shed_ratio"],
    }


# ---------------------------------------------------------------------------
# the scaled-down in-process instance (overload-smoke + perfgate)
# ---------------------------------------------------------------------------

def mini_drill(
    *,
    flush_delay_ms: float = 80.0,
    max_batch: int = 2,
    sat_clients: int = 4,
    sat_requests_per_client: int = 10,
    overload_multiplier: float = 3.0,
    overload_duration_s: float = 2.5,
    deadline_ms: float = 500.0,
    target_p99_ms: float = 250.0,
    min_limit: int = 2,
    recovery_probes: int = 20,
    probe: Optional[Callable[[int], Any]] = None,
) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """The deterministic, jax-free, crypto-free drill: an in-process
    daemon whose flush pipeline has a SIMULATED service time
    (``flush_delay_ms`` per dispatch, ``max_batch`` rows each), driven
    with invalid-pubkey checks the oracle answers instantly. Capacity
    is therefore ``max_batch / flush_delay`` rows/s by construction —
    small enough that a Python thread pool can offer 3x it — and every
    shed/admission mechanism runs for real.

    Returns ``(report, drain_report)``; the daemon is always drained.
    """
    from .admission import AdmissionController
    from .batcher import VerifyBatcher
    from .daemon import ServeDaemon
    from .service import SpecService

    admission = AdmissionController(
        1024, mode="adaptive", min_limit=min_limit,
        target_p99_ms=target_p99_ms, tick_s=0.02, brownout_ticks=2)
    batcher = VerifyBatcher(
        max_queue=1024, max_batch=max_batch, linger_ms=2.0, cache_size=0,
        admission=admission, flush_delay_ms=flush_delay_ms)
    service = SpecService(forks=("phase0",), presets=("minimal",),
                          batcher=batcher, request_timeout_s=30.0)
    daemon = ServeDaemon(service).start(warm=False)
    try:
        report = run_overload_drill(
            daemon.port, make_check=cheap_check,
            sat_clients=sat_clients,
            sat_requests_per_client=sat_requests_per_client,
            overload_multiplier=overload_multiplier,
            overload_duration_s=overload_duration_s,
            deadline_ms=deadline_ms,
            recovery_probes=recovery_probes,
            max_threads=48)
        report["overload_state"] = batcher.overload_snapshot()
        if probe is not None:
            report["probe"] = probe(daemon.port)
    finally:
        drain_report = daemon.drain(15)
    return report, drain_report
