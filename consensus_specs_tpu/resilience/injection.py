"""Chaos injection: named fault points wired into backend dispatch,
subprocess children, and generator case execution, so the supervisor's
behavior is itself tier-1-tested.

Arming:
- env knob (propagates to subprocess children automatically):
      CONSENSUS_SPECS_TPU_CHAOS="site=kind:count[:after],site2=kind"
  e.g. "bls.dispatch=transient:2"      fail the first 2 hits
       "gen.case=kill:1:2"            SIGKILL the process on the 3rd hit
       "engine.dispatch=deterministic" fail the first hit
- programmatic (tests): ``with inject("site", "transient", count=2): ...``

Kinds: transient / deterministic / environmental raise the matching
taxonomy Fault; ``kill`` delivers SIGKILL to the current process (the
crash-safety drill for the generator journal); ``hang`` sleeps
(default 3600 s, CONSENSUS_SPECS_TPU_CHAOS_HANG_S overrides) — the
wedged-tunnel simulation that deadline supervisors are drilled
against (tests/test_dryrun_guard.py).

Sites are plain strings; the convention is plane.point:
  bls.import  bls.dispatch  engine.import  engine.dispatch
  hash.dispatch  gen.case  bench.section  dryrun.child  replay.case
  sched.flush (per bucket dispatch of the cross-case deferred flush)
  sched.writer (per case written by the overlap writer thread)
  sched.worker (per worker slice of the sharded generator, fired in the
                PARENT's supervised wait: transient=respawn the slice
                — the per-rank journal resumes it; deterministic=the
                slice degrades to the in-process serial path; either
                way the merged tree + combined journal stay
                byte-identical — docs/GENPIPE.md "Sharded generation")
  serve.request (per request executed by the resident daemon)
  serve.flush (per cross-client micro-batch dispatched by the daemon's
               flusher thread; a fault here degrades that batch to the
               host oracle — docs/SERVE.md)
  serve.admission (every adaptive-admission controller tick, INSIDE the
               supervised control loop: transient=retried tick;
               deterministic=quarantine + admission degrades to the
               fixed bound; hang=the accept path's staleness watchdog
               trips the same quarantine WITHOUT ever blocking a
               request — docs/SERVE.md "Overload control")
  serve.replica (every fleet replica's supervise-loop tick, INSIDE the
               forked replica process — docs/SERVE.md "Fleet": kill=the
               replica SIGKILLs itself and the FleetSupervisor respawns
               the slot, which rejoins via /readyz; transient=the
               replica exits EX_TEMPFAIL, same respawn path;
               deterministic=the replica exits EX_CONFIG and the slot
               is quarantined — the ring shrinks and only its ~K/N keys
               move; hang=the loop stops beating the daemon heartbeat,
               /readyz flips 503 "stale", and routers steer around it
               via health staleness. Arm with
               CONSENSUS_SPECS_TPU_CHAOS_STATE pointed at a scratch
               file so "kill:1" means ONE replica across the fleet,
               not one per process — tests/test_serve_fleet.py)
  sim.step (top of every chain-simulator slot step, BEFORE any state
            mutation: transients retry the clean step, deterministic
            faults quarantine the site and every later step degrades to
            the interpreted-oracle path — docs/SIM.md)
  sim.epoch (every chain-simulator epoch rollover; a deterministic
             fault parks the REMAINDER of the run on the oracle path —
             the circuit-breaker response at epoch granularity)
  sim.net  (every non-lossless edge schedule of the partitioned sim's
            adversarial bus — docs/SIM.md "Partitioned network":
            transient=the pure schedule computation retries and the
            message REDELIVERS identically (the chain cannot move);
            deterministic=the edge quarantines to LOSSLESS delivery
            (the always-correct degradation: a perfect link) with a
            recorded event — the run stays live and convergent)
  sim.checkpoint (top of every crash-consistent snapshot attempt —
            docs/SIM.md "Checkpoint/resume": transient=retried write;
            deterministic=the boundary is SKIPPED with a recorded
            event and the next boundary retries — a faulted snapshot
            must never corrupt or stall the run)
  sim.checkpoint.write (between payload files INSIDE the snapshot tmp
            dir: the kill-mid-snapshot drill's SIGKILL site — a torn
            tmp dir must be invisible to --resume)
  fuzz.exec (top of every fuzz-farm case execution, INSIDE the forked
             worker — docs/FUZZ.md: transient=the case retries (cases
             are pure functions, a retry is safe); deterministic=the
             breaker opens and every later case on that worker degrades
             to an oracle-only pass (differential coverage loss is
             counted fuzz.degraded_execs, never silent); kill=the
             SIGKILL drill — the parent respawns the rank and its
             findings journal resumes the slice with no lost and no
             duplicated findings. Arm kill with
             CONSENSUS_SPECS_TPU_CHAOS_STATE so one kill means one
             worker across the farm — tests/test_fuzz_farm.py)
  fuzz.shrink (every shrinker re-verification step: transient=the step
             retries; deterministic=shrinking aborts and the finding is
             journaled RAW — a broken shrinker never eats a finding)

``chaos(site)`` is a no-op dict probe when nothing is armed — cheap
enough for hot paths.

Cross-process counting: hit counts are per-process by default, so an
env-armed ``kill:1`` would re-fire in every respawned child (retry
supervisors could never drive past it). Point
``CONSENSUS_SPECS_TPU_CHAOS_STATE`` at a scratch file and hits are
tallied there instead — "fire once" then means once across the whole
process tree.
"""
from __future__ import annotations

import contextlib
import json
import os
import signal
from typing import Dict, Optional

from .supervisor import record_event
from .taxonomy import DeterministicFault, EnvironmentalFault, TransientFault

_FAULTS = {
    "transient": TransientFault,
    "deterministic": DeterministicFault,
    "environmental": EnvironmentalFault,
}

ENV_KNOB = "CONSENSUS_SPECS_TPU_CHAOS"


class _Armed:
    __slots__ = ("kind", "count", "after", "hits", "_from_env")

    def __init__(self, kind: str, count: int, after: int):
        self.kind = kind
        self.count = count      # how many times to fire (-1 = always)
        self.after = after      # clean hits to allow before firing
        self.hits = 0
        self._from_env = False


_SITES: Dict[str, _Armed] = {}
_env_loaded: Optional[str] = None


def _parse_env(raw: str) -> Dict[str, _Armed]:
    sites: Dict[str, _Armed] = {}
    for clause in raw.split(","):
        clause = clause.strip()
        if not clause or "=" not in clause:
            continue
        site, _, spec = clause.partition("=")
        parts = spec.split(":")
        kind = parts[0].strip()
        if kind not in _FAULTS and kind not in ("kill", "hang"):
            raise ValueError(f"{ENV_KNOB}: unknown fault kind {kind!r} "
                             f"(have {sorted(_FAULTS)} + 'kill'/'hang')")
        count = int(parts[1]) if len(parts) > 1 and parts[1] != "*" else (
            1 if len(parts) <= 1 else -1)
        after = int(parts[2]) if len(parts) > 2 else 0
        sites[site.strip()] = _Armed(kind, count, after)
    return sites


def refresh() -> None:
    """Re-read the env knob (tests that monkeypatch os.environ call this;
    normal runs parse once, lazily)."""
    global _env_loaded
    raw = os.environ.get(ENV_KNOB, "")
    _env_loaded = raw
    # programmatically armed sites survive a refresh; env sites replace
    # only the env-sourced population
    for site in [s for s, a in _SITES.items() if a._from_env]:
        del _SITES[site]
    for site, armed in _parse_env(raw).items():
        armed._from_env = True
        _SITES[site] = armed


def arm(site: str, kind: str, count: int = 1, after: int = 0) -> None:
    if kind not in _FAULTS and kind not in ("kill", "hang"):
        raise ValueError(f"unknown fault kind {kind!r}")
    _SITES[site] = _Armed(kind, count, after)


def disarm(site: Optional[str] = None) -> None:
    if site is None:
        _SITES.clear()
    else:
        _SITES.pop(site, None)


@contextlib.contextmanager
def inject(site: str, kind: str, count: int = 1, after: int = 0):
    """Arm one site for the duration of a with-block (test hook)."""
    arm(site, kind, count=count, after=after)
    try:
        yield
    finally:
        disarm(site)


def armed_sites() -> Dict[str, str]:
    _maybe_load_env()
    return {site: a.kind for site, a in _SITES.items()}


def _maybe_load_env() -> None:
    if _env_loaded != os.environ.get(ENV_KNOB, ""):
        refresh()


def _bump_hits(site: str, armed: _Armed) -> int:
    """Advance and return this site's hit count — in the shared state
    file when CONSENSUS_SPECS_TPU_CHAOS_STATE names one (cross-process
    tally; test-grade read-modify-write), else in-process."""
    state_path = os.environ.get("CONSENSUS_SPECS_TPU_CHAOS_STATE")
    if not state_path:
        armed.hits += 1
        return armed.hits
    try:
        with open(state_path) as f:
            state = json.load(f)
    except (OSError, ValueError):
        state = {}
    state[site] = int(state.get(site, 0)) + 1
    tmp = f"{state_path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(state, f)
    os.replace(tmp, state_path)
    return state[site]


def chaos(site: str) -> None:
    """The injection point. Call at every supervised dispatch site; fires
    the armed fault (or SIGKILL) when this site is armed and its
    after/count window says so."""
    _maybe_load_env()
    armed = _SITES.get(site)
    if armed is None:
        return
    hits = _bump_hits(site, armed)
    position = hits - armed.after
    if position <= 0:
        return
    if armed.count >= 0 and position > armed.count:
        return
    record_event("injected", domain="chaos", capability=site, kind=armed.kind,
                 detail=f"hit {armed.hits} (after={armed.after}, count={armed.count})")
    if armed.kind == "kill":
        os.kill(os.getpid(), signal.SIGKILL)
    if armed.kind == "hang":
        import time

        time.sleep(float(os.environ.get("CONSENSUS_SPECS_TPU_CHAOS_HANG_S",
                                        "3600")))
        return
    raise _FAULTS[armed.kind](f"injected {armed.kind} fault @ {site}", domain=site)
