"""Structured fault taxonomy for the accelerated planes.

Every failure the system can meet falls into one of three classes, and
the class — not the exception type — decides the recovery action:

- TRANSIENT: the operation may succeed if simply tried again (device
  dispatch flake, resource exhaustion, subprocess timeout, a wedged
  tunnel connection). Recovery: retry with exponential backoff under a
  deadline.
- DETERMINISTIC: retrying is pointless — the same inputs will fail the
  same way (a miscompile, a wrong result caught by a cross-check, a
  compile error). Recovery: quarantine the capability and degrade to
  the always-correct host path.
- ENVIRONMENTAL: the capability's prerequisites are absent (jax not
  importable, native lib missing, no devices). Recovery: same as
  deterministic — quarantine + host fallback — but the event is
  recorded as an environment gap, not a defect.

The conformance-vector contract makes this tractable: the interpreted
spec and the golden vectors are always-available oracles, so every
accelerated path has a correct fallback to degrade to. The taxonomy is
pure stdlib — bench.py's supervisor (which never imports jax) and the
generator pipeline both load it.
"""
from __future__ import annotations

from typing import Optional

TRANSIENT = "transient"
DETERMINISTIC = "deterministic"
ENVIRONMENTAL = "environmental"

KINDS = (TRANSIENT, DETERMINISTIC, ENVIRONMENTAL)


class Fault(Exception):
    """A failure that already carries its classification (raised by
    injection hooks and by code that knows its own failure mode)."""

    kind: str = DETERMINISTIC

    def __init__(self, message: str = "", *, domain: str = ""):
        super().__init__(message)
        self.domain = domain


class TransientFault(Fault):
    kind = TRANSIENT


class DeterministicFault(Fault):
    kind = DETERMINISTIC


class EnvironmentalFault(Fault):
    kind = ENVIRONMENTAL


class QuarantinedError(Fault):
    """Raised when a quarantined capability is invoked with no fallback
    available — deterministic by definition (the breaker is open)."""

    kind = DETERMINISTIC


# Message substrings that mark a device/runtime error as retryable even
# though its Python type is opaque (jaxlib surfaces everything as
# XlaRuntimeError): resource pressure, dead connections, server-side
# deadline hits, and the tunnel's mid-compile disconnects.
_TRANSIENT_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "out of memory",
    "OOM",
    "DEADLINE_EXCEEDED",
    "UNAVAILABLE",
    "ABORTED",
    "remote_compile",
    "response body closed",
    "Connection reset",
    "Socket closed",
    "timed out",
)


def classify(exc: BaseException) -> str:
    """Map an exception to its fault class. Explicit Fault subclasses
    win; everything else is classified structurally, with DETERMINISTIC
    as the safe default (an unknown failure must quarantine and degrade
    to the correct host path, never spin in a retry loop)."""
    if isinstance(exc, Fault):
        return exc.kind
    if isinstance(exc, (ImportError, ModuleNotFoundError)):
        return ENVIRONMENTAL
    if isinstance(exc, (TimeoutError, ConnectionError, BrokenPipeError,
                        InterruptedError, MemoryError)):
        return TRANSIENT
    try:  # subprocess is stdlib but keep the import local: hot paths
        import subprocess

        if isinstance(exc, subprocess.TimeoutExpired):
            return TRANSIENT
    except Exception:  # pragma: no cover
        pass
    if isinstance(exc, FileNotFoundError):
        return ENVIRONMENTAL  # missing lib/binary, not a data error
    if isinstance(exc, OSError):
        return TRANSIENT  # I/O flake: fd churn, EAGAIN-class errors
    text = f"{type(exc).__name__}: {exc}"
    if any(marker in text for marker in _TRANSIENT_MARKERS):
        return TRANSIENT
    return DETERMINISTIC


# sysexits.h conventions a supervised child can use to report its own
# fault class (see exit_code_for / __graft_entry__'s dryrun child)
EX_TEMPFAIL = 75     # transient: retry me
EX_CONFIG = 78       # environmental: my prerequisites are missing
EX_SOFTWARE = 70     # deterministic: same inputs will fail the same way


def exit_code_for(kind: str) -> int:
    """The exit code a child should use to report a classified fault."""
    return {TRANSIENT: EX_TEMPFAIL, ENVIRONMENTAL: EX_CONFIG}.get(kind, EX_SOFTWARE)


def classify_exit(returncode: Optional[int]) -> Optional[str]:
    """Fault class of a child process exit. None for success.

    Signal deaths (negative rc, or the shell's 128+N convention) read as
    TRANSIENT: the child was killed from outside (deadline enforcement,
    OOM killer), which says nothing deterministic about its inputs. The
    sysexits codes above round-trip a child's own classification. Any
    other nonzero exit is the child reporting its own failure —
    DETERMINISTIC until a retry proves otherwise.
    """
    if returncode is None or returncode == 0:
        return None
    if returncode == EX_TEMPFAIL:
        return TRANSIENT
    if returncode == EX_CONFIG:
        return ENVIRONMENTAL
    if returncode < 0 or returncode in (124, 125) or returncode > 128:
        return TRANSIENT
    return DETERMINISTIC
