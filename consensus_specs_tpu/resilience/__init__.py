"""Resilience subsystem: one fault-domain layer for every plane.

The paper's conformance-vector contract gives this repo a property most
accelerated systems lack: the interpreted spec and the golden vectors
are always-available correctness oracles, so every accelerated path
(device BLS, device hashing, the SoA epoch engine, sharded collectives)
has a bit-identical host path to degrade to. This package wires that
degradation up as a system instead of per-plane hand-rolled handling:

- :mod:`taxonomy` — transient / deterministic / environmental fault
  classes and classifiers (exceptions + child exit codes).
- :mod:`supervisor` — ``supervised()`` retry-with-backoff for
  transients, quarantine circuit breaker + host fallback for
  deterministic faults, bounded structured event log.
- :mod:`injection` — chaos points (``chaos(site)``) armed by env knob
  or test fixture, so the recovery machinery is itself tier-1-tested.
- :mod:`journal` — crash-safe digest journal for ``run_generator``:
  resumed runs re-admit only byte-verified cases and regenerate
  corrupted output instead of silently shipping it.
- :mod:`selfcheck` — startup probes for known-bad paths (the jaxlib
  GSPMD sharded tree-reduce miscompile), auto-quarantining them with a
  recorded reason.

Consumers: ``crypto/bls`` + the ssz hashing backend (crypto plane),
``engine/backend`` (protocol plane), ``generators/gen_runner`` (vector
plane), ``bench.py`` child sections and ``__graft_entry__``'s multichip
dryrun (ops plane). Core modules are pure stdlib — bench.py's jax-free
parent supervisor imports them safely.

See docs/RESILIENCE.md for the taxonomy/quarantine matrix and knobs.
"""
from __future__ import annotations

from .injection import ENV_KNOB, arm, chaos, disarm, inject, refresh  # noqa: F401
from .journal import CaseJournal, verify_outputs  # noqa: F401
from .selfcheck import SHARDED_TREE_REDUCE, sharded_reduce_status  # noqa: F401
from .supervisor import (  # noqa: F401
    DEFAULT_POLICY,
    RetryPolicy,
    clear,
    events,
    is_quarantined,
    quarantine,
    quarantine_reason,
    quarantined,
    record_event,
    supervised,
)
from .taxonomy import (  # noqa: F401
    DETERMINISTIC,
    ENVIRONMENTAL,
    TRANSIENT,
    DeterministicFault,
    EnvironmentalFault,
    Fault,
    QuarantinedError,
    TransientFault,
    classify,
    classify_exit,
    exit_code_for,
)
