"""Supervised execution: retry transients, quarantine deterministic
failures, degrade to the host fallback — one recovery policy for every
plane (crypto backends, engine dispatch, subprocess children, generator
cases).

The quarantine registry is the circuit breaker: the first deterministic
(or environmental, or retry-exhausted) failure of a capability opens the
breaker, and every later ``supervised()`` call for that capability goes
straight to its fallback without touching the broken path again. Events
(retries, quarantines, fallbacks) are recorded in a bounded in-process
log that bench.py serializes into the BENCH json — degradation is
visible in the trajectory, never silent.

Pure stdlib: importable from bench.py's jax-free parent supervisor.
"""
from __future__ import annotations

import os
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from . import taxonomy
from .taxonomy import (  # noqa: F401  (re-exported convenience)
    DETERMINISTIC,
    ENVIRONMENTAL,
    TRANSIENT,
    QuarantinedError,
)


@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff under a deadline, for TRANSIENT faults only."""

    max_attempts: int = 3          # total tries (1 initial + retries)
    base_delay_s: float = 0.05
    factor: float = 2.0
    max_delay_s: float = 2.0
    deadline_s: Optional[float] = None  # wall-clock cap across all tries

    def delay(self, retry_index: int) -> float:
        return min(self.base_delay_s * (self.factor ** retry_index), self.max_delay_s)


DEFAULT_POLICY = RetryPolicy()

# ---------------------------------------------------------------------------
# event log
# ---------------------------------------------------------------------------

_EVENTS: deque = deque(maxlen=512)

# Listener hook: the obs tracing plane registers here so every fault
# event (retry, quarantine, fallback, chaos injection, probe) also
# lands as an instant event on the owning trace span. Listeners must
# never break fault handling: exceptions are swallowed. Kept as a
# plain callback list so resilience stays importable with no obs
# dependency (obs imports this module, never the reverse).
_LISTENERS: List[Callable[[dict], None]] = []


def add_listener(fn: Callable[[dict], None]) -> None:
    if fn not in _LISTENERS:
        _LISTENERS.append(fn)


def remove_listener(fn: Callable[[dict], None]) -> None:
    try:
        _LISTENERS.remove(fn)
    except ValueError:
        pass


def record_event(event: str, *, domain: str = "", capability: str = "",
                 kind: str = "", detail: str = "") -> dict:
    entry = {
        "t": round(time.time(), 3),
        "event": event,
        "domain": domain,
        "capability": capability,
        "kind": kind,
        "detail": detail[:500],
    }
    _EVENTS.append(entry)
    for listener in list(_LISTENERS):
        try:
            listener(entry)
        except Exception:
            pass
    return entry


def events(clear: bool = False) -> List[dict]:
    out = list(_EVENTS)
    if clear:
        _EVENTS.clear()
    return out


# ---------------------------------------------------------------------------
# quarantine registry (the circuit breaker)
# ---------------------------------------------------------------------------

_QUARANTINED: Dict[str, str] = {}


def _env_quarantined() -> Dict[str, str]:
    """Capabilities pre-quarantined via env (testing / known-bad boxes):
    CONSENSUS_SPECS_TPU_QUARANTINE="cap1,cap2"."""
    raw = os.environ.get("CONSENSUS_SPECS_TPU_QUARANTINE", "")
    return {c.strip(): "pre-quarantined via CONSENSUS_SPECS_TPU_QUARANTINE"
            for c in raw.split(",") if c.strip()}


def quarantine(capability: str, reason: str, *, kind: str = DETERMINISTIC,
               domain: str = "") -> bool:
    """Open the breaker for ``capability``. Returns True the FIRST time
    (the event fires once); later calls are no-ops."""
    if capability in _QUARANTINED:
        return False
    _QUARANTINED[capability] = reason
    record_event("quarantine", domain=domain, capability=capability,
                 kind=kind, detail=reason)
    return True


def is_quarantined(capability: str) -> bool:
    return capability in _QUARANTINED or capability in _env_quarantined()


def quarantine_reason(capability: str) -> Optional[str]:
    if capability in _QUARANTINED:
        return _QUARANTINED[capability]
    return _env_quarantined().get(capability)


def quarantined() -> Dict[str, str]:
    out = dict(_env_quarantined())
    out.update(_QUARANTINED)
    return out


def clear(capability: Optional[str] = None) -> None:
    """Close the breaker(s) — test/repair hook."""
    if capability is None:
        _QUARANTINED.clear()
    else:
        _QUARANTINED.pop(capability, None)


# ---------------------------------------------------------------------------
# supervised execution
# ---------------------------------------------------------------------------

def supervised(fn: Callable, *, domain: str, capability: Optional[str] = None,
               policy: RetryPolicy = DEFAULT_POLICY,
               fallback: Optional[Callable] = None,
               classify: Callable[[BaseException], str] = taxonomy.classify,
               passthrough: tuple = (),
               sleep: Callable[[float], None] = time.sleep):
    """Run ``fn()`` under the recovery policy.

    - TRANSIENT faults retry with exponential backoff up to
      ``policy.max_attempts`` tries within ``policy.deadline_s``.
    - DETERMINISTIC / ENVIRONMENTAL faults (and exhausted transients —
      a fault that never stops being "transient" is an environment
      problem) quarantine ``capability`` and run ``fallback()``.
    - A capability whose breaker is already open skips ``fn`` entirely.
    - ``passthrough`` exception types re-raise untouched (control-flow
      exceptions like SkippedTest are not faults).

    Without a fallback the terminal fault re-raises, after the breaker
    state is recorded — callers that cannot degrade still report.
    """
    if capability is not None and is_quarantined(capability):
        if fallback is not None:
            record_event("fallback", domain=domain, capability=capability,
                         detail=f"breaker open: {quarantine_reason(capability)}")
            return fallback()
        raise QuarantinedError(
            f"{capability} is quarantined ({quarantine_reason(capability)}) "
            "and no fallback is available", domain=domain)

    t0 = time.monotonic()
    retries = 0
    while True:
        try:
            return fn()
        except BaseException as exc:
            if isinstance(exc, (KeyboardInterrupt, SystemExit)) or (
                    passthrough and isinstance(exc, passthrough)):
                raise
            kind = classify(exc)
            detail = f"{type(exc).__name__}: {exc}"
            if kind == TRANSIENT:
                within_deadline = (policy.deadline_s is None
                                   or time.monotonic() - t0 < policy.deadline_s)
                if retries + 1 < policy.max_attempts and within_deadline:
                    record_event("retry", domain=domain, capability=capability or "",
                                 kind=kind, detail=detail)
                    sleep(policy.delay(retries))
                    retries += 1
                    continue
                kind = ENVIRONMENTAL  # transients that never clear
                detail = f"retries exhausted ({retries + 1} tries): {detail}"
            if capability is not None:
                quarantine(capability, detail, kind=kind, domain=domain)
            else:
                record_event("gave_up", domain=domain, kind=kind, detail=detail)
            if fallback is not None:
                record_event("fallback", domain=domain,
                             capability=capability or "", kind=kind, detail=detail)
                return fallback()
            raise
