"""Crash-safe case journal for the vector generator.

The INCOMPLETE sentinel (gen_runner) already marks cases that died
mid-write; what it cannot catch is a case directory that LOOKS complete
but holds corrupted bytes (a truncated ``.ssz_snappy`` after a disk-full
write, a tampered or half-flushed yaml). The journal closes that gap:
every committed case appends one JSON line with the sha256 of each part
file (flushed + fsync'd — a ``kill -9`` can lose at most the in-flight
case, which the sentinel already covers), and a resumed run re-admits a
case only when every journaled digest still matches the bytes on disk.
Cases that fail verification are regenerated, not silently shipped.

Pre-journal corpora (no journal file, or untracked cases) degrade to a
structural check: every ``.ssz_snappy`` must snappy-decompress and every
``.yaml`` must parse. That catches truncation and malformed yaml even
with no recorded digests.

Pure stdlib + the in-tree snappy codec; no jax.
"""
from __future__ import annotations

import hashlib
import json
import os
from pathlib import Path
from typing import Dict, Optional, Tuple

from .supervisor import record_event

JOURNAL_NAME = ".gen_journal.jsonl"

# per-rank journals for the sharded generator (sched/shard.py): each
# supervised worker appends to its own file so crash safety never needs
# cross-process append coordination; the parent merges them into
# JOURNAL_NAME deterministically after every rank completes
RANK_JOURNAL_FMT = ".gen_journal.rank{rank:04d}.jsonl"


def rank_journal_name(rank: int) -> str:
    return RANK_JOURNAL_FMT.format(rank=rank)


def load_ops(path: Path) -> list:
    """The raw op stream of one journal file: ``{"case", "parts"}``
    records and ``{"case", "status": "invalidated"}`` tombstones, in
    append order, torn trailing line tolerated. The sharded merge
    (sched/shard.py) replays these on top of a prior merged journal so a
    rank's invalidations are not resurrected by stale merged entries."""
    ops = []
    if not path.exists():
        return ops
    with open(path, "rb") as f:
        for line in f:
            try:
                entry = json.loads(line)
                if "case" in entry and ("parts" in entry or "status" in entry):
                    ops.append(entry)
            except (ValueError, KeyError, TypeError):
                continue
    return ops


def encode_entry(case: str, parts: Dict[str, str]) -> str:
    """The canonical one-line encoding of a journal entry — shared by
    ``CaseJournal._append`` and the sharded merge so a merged journal is
    byte-identical to one the serial writer would have produced."""
    return json.dumps({"case": case, "parts": parts}, sort_keys=True) + "\n"

COMPLETE = "complete"
ABSENT = "absent"
CORRUPT = "corrupt"


def verify_outputs(case_dir: Path) -> Optional[str]:
    """Structural integrity of a case directory (no digests needed):
    None when sound, else the reason it is corrupt."""
    import yaml

    from ..utils import snappy

    if (case_dir / "INCOMPLETE").exists():
        return "INCOMPLETE sentinel present (crashed mid-write)"
    part_seen = False
    for p in sorted(case_dir.iterdir()):
        if not p.is_file():
            continue
        if p.suffix == ".ssz_snappy":
            part_seen = True
            try:
                snappy.decompress(p.read_bytes())
            except Exception as e:
                return f"{p.name}: undecodable snappy ({type(e).__name__}: {e})"
        elif p.suffix == ".yaml":
            part_seen = True
            try:
                with open(p) as f:
                    yaml.safe_load(f)
            except Exception as e:
                return f"{p.name}: malformed yaml ({type(e).__name__})"
    if not part_seen:
        return "no part files"
    return None


class CaseJournal:
    """Append-only digest journal at ``<output_dir>/.gen_journal.jsonl``."""

    def __init__(self, output_dir: Path, name: str = JOURNAL_NAME):
        self.path = Path(output_dir) / name
        self._entries: Dict[str, Dict[str, str]] = {}
        self._load()

    def absorb(self, path: Path) -> int:
        """Pre-load entries from another journal file (the merged
        journal of a PRIOR sharded run) for admit decisions only — no
        lines are appended to this journal. Entries already present
        (this journal's own appends) win. Returns the count absorbed."""
        absorbed = 0
        for op in load_ops(Path(path)):
            if op.get("status") == "invalidated" or op["case"] in self._entries:
                continue
            self._entries[op["case"]] = op["parts"]
            absorbed += 1
        return absorbed

    def _load(self) -> None:
        if not self.path.exists():
            return
        with open(self.path, "rb") as f:
            for line in f:
                # a kill mid-append leaves at most one partial trailing
                # line — tolerated, that case just regenerates
                try:
                    entry = json.loads(line)
                    if entry.get("status") == "invalidated":
                        self._entries.pop(entry["case"], None)
                    else:
                        self._entries[entry["case"]] = entry["parts"]
                except (ValueError, KeyError, TypeError):
                    continue

    def _append(self, entry: dict) -> None:
        self.path.parent.mkdir(parents=True, exist_ok=True)
        with open(self.path, "a") as f:
            f.write(json.dumps(entry, sort_keys=True) + "\n")
            f.flush()
            os.fsync(f.fileno())

    def entries(self) -> Dict[str, Dict[str, str]]:
        """{case rel-path: {part file: sha256 hex}} for every currently
        journaled case — the per-case digest view consumers compare to
        prove byte-identity across generation modes (tools/gen_bench.py,
        tests/test_gen_sched.py)."""
        return {case: dict(parts) for case, parts in self._entries.items()}

    def record(self, rel: str, case_dir: Path) -> None:
        """Journal a committed case: digest every part file, fsync."""
        parts = {
            p.name: hashlib.sha256(p.read_bytes()).hexdigest()
            for p in sorted(case_dir.iterdir())
            if p.is_file()
        }
        self._append({"case": rel, "parts": parts})
        self._entries[rel] = parts

    def ensure_recorded(self, rel: str, case_dir: Path) -> None:
        """Backfill a digest entry for a case admitted on the structural
        (pre-journal) path. A kill in the window between a case's last
        part write and its journal fsync leaves a fully-written case dir
        with no entry; without backfill the case would be admitted on
        resume yet stay invisible to digest verification and to the
        sharded merge's combined journal (which must hold EVERY case for
        worker-count-independent byte-identity — sched/shard.py)."""
        if rel not in self._entries:
            self.record(rel, case_dir)

    def invalidate(self, rel: str) -> None:
        """Drop a case from the journal (it failed or was removed)."""
        if rel in self._entries:
            self._append({"case": rel, "status": "invalidated"})
            del self._entries[rel]

    def status(self, rel: str, case_dir: Path) -> Tuple[str, str]:
        """(COMPLETE | ABSENT | CORRUPT, reason) for one case dir."""
        if not case_dir.exists():
            return ABSENT, ""
        if (case_dir / "INCOMPLETE").exists():
            return CORRUPT, "INCOMPLETE sentinel present (crashed mid-write)"
        parts = self._entries.get(rel)
        if parts is None:
            # pre-journal case: structural check only
            reason = verify_outputs(case_dir)
            if reason is None:
                return COMPLETE, ""
            return CORRUPT, reason
        for name, want in parts.items():
            p = case_dir / name
            if not p.exists():
                return CORRUPT, f"{name}: journaled part missing"
            got = hashlib.sha256(p.read_bytes()).hexdigest()
            if got != want:
                return CORRUPT, f"{name}: digest mismatch (truncated or tampered)"
        stray = {p.name for p in case_dir.iterdir() if p.is_file()} - set(parts)
        if stray:
            return CORRUPT, f"unjournaled stray parts: {sorted(stray)}"
        return COMPLETE, ""

    def admit(self, rel: str, case_dir: Path) -> bool:
        """Resume decision: True to skip (verified complete), False to
        regenerate — recording WHY when the case was corrupt."""
        status, reason = self.status(rel, case_dir)
        if status == COMPLETE:
            return True
        if status == CORRUPT:
            record_event("regenerate", domain="generator", capability="gen.journal",
                         kind="deterministic", detail=f"{rel}: {reason}")
        return False
