"""Startup self-check probes for known-bad accelerated paths.

The jax_graft image ships jaxlib 0.4.36, whose CPU GSPMD partitioner
miscompiles the sharded Merkle TREE REDUCE once the row count drops
below the shard count (the final levels of every root computation): the
sharded result silently diverges from the single-device result. Before
this layer, that bug hard-failed ``tests/test_multichip.py`` and the
``dryrun_multichip`` child. The probe below reproduces it in miniature
(16 rows over the mesh, one small compile), and on mismatch QUARANTINES
the ``jax.sharded_tree_reduce`` capability so consumers degrade to the
single-device / host path with a recorded reason instead of failing.

The probe result is cached per process; ``CONSENSUS_SPECS_TPU_QUARANTINE=
jax.sharded_tree_reduce`` pre-opens the breaker without paying the probe
(known-bad boxes, CI).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from . import supervisor
from .supervisor import record_event

SHARDED_TREE_REDUCE = "jax.sharded_tree_reduce"

OK = "ok"
QUARANTINED = "quarantined"
UNAVAILABLE = "unavailable"


@dataclass(frozen=True)
class ProbeResult:
    capability: str
    status: str  # ok | quarantined | unavailable
    detail: str

    @property
    def quarantined(self) -> bool:
        return self.status == QUARANTINED


_cached: Optional[ProbeResult] = None


def sharded_reduce_status(force: bool = False) -> ProbeResult:
    """Probe (once per process) whether the sharded tree reduce computes
    the same root as the single-device path; quarantine it if not."""
    global _cached
    if _cached is not None and not force:
        return _cached
    if supervisor.is_quarantined(SHARDED_TREE_REDUCE):
        _cached = ProbeResult(SHARDED_TREE_REDUCE, QUARANTINED,
                              supervisor.quarantine_reason(SHARDED_TREE_REDUCE) or "")
        return _cached
    _cached = _probe()
    return _cached


def _probe() -> ProbeResult:
    try:
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from ..ops.sha256 import merkle_reduce_jit

        devices = jax.devices()
        if len(devices) < 2:
            return ProbeResult(
                SHARDED_TREE_REDUCE, UNAVAILABLE,
                "single device: sharded reduce never exercised")

        # largest power-of-two shard count, and just enough rows that the
        # reduce drops below it — the exact miscompile window, at the
        # smallest (cheapest-to-compile) shape that exhibits it
        n_shards = 1 << (len(devices).bit_length() - 1)
        rows = 2 * n_shards
        levels = rows.bit_length() - 1
        rng = np.random.default_rng(97)
        words = jnp.asarray(rng.integers(0, 2**32, size=(rows, 8), dtype=np.uint32))
        want = np.asarray(merkle_reduce_jit(words, levels))

        mesh = Mesh(np.array(devices[:n_shards]), ("dp",))
        sharded = jax.device_put(words, NamedSharding(mesh, P("dp", None)))
        got = np.asarray(merkle_reduce_jit(sharded, levels))
    except Exception as e:
        # no jax / no mesh / probe itself failed: the capability is not
        # provably broken, just unprobeable — report, don't quarantine
        detail = f"probe unavailable: {type(e).__name__}: {e}"
        record_event("probe", domain="selfcheck", capability=SHARDED_TREE_REDUCE,
                     kind="environmental", detail=detail)
        return ProbeResult(SHARDED_TREE_REDUCE, UNAVAILABLE, detail)

    if not np.array_equal(got, want):
        detail = (f"GSPMD sharded tree-reduce miscompile detected: "
                  f"{rows} rows over {n_shards} shards diverges from the "
                  "single-device root (known jaxlib 0.4.36 CPU bug when "
                  "reduce rows < shard count)")
        supervisor.quarantine(SHARDED_TREE_REDUCE, detail, domain="selfcheck")
        return ProbeResult(SHARDED_TREE_REDUCE, QUARANTINED, detail)

    record_event("probe", domain="selfcheck", capability=SHARDED_TREE_REDUCE,
                 kind="", detail=f"ok ({rows} rows over {n_shards} shards)")
    return ProbeResult(SHARDED_TREE_REDUCE, OK,
                       f"sharded tree reduce matches single-device root "
                       f"({rows} rows over {n_shards} shards)")
