"""Shared control-flow exceptions."""


class SkippedTest(Exception):
    """A test case that is deliberately not applicable (wrong preset/fork).

    pytest mode converts it to a pytest.skip; generator mode counts it as
    skipped (ref gen_runner.py skip semantics)."""
