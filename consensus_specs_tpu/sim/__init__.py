"""Chain simulator: a long-horizon "mainnet day" under chaos, on the
vectorized hot path (docs/SIM.md, ROADMAP #5).

Every plane of this repo — the SoA epoch engine, the resilience
quarantine machinery, the tracing/metrics/ledger evidence stack —
existed but was exercised by *single-shot* workloads (one epoch, one
block, one request). This package drives them together the way real
consensus clients are stressed: thousands of slots of proposals on
competing forks, attestation committees voting across reorgs,
equivocation slashings, empty slots and late blocks, all fed through
the phase0 fork-choice Store (``on_tick``/``on_block``/
``on_attestation``/``on_attester_slashing``, ``get_head``, proposer
boost) and the full state-transition path.

- :mod:`scenario` — the seeded event-stream generator. The whole
  timeline (fork windows, empty slots, late deliveries, equivocation
  slots, committee vote splits) is precomputed from ONE
  ``random.Random(seed)`` stream, so a scenario is a pure function of
  its seed: byte-reproducible across processes, machines and engine
  modes (knob: ``CONSENSUS_SPECS_TPU_SIM_SEED``).
- :mod:`driver` — ``ChainSim`` interprets the scenario against the
  live Store, records an epoch-boundary checkpoint digest
  (``get_head`` root + head-state ``hash_tree_root`` + FFG
  checkpoints), and prunes the Store at finality like a real client.
  ``run_differential`` runs the same scenario twice — interpreted
  oracle vs the vectorized engine (SoA epoch stages + batched
  attestation path) — and asserts bit-identity at every checkpoint.
  Chaos sites ``sim.step`` / ``sim.epoch`` let resilience faults fire
  mid-simulation; quarantine degrades the run to the oracle path and
  the chain must stay bit-identical.

Evidence: ``sim.*`` spans/counters in the trace plane,
``chain_sim_slots_per_s`` (+ vectorized-vs-oracle speedup) banked in
the perf ledger by ``bench.py --section chain_sim`` and
``tools/sim_run.py``, and ``perfgate_chain_sim_ms`` gating CI.
"""
from __future__ import annotations

from .checkpoint import SnapshotManager  # noqa: F401
from .driver import ChainSim, SimResult, run_differential, run_sim  # noqa: F401
from .net import (  # noqa: F401
    MessageBus,
    NetConfig,
    PartitionWindow,
    default_partitions,
)
from .partition import (  # noqa: F401
    PartitionConfig,
    PartitionedChainSim,
    PartitionedResult,
    run_partitioned,
    run_partitioned_differential,
)
from .scenario import (  # noqa: F401
    SEED_ENV,
    ForkWindow,
    Scenario,
    ScenarioConfig,
    SlotPlan,
    seed_from_env,
)

__all__ = [
    "SEED_ENV",
    "ChainSim",
    "ForkWindow",
    "MessageBus",
    "NetConfig",
    "PartitionConfig",
    "PartitionWindow",
    "PartitionedChainSim",
    "PartitionedResult",
    "Scenario",
    "ScenarioConfig",
    "SimResult",
    "SlotPlan",
    "SnapshotManager",
    "default_partitions",
    "run_differential",
    "run_partitioned",
    "run_partitioned_differential",
    "run_sim",
    "seed_from_env",
]
