"""Crash-consistent checkpoint/resume for the partitioned chain sim
(docs/SIM.md "Checkpoint/resume").

A multi-hour simulated day used to die unrecoverable at the first
SIGKILL; this module makes any such run resumable to a byte-identical
final chain. Every K epochs the driver hands over its full serializable
state (``PartitionedChainSim.state_payload()`` — per-node Stores +
head-state caches' source of truth, the bus's in-flight queue and
cursors, scenario/equivocator stream positions, stats) and the manager
lands it with the same discipline as the generator journal
(resilience/journal.py):

1. everything is written into a ``snap-<slot>.tmp.<pid>`` directory,
   each file fsync'd;
2. a ``MANIFEST.json`` with a sha256 per payload file is written LAST
   and fsync'd — a snapshot without a valid manifest does not exist;
3. the tmp dir is atomically renamed to ``snap-<slot>`` and the parent
   directory fsync'd;
4. older snapshots beyond ``keep`` are deleted only after the rename
   lands.

A SIGKILL at ANY point therefore leaves either the previous snapshots
untouched (torn tmp dirs are ignored and swept) or the new one fully
committed. Loading walks snapshots newest-first and **verifies every
digest**: a tampered or truncated snapshot is rejected with a recorded
event and the loader rolls back to the previous one — corruption can
cost progress, never correctness.

Chaos site ``sim.checkpoint`` (docs/RESILIENCE.md): fires at the top of
every snapshot attempt. Transient faults retry the write (the payload
is a pure function of sim state — safe); a deterministic fault SKIPS
this boundary with a recorded event and the run continues unscathed —
a faulted snapshot must never corrupt or stall the run; the next
boundary simply tries again. ``sim.checkpoint.write`` fires between
payload file writes inside the tmp dir, which is where the
kill-mid-snapshot drill lands its SIGKILL.
"""
from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

from .. import obs
from ..obs import metrics
from ..resilience import chaos, record_event, supervised

SNAP_RE = re.compile(r"^snap-(\d{8})$")
MANIFEST = "MANIFEST.json"
PAYLOAD_FILES = ("meta.json", "nodes.json", "bus.json")


# ---------------------------------------------------------------------------
# Store (de)serialization — SSZ bytes + JSON scalars, no pickling
# ---------------------------------------------------------------------------


def store_to_dict(spec: Any, store: Any) -> Dict[str, Any]:
    """One fork-choice Store as a JSON-able dict (SSZ payloads hex)."""
    def cp(c) -> Dict[str, Any]:
        return {"epoch": int(c.epoch), "root": bytes(c.root).hex()}

    return {
        "time": int(store.time),
        "genesis_time": int(store.genesis_time),
        "justified_checkpoint": cp(store.justified_checkpoint),
        "finalized_checkpoint": cp(store.finalized_checkpoint),
        "best_justified_checkpoint": cp(store.best_justified_checkpoint),
        "proposer_boost_root": bytes(store.proposer_boost_root).hex(),
        "equivocating_indices": sorted(int(i)
                                       for i in store.equivocating_indices),
        "blocks": {bytes(r).hex(): bytes(b.encode_bytes()).hex()
                   for r, b in store.blocks.items()},
        "block_states": {bytes(r).hex(): bytes(s.encode_bytes()).hex()
                         for r, s in store.block_states.items()},
        "checkpoint_states": [
            {"epoch": int(c.epoch), "root": bytes(c.root).hex(),
             "state": bytes(s.encode_bytes()).hex()}
            for c, s in store.checkpoint_states.items()],
        "latest_messages": {
            str(int(i)): {"epoch": int(m.epoch),
                          "root": bytes(m.root).hex()}
            for i, m in store.latest_messages.items()},
    }


def store_from_dict(spec: Any, d: Dict[str, Any]) -> Any:
    def cp(e) -> Any:
        return spec.Checkpoint(epoch=spec.Epoch(e["epoch"]),
                               root=spec.Root(bytes.fromhex(e["root"])))

    store = spec.Store(
        time=spec.uint64(d["time"]),
        genesis_time=spec.uint64(d["genesis_time"]),
        justified_checkpoint=cp(d["justified_checkpoint"]),
        finalized_checkpoint=cp(d["finalized_checkpoint"]),
        best_justified_checkpoint=cp(d["best_justified_checkpoint"]),
        proposer_boost_root=spec.Root(
            bytes.fromhex(d["proposer_boost_root"])),
        equivocating_indices=set(
            spec.ValidatorIndex(i) for i in d["equivocating_indices"]),
    )
    for root_hex, block_hex in d["blocks"].items():
        store.blocks[spec.Root(bytes.fromhex(root_hex))] = (
            spec.BeaconBlock.decode_bytes(bytes.fromhex(block_hex)))
    for root_hex, state_hex in d["block_states"].items():
        store.block_states[spec.Root(bytes.fromhex(root_hex))] = (
            spec.BeaconState.decode_bytes(bytes.fromhex(state_hex)))
    for entry in d["checkpoint_states"]:
        c = spec.Checkpoint(epoch=spec.Epoch(entry["epoch"]),
                            root=spec.Root(bytes.fromhex(entry["root"])))
        store.checkpoint_states[c] = spec.BeaconState.decode_bytes(
            bytes.fromhex(entry["state"]))
    for idx, m in d["latest_messages"].items():
        store.latest_messages[spec.ValidatorIndex(int(idx))] = (
            spec.LatestMessage(epoch=spec.Epoch(m["epoch"]),
                               root=spec.Root(bytes.fromhex(m["root"]))))
    return store


# ---------------------------------------------------------------------------
# snapshot manager
# ---------------------------------------------------------------------------


def _fsync_dir(path: Path) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _write_json(path: Path, obj: Any) -> str:
    data = json.dumps(obj, sort_keys=True).encode()
    with open(path, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    return hashlib.sha256(data).hexdigest()


class SnapshotManager:
    """Owns one checkpoint directory: atomic snapshot writes, digest-
    verified loads with rollback, bounded retention."""

    def __init__(self, directory: os.PathLike, keep: int = 2) -> None:
        self.dir = Path(directory)
        self.keep = max(1, keep)
        self.dir.mkdir(parents=True, exist_ok=True)

    # -- write ----------------------------------------------------------

    def maybe_snapshot(self, sim: Any, slot: int) -> bool:
        """Snapshot under the resilience supervisor. Returns True when a
        snapshot landed; False when this boundary was skipped (the
        degradation contract: a faulted snapshot never corrupts or
        stalls the run)."""

        def attempt() -> bool:
            chaos("sim.checkpoint")
            with obs.span("sim.checkpoint.write", slot=slot):
                self._write(sim.state_payload(), slot)
            return True

        def degraded() -> bool:
            metrics.count("sim.checkpoint.skipped")
            record_event("fallback", domain="sim.checkpoint",
                         capability="sim.checkpoint",
                         detail=f"snapshot at slot {slot} skipped; next "
                                "boundary will retry")
            obs.instant("sim.checkpoint.skipped", slot=slot)
            return False

        return bool(supervised(attempt, domain="sim.checkpoint",
                               capability="sim.checkpoint",
                               fallback=degraded))

    def _write(self, payload: Dict[str, Any], slot: int) -> Path:
        final = self.dir / f"snap-{slot:08d}"
        tmp = self.dir / f"snap-{slot:08d}.tmp.{os.getpid()}"
        if tmp.exists():
            shutil.rmtree(tmp)
        tmp.mkdir(parents=True)
        meta = {k: payload[k] for k in payload if k not in ("nodes", "bus")}
        digests = {"meta.json": _write_json(tmp / "meta.json", meta)}
        # the kill-mid-snapshot drill lands HERE: a torn tmp dir with a
        # committed meta but no manifest must be invisible to resume
        chaos("sim.checkpoint.write")
        digests["nodes.json"] = _write_json(tmp / "nodes.json",
                                            payload["nodes"])
        digests["bus.json"] = _write_json(tmp / "bus.json", payload["bus"])
        _write_json(tmp / MANIFEST, {"slot": slot, "files": digests})
        _fsync_dir(tmp)
        if final.exists():
            shutil.rmtree(final)
        os.replace(tmp, final)
        _fsync_dir(self.dir)
        self._sweep()
        metrics.count("sim.checkpoint.written")
        obs.instant("sim.checkpoint.written", slot=slot)
        return final

    def _sweep(self) -> None:
        """Drop torn tmp dirs and snapshots beyond the retention bound
        (never the ones we may still roll back to)."""
        for entry in self.dir.iterdir():
            if ".tmp." in entry.name and entry.is_dir():
                shutil.rmtree(entry, ignore_errors=True)
        snaps = self.snapshots()
        for slot, path in snaps[:-self.keep]:
            shutil.rmtree(path, ignore_errors=True)

    # -- read -----------------------------------------------------------

    def snapshots(self) -> List[Tuple[int, Path]]:
        out = []
        for entry in sorted(self.dir.iterdir()):
            m = SNAP_RE.match(entry.name)
            if m and entry.is_dir():
                out.append((int(m.group(1)), entry))
        return out

    def _verify(self, path: Path) -> Optional[Dict[str, Any]]:
        """Digest-checked load of one snapshot; None when anything is
        missing, torn, or tampered."""
        try:
            manifest = json.loads((path / MANIFEST).read_bytes())
            files = manifest["files"]
        except (OSError, ValueError, KeyError):
            return None
        parts: Dict[str, Any] = {}
        for name in PAYLOAD_FILES:
            want = files.get(name)
            if want is None:
                return None
            try:
                data = (path / name).read_bytes()
            except OSError:
                return None
            if hashlib.sha256(data).hexdigest() != want:
                return None
            try:
                parts[name] = json.loads(data)
            except ValueError:
                return None
        payload = dict(parts["meta.json"])
        payload["nodes"] = parts["nodes.json"]
        payload["bus"] = parts["bus.json"]
        return payload

    def load_latest(self) -> Optional[Tuple[int, Dict[str, Any]]]:
        """The newest VALID snapshot — tampered/truncated candidates are
        rejected with a recorded event and the loader rolls back to the
        previous one."""
        for slot, path in reversed(self.snapshots()):
            payload = self._verify(path)
            if payload is not None:
                obs.instant("sim.checkpoint.loaded", slot=slot)
                metrics.count("sim.checkpoint.loaded")
                return slot, payload
            metrics.count("sim.checkpoint.rejected")
            record_event("fault", domain="sim.checkpoint",
                         capability="sim.checkpoint",
                         kind="deterministic",
                         detail=f"snapshot {path.name} failed digest "
                                "verification; rolling back")
            obs.instant("sim.checkpoint.rejected", snapshot=path.name)
        return None


__all__ = ["SnapshotManager", "store_from_dict", "store_to_dict"]
