"""Partitioned multi-node chain simulation (docs/SIM.md "Partitioned
network").

N simulated nodes, each owning its OWN fork-choice Store and state
cache, connected only by the seeded adversarial message bus
(:mod:`sim.net`). Nothing is shared: a node knows exactly what the bus
delivered to it, so stale, duplicate, out-of-order and cross-partition
intake exercise the spec's real rejection ladders
(``validate_on_attestation``'s unknown-root / stale-target asserts,
``on_block``'s missing-parent assert) instead of being simulated away.

Mechanics per slot:

- every node ``on_tick``s, then drains its bus deliveries (adversarially
  reordered). A block whose parent has not arrived yet parks in the
  node's pending buffer and retries next slot (the client-side sync
  queue); a rejected wire attestation retries a few slots (it may
  reference a block still in flight) before it is dropped for good.
- the slot's proposer is discovered, not assigned: each node computes
  the proposer index from ITS OWN head view and proposes only when that
  validator is homed locally (``validator % nodes``). Agreeing nodes
  elect exactly one proposer; partitioned groups each elect their own —
  real competing branches, not scripted forks.
- every node attests its own head with its locally-homed committee
  members; attestations ride the bus to everyone else and arrive at the
  node itself next slot (the spec's "only affects subsequent slots").
- equivocation slashing evidence (scenario-planned) is built by one
  node, applied to its Store, broadcast, and included in blocks through
  ``slashing_includable`` — the same double path as the single driver.
- at every epoch boundary each node records its own checkpoint digest
  and prunes its Store at ITS OWN finality.

**Eventual convergence** (the acceptance contract): after every
partition heals, all honest nodes must reach an identical head root and
FFG checkpoint digest within ``converge_within`` slots (bounded because
the bus is eventually reliable — sim/net.py). The measured lag per heal
feeds the ``sim.convergence_lag_slots`` histogram and the run FAILS if
any heal misses the bound.

**Differential**: :func:`run_partitioned_differential` replays the same
configuration on the interpreted oracle and the vectorized engine and
demands bit-identity of every node's checkpoint stream — the same
contract as ``run_differential``, per node.

Chaos sites: ``sim.step`` / ``sim.epoch`` (same semantics as the
single-node driver: degrade to the interpreted-oracle engine path,
chain must not move), plus the bus's ``sim.net`` and the snapshot
plane's ``sim.checkpoint`` (sim/checkpoint.py).
"""
from __future__ import annotations

import contextlib
import hashlib
import json
import random
import time
from dataclasses import dataclass, field as dc_field, replace
from typing import Any, Dict, List, Optional, Tuple

from .. import engine, obs
from ..crypto import bls
from ..obs import chain as chain_health
from ..obs import metrics
from ..resilience import chaos, supervised
from ..specs import build_spec
from .driver import (
    ENGINE_MODES,
    _REJECTED,
    attestation_includable,
    slashing_includable,
)
from .net import (
    KIND_ATTESTATION,
    KIND_BLOCK,
    KIND_SLASHING,
    PHASE_MID,
    MessageBus,
    NetConfig,
    PartitionWindow,
    default_partitions,
    partitions_from_dicts,
    partitions_to_dicts,
)
from .scenario import Scenario, ScenarioConfig

# bounded client-side retry queues (sync/gossip stand-ins)
BLOCK_RETRIES = 16
ATT_RETRIES = 8

NODE_STAT_KEYS = (
    "blocks_proposed", "blocks_delivered", "blocks_duplicate",
    "blocks_rejected", "blocks_parked", "proposals_foreign",
    "slashed_proposer_slots", "failed_proposals",
    "attestations_sent", "attestations_accepted", "attestations_rejected",
    "attestations_parked", "slashings_included", "reorgs", "pruned_blocks",
)


@dataclass(frozen=True)
class PartitionConfig:
    """One partitioned run. ``partitions=None`` derives the scheduled
    windows from the seed (:func:`sim.net.default_partitions`)."""

    seed: int = 0
    slots: int = 256
    fork: str = "altair"
    preset: str = "minimal"
    validators: int = 64
    nodes: int = 3
    p_empty: float = 0.04
    equivocations: int = 2
    equivocation_width: int = 2
    sign: bool = False
    net: NetConfig = dc_field(default_factory=NetConfig)
    partitions: Optional[Tuple[PartitionWindow, ...]] = None
    converge_within: Optional[int] = None   # default: 3 epochs
    checkpoint_every: int = 4               # epochs between snapshots
    # fraction of validators that never attest (seed-derived subset):
    # the chain-health smoke's planted finality stall mutes 40% so FFG
    # never reaches the 2/3 justification quorum
    mute_attesters: float = 0.0
    # proposers cap per-block attestation inclusion below the spec max:
    # the pool is deduplicated and pruned on-chain, but a smaller cap
    # keeps interpreted-oracle block processing affordable at 3+ nodes
    max_block_attestations: int = 16

    def resolved_partitions(self) -> Tuple[PartitionWindow, ...]:
        if self.partitions is not None:
            return self.partitions
        return default_partitions(self.seed, self.slots, self.nodes)

    def resolved_net(self) -> NetConfig:
        return replace(self.net, seed=self.seed, nodes=self.nodes)

    def scenario_config(self) -> ScenarioConfig:
        # the partitioned sim reuses the scenario's empty-slot and
        # equivocation streams; explicit fork windows and late blocks
        # are OFF — partitions and bus delays produce them organically
        return ScenarioConfig(
            seed=self.seed, slots=self.slots, fork=self.fork,
            preset=self.preset, validators=self.validators,
            p_empty=self.p_empty, p_fork=0.0, p_late=0.0,
            equivocations=self.equivocations,
            equivocation_width=self.equivocation_width, sign=self.sign)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "seed": self.seed, "slots": self.slots, "fork": self.fork,
            "preset": self.preset, "validators": self.validators,
            "nodes": self.nodes, "p_empty": self.p_empty,
            "equivocations": self.equivocations,
            "equivocation_width": self.equivocation_width,
            "sign": self.sign, "net": self.resolved_net().to_dict(),
            "partitions": partitions_to_dicts(self.resolved_partitions()),
            "converge_within": self.converge_within,
            "checkpoint_every": self.checkpoint_every,
            "max_block_attestations": self.max_block_attestations,
            "mute_attesters": self.mute_attesters,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PartitionConfig":
        return cls(
            seed=int(d["seed"]), slots=int(d["slots"]), fork=d["fork"],
            preset=d["preset"], validators=int(d["validators"]),
            nodes=int(d["nodes"]), p_empty=float(d["p_empty"]),
            equivocations=int(d["equivocations"]),
            equivocation_width=int(d["equivocation_width"]),
            sign=bool(d["sign"]), net=NetConfig.from_dict(d["net"]),
            partitions=partitions_from_dicts(d["partitions"]),
            converge_within=(None if d.get("converge_within") is None
                             else int(d["converge_within"])),
            checkpoint_every=int(d["checkpoint_every"]),
            max_block_attestations=int(d.get("max_block_attestations", 16)),
            mute_attesters=float(d.get("mute_attesters", 0.0)))


class _Node:
    """One simulated node: its Store plus the client-side queues."""

    def __init__(self, node_id: int, store: Any) -> None:
        self.id = node_id
        self.store = store
        # inclusion pool: att root -> att, insertion-ordered, dedup'd;
        # entries drop when seen on-chain (block intake) or past horizon
        self.pool: Dict[bytes, Any] = {}
        self.wire_next: List[Any] = []            # own atts, intake next slot
        self.pending_blocks: List[Tuple[Any, int]] = []
        self.pending_atts: List[Tuple[Any, int]] = []
        self.slashing_queue: List[Any] = []
        self.known_slashings: set = set()
        self.checkpoints: List[Dict[str, Any]] = []
        self.stats: Dict[str, int] = {k: 0 for k in NODE_STAT_KEYS}
        self.prev_head: Optional[bytes] = None
        self.head: Optional[bytes] = None
        self.last_pruned_epoch = 0
        self.step_states: Dict[Tuple[bytes, int], Any] = {}


@dataclass
class PartitionedResult:
    engine: str
    config: PartitionConfig
    node_checkpoints: List[List[Dict[str, Any]]]
    node_stats: List[Dict[str, int]]
    stats: Dict[str, int]
    net: Dict[str, int]
    convergence: List[Dict[str, Any]]
    converged: bool
    final_heads: List[str]
    seconds: float

    @property
    def slots_per_s(self) -> float:
        return self.config.slots / self.seconds if self.seconds > 0 else 0.0

    def digest(self) -> str:
        """The byte-identity handle the kill/resume drills compare:
        sha256 over everything deterministic (never wall time)."""
        payload = {
            "node_checkpoints": self.node_checkpoints,
            "node_stats": self.node_stats,
            "stats": self.stats,
            "net": self.net,
            "convergence": self.convergence,
            "final_heads": self.final_heads,
        }
        return hashlib.sha256(
            json.dumps(payload, sort_keys=True).encode()).hexdigest()

    def chain_digest(self) -> str:
        """Chain content only (per-node checkpoint streams + final
        heads) — the handle for comparisons across runs whose snapshot
        or degradation accounting legitimately differs (e.g. a
        ``sim.checkpoint`` chaos run vs the clean baseline)."""
        payload = {
            "node_checkpoints": self.node_checkpoints,
            "final_heads": self.final_heads,
        }
        return hashlib.sha256(
            json.dumps(payload, sort_keys=True).encode()).hexdigest()

    def to_dict(self) -> Dict[str, Any]:
        return {
            "engine": self.engine,
            "config": self.config.to_dict(),
            "seconds": round(self.seconds, 3),
            "slots_per_s": round(self.slots_per_s, 2),
            "stats": dict(self.stats),
            "net": dict(self.net),
            "node_stats": [dict(s) for s in self.node_stats],
            "convergence": list(self.convergence),
            "converged": self.converged,
            "final_heads": list(self.final_heads),
            "digest": self.digest(),
            "chain_digest": self.chain_digest(),
            "checkpoints": sum(len(c) for c in self.node_checkpoints),
        }


class PartitionedChainSim:
    """One partitioned run. Optionally checkpointing (``manager``) and
    resumable (:meth:`from_snapshot`)."""

    def __init__(self, config: PartitionConfig,
                 engine_label: str = "interpreted",
                 manager: Optional[Any] = None) -> None:
        from ..test_framework.genesis import create_genesis_state

        self.config = config
        self.engine_label = engine_label
        self.manager = manager
        self.spec = build_spec(config.fork, config.preset)
        self.scenario = Scenario(config.scenario_config())
        self.partitions = config.resolved_partitions()
        self.bus = MessageBus(config.resolved_net(), self.partitions)
        spec = self.spec
        self.spe = int(spec.SLOTS_PER_EPOCH)
        self.converge_within = (config.converge_within
                                if config.converge_within is not None
                                else 3 * self.spe)

        genesis = create_genesis_state(
            spec, [spec.MAX_EFFECTIVE_BALANCE] * config.validators,
            spec.MAX_EFFECTIVE_BALANCE)
        anchor_block = spec.BeaconBlock(state_root=spec.hash_tree_root(genesis))
        self.nodes = [
            _Node(i, spec.get_forkchoice_store(genesis.copy(), anchor_block))
            for i in range(config.nodes)
        ]
        self.stats: Dict[str, int] = {
            "equivocations": 0, "degraded_steps": 0, "degraded_epochs": 0,
            "snapshots_written": 0, "snapshots_skipped": 0,
        }
        # per-window convergence ledger; "lag" counts CONNECTED slots
        # since the heal (the clock pauses while a later scheduled
        # window has the network split again — convergence is bounded
        # in connectivity, not in wall slots)
        self.convergence: List[Dict[str, Any]] = [
            {"window": i, "start": w.start, "heal": w.end,
             "converged_slot": None, "lag": None, "connected_slots": 0}
            for i, w in enumerate(self.partitions)
        ]
        self.next_slot = 1
        self._oracle_forced = False
        self._cur_slot = 0
        eq_rng = random.Random(f"chain-sim:{config.seed}:equiv")
        self._equivocators = list(range(config.validators))
        eq_rng.shuffle(self._equivocators)
        self._equiv_consumed = 0
        # planted-stall knob: a seed-derived subset of validators that
        # never attest (pure function of (seed, validators, fraction))
        mute_rng = random.Random(f"chain-sim:{config.seed}:mute")
        ids = list(range(config.validators))
        mute_rng.shuffle(ids)
        self._muted = frozenset(
            ids[:int(round(config.mute_attesters * config.validators))])
        # the consensus health plane (obs/chain.py): observational only —
        # armed and unarmed runs are bit-identical by construction; the
        # scheduled-window export keeps planned partitions from reading
        # as split-brain/stall findings
        self.health = chain_health.build(
            config.nodes, self.spe,
            windows=self.bus.scheduled_windows(),
            label=f"sim.partition.{engine_label}",
            bundle_cb=self._forensic_payload)

    # -- plumbing -------------------------------------------------------

    def _home(self, validator: int) -> int:
        return int(validator) % self.config.nodes

    def _state_at(self, node: _Node, root: bytes, slot: int):
        key = (bytes(root), slot)
        cached = node.step_states.get(key)
        if cached is not None:
            return cached
        st = node.store.block_states[root]
        if int(st.slot) < slot:
            st = st.copy()
            self.spec.process_slots(st, self.spec.Slot(slot))
        node.step_states[key] = st
        return st

    def _is_ancestor(self, node: _Node, ancestor: bytes, root: bytes) -> bool:
        spec, store = self.spec, node.store
        try:
            slot = store.blocks[ancestor].slot
            return bytes(spec.get_ancestor(store, root, slot)) == bytes(ancestor)
        except KeyError:
            return False

    # -- intake ---------------------------------------------------------

    def _deliver_block(self, node: _Node, signed, retries: int = 0,
                       phase: str = "top") -> None:
        """``on_block`` plus the spec's implied intake of the block's
        payload. A rejected block (parent still in flight, typically)
        parks in the node's pending buffer — the client-side sync queue
        — and retries next slot, ``BLOCK_RETRIES`` times. ``phase``
        labels the black-box intake entry (top/mid/own/retry)."""
        spec, store = self.spec, node.store
        root = spec.hash_tree_root(signed.message)
        msg_id = bytes(root).hex()[:16]
        health = self.health
        if root in store.blocks:
            node.stats["blocks_duplicate"] += 1
            if health is not None:
                health.record_intake(node.id, self._cur_slot, phase,
                                     "block", msg_id, "duplicate")
            return
        try:
            spec.on_block(store, signed)
        except _REJECTED:
            if retries + 1 >= BLOCK_RETRIES:
                node.stats["blocks_rejected"] += 1
                outcome = "rejected"
            else:
                node.pending_blocks.append((signed, retries + 1))
                node.stats["blocks_parked"] += 1
                metrics.count("sim.net.blocks_parked")
                outcome = "parked"
            if health is not None:
                health.record_intake(node.id, self._cur_slot, phase,
                                     "block", msg_id, outcome)
            return
        block_slot = int(signed.message.slot)
        for att in signed.message.body.attestations:
            try:
                spec.on_attestation(store, att, is_from_block=True)
            except _REJECTED:
                node.stats["attestations_rejected"] += 1
            if health is not None and node.id == 0:
                # inclusion distance is a chain property, not a view
                # property: count each on-chain attestation once (node 0
                # stands in; converged nodes see identical blocks)
                health.record_inclusion(block_slot, int(att.data.slot))
        for slashing in signed.message.body.attester_slashings:
            try:
                spec.on_attester_slashing(store, slashing)
            except _REJECTED:
                pass
        node.stats["blocks_delivered"] += 1
        if health is not None:
            health.record_intake(node.id, self._cur_slot, phase, "block",
                                 msg_id, "accepted")

    def _deliver_attestation(self, node: _Node, att, retries: int = 0,
                             phase: str = "top") -> None:
        health = self.health
        # a cheap stable id (slot:index) — hashing every rejected vote
        # would put tree roots on the intake hot path for ring cosmetics
        msg_id = f"att:{int(att.data.slot)}:{int(att.data.index)}"
        try:
            self.spec.on_attestation(node.store, att, is_from_block=False)
        except _REJECTED:
            # the vote may reference a block still in flight: park and
            # retry a few slots before dropping for good
            if retries + 1 >= ATT_RETRIES:
                node.stats["attestations_rejected"] += 1
                outcome = "rejected"
            else:
                node.pending_atts.append((att, retries + 1))
                node.stats["attestations_parked"] += 1
                outcome = "parked"
            if health is not None:
                health.record_intake(node.id, self._cur_slot, phase,
                                     "attestation", msg_id, outcome)
            return
        node.stats["attestations_accepted"] += 1
        node.pool.setdefault(bytes(self.spec.hash_tree_root(att)), att)
        if health is not None:
            health.record_intake(node.id, self._cur_slot, phase,
                                 "attestation", msg_id, "accepted")

    def _deliver_slashing(self, node: _Node, slashing) -> None:
        digest = bytes(self.spec.hash_tree_root(slashing))
        if digest in node.known_slashings:
            if self.health is not None:
                self.health.record_intake(node.id, self._cur_slot, "top",
                                          "slashing", digest.hex()[:16],
                                          "duplicate")
            return
        node.known_slashings.add(digest)
        try:
            self.spec.on_attester_slashing(node.store, slashing)
        except _REJECTED:
            pass
        node.slashing_queue.append(slashing)
        if self.health is not None:
            self.health.record_intake(node.id, self._cur_slot, "top",
                                      "slashing", digest.hex()[:16],
                                      "accepted")

    def _intake(self, slot: int, node: _Node) -> None:
        pending_blocks, node.pending_blocks = node.pending_blocks, []
        for signed, retries in pending_blocks:
            self._deliver_block(node, signed, retries, phase="retry")
        pending_atts, node.pending_atts = node.pending_atts, []
        for att, retries in pending_atts:
            self._deliver_attestation(node, att, retries, phase="retry")
        wire, node.wire_next = node.wire_next, []
        for att in wire:
            self._deliver_attestation(node, att)
        for kind, obj, _src in self.bus.deliveries(slot, node.id):
            if kind == KIND_BLOCK:
                self._deliver_block(node, obj)
            elif kind == KIND_ATTESTATION:
                self._deliver_attestation(node, obj)
            else:
                self._deliver_slashing(node, obj)
        # one same-slot retry of what this intake just parked: an
        # attestation shuffled ahead of its own block (the reorder case)
        # applies as soon as the block lands, like a client's pending
        # queue draining on a new-block event
        parked_now, node.pending_atts = node.pending_atts, []
        for att, retries in parked_now:
            node.stats["attestations_parked"] -= 1
            self._deliver_attestation(node, att, retries - 1, phase="retry")

    # -- per-slot mechanics --------------------------------------------

    def _propose(self, slot: int, node: _Node) -> None:
        from ..test_framework.block import build_empty_block
        from ..test_framework.block_processing import (
            state_transition_and_sign_block,
        )

        spec = self.spec
        tip = node.head
        view = self._state_at(node, tip, slot)
        try:
            block = build_empty_block(spec, view, spec.Slot(slot))
        except _REJECTED:
            node.stats["failed_proposals"] += 1
            return
        proposer = int(block.proposer_index)
        if self._home(proposer) != node.id:
            # the proposer lives on another node: from THIS node's view
            # somebody else owns the slot (agreeing nodes elect exactly
            # one proposer; split views may elect one per branch)
            node.stats["proposals_foreign"] += 1
            return
        if view.validators[proposer].slashed:
            node.stats["slashed_proposer_slots"] += 1
            return

        # newest-first up to the cap: fresh votes are what carries FFG
        # justification on this branch; older pool entries re-include
        # redundantly (the spec is idempotent about it) but boundedly.
        # A vote included only on a branch that later LOSES is thereby
        # re-included on the winner too — nothing is popped on intake,
        # so reorgs cannot orphan votes out of existence.
        cap = min(int(spec.MAX_ATTESTATIONS),
                  self.config.max_block_attestations)
        included = 0
        for att in reversed(node.pool.values()):
            if included >= cap:
                break
            if attestation_includable(spec, view, att):
                block.body.attestations.append(att)
                included += 1
        if node.slashing_queue:
            kept = []
            for slashing in node.slashing_queue:
                if (len(block.body.attester_slashings)
                        < int(spec.MAX_ATTESTER_SLASHINGS)
                        and slashing_includable(spec, view, slashing)):
                    block.body.attester_slashings.append(slashing)
                    node.stats["slashings_included"] += 1
                else:
                    kept.append(slashing)
            node.slashing_queue = kept

        try:
            pre = node.store.block_states[tip].copy()
            signed = state_transition_and_sign_block(spec, pre, block)
        except Exception:
            node.stats["failed_proposals"] += 1
            return
        node.stats["blocks_proposed"] += 1
        metrics.count("sim.blocks_proposed")
        self._deliver_block(node, signed, phase="own")  # lands at once
        self.bus.send(slot, node.id, KIND_BLOCK, signed)

    def _attest(self, slot: int, node: _Node) -> None:
        from ..test_framework.attestations import get_valid_attestation

        spec = self.spec
        head_state = self._state_at(node, node.head, slot)
        epoch = spec.compute_epoch_at_slot(spec.Slot(slot))
        committees = int(spec.get_committee_count_per_slot(head_state, epoch))
        for index in range(committees):
            committee = spec.get_beacon_committee(
                head_state, spec.Slot(slot), spec.CommitteeIndex(index))
            mine = {int(v) for v in committee
                    if self._home(v) == node.id and int(v) not in self._muted}
            if not mine:
                continue
            try:
                att = get_valid_attestation(
                    spec, head_state, slot=spec.Slot(slot),
                    index=spec.CommitteeIndex(index),
                    filter_participant_set=lambda comm, v=mine: comm & v,
                    signed=self.config.sign)
            except _REJECTED:
                continue
            if not any(att.aggregation_bits):
                continue
            # pooled for inclusion at wire intake next slot (inclusion
            # delay >= 1 anyway, so nothing is lost by not pooling now)
            node.wire_next.append(att)
            node.stats["attestations_sent"] += 1
            metrics.count("sim.attestations")
            self.bus.send(slot, node.id, KIND_ATTESTATION, att)

    def _emit_equivocation(self, slot: int) -> None:
        from ..test_framework.attester_slashings import (
            get_valid_attester_slashing_by_indices,
        )

        spec = self.spec
        node = self.nodes[slot % self.config.nodes]
        width = max(1, int(self.config.equivocation_width))
        if len(self._equivocators) - self._equiv_consumed < width:
            return
        indices = sorted(
            self._equivocators[self._equiv_consumed:self._equiv_consumed + width])
        self._equiv_consumed += width
        state = self._state_at(node, node.head, slot)
        try:
            slashing = get_valid_attester_slashing_by_indices(
                spec, state, indices, slot=spec.Slot(slot),
                signed_1=self.config.sign, signed_2=self.config.sign)
        except _REJECTED:
            return
        self._deliver_slashing(node, slashing)
        self.bus.send(slot, node.id, KIND_SLASHING, slashing)
        self.stats["equivocations"] += 1
        metrics.count("sim.equivocations")
        obs.instant("sim.equivocation", slot=slot, width=width, node=node.id)

    # -- convergence ----------------------------------------------------

    def _view_digest(self, node: _Node) -> Tuple[bytes, int, str, int, str]:
        store = node.store
        return (bytes(node.head),
                int(store.justified_checkpoint.epoch),
                bytes(store.justified_checkpoint.root).hex(),
                int(store.finalized_checkpoint.epoch),
                bytes(store.finalized_checkpoint.root).hex())

    def _check_convergence(self, slot: int) -> None:
        watching = [c for c in self.convergence
                    if c["heal"] < slot and c["converged_slot"] is None]
        if not watching:
            return
        connected = self.bus.window_at(slot) is None
        if connected:
            for c in watching:
                c["connected_slots"] += 1
        views = {self._view_digest(n) for n in self.nodes}
        if len(views) != 1 or not connected:
            return
        for c in watching:
            lag = c["connected_slots"]
            c["converged_slot"] = slot
            c["lag"] = lag
            metrics.observe("sim.convergence_lag_slots", float(lag))
            metrics.count("sim.net.heals_converged")
            obs.instant("sim.net.converged", window=c["window"], slot=slot,
                        lag=lag)

    # -- slot step ------------------------------------------------------

    def _node_view(self, node: _Node) -> Dict[str, Any]:
        """One node's consensus view for the health plane (obs/chain.py)."""
        store = node.store
        return {
            "head": bytes(node.head).hex(),
            "head_slot": int(store.blocks[node.head].slot),
            "justified_epoch": int(store.justified_checkpoint.epoch),
            "finalized_epoch": int(store.finalized_checkpoint.epoch),
            "pending_blocks": len(node.pending_blocks),
            "pending_atts": len(node.pending_atts),
            "fork_count": chain_health.fork_count(store),
        }

    def _step(self, slot: int) -> None:
        spec = self.spec
        plan = self.scenario.plan(slot)
        self._cur_slot = slot
        for node in self.nodes:
            node.step_states.clear()
            spec.on_tick(node.store, node.store.genesis_time
                         + slot * int(spec.config.SECONDS_PER_SLOT))
            self._intake(slot, node)
            node.head = spec.get_head(node.store)

        # convergence is judged at the top of the slot, after intake and
        # BEFORE this slot's proposal (a proposer always sees its own
        # block one slot before everyone else — that skew is protocol,
        # not divergence)
        self._check_convergence(slot)

        # the chain-health plane observes the same post-intake,
        # pre-proposal point (connected honest nodes agree here)
        if self.health is not None:
            self.health.on_slot(
                slot, [self._node_view(n) for n in self.nodes],
                partitioned=self.bus.window_at(slot) is not None)

        if plan.equivocate:
            self._emit_equivocation(slot)

        if plan.propose:
            for node in self.nodes:
                self._propose(slot, node)

        # mid-slot: timely blocks proposed THIS slot cross the wire
        # before anyone attests (the attestation-deadline timing that
        # keeps FFG participation honest — docs/SIM.md)
        for node in self.nodes:
            for kind, obj, _src in self.bus.deliveries(slot, node.id,
                                                       PHASE_MID):
                if kind == KIND_BLOCK:
                    self._deliver_block(node, obj, phase="mid")

        for node in self.nodes:
            # proposals and mid-slot deliveries may have moved this
            # node's head: refresh before attesting
            head = spec.get_head(node.store)
            if (node.prev_head is not None
                    and bytes(head) != bytes(node.prev_head)
                    and not self._is_ancestor(node, node.prev_head, head)):
                node.stats["reorgs"] += 1
                metrics.count("sim.reorgs")
                if self.health is not None:
                    self.health.record_reorg(
                        node.id, slot,
                        chain_health.reorg_depth(node.store, node.prev_head,
                                                 head))
            node.prev_head = head
            node.head = head
            self._attest(slot, node)

    @contextlib.contextmanager
    def _forced_oracle(self):
        was_vec = engine.is_vectorized()
        was_batch = engine.is_batched_attestations()
        engine.use_interpreted_epoch()
        engine.use_direct_attestations()
        try:
            yield
        finally:
            if was_vec:
                engine.use_vectorized_epoch()
            if was_batch:
                engine.use_batched_attestations()

    def _run_step(self, slot: int) -> None:
        def attempt():
            chaos("sim.step")
            if self._oracle_forced:
                with self._forced_oracle():
                    self._step(slot)
            else:
                self._step(slot)

        def degraded():
            self.stats["degraded_steps"] += 1
            metrics.count("sim.degraded_steps")
            obs.instant("sim.degraded", site="sim.step", slot=slot)
            with self._forced_oracle():
                self._step(slot)

        supervised(attempt, domain="sim", capability="sim.step",
                   fallback=degraded)

    # -- epoch rollover + pruning --------------------------------------

    def _prune(self, node: _Node, slot: int) -> None:
        spec, store = self.spec, node.store
        fin = store.finalized_checkpoint
        fin_epoch = int(fin.epoch)
        if fin_epoch <= node.last_pruned_epoch:
            return
        node.last_pruned_epoch = fin_epoch
        fin_slot = spec.compute_start_slot_at_epoch(fin.epoch)
        keep = set()
        for root in list(store.blocks):
            try:
                if bytes(spec.get_ancestor(store, root, fin_slot)) == bytes(fin.root):
                    keep.add(bytes(root))
            except KeyError:
                continue
        dropped = [r for r in list(store.blocks) if bytes(r) not in keep]
        for root in dropped:
            del store.blocks[root]
            del store.block_states[root]
        for index in [i for i, m in store.latest_messages.items()
                      if bytes(m.root) not in keep]:
            del store.latest_messages[index]
        for cp in [c for c in store.checkpoint_states
                   if int(c.epoch) < fin_epoch and c != store.justified_checkpoint]:
            del store.checkpoint_states[cp]
        horizon = slot - self.spe
        node.pool = {k: a for k, a in node.pool.items()
                     if int(a.data.slot) >= horizon}
        if dropped:
            node.stats["pruned_blocks"] += len(dropped)
            metrics.count("sim.pruned_blocks", len(dropped))

    def _epoch_rollover(self, slot: int) -> None:
        spec = self.spec

        def attempt():
            chaos("sim.epoch")

        def degraded():
            self.stats["degraded_epochs"] += 1
            self._oracle_forced = True
            metrics.count("sim.degraded_epochs")
            obs.instant("sim.degraded", site="sim.epoch", slot=slot)

        supervised(attempt, domain="sim", capability="sim.epoch",
                   fallback=degraded)

        epoch = slot // self.spe
        participations: List[Optional[float]] = []
        finalized: List[int] = []
        for node in self.nodes:
            store = node.store
            head = spec.get_head(store)
            head_state = store.block_states[head]
            node.checkpoints.append({
                "node": node.id,
                "epoch": epoch,
                "slot": slot,
                "head": bytes(head).hex(),
                "head_slot": int(store.blocks[head].slot),
                "state_root": bytes(spec.hash_tree_root(head_state)).hex(),
                "justified_epoch": int(store.justified_checkpoint.epoch),
                "finalized_epoch": int(store.finalized_checkpoint.epoch),
            })
            if self.health is not None:
                participations.append(
                    chain_health.participation_rate(spec, head_state))
                finalized.append(int(store.finalized_checkpoint.epoch))
            self._prune(node, slot)
        metrics.count("sim.epochs")
        if self.health is not None:
            self.health.on_epoch(epoch, slot, participations, finalized)

    # -- forensics ------------------------------------------------------

    def _forensic_payload(self) -> Dict[str, Any]:
        """The heavyweight half of a chain forensic bundle
        (obs/chain.py): every node's full Store dump, the in-flight bus
        state, and the (seeded) config — with the intake rings the plane
        itself adds, enough to replay the divergence without rerunning
        the day."""
        from .checkpoint import store_to_dict

        spec = self.spec
        return {
            "engine": self.engine_label,
            "slot": self._cur_slot,
            "config": self.config.to_dict(),
            "convergence": [dict(c) for c in self.convergence],
            "node_stats": [dict(n.stats) for n in self.nodes],
            "nodes": [{
                "id": n.id,
                "head": (bytes(n.head).hex() if n.head is not None else None),
                "store": store_to_dict(spec, n.store),
            } for n in self.nodes],
            "bus": {"config": self.bus.config.to_dict(),
                    "windows": partitions_to_dicts(self.partitions),
                    "state": self.bus.state_dict()},
        }

    # -- entry points ---------------------------------------------------

    def run(self) -> PartitionedResult:
        cfg = self.config
        was_bls = bls.bls_active
        bls.bls_active = bool(cfg.sign)
        t0 = time.perf_counter()
        try:
            with obs.span("sim.partition.run", engine=self.engine_label,
                          fork=cfg.fork, preset=cfg.preset, seed=cfg.seed,
                          slots=cfg.slots, nodes=cfg.nodes,
                          windows=len(self.partitions)):
                for slot in range(self.next_slot, cfg.slots + 1):
                    with obs.span("sim.slot", slot=slot):
                        self._run_step(slot)
                    rollover = (slot + 1) % self.spe == 0
                    if rollover:
                        with obs.span("sim.epoch", slot=slot):
                            self._epoch_rollover(slot)
                    # the snapshot (when due) is taken with next_slot
                    # already advanced: a resume continues AFTER the
                    # epoch whose checkpoints the snapshot contains
                    self.next_slot = slot + 1
                    if (rollover and self.manager is not None
                            and (slot // self.spe) % max(
                                1, cfg.checkpoint_every) == 0):
                        # counted BEFORE the write so the snapshot's own
                        # payload carries it — a resumed run's final
                        # stats then match the uninterrupted run's
                        self.stats["snapshots_written"] += 1
                        if not self.manager.maybe_snapshot(self, slot):
                            self.stats["snapshots_written"] -= 1
                            self.stats["snapshots_skipped"] += 1
        finally:
            bls.bls_active = was_bls
        seconds = time.perf_counter() - t0
        result = self._result(seconds)
        if self.health is not None:
            if not result.converged:
                # a heal that never converged IS the divergence the
                # black box exists for: ship the bundle before anything
                # else reads the result
                self.health.write_bundle(
                    "convergence failure: "
                    f"{[c for c in result.convergence if c['lag'] is None or c['lag'] > self.converge_within]}"[:400])
            self.health.close()
        return result

    def _result(self, seconds: float) -> PartitionedResult:
        converged = all(
            c["lag"] is not None and c["lag"] <= self.converge_within
            for c in self.convergence)
        return PartitionedResult(
            engine=self.engine_label,
            config=self.config,
            node_checkpoints=[list(n.checkpoints) for n in self.nodes],
            node_stats=[dict(n.stats) for n in self.nodes],
            stats=dict(self.stats),
            net=dict(self.bus.stats),
            convergence=[dict(c) for c in self.convergence],
            converged=converged,
            final_heads=[bytes(n.head).hex() if n.head is not None else ""
                         for n in self.nodes],
            seconds=seconds,
        )

    # -- checkpoint serialization --------------------------------------

    def state_payload(self) -> Dict[str, Any]:
        """Everything the next process needs to continue this run with
        byte-identical results (sim/checkpoint.py writes it)."""
        from .checkpoint import store_to_dict

        spec = self.spec
        nodes = []
        for node in self.nodes:
            nodes.append({
                "id": node.id,
                "store": store_to_dict(spec, node.store),
                "pool": [bytes(a.encode_bytes()).hex()
                         for a in node.pool.values()],
                "wire_next": [bytes(a.encode_bytes()).hex()
                              for a in node.wire_next],
                "pending_blocks": [
                    {"ssz": bytes(b.encode_bytes()).hex(), "retries": r}
                    for b, r in node.pending_blocks],
                "pending_atts": [
                    {"ssz": bytes(a.encode_bytes()).hex(), "retries": r}
                    for a, r in node.pending_atts],
                "slashing_queue": [bytes(s.encode_bytes()).hex()
                                   for s in node.slashing_queue],
                "known_slashings": sorted(d.hex()
                                          for d in node.known_slashings),
                "checkpoints": list(node.checkpoints),
                "stats": dict(node.stats),
                "prev_head": (bytes(node.prev_head).hex()
                              if node.prev_head is not None else None),
                "head": (bytes(node.head).hex()
                         if node.head is not None else None),
                "last_pruned_epoch": node.last_pruned_epoch,
            })
        return {
            "config": self.config.to_dict(),
            "engine": self.engine_label,
            "next_slot": self.next_slot,
            "stats": dict(self.stats),
            "oracle_forced": self._oracle_forced,
            "equiv_consumed": self._equiv_consumed,
            "convergence": [dict(c) for c in self.convergence],
            "bus": self.bus.state_dict(),
            "nodes": nodes,
        }

    @classmethod
    def from_snapshot(cls, payload: Dict[str, Any],
                      engine_label: Optional[str] = None,
                      manager: Optional[Any] = None) -> "PartitionedChainSim":
        from .checkpoint import store_from_dict

        config = PartitionConfig.from_dict(payload["config"])
        sim = cls(config, engine_label=engine_label or payload["engine"],
                  manager=manager)
        spec = sim.spec
        sim.next_slot = int(payload["next_slot"])
        sim.stats = {k: int(v) for k, v in payload["stats"].items()}
        sim._oracle_forced = bool(payload["oracle_forced"])
        sim._equiv_consumed = int(payload["equiv_consumed"])
        sim.convergence = [dict(c) for c in payload["convergence"]]
        sim.bus.restore_state(spec, payload["bus"])

        def _att(h):
            return spec.Attestation.decode_bytes(bytes.fromhex(h))

        for node, nd in zip(sim.nodes, payload["nodes"]):
            node.store = store_from_dict(spec, nd["store"])
            node.pool = {}
            for h in nd["pool"]:
                att = _att(h)
                node.pool[bytes(spec.hash_tree_root(att))] = att
            node.wire_next = [_att(h) for h in nd["wire_next"]]
            node.pending_blocks = [
                (spec.SignedBeaconBlock.decode_bytes(bytes.fromhex(e["ssz"])),
                 int(e["retries"])) for e in nd["pending_blocks"]]
            node.pending_atts = [(_att(e["ssz"]), int(e["retries"]))
                                 for e in nd["pending_atts"]]
            node.slashing_queue = [
                spec.AttesterSlashing.decode_bytes(bytes.fromhex(h))
                for h in nd["slashing_queue"]]
            node.known_slashings = {bytes.fromhex(h)
                                    for h in nd["known_slashings"]}
            node.checkpoints = list(nd["checkpoints"])
            node.stats = {k: int(v) for k, v in nd["stats"].items()}
            node.prev_head = (bytes.fromhex(nd["prev_head"])
                              if nd["prev_head"] else None)
            node.head = bytes.fromhex(nd["head"]) if nd["head"] else None
            node.last_pruned_epoch = int(nd["last_pruned_epoch"])
            node.head = (spec.get_head(node.store)
                         if node.head is None else node.head)
        return sim


# ---------------------------------------------------------------------------
# run helpers (engine installation managed, like sim/driver.py)
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def _engine_mode(mode: str):
    if mode not in ENGINE_MODES:
        raise ValueError(f"unknown engine mode {mode!r} (have {ENGINE_MODES})")
    was_vec = engine.is_vectorized()
    was_batch = engine.is_batched_attestations()
    if mode == "vectorized":
        engine.use_vectorized_epoch()
        engine.use_batched_attestations()
    else:
        engine.use_interpreted_epoch()
        engine.use_direct_attestations()
    try:
        yield
    finally:
        (engine.use_vectorized_epoch if was_vec else engine.use_interpreted_epoch)()
        (engine.use_batched_attestations if was_batch
         else engine.use_direct_attestations)()


def run_partitioned(config: PartitionConfig,
                    engine_mode: str = "interpreted",
                    manager: Optional[Any] = None,
                    resume_payload: Optional[Dict[str, Any]] = None) -> PartitionedResult:
    """One full (or resumed) partitioned run under one engine mode."""
    if resume_payload is not None:
        sim = PartitionedChainSim.from_snapshot(
            resume_payload, engine_label=engine_mode, manager=manager)
    else:
        sim = PartitionedChainSim(config, engine_label=engine_mode,
                                  manager=manager)
    with _engine_mode(engine_mode):
        result = sim.run()
    result.sim = sim  # forensic access (bundle on differential mismatch)
    return result


def compare_node_checkpoints(a: PartitionedResult,
                             b: PartitionedResult) -> List[Dict[str, Any]]:
    """Field-level mismatches between two runs' per-node checkpoint
    streams (the per-node differential contract)."""
    mismatches: List[Dict[str, Any]] = []
    for node_id, (ca_list, cb_list) in enumerate(
            zip(a.node_checkpoints, b.node_checkpoints)):
        if len(ca_list) != len(cb_list):
            mismatches.append({"node": node_id, "field": "checkpoint_count",
                               a.engine: len(ca_list),
                               b.engine: len(cb_list)})
        for ca, cb in zip(ca_list, cb_list):
            for fld in ("head", "state_root", "head_slot",
                        "justified_epoch", "finalized_epoch"):
                if ca[fld] != cb[fld]:
                    mismatches.append({"node": node_id, "epoch": ca["epoch"],
                                       "field": fld, a.engine: ca[fld],
                                       b.engine: cb[fld]})
    return mismatches


def run_partitioned_differential(config: PartitionConfig) -> Dict[str, Any]:
    """The acceptance contract, per node: the same partitioned scenario
    through the interpreted oracle and the vectorized engine must yield
    bit-identical checkpoint streams on EVERY node, and both passes must
    converge after every heal."""
    oracle = run_partitioned(config, "interpreted")
    vectorized = run_partitioned(config, "vectorized")
    mismatches = compare_node_checkpoints(oracle, vectorized)
    identical = not mismatches and oracle.digest() == vectorized.digest()
    if not identical:
        # an oracle-vs-engine mismatch ships both sides' forensics
        for result in (oracle, vectorized):
            sim = getattr(result, "sim", None)
            if sim is not None and sim.health is not None:
                sim.health.write_bundle(
                    "oracle-vs-engine checkpoint mismatch",
                    {"mismatches": mismatches[:20]})
    return {
        "identical": identical,
        "converged": oracle.converged and vectorized.converged,
        "checkpoints": sum(len(c) for c in oracle.node_checkpoints),
        "mismatches": mismatches,
        "speedup": (round(oracle.seconds / vectorized.seconds, 3)
                    if vectorized.seconds > 0 else None),
        "oracle": oracle,
        "vectorized": vectorized,
    }


__all__ = [
    "PartitionConfig", "PartitionedChainSim", "PartitionedResult",
    "compare_node_checkpoints", "run_partitioned",
    "run_partitioned_differential",
]
