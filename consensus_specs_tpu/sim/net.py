"""Seeded adversarial message bus for the partitioned chain simulator
(docs/SIM.md "Partitioned network").

The single-node sim (sim/driver.py) feeds one Store with perfect
in-order delivery — the one condition fork choice exists to survive is
the one it never produces. This module is the missing network: N
simulated nodes exchange blocks, attestations and slashing evidence
through a bus whose every decision — drop, delay, duplicate, reorder,
partition cut — is a pure function of ``(seed, slot, edge, seq,
attempt)``. Nothing is drawn from wall clocks, delivery history, or
chain state, so a run is byte-reproducible and any prefix of it can be
resumed from a checkpoint (sim/checkpoint.py) with the remaining
deliveries identical to an uninterrupted run.

Delivery semantics per edge ``src -> dst``:

- a **timely block** (no drop, no delay dice) arrives the SAME slot in
  the mid-slot phase — after the destination's own proposal, before its
  attesters vote — exactly the mainnet timing attestation deadlines and
  proposer boost are built around (attesters must see the block or FFG
  participation starves); attestations and slashing evidence base at
  next slot (the aggregation interval);
- **drop** re-broadcasts: the attempt is lost and a retransmit is
  scheduled ``retransmit_delay`` slots later (gossip + sync in real
  clients); after ``max_attempts`` the message delivers unconditionally
  — the bus is lossy but *eventually reliable*, which is what makes the
  post-heal convergence bound provable rather than probabilistic;
- **delay** defers delivery up to ``delay_max`` extra slots;
- **duplicate** schedules a second copy (duplicate intake must ride the
  spec's own idempotence, not a bus-side dedup);
- **reorder**: everything due at one ``(slot, dst)`` is shuffled by a
  seeded stream before intake;
- **partition**: while a :class:`PartitionWindow` covers the send slot
  and the edge crosses the group cut, the message is HELD and delivered
  shortly after the heal (the mail the reconnecting peers exchange).

Chaos site ``sim.net`` (docs/RESILIENCE.md): fires on every non-lossless
edge schedule. A transient fault retries the pure schedule computation —
the message is redelivered identically, so the chain cannot move. A
deterministic fault QUARANTINES the edge to lossless delivery (the
always-correct degradation: a perfectly reliable link) with a recorded
event; with the breaker open, every later edge degrades the same way as
it next sends. Either way the run stays live and convergent.
"""
from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Set, Tuple

from .. import obs
from ..obs import metrics
from ..resilience import chaos, record_event, supervised

# message kinds on the wire (serialization dispatch for checkpointing)
KIND_BLOCK = "block"
KIND_ATTESTATION = "attestation"
KIND_SLASHING = "slashing"

# intra-slot delivery phases: TOP = before the destination's proposal
# (the ordinary intake), MID = after proposals, before attestations
# (where timely same-slot blocks land)
PHASE_TOP = 0
PHASE_MID = 1


@dataclass(frozen=True)
class PartitionWindow:
    """One scheduled partition episode: between ``start`` and ``end``
    (inclusive) the node set is split into ``groups``; edges crossing
    the cut hold their traffic until shortly after the heal."""

    start: int
    end: int
    groups: Tuple[Tuple[int, ...], ...]

    def group_of(self, node: int) -> int:
        for gi, members in enumerate(self.groups):
            if node in members:
                return gi
        return -1

    def crosses(self, src: int, dst: int) -> bool:
        return self.group_of(src) != self.group_of(dst)

    def to_dict(self) -> Dict[str, Any]:
        return {"start": self.start, "end": self.end,
                "groups": [list(g) for g in self.groups]}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PartitionWindow":
        return cls(start=int(d["start"]), end=int(d["end"]),
                   groups=tuple(tuple(int(n) for n in g)
                                for g in d["groups"]))


@dataclass(frozen=True)
class NetConfig:
    """Adversarial-delivery knobs. Defaults give a lossy, reordering
    network that still converges within a couple of epochs of a heal."""

    seed: int = 0
    nodes: int = 3
    p_drop: float = 0.08
    p_delay: float = 0.12
    delay_max: int = 2
    p_duplicate: float = 0.06
    max_attempts: int = 3          # drops before unconditional delivery
    retransmit_delay: int = 2      # slots between re-broadcast attempts
    heal_spread: int = 2           # held mail lands within this many slots

    def to_dict(self) -> Dict[str, Any]:
        return {"seed": self.seed, "nodes": self.nodes,
                "p_drop": self.p_drop, "p_delay": self.p_delay,
                "delay_max": self.delay_max,
                "p_duplicate": self.p_duplicate,
                "max_attempts": self.max_attempts,
                "retransmit_delay": self.retransmit_delay,
                "heal_spread": self.heal_spread}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "NetConfig":
        return cls(seed=int(d["seed"]), nodes=int(d["nodes"]),
                   p_drop=float(d["p_drop"]), p_delay=float(d["p_delay"]),
                   delay_max=int(d["delay_max"]),
                   p_duplicate=float(d["p_duplicate"]),
                   max_attempts=int(d["max_attempts"]),
                   retransmit_delay=int(d["retransmit_delay"]),
                   heal_spread=int(d["heal_spread"]))


def default_partitions(seed: int, slots: int, nodes: int,
                       count: int = 2) -> Tuple[PartitionWindow, ...]:
    """The scheduled partition plan: ``count`` non-overlapping windows,
    each splitting the node set in two — a pure function of
    ``(seed, slots, nodes, count)``."""
    if nodes < 2 or slots < 64:
        return ()
    rng = random.Random(f"sim-net:{seed}:partitions:{slots}:{nodes}:{count}")
    windows: List[PartitionWindow] = []
    # leave the first two epochs clean (the chain needs a justified
    # base) and the tail clear so the final heal can converge in-run
    lo, hi = 20, slots - 28
    if hi <= lo:
        return ()
    span = (hi - lo) // max(1, count)
    if span < 14:
        count = max(1, (hi - lo) // 14)
        span = (hi - lo) // count
    for i in range(count):
        seg_lo = lo + i * span
        length = rng.randint(10, min(18, max(10, span - 4)))
        if seg_lo + length >= hi:
            break
        start = seg_lo + rng.randint(0, max(1, span - length - 2))
        ids = list(range(nodes))
        rng.shuffle(ids)
        cut = rng.randint(1, nodes - 1)
        windows.append(PartitionWindow(
            start=start, end=start + length - 1,
            groups=(tuple(sorted(ids[:cut])), tuple(sorted(ids[cut:])))))
    return tuple(windows)


@dataclass
class _Entry:
    """One scheduled delivery."""

    deliver_slot: int
    dst: int
    src: int
    kind: str
    seq: int
    obj: Any
    phase: int = PHASE_TOP

    def to_dict(self) -> Dict[str, Any]:
        return {"deliver_slot": self.deliver_slot, "dst": self.dst,
                "src": self.src, "kind": self.kind, "seq": self.seq,
                "phase": self.phase,
                "ssz": bytes(self.obj.encode_bytes()).hex()}


# decoder table built per spec module (kind -> SSZ type attr)
_KIND_TYPES = {KIND_BLOCK: "SignedBeaconBlock",
               KIND_ATTESTATION: "Attestation",
               KIND_SLASHING: "AttesterSlashing"}


class MessageBus:
    """The seeded adversarial bus. One instance per run; fully
    serializable (``state_dict``/``restore_state``) so a checkpointed
    run resumes with identical in-flight traffic."""

    def __init__(self, config: NetConfig,
                 partitions: Tuple[PartitionWindow, ...] = ()) -> None:
        self.config = config
        self.partitions = tuple(partitions)
        self.queue: List[_Entry] = []
        self.seq = 0
        self.lossless_edges: Set[Tuple[int, int]] = set()
        self.stats: Dict[str, int] = {
            "sent": 0, "delivered": 0, "dropped_attempts": 0,
            "delayed": 0, "duplicated": 0, "held": 0,
            "quarantined_edges": 0,
        }

    # -- partition plan -------------------------------------------------

    def window_at(self, slot: int) -> Optional[PartitionWindow]:
        for w in self.partitions:
            if w.start <= slot <= w.end:
                return w
        return None

    def scheduled_windows(self) -> Tuple[Tuple[int, int], ...]:
        """The partition schedule as ``((start, end), ...)`` slot spans —
        the export the consensus watchdogs (obs/chain.py) gate on:
        finality stalls, participation droops and head disagreement
        INSIDE a scheduled window (or its post-heal grace) are the
        planned experiment, not the chain being sick. An unscheduled
        split — the same bus behavior with no exported window — is
        exactly what the split_brain watchdog exists to flag."""
        return tuple((int(w.start), int(w.end)) for w in self.partitions)

    # -- sending --------------------------------------------------------

    def send(self, slot: int, src: int, kind: str, obj: Any,
             extra_delay: int = 0) -> None:
        """Broadcast ``obj`` from ``src`` to every other node through
        the per-edge adversarial schedule."""
        seq = self.seq
        self.seq += 1
        self.stats["sent"] += 1
        for dst in range(self.config.nodes):
            if dst == src:
                continue
            self._schedule_edge(slot, src, dst, kind, obj, seq, extra_delay)

    def _schedule_edge(self, slot: int, src: int, dst: int, kind: str,
                       obj: Any, seq: int, extra_delay: int) -> None:
        edge = (src, dst)
        if edge in self.lossless_edges:
            # a quarantined edge is a perfect link: blocks timely
            # (same-slot mid-phase), everything else next slot
            if kind == KIND_BLOCK and extra_delay == 0:
                self.queue.append(_Entry(slot, dst, src, kind, seq, obj,
                                         PHASE_MID))
            else:
                self.queue.append(_Entry(slot + 1 + extra_delay, dst, src,
                                         kind, seq, obj))
            return

        def attempt() -> List[Tuple[int, int]]:
            # transient faults retry this pure computation — the
            # message is redelivered on an identical schedule
            chaos("sim.net")
            return self._plan_edge(slot, src, dst, kind, seq, extra_delay)

        def degraded() -> List[Tuple[int, int]]:
            # deterministic fault: the edge is quarantined to lossless
            # delivery — the always-correct network
            if edge not in self.lossless_edges:
                self.lossless_edges.add(edge)
                self.stats["quarantined_edges"] += 1
                metrics.count("sim.net.quarantined_edges")
                record_event("fallback", domain="sim.net",
                             capability="sim.net",
                             detail=f"edge {src}->{dst} quarantined to "
                                    "lossless delivery")
                obs.instant("sim.net.edge_quarantined", src=src, dst=dst,
                            slot=slot)
            base = ((slot, PHASE_MID)
                    if kind == KIND_BLOCK and extra_delay == 0
                    else (slot + 1 + extra_delay, PHASE_TOP))
            return [base]

        plans = supervised(attempt, domain="sim.net", capability="sim.net",
                           fallback=degraded)
        for deliver, phase in plans:
            self.queue.append(_Entry(deliver, dst, src, kind, seq, obj,
                                     phase))

    def _plan_edge(self, send_slot: int, src: int, dst: int, kind: str,
                   seq: int, extra_delay: int,
                   attempt: int = 0) -> List[Tuple[int, int]]:
        """Delivery ``(slot, phase)`` plan for one edge transmission — a
        pure function of ``(seed, send_slot, edge, kind, seq, attempt)``."""
        cfg = self.config
        rng = random.Random(f"sim-net:{cfg.seed}:{send_slot}:{src}>{dst}:"
                            f"{seq}:{attempt}")
        late_base = send_slot + 1 + extra_delay
        window = self.window_at(send_slot)
        if window is not None and window.crosses(src, dst):
            # held across the cut: delivered shortly after the heal
            self.stats["held"] += 1
            metrics.count("sim.net.held")
            return [(window.end + 1 + rng.randint(0, cfg.heal_spread),
                     PHASE_TOP)]
        r = rng.random()
        if r < cfg.p_drop and attempt < cfg.max_attempts:
            # this attempt is lost; a re-broadcast fires later (bounded:
            # after max_attempts the message delivers unconditionally)
            self.stats["dropped_attempts"] += 1
            metrics.count("sim.net.dropped")
            return self._plan_edge(send_slot + cfg.retransmit_delay, src,
                                   dst, kind, seq, extra_delay, attempt + 1)
        if r < cfg.p_drop + cfg.p_delay:
            self.stats["delayed"] += 1
            metrics.count("sim.net.delayed")
            deliver = (late_base + rng.randint(1, cfg.delay_max), PHASE_TOP)
        elif (kind == KIND_BLOCK and attempt == 0 and extra_delay == 0):
            # a timely block crosses the wire within its own slot and
            # lands mid-slot — after dst's proposal, before its
            # attesters vote (the mainnet attestation-deadline timing)
            deliver = (send_slot, PHASE_MID)
        else:
            deliver = (late_base, PHASE_TOP)
        out = [deliver]
        if rng.random() < cfg.p_duplicate:
            self.stats["duplicated"] += 1
            metrics.count("sim.net.duplicated")
            out.append((deliver[0] + rng.randint(0, 1), PHASE_TOP))
        return out

    # -- delivery -------------------------------------------------------

    def deliveries(self, slot: int, dst: int,
                   phase: int = PHASE_TOP) -> List[Tuple[str, Any, int]]:
        """Everything due for ``dst`` at ``slot``/``phase``,
        adversarially reordered by a seeded shuffle. Returns
        ``(kind, obj, src)``. Anything from an earlier slot is due at
        the TOP phase regardless of its scheduled phase."""
        def due_now(e: _Entry) -> bool:
            if e.dst != dst:
                return False
            if e.deliver_slot < slot:
                return phase == PHASE_TOP
            return e.deliver_slot == slot and e.phase == phase
        due = [e for e in self.queue if due_now(e)]
        if not due:
            return []
        self.queue = [e for e in self.queue if not due_now(e)]
        due.sort(key=lambda e: (e.deliver_slot, e.seq))
        rng = random.Random(f"sim-net:{self.config.seed}:order:{slot}:"
                            f"{dst}:{phase}")
        rng.shuffle(due)
        self.stats["delivered"] += len(due)
        metrics.count("sim.net.delivered", len(due))
        return [(e.kind, e.obj, e.src) for e in due]

    def pending(self) -> int:
        return len(self.queue)

    # -- checkpoint serialization --------------------------------------

    def state_dict(self) -> Dict[str, Any]:
        return {
            "seq": self.seq,
            "lossless_edges": sorted(list(e) for e in self.lossless_edges),
            "stats": dict(self.stats),
            "queue": [e.to_dict() for e in sorted(
                self.queue, key=lambda e: (e.deliver_slot, e.dst, e.seq))],
        }

    def restore_state(self, spec: Any, state: Dict[str, Any]) -> None:
        self.seq = int(state["seq"])
        self.lossless_edges = {tuple(e) for e in state["lossless_edges"]}
        self.stats = {k: int(v) for k, v in state["stats"].items()}
        self.queue = []
        for d in state["queue"]:
            ssz_type = getattr(spec, _KIND_TYPES[d["kind"]])
            obj = ssz_type.decode_bytes(bytes.fromhex(d["ssz"]))
            self.queue.append(_Entry(int(d["deliver_slot"]), int(d["dst"]),
                                     int(d["src"]), d["kind"],
                                     int(d["seq"]), obj,
                                     int(d.get("phase", PHASE_TOP))))


def partitions_to_dicts(windows: Tuple[PartitionWindow, ...]) -> List[Dict[str, Any]]:
    return [w.to_dict() for w in windows]


def partitions_from_dicts(dicts: List[Dict[str, Any]]) -> Tuple[PartitionWindow, ...]:
    return tuple(PartitionWindow.from_dict(d) for d in dicts)


__all__ = [
    "KIND_ATTESTATION", "KIND_BLOCK", "KIND_SLASHING", "MessageBus",
    "NetConfig", "PartitionWindow", "default_partitions",
    "partitions_from_dicts", "partitions_to_dicts",
]
