"""Simulation driver: feed a seeded scenario through the fork-choice
Store and the full state-transition path, with a differential mode that
holds the vectorized engine to bit-identity against the interpreted
oracle at every epoch checkpoint.

One :class:`ChainSim` owns one ``spec.Store`` and interprets the
scenario slot by slot, the way a (drastically simplified) honest client
plus a minority adversary would:

- ``on_tick`` advances store time every slot; proposer boost applies to
  timely blocks exactly as in the spec.
- honest proposers build on ``get_head(store)`` (so a winning fork
  branch is adopted — a real reorg); the fork-window adversary builds
  its own competing chain.
- committees attest every slot, split between the branches by the
  scenario's pure ``vote_split``; attestations arrive over the wire the
  NEXT slot (``on_attestation``) and ride along in blocks
  (``is_from_block=True``), both exactly like the spec's intake paths.
- equivocation events deliver attester-slashing evidence to the Store
  (``equivocating_indices``) and into the next canonical block
  (in-state slashing).
- at every epoch boundary the sim records a checkpoint digest —
  ``get_head`` root, head-state ``hash_tree_root``, FFG checkpoints —
  and prunes the Store at finality like a real client (the naive
  spec-shaped ``get_head`` is quadratic in live blocks; pruning keeps
  the live set bounded, and votes for pruned branches can never weigh a
  surviving candidate, so pruning is weight-neutral by construction).

Chaos sites (docs/RESILIENCE.md): ``sim.step`` fires at the top of
every slot step, ``sim.epoch`` at every epoch rollover — both BEFORE
any state mutation, so retries re-run a clean step. A deterministic
fault quarantines the site and the supervisor's fallback re-runs the
step on the interpreted-oracle path (counted in
``stats["degraded_steps"]``/``["degraded_epochs"]``); the engine's
bit-identity contract means degradation may slow the run but can never
change a checkpoint — the chaos differential tests assert exactly that.

Determinism: all simulation randomness comes from the scenario's
seed-derived streams; BLS signing is stubbed (``bls_active=False``)
unless ``config.sign``, so a run is a pure function of
``(config, engine mode)`` — and engine modes are bit-identical.
"""
from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from .. import engine, obs
from ..crypto import bls
from ..obs import chain as chain_health
from ..obs import metrics
from ..resilience import chaos, supervised
from ..specs import build_spec
from .scenario import Scenario, ScenarioConfig

ENGINE_MODES = ("interpreted", "vectorized")

# exception classes the spec's intake paths use as rejection control flow
_REJECTED = (AssertionError, KeyError, IndexError, ValueError)


def attestation_includable(spec, state, att) -> bool:
    """``process_attestation``'s rejection ladder (minus the signature,
    which the builder already made valid) against a proposal state —
    anything passing here is includable on that branch. Shared by the
    single-node and partitioned drivers."""
    data = att.data
    try:
        assert data.target.epoch in (spec.get_previous_epoch(state),
                                     spec.get_current_epoch(state))
        assert data.target.epoch == spec.compute_epoch_at_slot(data.slot)
        assert (data.slot + spec.MIN_ATTESTATION_INCLUSION_DELAY
                <= state.slot <= data.slot + spec.SLOTS_PER_EPOCH)
        assert data.index < spec.get_committee_count_per_slot(state, data.target.epoch)
        committee = spec.get_beacon_committee(state, data.slot, data.index)
        assert len(att.aggregation_bits) == len(committee)
        if hasattr(state, "current_epoch_participation"):
            spec.get_attestation_participation_flag_indices(
                state, data, state.slot - data.slot)
        elif data.target.epoch == spec.get_current_epoch(state):
            assert data.source == state.current_justified_checkpoint
        else:
            assert data.source == state.previous_justified_checkpoint
        return True
    except _REJECTED:
        return False


def slashing_includable(spec, state, slashing) -> bool:
    """``process_attester_slashing``'s preconditions against a proposal
    state (shared by both drivers)."""
    try:
        att_1, att_2 = slashing.attestation_1, slashing.attestation_2
        assert spec.is_slashable_attestation_data(att_1.data, att_2.data)
        assert spec.is_valid_indexed_attestation(state, att_1)
        assert spec.is_valid_indexed_attestation(state, att_2)
        epoch = spec.get_current_epoch(state)
        indices = set(att_1.attesting_indices) & set(att_2.attesting_indices)
        return any(spec.is_slashable_validator(state.validators[i], epoch)
                   for i in indices)
    except _REJECTED:
        return False


@dataclass
class SimResult:
    engine: str
    fork: str
    preset: str
    seed: int
    slots: int
    checkpoints: List[Dict[str, Any]]
    stats: Dict[str, int]
    scenario: Dict[str, int]
    seconds: float

    @property
    def slots_per_s(self) -> float:
        return self.slots / self.seconds if self.seconds > 0 else float("inf")

    def to_dict(self) -> Dict[str, Any]:
        return {
            "engine": self.engine,
            "fork": self.fork,
            "preset": self.preset,
            "seed": self.seed,
            "slots": self.slots,
            "seconds": round(self.seconds, 3),
            "slots_per_s": round(self.slots_per_s, 2),
            "scenario": dict(self.scenario),
            "stats": dict(self.stats),
            "checkpoints": list(self.checkpoints),
        }


@contextlib.contextmanager
def _engine_mode(mode: str):
    """Install one engine mode for the duration, restoring the previous
    installation after (the sim must never leak engine state into the
    caller's process)."""
    if mode not in ENGINE_MODES:
        raise ValueError(f"unknown engine mode {mode!r} (have {ENGINE_MODES})")
    was_vec = engine.is_vectorized()
    was_batch = engine.is_batched_attestations()
    if mode == "vectorized":
        engine.use_vectorized_epoch()
        engine.use_batched_attestations()
    else:
        engine.use_interpreted_epoch()
        engine.use_direct_attestations()
    try:
        yield
    finally:
        (engine.use_vectorized_epoch if was_vec else engine.use_interpreted_epoch)()
        (engine.use_batched_attestations if was_batch else engine.use_direct_attestations)()


class ChainSim:
    """One simulated chain run. Build with a config (or a prebuilt
    :class:`Scenario`), call :meth:`run` under the engine mode you want
    — or use :func:`run_sim` / :func:`run_differential` which manage
    the engine installation for you."""

    def __init__(self, config: ScenarioConfig,
                 scenario: Optional[Scenario] = None,
                 engine_label: str = "interpreted") -> None:
        from ..test_framework.genesis import create_genesis_state

        self.config = config
        self.scenario = scenario or Scenario(config)
        self.engine_label = engine_label
        self.spec = build_spec(config.fork, config.preset)
        spec = self.spec
        genesis = create_genesis_state(
            spec,
            [spec.MAX_EFFECTIVE_BALANCE] * config.validators,
            spec.MAX_EFFECTIVE_BALANCE,
        )
        anchor_block = spec.BeaconBlock(state_root=spec.hash_tree_root(genesis))
        self.store = spec.get_forkchoice_store(genesis, anchor_block)
        self.anchor_root = spec.hash_tree_root(anchor_block)

        self.fork_tip: Optional[bytes] = None
        self.prev_head: Optional[bytes] = None
        self.wire: List[Any] = []                   # attestations, next-slot delivery
        self.pools: Dict[str, List[Any]] = {"canonical": [], "fork": []}
        self.late_queue: List[Tuple[int, Any]] = []  # (deliver_slot, signed block)
        self.slashing_queue: List[Any] = []
        self.checkpoints: List[Dict[str, Any]] = []
        self.stats: Dict[str, int] = {
            "blocks_proposed": 0, "blocks_delivered": 0, "blocks_dropped": 0,
            "late_blocks": 0, "late_delivered": 0, "failed_proposals": 0,
            "attestations_sent": 0, "attestations_rejected": 0,
            "fork_blocks": 0, "reorgs": 0, "equivocations": 0,
            "slashings_included": 0, "empty_slots": 0,
            "slashed_proposer_slots": 0,
            "degraded_steps": 0, "degraded_epochs": 0, "pruned_blocks": 0,
        }
        self._oracle_forced = False
        self._last_pruned_epoch = 0
        # deterministic pool of never-yet-slashed equivocators
        import random as _random

        eq_rng = _random.Random(f"chain-sim:{config.seed}:equiv")
        self._equivocators = list(range(config.validators))
        eq_rng.shuffle(self._equivocators)
        self._step_states: Dict[Tuple[bytes, int], Any] = {}
        self._cur_slot = 0
        # the consensus health plane (obs/chain.py): the single-node sim
        # is a 1-node chain — finality/participation/reorg telemetry and
        # watchdogs apply; split-brain cannot (one view)
        self.health = chain_health.build(
            1, int(spec.SLOTS_PER_EPOCH),
            label=f"sim.{engine_label}", bundle_cb=self._forensic_payload)

    # -- plumbing -----------------------------------------------------------

    def _state_at(self, root: bytes, slot: int):
        """The chain state of ``root``'s branch advanced to ``slot``
        (read-only use; store states are never mutated). Cached per step."""
        key = (bytes(root), slot)
        cached = self._step_states.get(key)
        if cached is not None:
            return cached
        st = self.store.block_states[root]
        if int(st.slot) < slot:
            st = st.copy()
            self.spec.process_slots(st, self.spec.Slot(slot))
        self._step_states[key] = st
        return st

    def _is_ancestor(self, ancestor: bytes, root: bytes) -> bool:
        spec, store = self.spec, self.store
        try:
            slot = store.blocks[ancestor].slot
            return bytes(spec.get_ancestor(store, root, slot)) == bytes(ancestor)
        except KeyError:
            return False

    def _deliver_block(self, signed_block, late: bool = False) -> bool:
        """on_block + the spec's implied intake of the block's
        attestations and attester slashings (test_framework/fork_choice
        add_block semantics)."""
        spec, store = self.spec, self.store
        health = self.health
        msg_id = bytes(spec.hash_tree_root(signed_block.message)).hex()[:16] \
            if health is not None else ""
        phase = "late" if late else "top"
        try:
            spec.on_block(store, signed_block)
        except _REJECTED:
            self.stats["blocks_dropped"] += 1
            if health is not None:
                health.record_intake(0, self._cur_slot, phase, "block",
                                     msg_id, "rejected")
            return False
        block_slot = int(signed_block.message.slot)
        for att in signed_block.message.body.attestations:
            try:
                spec.on_attestation(store, att, is_from_block=True)
            except _REJECTED:
                self.stats["attestations_rejected"] += 1
            if health is not None:
                health.record_inclusion(block_slot, int(att.data.slot))
        for slashing in signed_block.message.body.attester_slashings:
            try:
                spec.on_attester_slashing(store, slashing)
            except _REJECTED:
                pass
        self.stats["blocks_delivered"] += 1
        if late:
            self.stats["late_delivered"] += 1
        if health is not None:
            health.record_intake(0, self._cur_slot, phase, "block", msg_id,
                                 "accepted")
        return True

    def _includable(self, state, att) -> bool:
        return attestation_includable(self.spec, state, att)

    def _slashing_includable(self, state, slashing) -> bool:
        return slashing_includable(self.spec, state, slashing)

    # -- per-slot mechanics -------------------------------------------------

    def _open_fork(self, slot: int) -> None:
        """The adversary forks from the canonical head's parent (a
        sibling contest) — or from the head itself when the parent is
        already pruned/unknown."""
        head = self.spec.get_head(self.store)
        parent = self.store.blocks[head].parent_root
        self.fork_tip = parent if parent in self.store.blocks else head
        metrics.count("sim.fork_windows")
        obs.instant("sim.fork_start", slot=slot)

    def _propose(self, slot: int, branch: str, late_by: int = 0) -> None:
        from ..test_framework.block import build_empty_block
        from ..test_framework.block_processing import state_transition_and_sign_block

        spec = self.spec
        tip = self.fork_tip if branch == "fork" else self.spec.get_head(self.store)
        if tip is None:
            return
        # the proposer's view at the proposal slot (read-only, cached):
        # attestation/slashing admission is judged against it, exactly as
        # process_attestation will judge it inside the transition below
        view = self._state_at(tip, slot)
        block = build_empty_block(spec, view, spec.Slot(slot))
        if view.validators[block.proposer_index].slashed:
            # a slashed proposer cannot propose (process_block_header
            # rejects it): the slot goes empty on this branch — the same
            # thing mainnet sees after a proposer is slashed
            self.stats["slashed_proposer_slots"] += 1
            return

        pool = self.pools[branch]
        included = 0
        for att in pool:
            if included >= int(spec.MAX_ATTESTATIONS):
                break
            if self._includable(view, att):
                block.body.attestations.append(att)
                included += 1
        if branch == "canonical" and self.slashing_queue:
            kept = []
            for slashing in self.slashing_queue:
                if (len(block.body.attester_slashings) < int(spec.MAX_ATTESTER_SLASHINGS)
                        and self._slashing_includable(view, slashing)):
                    block.body.attester_slashings.append(slashing)
                    self.stats["slashings_included"] += 1
                else:
                    kept.append(slashing)
            self.slashing_queue = kept

        try:
            pre = self.store.block_states[tip].copy()
            signed = state_transition_and_sign_block(spec, pre, block)
        except Exception:
            self.stats["failed_proposals"] += 1
            return
        self.stats["blocks_proposed"] += 1
        metrics.count("sim.blocks_proposed")
        if branch == "fork":
            self.stats["fork_blocks"] += 1
            self.fork_tip = spec.hash_tree_root(block)
        if late_by > 0:
            self.stats["late_blocks"] += 1
            self.late_queue.append((slot + late_by, signed))
        else:
            self._deliver_block(signed)

    def _attest(self, slot: int, plan) -> None:
        from ..test_framework.attestations import get_valid_attestation

        spec = self.spec
        head = spec.get_head(self.store)
        head_state = self._state_at(head, slot)
        fork_live = (plan.fork is not None and self.fork_tip is not None
                     and bytes(self.fork_tip) != bytes(head))
        support = plan.fork.support_at(slot) if fork_live else 0.0
        fork_state = self._state_at(self.fork_tip, slot) if fork_live else None

        epoch = spec.compute_epoch_at_slot(spec.Slot(slot))
        committees = int(spec.get_committee_count_per_slot(head_state, epoch))
        for index in range(committees):
            committee = spec.get_beacon_committee(
                head_state, spec.Slot(slot), spec.CommitteeIndex(index))
            fork_voters = (self.scenario.vote_split(slot, committee, support)
                           if support > 0 else set())
            canonical_voters = {int(i) for i in committee} - fork_voters
            for voters, state in ((canonical_voters, head_state),
                                  (fork_voters, fork_state)):
                if not voters or state is None:
                    continue
                try:
                    att = get_valid_attestation(
                        spec, state, slot=spec.Slot(slot),
                        index=spec.CommitteeIndex(index),
                        filter_participant_set=lambda comm, v=voters: comm & v,
                        signed=self.config.sign,
                    )
                except _REJECTED:
                    continue
                if not any(att.aggregation_bits):
                    continue
                self.wire.append(att)
                self.pools["canonical" if state is head_state else "fork"].append(att)
                self.stats["attestations_sent"] += 1
                metrics.count("sim.attestations")

    def _emit_equivocation(self, slot: int) -> None:
        from ..test_framework.attester_slashings import (
            get_valid_attester_slashing_by_indices,
        )

        spec = self.spec
        width = max(1, int(self.config.equivocation_width))
        if len(self._equivocators) < width:
            return
        indices = sorted(self._equivocators[:width])
        del self._equivocators[:width]
        state = self._state_at(spec.get_head(self.store), slot)
        try:
            slashing = get_valid_attester_slashing_by_indices(
                spec, state, indices, slot=spec.Slot(slot),
                signed_1=self.config.sign, signed_2=self.config.sign,
            )
        except _REJECTED:
            return
        try:
            spec.on_attester_slashing(self.store, slashing)
        except _REJECTED:
            return
        self.slashing_queue.append(slashing)
        self.stats["equivocations"] += 1
        metrics.count("sim.equivocations")
        obs.instant("sim.equivocation", slot=slot, width=width)

    def _node_view(self) -> Dict[str, Any]:
        """The single node's consensus view for the health plane."""
        spec, store = self.spec, self.store
        head = spec.get_head(store)
        return {
            "head": bytes(head).hex(),
            "head_slot": int(store.blocks[head].slot),
            "justified_epoch": int(store.justified_checkpoint.epoch),
            "finalized_epoch": int(store.finalized_checkpoint.epoch),
            "pending_blocks": len(self.late_queue),
            "pending_atts": len(self.wire),
            "fork_count": chain_health.fork_count(store),
        }

    def _step(self, slot: int, plan) -> None:
        spec, store = self.spec, self.store
        self._step_states.clear()
        self._cur_slot = slot
        spec.on_tick(store, store.genesis_time
                     + slot * int(spec.config.SECONDS_PER_SLOT))

        due = [entry for entry in self.late_queue if entry[0] <= slot]
        if due:
            self.late_queue = [e for e in self.late_queue if e[0] > slot]
            for _, signed in due:
                self._deliver_block(signed, late=True)

        wire, self.wire = self.wire, []
        for att in wire:
            try:
                spec.on_attestation(store, att, is_from_block=False)
            except _REJECTED:
                self.stats["attestations_rejected"] += 1

        # top-of-slot chain-health observation (post-intake, pre-proposal
        # — the same point the partitioned lane samples)
        if self.health is not None:
            self.health.on_slot(slot, [self._node_view()])

        if plan.equivocate:
            self._emit_equivocation(slot)

        if plan.fork is not None and slot == plan.fork.start:
            self._open_fork(slot)
        if plan.propose:
            self._propose(slot, "canonical", late_by=plan.late_by)
        else:
            self.stats["empty_slots"] += 1
        if plan.fork is not None and self.fork_tip is not None:
            self._propose(slot, "fork")
        if plan.fork is not None and slot == plan.fork.end:
            # window closes: surviving fork attestations compete for
            # inclusion on whichever branch won (the includable filter
            # rejects the rest); the adversary stops proposing
            self.pools["canonical"].extend(self.pools["fork"])
            self.pools["fork"] = []
            self.fork_tip = None

        self._attest(slot, plan)

        head = spec.get_head(store)
        if (self.prev_head is not None and bytes(head) != bytes(self.prev_head)
                and not self._is_ancestor(self.prev_head, head)):
            self.stats["reorgs"] += 1
            metrics.count("sim.reorgs")
            obs.instant("sim.reorg", slot=slot)
            if self.health is not None:
                self.health.record_reorg(
                    0, slot, chain_health.reorg_depth(store, self.prev_head,
                                                      head))
        self.prev_head = head

    # -- degradation + epoch rollover --------------------------------------

    @contextlib.contextmanager
    def _forced_oracle(self):
        """Quarantine response: the step runs on the interpreted oracle
        (bit-identical by the engine's contract), then the previous
        installation is restored."""
        was_vec = engine.is_vectorized()
        was_batch = engine.is_batched_attestations()
        engine.use_interpreted_epoch()
        engine.use_direct_attestations()
        try:
            yield
        finally:
            if was_vec:
                engine.use_vectorized_epoch()
            if was_batch:
                engine.use_batched_attestations()

    def _run_step(self, slot: int, plan) -> None:
        def attempt():
            chaos("sim.step")  # pre-mutation: a retry re-runs a clean step
            if self._oracle_forced:
                with self._forced_oracle():
                    self._step(slot, plan)
            else:
                self._step(slot, plan)

        def degraded():
            self.stats["degraded_steps"] += 1
            metrics.count("sim.degraded_steps")
            obs.instant("sim.degraded", site="sim.step", slot=slot)
            with self._forced_oracle():
                self._step(slot, plan)

        supervised(attempt, domain="sim", capability="sim.step",
                   fallback=degraded)

    def _epoch_rollover(self, slot: int) -> None:
        spec, store = self.spec, self.store

        def attempt():
            chaos("sim.epoch")

        def degraded():
            # a deterministic fault at epoch granularity parks the whole
            # remaining run on the oracle path (circuit-breaker response)
            self.stats["degraded_epochs"] += 1
            self._oracle_forced = True
            metrics.count("sim.degraded_epochs")
            obs.instant("sim.degraded", site="sim.epoch", slot=slot)

        supervised(attempt, domain="sim", capability="sim.epoch",
                   fallback=degraded)

        epoch = slot // int(spec.SLOTS_PER_EPOCH)
        head = spec.get_head(store)
        head_state = store.block_states[head]
        self.checkpoints.append({
            "epoch": epoch,
            "slot": slot,
            "head": bytes(head).hex(),
            "head_slot": int(store.blocks[head].slot),
            "state_root": bytes(spec.hash_tree_root(head_state)).hex(),
            "justified_epoch": int(store.justified_checkpoint.epoch),
            "finalized_epoch": int(store.finalized_checkpoint.epoch),
        })
        metrics.count("sim.epochs")
        if self.health is not None:
            self.health.on_epoch(
                epoch, slot,
                [chain_health.participation_rate(spec, head_state)],
                [int(store.finalized_checkpoint.epoch)])
        self._prune(slot)

    def _prune(self, slot: int) -> None:
        """Drop everything not descending from the finalized checkpoint
        (weight-neutral: a vote for a pruned branch forked off below the
        finalized slot, so its ancestor at any surviving candidate's slot
        can never equal that candidate)."""
        spec, store = self.spec, self.store
        fin = store.finalized_checkpoint
        fin_epoch = int(fin.epoch)
        if fin_epoch <= self._last_pruned_epoch:
            return
        self._last_pruned_epoch = fin_epoch
        fin_slot = spec.compute_start_slot_at_epoch(fin.epoch)
        keep = set()
        for root in list(store.blocks):
            try:
                if bytes(spec.get_ancestor(store, root, fin_slot)) == bytes(fin.root):
                    keep.add(bytes(root))
            except KeyError:
                continue
        dropped = [r for r in list(store.blocks) if bytes(r) not in keep]
        for root in dropped:
            del store.blocks[root]
            del store.block_states[root]
        for index in [i for i, m in store.latest_messages.items()
                      if bytes(m.root) not in keep]:
            del store.latest_messages[index]
        for cp in [c for c in store.checkpoint_states
                   if int(c.epoch) < fin_epoch and c != store.justified_checkpoint]:
            del store.checkpoint_states[cp]
        horizon = slot - int(spec.SLOTS_PER_EPOCH)
        for name in ("canonical", "fork"):
            self.pools[name] = [a for a in self.pools[name]
                                if int(a.data.slot) >= horizon]
        if dropped:
            self.stats["pruned_blocks"] += len(dropped)
            metrics.count("sim.pruned_blocks", len(dropped))

    # -- forensics ----------------------------------------------------------

    def _forensic_payload(self) -> Dict[str, Any]:
        """The single-node half of a chain forensic bundle: the Store
        dump + the (seeded) config — with the intake ring the plane
        itself adds."""
        import dataclasses

        from .checkpoint import store_to_dict

        return {
            "engine": self.engine_label,
            "slot": self._cur_slot,
            "config": dataclasses.asdict(self.config),
            "stats": dict(self.stats),
            "nodes": [{"id": 0,
                       "head": (bytes(self.prev_head).hex()
                                if self.prev_head is not None else None),
                       "store": store_to_dict(self.spec, self.store)}],
        }

    # -- entry point --------------------------------------------------------

    def run(self) -> SimResult:
        cfg = self.config
        spec = self.spec
        spe = int(spec.SLOTS_PER_EPOCH)
        was_bls = bls.bls_active
        bls.bls_active = bool(cfg.sign)
        t0 = time.perf_counter()
        try:
            with obs.span("sim.run", engine=self.engine_label, fork=cfg.fork,
                          preset=cfg.preset, seed=cfg.seed, slots=cfg.slots):
                for slot in range(1, cfg.slots + 1):
                    plan = self.scenario.plan(slot)
                    with obs.span("sim.slot", slot=slot):
                        self._run_step(slot, plan)
                    if (slot + 1) % spe == 0:
                        with obs.span("sim.epoch", slot=slot):
                            self._epoch_rollover(slot)
        finally:
            bls.bls_active = was_bls
            if self.health is not None:
                self.health.close()
        seconds = time.perf_counter() - t0
        return SimResult(
            engine=self.engine_label, fork=cfg.fork, preset=cfg.preset,
            seed=cfg.seed, slots=cfg.slots, checkpoints=self.checkpoints,
            stats=self.stats, scenario=self.scenario.summary(),
            seconds=seconds,
        )


def run_sim(config: ScenarioConfig, engine_mode: str = "interpreted",
            scenario: Optional[Scenario] = None) -> SimResult:
    """One full run under one engine mode (installation scoped + restored)."""
    sim = ChainSim(config, scenario=scenario, engine_label=engine_mode)
    with _engine_mode(engine_mode):
        result = sim.run()
    result.sim = sim  # forensic access (bundle on differential mismatch)
    return result


def compare_checkpoints(a: SimResult, b: SimResult) -> List[Dict[str, Any]]:
    """Field-level mismatches between two checkpoint streams."""
    mismatches: List[Dict[str, Any]] = []
    if len(a.checkpoints) != len(b.checkpoints):
        mismatches.append({"field": "checkpoint_count",
                           a.engine: len(a.checkpoints),
                           b.engine: len(b.checkpoints)})
    for ca, cb in zip(a.checkpoints, b.checkpoints):
        for fld in ("head", "state_root", "head_slot",
                    "justified_epoch", "finalized_epoch"):
            if ca[fld] != cb[fld]:
                mismatches.append({"epoch": ca["epoch"], "field": fld,
                                   a.engine: ca[fld], b.engine: cb[fld]})
    return mismatches


def run_differential(config: ScenarioConfig) -> Dict[str, Any]:
    """The acceptance contract: the same scenario through the interpreted
    oracle and through the vectorized engine (SoA epoch stages + batched
    attestations) must be bit-identical — same ``get_head`` root, same
    head-state ``hash_tree_root``, same FFG checkpoints — at EVERY epoch
    checkpoint. Returns both results plus the mismatch list (empty on
    success) and the vectorized-vs-oracle wall-clock speedup."""
    scenario = Scenario(config)
    oracle = run_sim(config, "interpreted", scenario=scenario)
    vectorized = run_sim(config, "vectorized", scenario=scenario)
    mismatches = compare_checkpoints(oracle, vectorized)
    if mismatches:
        # an oracle-vs-engine mismatch ships both sides' forensics (the
        # black-box bundle: store dump + intake ring + seeded config)
        for result in (oracle, vectorized):
            sim = getattr(result, "sim", None)
            if sim is not None and sim.health is not None:
                sim.health.write_bundle("oracle-vs-engine checkpoint mismatch",
                                        {"mismatches": mismatches[:20]})
    return {
        "identical": not mismatches,
        "checkpoints": len(oracle.checkpoints),
        "mismatches": mismatches,
        "speedup": (round(oracle.seconds / vectorized.seconds, 3)
                    if vectorized.seconds > 0 else None),
        "oracle": oracle,
        "vectorized": vectorized,
    }
