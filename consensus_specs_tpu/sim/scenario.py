"""Seeded scenario generator: the deterministic event timeline of one
simulated "mainnet day".

Determinism contract (docs/SIM.md): the ENTIRE timeline — which slots
are empty, where competing fork windows open and close, which fraction
of each committee votes the fork branch, which blocks arrive late and
by how much, which slots emit equivocation slashings, and every
per-validator vote assignment — is drawn from ``random.Random`` streams
derived only from ``(config.seed, slot)``. Nothing is drawn from chain
state, wall clocks, or global RNGs, so the same config replays the same
timeline in every process and under every engine mode; the driver's
differential pass depends on this. ``CONSENSUS_SPECS_TPU_SIM_SEED``
overrides the default seed for CI byte-reproducibility.

Grammar (one :class:`SlotPlan` per slot):

- ``propose`` — the canonical branch proposes at this slot (False =
  empty slot; the tip carries across the gap).
- ``late_by`` — the canonical proposal is withheld and delivered that
  many slots later (the proposer's block misses its slot: the next
  proposer builds on the OLD tip, and the late arrival becomes either
  an uncle or a short reorg).
- ``fork`` — the :class:`ForkWindow` covering this slot, if any: a
  competing branch forked from the canonical head's parent, proposing
  its own blocks while ``support`` of each committee votes for it.
  Windows that ``win`` swing (almost) the whole committee to the fork
  branch for their final slots — the reorg case; windows that lose
  starve and die.
- ``equivocate`` — this slot emits an attester-slashing pair (the
  double-vote evidence) for a few fresh validators: delivered to the
  Store (``equivocating_indices``) and included in the next canonical
  block (in-state slashing).
"""
from __future__ import annotations

import os
import random
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Set

SEED_ENV = "CONSENSUS_SPECS_TPU_SIM_SEED"


def seed_from_env(default: int = 0) -> int:
    """The explicit seed knob (satellite: CI reruns are byte-identical
    because the seed is pinned in the environment, not implicit)."""
    raw = os.environ.get(SEED_ENV, "").strip()
    if not raw:
        return default
    return int(raw, 0)


@dataclass(frozen=True)
class ScenarioConfig:
    """Knobs of one simulated chain run (defaults: a lively minimal-preset
    chain that still finalizes)."""

    seed: int = 0
    slots: int = 256
    fork: str = "altair"
    preset: str = "minimal"
    validators: int = 64
    # event densities (probabilities per slot unless noted)
    p_empty: float = 0.06
    p_fork: float = 0.05          # chance a fork window OPENS at an eligible slot
    fork_len_min: int = 3
    fork_len_max: int = 6
    fork_support_min: float = 0.2  # committee fraction voting the fork branch
    fork_support_max: float = 0.45
    p_fork_wins: float = 0.35     # fork windows that end in a reorg
    p_late: float = 0.05
    late_max: int = 3
    equivocations: int = 4        # attester-slashing events over the whole run
    equivocation_width: int = 2   # validators double-voting per event
    sign: bool = False            # real BLS signatures (slow; short runs only)

    def with_slots(self, slots: int) -> "ScenarioConfig":
        return replace(self, slots=slots)


@dataclass(frozen=True)
class ForkWindow:
    """One competing-branch episode."""

    start: int      # first slot the fork branch proposes at
    end: int        # last slot of the window (inclusive)
    support: float  # committee fraction voting the fork branch
    wins: bool      # True: votes swing to the fork at the end (reorg)

    # the final slots where a winning fork gets (almost) all votes
    SWING_SLOTS = 2
    SWING_SUPPORT = 0.9

    def support_at(self, slot: int) -> float:
        if self.wins and slot > self.end - self.SWING_SLOTS:
            return self.SWING_SUPPORT
        return self.support


@dataclass(frozen=True)
class SlotPlan:
    slot: int
    propose: bool = True
    late_by: int = 0
    fork: Optional[ForkWindow] = None
    equivocate: bool = False


@dataclass
class Scenario:
    """The precomputed timeline. ``plan(slot)`` is a pure lookup."""

    config: ScenarioConfig
    empty_slots: Set[int] = field(default_factory=set)
    late_blocks: Dict[int, int] = field(default_factory=dict)
    fork_windows: List[ForkWindow] = field(default_factory=list)
    equivocation_slots: Set[int] = field(default_factory=set)

    def __post_init__(self) -> None:
        cfg = self.config
        rng = random.Random(f"chain-sim:{cfg.seed}")
        windows: List[ForkWindow] = []
        slot = 2  # slot 0 is the anchor; leave slot 1 clean so the chain roots
        guard = max(0, cfg.slots - cfg.fork_len_max - 2)
        while slot < guard:
            if rng.random() < cfg.p_fork:
                length = rng.randint(cfg.fork_len_min, cfg.fork_len_max)
                windows.append(ForkWindow(
                    start=slot,
                    end=slot + length - 1,
                    support=rng.uniform(cfg.fork_support_min, cfg.fork_support_max),
                    wins=rng.random() < cfg.p_fork_wins,
                ))
                slot += length + 2  # windows never touch (one live fork at a time)
            else:
                slot += 1
        self.fork_windows = windows
        in_fork = {s for w in windows for s in range(w.start, w.end + 1)}

        for s in range(2, cfg.slots):
            if s in in_fork:
                continue  # fork slots always propose (the contest needs blocks)
            r = rng.random()
            if r < cfg.p_empty:
                self.empty_slots.add(s)
            elif r < cfg.p_empty + cfg.p_late:
                self.late_blocks[s] = rng.randint(1, cfg.late_max)

        # equivocation events: spread over the run, clear of the first two
        # epochs (the chain needs a justified base before slashing drama)
        eligible = [s for s in range(16, cfg.slots)
                    if s not in self.empty_slots and s not in in_fork]
        rng.shuffle(eligible)
        self.equivocation_slots = set(sorted(eligible[: cfg.equivocations]))

    def window_at(self, slot: int) -> Optional[ForkWindow]:
        for w in self.fork_windows:
            if w.start <= slot <= w.end:
                return w
        return None

    def plan(self, slot: int) -> SlotPlan:
        return SlotPlan(
            slot=slot,
            propose=slot not in self.empty_slots,
            late_by=self.late_blocks.get(slot, 0),
            fork=self.window_at(slot),
            equivocate=slot in self.equivocation_slots,
        )

    def vote_split(self, slot: int, members, support: float) -> Set[int]:
        """The fork-branch voter subset of one committee: a pure function
        of (seed, slot, member index) so both differential passes split
        identically."""
        rng = random.Random(f"chain-sim:{self.config.seed}:votes:{slot}")
        return {int(m) for m in sorted(int(x) for x in members)
                if rng.random() < support}

    def summary(self) -> Dict[str, int]:
        return {
            "slots": self.config.slots,
            "empty_slots": len(self.empty_slots),
            "late_blocks": len(self.late_blocks),
            "fork_windows": len(self.fork_windows),
            "planned_reorgs": sum(1 for w in self.fork_windows if w.wins),
            "equivocation_events": len(self.equivocation_slots),
        }
