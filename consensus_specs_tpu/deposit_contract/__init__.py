"""Deposit-contract reference model: the eth1 on-chain contract the
beacon chain bootstraps from, re-implemented as an executable Python
model with the exact on-chain semantics (incremental 32-depth SHA-256
Merkle tree, little-endian count mix-in, gwei validation rules).

Reference surface being modeled (NOT transcribed — this is an
independent implementation of the documented interface):
  solidity_deposit_contract/deposit_contract.sol (178 LoC Solidity):
    get_deposit_root() -> bytes32
    get_deposit_count() -> bytes (8, little-endian)
    deposit(pubkey[48], withdrawal_credentials[32], signature[96],
            deposit_data_root) payable
  specs/phase0/deposit-contract.md (semantics: incremental Merkle
  accumulator over DepositData hash_tree_roots, depth 32).

Design notes:
- The contract's root is definitionally equal to the SSZ
  hash_tree_root of List[DepositData, 2**32]: Merkle depth 32 over
  per-deposit container roots, then sha256(root || count_le64 ||
  bytes24(0)) — exactly SSZ's mix_in_length with the length in the
  first 8 bytes of the length chunk. Tests pin this equality against
  the SSZ library.
- Unlike the chain contract, the model can also *emit Merkle proofs*
  (the full tree is retained), so test harnesses can drive the spec's
  process_deposit / is_valid_merkle_branch (beacon-chain.md:742,1854)
  with real branches instead of hand-built ones.
- ABI surface: abi() returns the canonical JSON fragment a web3-style
  harness would bind against.
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import List, Optional

TREE_DEPTH = 32
MAX_DEPOSITS = 2**TREE_DEPTH
GWEI = 10**9
MIN_DEPOSIT_WEI = GWEI * 10**9  # 1 ETH in wei


def _sha256(x: bytes) -> bytes:
    return hashlib.sha256(x).digest()


def _zerohashes() -> List[bytes]:
    zh = [b"\x00" * 32]
    for _ in range(TREE_DEPTH):
        zh.append(_sha256(zh[-1] + zh[-1]))
    return zh


ZERO_HASHES = _zerohashes()


def compute_deposit_data_root(
    pubkey: bytes, withdrawal_credentials: bytes, amount_gwei: int, signature: bytes
) -> bytes:
    """SSZ hash_tree_root of DepositData computed with raw chunk hashing
    (the same fixed-shape reduction the on-chain code performs):
      pubkey_root  = H(pubkey || 0^16)
      sig_root     = H(H(sig[0:64]) || H(sig[64:96] || 0^32))
      node         = H(H(pubkey_root || wc) || H(amount_le8 || 0^24 || sig_root))
    """
    pubkey_root = _sha256(pubkey + b"\x00" * 16)
    sig_root = _sha256(
        _sha256(signature[:64]) + _sha256(signature[64:] + b"\x00" * 32)
    )
    amount_chunk = amount_gwei.to_bytes(8, "little") + b"\x00" * 24
    return _sha256(
        _sha256(pubkey_root + withdrawal_credentials)
        + _sha256(amount_chunk + sig_root)
    )


class DepositContractError(ValueError):
    """Model analog of a contract revert."""


@dataclass
class DepositEvent:
    pubkey: bytes
    withdrawal_credentials: bytes
    amount: bytes  # 8-byte little-endian gwei (as emitted on-chain)
    signature: bytes
    index: bytes  # 8-byte little-endian deposit index


@dataclass
class DepositContract:
    """Stateful model. `deposit` mirrors the payable entrypoint
    (value in wei); the incremental-tree `branch` is the O(log n)
    on-chain accumulator, while `leaves` additionally retains history
    for proof generation (test-harness affordance)."""

    branch: List[bytes] = field(default_factory=lambda: [b"\x00" * 32] * TREE_DEPTH)
    deposit_count: int = 0
    leaves: List[bytes] = field(default_factory=list)
    events: List[DepositEvent] = field(default_factory=list)

    # -- views ---------------------------------------------------------------

    def get_deposit_count(self) -> bytes:
        return self.deposit_count.to_bytes(8, "little")

    def get_deposit_root(self) -> bytes:
        node = b"\x00" * 32
        size = self.deposit_count
        for height in range(TREE_DEPTH):
            if size & 1:
                node = _sha256(self.branch[height] + node)
            else:
                node = _sha256(node + ZERO_HASHES[height])
            size >>= 1
        return _sha256(node + self.get_deposit_count() + b"\x00" * 24)

    # -- entrypoint ----------------------------------------------------------

    def deposit(
        self,
        pubkey: bytes,
        withdrawal_credentials: bytes,
        signature: bytes,
        deposit_data_root: bytes,
        value_wei: int,
    ) -> DepositEvent:
        if len(pubkey) != 48:
            raise DepositContractError("DepositContract: invalid pubkey length")
        if len(withdrawal_credentials) != 32:
            raise DepositContractError(
                "DepositContract: invalid withdrawal_credentials length"
            )
        if len(signature) != 96:
            raise DepositContractError("DepositContract: invalid signature length")
        if value_wei < MIN_DEPOSIT_WEI:
            raise DepositContractError("DepositContract: deposit value too low")
        if value_wei % GWEI != 0:
            raise DepositContractError(
                "DepositContract: deposit value not multiple of gwei"
            )
        amount_gwei = value_wei // GWEI
        node = compute_deposit_data_root(
            pubkey, withdrawal_credentials, amount_gwei, signature
        )
        if node != bytes(deposit_data_root):
            raise DepositContractError(
                "DepositContract: reconstructed DepositData does not match supplied deposit_data_root"
            )
        if self.deposit_count >= MAX_DEPOSITS - 1:
            raise DepositContractError("DepositContract: merkle tree full")

        event = DepositEvent(
            pubkey=bytes(pubkey),
            withdrawal_credentials=bytes(withdrawal_credentials),
            amount=amount_gwei.to_bytes(8, "little"),
            signature=bytes(signature),
            index=self.deposit_count.to_bytes(8, "little"),
        )
        self.events.append(event)
        self.leaves.append(node)

        # incremental insert: ripple the new leaf up to the first
        # even-sized level and park it there
        self.deposit_count += 1
        size = self.deposit_count
        for height in range(TREE_DEPTH):
            if size & 1:
                self.branch[height] = node
                break
            node = _sha256(self.branch[height] + node)
            size >>= 1
        return event

    # -- proof generation (model extra; the chain contract has no view
    #    for this — clients reconstruct from event logs the same way) ---------

    def get_merkle_proof(self, index: int) -> List[bytes]:
        """Branch for leaf `index` against the CURRENT root, 33 elements:
        32 tree siblings + the length mix-in chunk — exactly the shape
        process_deposit validates with is_valid_merkle_branch(depth =
        DEPOSIT_CONTRACT_TREE_DEPTH + 1) (beacon-chain.md:1854)."""
        if not 0 <= index < self.deposit_count:
            raise DepositContractError("proof index out of range")
        layer = list(self.leaves)
        proof: List[bytes] = []
        idx = index
        for height in range(TREE_DEPTH):
            sibling = idx ^ 1
            if sibling < len(layer):
                proof.append(layer[sibling])
            else:
                proof.append(ZERO_HASHES[height])
            nxt = []
            for i in range(0, len(layer), 2):
                left = layer[i]
                right = layer[i + 1] if i + 1 < len(layer) else ZERO_HASHES[height]
                nxt.append(_sha256(left + right))
            layer = nxt
            idx >>= 1
        proof.append(self.get_deposit_count() + b"\x00" * 24)
        return proof


def abi() -> list:
    """Canonical ABI fragment (the shape a web3 binding consumes)."""
    return [
        {
            "name": "get_deposit_root",
            "type": "function",
            "stateMutability": "view",
            "inputs": [],
            "outputs": [{"name": "", "type": "bytes32"}],
        },
        {
            "name": "get_deposit_count",
            "type": "function",
            "stateMutability": "view",
            "inputs": [],
            "outputs": [{"name": "", "type": "bytes"}],
        },
        {
            "name": "deposit",
            "type": "function",
            "stateMutability": "payable",
            "inputs": [
                {"name": "pubkey", "type": "bytes"},
                {"name": "withdrawal_credentials", "type": "bytes"},
                {"name": "signature", "type": "bytes"},
                {"name": "deposit_data_root", "type": "bytes32"},
            ],
            "outputs": [],
        },
        {
            "name": "DepositEvent",
            "type": "event",
            "inputs": [
                {"name": "pubkey", "type": "bytes", "indexed": False},
                {"name": "withdrawal_credentials", "type": "bytes", "indexed": False},
                {"name": "amount", "type": "bytes", "indexed": False},
                {"name": "signature", "type": "bytes", "indexed": False},
                {"name": "index", "type": "bytes", "indexed": False},
            ],
        },
    ]
