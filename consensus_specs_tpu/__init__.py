"""consensus_specs_tpu — a TPU-native executable consensus-spec framework.

Capabilities mirror the reference consensus-specs repo (eth2spec v1.1.10,
see SURVEY.md): SSZ type system + Merkleization, BLS12-381 signatures,
per-fork executable beacon-chain specs (phase0/altair/bellatrix/capella),
fork choice, light client sync, and a dual-mode pytest / test-vector
generator framework.

TPU-first design: the two compute-bound primitives — SHA-256 Merkleization
and BLS12-381 verification — are batched JAX/Pallas kernels selected through
backend hook points (`ssz.hashing.set_backend`, `crypto.bls.use_backend`),
so whole-epoch batches run on device while protocol control flow stays on
host (the boundary drawn by BASELINE.json).
"""

__version__ = "0.1.0"
