"""StatePlane: the structure-of-arrays mirror of BeaconState's
registry-axis fields.

The spec stores the validator registry as an array-of-structures
(``List[Validator, ...]`` of 8-field containers) because that is what the
SSZ Merkleization contract demands. Epoch processing, however, is
registry-axis math: every hot sub-transition (rewards, inactivity,
effective-balance hysteresis, registry churn, slashings) reads a few
columns across ALL validators and writes a few columns back — exactly
the access pattern a training stack vectorizes by transposing
per-example structs into per-field arrays. ``StatePlane`` is that
transpose: one NumPy array per registry column, extracted in one pass
and written back sparsely (only changed rows), so the SSZ backing's
dirty-tracked incremental re-root still sees a minimal diff.

Exactness contract: every integer op in the vectorized stages must be
bit-identical to the spec's unbounded-int arithmetic. uint64 columns
make that nontrivial — NumPy wraps silently on multiply overflow — so
the guarded helpers below prove (with Python-int bounds checks) that a
product fits 64 bits before taking the array fast path, and fall back
to exact object-int rows otherwise. The crosscheck harness
(engine/crosscheck.py) enforces the contract against the interpreted
oracle on randomized states.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

U64_MAX = 2**64 - 1


def u64(seq, n: int) -> np.ndarray:
    """One registry column as uint64 (FAR_FUTURE_EPOCH == 2**64-1 fits)."""
    return np.fromiter((int(v) for v in seq), dtype=np.uint64, count=n)


def mul_floordiv(a: np.ndarray, mul: int, div: int) -> np.ndarray:
    """Exact elementwise ``a * mul // div`` for a uint64 column.

    Fast path only when the extreme row provably fits 64 bits; otherwise
    every row goes through Python ints (exact, slow, rare)."""
    mul, div = int(mul), int(div)
    if a.size == 0:
        return a.copy()
    if mul == 0:
        return np.zeros_like(a)
    if int(a.max()) * mul <= U64_MAX:
        return (a * np.uint64(mul)) // np.uint64(div)
    return np.fromiter(
        (int(x) * mul // div for x in a.tolist()), dtype=np.uint64, count=a.size
    )


def pairwise_mul_floordiv(a: np.ndarray, b: np.ndarray, div: int) -> np.ndarray:
    """Exact elementwise ``a * b // div`` for two uint64 columns (the
    effective-balance x inactivity-score product, whose second factor is
    unbounded in adversarial states)."""
    div = int(div)
    if a.size == 0:
        return a.copy()
    if int(a.max()) * int(b.max()) <= U64_MAX:
        return (a * b) // np.uint64(div)
    return np.fromiter(
        (int(x) * int(y) // div for x, y in zip(a.tolist(), b.tolist())),
        dtype=np.uint64,
        count=a.size,
    )


def apply_deltas(balances: np.ndarray, rewards: np.ndarray, penalties: np.ndarray) -> np.ndarray:
    """One increase_balance/decrease_balance sweep: add rewards, then
    floor-at-zero subtract penalties (beacon-chain.md:1100-1117 order)."""
    b = balances + rewards
    return np.where(penalties > b, np.uint64(0), b - penalties)


class StatePlane:
    """Registry-axis columns of one BeaconState, plus sparse write-back.

    Columns are NumPy uint64/uint8/bool; altair-family columns are None
    on phase0 states. ``writeback_*`` methods push only rows that differ
    from the extraction snapshot, preserving the SSZ dirty-tracking
    economy of the interpreted path.
    """

    __slots__ = (
        "n",
        "balances",
        "effective_balance",
        "slashed",
        "activation_eligibility_epoch",
        "activation_epoch",
        "exit_epoch",
        "withdrawable_epoch",
        "previous_participation",
        "current_participation",
        "inactivity_scores",
        "fully_withdrawn_epoch",
    )

    def __init__(self, state) -> None:
        vals = list(state.validators)
        n = self.n = len(vals)
        self.balances = u64(state.balances, n)
        self.effective_balance = u64((v.effective_balance for v in vals), n)
        self.slashed = np.fromiter((bool(v.slashed) for v in vals), dtype=bool, count=n)
        self.activation_eligibility_epoch = u64(
            (v.activation_eligibility_epoch for v in vals), n
        )
        self.activation_epoch = u64((v.activation_epoch for v in vals), n)
        self.exit_epoch = u64((v.exit_epoch for v in vals), n)
        self.withdrawable_epoch = u64((v.withdrawable_epoch for v in vals), n)
        self.previous_participation: Optional[np.ndarray] = None
        self.current_participation: Optional[np.ndarray] = None
        self.inactivity_scores: Optional[np.ndarray] = None
        self.fully_withdrawn_epoch: Optional[np.ndarray] = None
        if vals and hasattr(vals[0], "fully_withdrawn_epoch"):  # capella family
            self.fully_withdrawn_epoch = u64(
                (v.fully_withdrawn_epoch for v in vals), n
            )
        if hasattr(state, "previous_epoch_participation"):
            self.previous_participation = np.fromiter(
                state.previous_epoch_participation, dtype=np.uint8, count=n
            )
            self.current_participation = np.fromiter(
                state.current_epoch_participation, dtype=np.uint8, count=n
            )
            self.inactivity_scores = u64(state.inactivity_scores, n)

    # -- masks ---------------------------------------------------------------

    def active_mask(self, epoch: int) -> np.ndarray:
        """is_active_validator per row (beacon-chain.md:630)."""
        e = np.uint64(int(epoch))
        return (self.activation_epoch <= e) & (e < self.exit_epoch)

    def eligible_mask(self, previous_epoch: int) -> np.ndarray:
        """get_eligible_validator_indices per row (beacon-chain.md:1430)."""
        pe = int(previous_epoch)
        return self.active_mask(pe) | (
            self.slashed & (np.uint64(pe + 1) < self.withdrawable_epoch)
        )

    def total_balance(self, mask: np.ndarray, increment: int) -> int:
        """get_total_balance over a row mask (max(increment, sum))."""
        return max(int(increment), int(self.effective_balance[mask].sum(dtype=object)))

    def total_active_balance(self, current_epoch: int, increment: int) -> int:
        return self.total_balance(self.active_mask(current_epoch), increment)

    def participation_mask(self, flag_index: int, epoch: int, previous_epoch: int) -> np.ndarray:
        """get_unslashed_participating_indices as a row mask: active at
        ``epoch``, flag set in that epoch's participation, not slashed."""
        part = (
            self.current_participation
            if epoch != previous_epoch
            else self.previous_participation
        )
        flag = np.uint8(1 << int(flag_index))
        return self.active_mask(epoch) & ((part & flag) != 0) & ~self.slashed

    # -- sparse write-back ---------------------------------------------------

    def writeback_balances(self, state, new: np.ndarray) -> None:
        for i in np.nonzero(new != self.balances)[0]:
            state.balances[int(i)] = int(new[i])
        self.balances = new

    def writeback_inactivity_scores(self, state, new: np.ndarray) -> None:
        for i in np.nonzero(new != self.inactivity_scores)[0]:
            state.inactivity_scores[int(i)] = int(new[i])
        self.inactivity_scores = new

    def writeback_validator_column(self, state, field: str, new: np.ndarray) -> None:
        old = getattr(self, field)
        for i in np.nonzero(new != old)[0]:
            setattr(state.validators[int(i)], _FIELD_NAMES[field], int(new[i]))
        setattr(self, field, new)


# plane column -> Validator container field
_FIELD_NAMES = {
    "effective_balance": "effective_balance",
    "activation_eligibility_epoch": "activation_eligibility_epoch",
    "activation_epoch": "activation_epoch",
    "exit_epoch": "exit_epoch",
    "withdrawable_epoch": "withdrawable_epoch",
    "fully_withdrawn_epoch": "fully_withdrawn_epoch",  # capella family
}
