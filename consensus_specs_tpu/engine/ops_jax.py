"""jnp device path for the engine's bulk delta arithmetic (opt-in via
``engine.use_backend("jax")``).

Only the embarrassingly-parallel elementwise piece moves to the device:
the altair flag-weight reward/penalty formula over the whole registry
(altair/beacon-chain.md:367-389). Everything stateful (masks, sums,
sequential churn) stays on host. The kernel runs under x64 so uint64
columns keep their width; callers must have proved the products fit 64
bits before dispatching (see engine.backend.delta_kernel) — the kernel
itself wraps on overflow like any fixed-width lane.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import enable_x64

# The engine's columns are uint64: without x64 jax silently truncates
# them to uint32, which is a correctness bug, not a performance choice.
# x64 is entered as a SCOPED context around trace + execution (never a
# global flag flip) so the repo's uint32-limb crypto kernels and any
# test sharing the process keep their default dtype world.


@partial(jax.jit, static_argnames=("leak", "penalize"))
def _flag_deltas_jit(increments, in_mask, eligible, brpi, weight, upi, active_increments,
                     wd, leak, penalize):
    base = increments * brpi
    reward = (base * weight * upi) // (active_increments * wd)
    penalty = (base * weight) // wd
    zero = jnp.uint64(0)
    if leak:  # static: participating rows earn nothing during a leak
        rewards = jnp.zeros_like(base)
    else:
        rewards = jnp.where(in_mask & eligible, reward, zero)
    penalties = (
        jnp.where(~in_mask & eligible, penalty, zero) if penalize else jnp.zeros_like(base)
    )
    return rewards, penalties


def flag_deltas(increments: np.ndarray, in_mask: np.ndarray, eligible: np.ndarray,
                brpi: int, weight: int, upi: int, active_increments: int,
                wd: int, leak: bool, penalize: bool):
    """One flag's (rewards, penalties) columns, computed on device and
    materialized back to host NumPy (conversions included in the x64
    scope — outside it, asarray would truncate uint64 to uint32)."""
    with enable_x64():
        r, p = _flag_deltas_jit(
            jnp.asarray(increments),
            jnp.asarray(in_mask),
            jnp.asarray(eligible),
            jnp.uint64(brpi),
            jnp.uint64(weight),
            jnp.uint64(upi),
            jnp.uint64(active_increments),
            jnp.uint64(wd),
            bool(leak),
            bool(penalize),
        )
        return np.asarray(r), np.asarray(p)
