"""Vectorized epoch-processing stages — batched gathers/scatters/
segment-sums over the StatePlane, bit-identical to the interpreted spec.

Each ``vectorized_process_*`` takes ``(spec, state)`` and mutates the
state exactly like the spec module's ``process_*`` of the same name.
Fork families are dispatched on ``spec.fork``: phase0 accounts rewards
from pending attestations (committee resolution via the cached shuffle
permutation), altair and later from participation flags; the
fork-specific quotients (PROPORTIONAL_SLASHING_MULTIPLIER*,
INACTIVITY_PENALTY_QUOTIENT*) are resolved the way the fork-delta
compiler resolved them into each flat module.

Every formula keeps the spec's operation ORDER (sequential floordivs,
per-pair increase-then-floored-decrease balance application) — integer
floordiv does not commute, and the crosscheck harness holds these
implementations to hash_tree_root equality with the interpreted oracle.
"""
from __future__ import annotations

import math
from typing import Dict, List, Sequence, Tuple

import numpy as np

from . import backend
from .attestations import EpochCommittees, attester_mask, resolve_members
from .plane import (
    U64_MAX,
    StatePlane,
    apply_deltas,
    mul_floordiv,
    pairwise_mul_floordiv,
)


def _epochs(spec, state) -> Tuple[int, int]:
    return int(spec.get_previous_epoch(state)), int(spec.get_current_epoch(state))


def _finality_delay(spec, state, prev: int) -> int:
    return prev - int(state.finalized_checkpoint.epoch)


def _is_leak(spec, state, prev: int) -> bool:
    return _finality_delay(spec, state, prev) > int(spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY)


# Fork-delta quotient resolution: the flat spec modules carry the
# suffixed constant their own fork resolved (altair re-tuned both
# quotients, bellatrix re-tuned them again, capella kept bellatrix's) —
# dispatch must name every production fork explicitly so a new fork
# can't silently inherit the wrong penalty family.
_BELLATRIX_FAMILY = ("bellatrix", "capella")


def _inactivity_quotient(spec) -> int:
    if spec.fork == "altair":
        return int(spec.INACTIVITY_PENALTY_QUOTIENT_ALTAIR)
    if spec.fork in _BELLATRIX_FAMILY:
        return int(spec.INACTIVITY_PENALTY_QUOTIENT_BELLATRIX)
    raise ValueError(f"no inactivity-quotient family for fork {spec.fork!r}")


def _slashings_multiplier(spec) -> int:
    if spec.fork == "phase0":
        return int(spec.PROPORTIONAL_SLASHING_MULTIPLIER)
    if spec.fork == "altair":
        return int(spec.PROPORTIONAL_SLASHING_MULTIPLIER_ALTAIR)
    if spec.fork in _BELLATRIX_FAMILY:
        return int(spec.PROPORTIONAL_SLASHING_MULTIPLIER_BELLATRIX)
    raise ValueError(f"no slashing-multiplier family for fork {spec.fork!r}")


# ---------------------------------------------------------------------------
# Justification & finalization
# ---------------------------------------------------------------------------

def vectorized_process_justification_and_finalization(spec, state) -> None:
    prev, cur = _epochs(spec, state)
    if cur <= int(spec.GENESIS_EPOCH) + 1:
        return
    plane = StatePlane(state)
    incr = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    tab = plane.total_active_balance(cur, incr)
    if spec.fork == "phase0":
        cache: Dict[int, EpochCommittees] = {}
        prev_resolved = resolve_members(
            spec, state, spec.get_matching_target_attestations(state, spec.Epoch(prev)), cache
        )
        cur_resolved = resolve_members(
            spec, state, spec.get_matching_target_attestations(state, spec.Epoch(cur)), cache
        )
        prev_bal = plane.total_balance(
            attester_mask(plane.n, prev_resolved, plane.slashed), incr
        )
        cur_bal = plane.total_balance(
            attester_mask(plane.n, cur_resolved, plane.slashed), incr
        )
    else:
        tt = int(spec.TIMELY_TARGET_FLAG_INDEX)
        prev_bal = plane.total_balance(plane.participation_mask(tt, prev, prev), incr)
        cur_bal = plane.total_balance(plane.participation_mask(tt, cur, prev), incr)
    # the FFG checkpoint/bitvector update is O(1): delegate to the spec
    spec.weigh_justification_and_finalization(
        state, spec.Gwei(tab), spec.Gwei(prev_bal), spec.Gwei(cur_bal)
    )


# ---------------------------------------------------------------------------
# Rewards & penalties — phase0 family (pending-attestation components)
# ---------------------------------------------------------------------------

class _Phase0Ctx:
    """Shared reward-accounting inputs: one committee resolution, one
    base-reward column, reused by all four component passes."""

    def __init__(self, spec, state, plane: StatePlane) -> None:
        self.spec, self.plane = spec, plane
        self.prev, self.cur = _epochs(spec, state)
        self.incr = int(spec.EFFECTIVE_BALANCE_INCREMENT)
        self.tab = plane.total_active_balance(self.cur, self.incr)
        sqrt_total = math.isqrt(self.tab)
        self.finality_delay = _finality_delay(spec, state, self.prev)
        self.leak = self.finality_delay > int(spec.MIN_EPOCHS_TO_INACTIVITY_PENALTY)
        self.eligible = plane.eligible_mask(self.prev)
        base = mul_floordiv(plane.effective_balance, int(spec.BASE_REWARD_FACTOR), sqrt_total)
        self.base = base // np.uint64(int(spec.BASE_REWARDS_PER_EPOCH))

        cache: Dict[int, EpochCommittees] = {}
        prev_e = spec.Epoch(self.prev)
        self.src = resolve_members(
            spec, state, spec.get_matching_source_attestations(state, prev_e), cache
        )
        by_id = {id(a): m for a, m in self.src}
        self.tgt = [
            (a, by_id[id(a)]) for a in spec.get_matching_target_attestations(state, prev_e)
        ]
        self.head = [
            (a, by_id[id(a)]) for a in spec.get_matching_head_attestations(state, prev_e)
        ]


def _component_deltas(ctx: _Phase0Ctx, resolved: Sequence) -> Tuple[np.ndarray, np.ndarray]:
    """get_attestation_component_deltas over one attestation set."""
    plane, n = ctx.plane, ctx.plane.n
    unslashed = attester_mask(n, resolved, plane.slashed)
    att_bal = plane.total_balance(unslashed, ctx.incr)
    rewards = np.zeros(n, dtype=np.uint64)
    penalties = np.zeros(n, dtype=np.uint64)
    rmask = ctx.eligible & unslashed
    if ctx.leak:
        rewards[rmask] = ctx.base[rmask]
    else:
        rewards[rmask] = mul_floordiv(
            ctx.base[rmask], att_bal // ctx.incr, ctx.tab // ctx.incr
        )
    pmask = ctx.eligible & ~unslashed
    penalties[pmask] = ctx.base[pmask]
    return rewards, penalties


def _inclusion_delay_rewards(ctx: _Phase0Ctx) -> np.ndarray:
    """get_inclusion_delay_deltas: stable sweep by inclusion delay,
    earliest attestation wins each index (beacon-chain.md:1496)."""
    spec, plane, n = ctx.spec, ctx.plane, ctx.plane.n
    rewards = np.zeros(n, dtype=np.uint64)
    prq = np.uint64(int(spec.PROPOSER_REWARD_QUOTIENT))
    unslashed_src = attester_mask(n, ctx.src, plane.slashed)
    assigned = np.zeros(n, dtype=bool)
    for a, members in sorted(ctx.src, key=lambda t: int(t[0].inclusion_delay)):
        if members.size == 0:
            continue
        sel = members[unslashed_src[members] & ~assigned[members]]
        if sel.size == 0:
            continue
        assigned[sel] = True
        base_sel = ctx.base[sel]
        proposer_cut = base_sel // prq
        rewards[int(a.proposer_index)] += proposer_cut.sum(dtype=np.uint64)
        rewards[sel] += (base_sel - proposer_cut) // np.uint64(int(a.inclusion_delay))
    return rewards


def _phase0_inactivity_penalties(ctx: _Phase0Ctx) -> np.ndarray:
    """get_inactivity_penalty_deltas (quadratic leak, phase0 form)."""
    spec, plane, n = ctx.spec, ctx.plane, ctx.plane.n
    penalties = np.zeros(n, dtype=np.uint64)
    if ctx.leak:
        target_unslashed = attester_mask(n, ctx.tgt, plane.slashed)
        brpe = np.uint64(int(spec.BASE_REWARDS_PER_EPOCH))
        prq = np.uint64(int(spec.PROPOSER_REWARD_QUOTIENT))
        flat = brpe * ctx.base - ctx.base // prq
        penalties[ctx.eligible] += flat[ctx.eligible]
        extra = ctx.eligible & ~target_unslashed
        penalties[extra] += mul_floordiv(
            plane.effective_balance[extra],
            ctx.finality_delay,
            int(spec.INACTIVITY_PENALTY_QUOTIENT),
        )
    return penalties


def _phase0_rewards_and_penalties(spec, state, plane: StatePlane) -> None:
    ctx = _Phase0Ctx(spec, state, plane)
    r_src, p_src = _component_deltas(ctx, ctx.src)
    r_tgt, p_tgt = _component_deltas(ctx, ctx.tgt)
    r_head, p_head = _component_deltas(ctx, ctx.head)
    rewards = r_src + r_tgt + r_head + _inclusion_delay_rewards(ctx)
    penalties = p_src + p_tgt + p_head + _phase0_inactivity_penalties(ctx)
    plane.writeback_balances(state, apply_deltas(plane.balances, rewards, penalties))


# ---------------------------------------------------------------------------
# Rewards & penalties — altair family (flag weights + inactivity scores)
# ---------------------------------------------------------------------------

def _flag_deltas(increments: np.ndarray, in_mask: np.ndarray, eligible: np.ndarray,
                 brpi: int, weight: int, upi: int, active_increments: int,
                 wd: int, leak: bool, penalize: bool) -> Tuple[np.ndarray, np.ndarray]:
    """get_flag_index_deltas arithmetic for one flag. Dispatches to the
    jitted device kernel only when the backend is on, the registry is
    large enough to amortize dispatch, AND the host-side bound proves the
    reward numerator fits 64 bits (the kernel has no exact fallback)."""
    n = increments.size
    hi = int(increments.max()) if n else 0
    fits = hi * brpi * weight * max(upi, 1) <= U64_MAX
    if fits and n >= backend.DEVICE_MIN_ROWS:
        out = backend.dispatch_delta_kernel(
            increments, in_mask, eligible, brpi, weight, upi,
            active_increments, wd, leak, penalize)
        if out is not None:
            return out
        # fall through: backend off, quarantined, or dispatch failed —
        # the numpy path below is the bit-identical host fallback
    rewards = np.zeros(n, dtype=np.uint64)
    penalties = np.zeros(n, dtype=np.uint64)
    base = mul_floordiv(increments, brpi, 1)
    rmask = in_mask & eligible
    if not leak:
        rewards[rmask] = mul_floordiv(base[rmask], weight * upi, active_increments * wd)
    if penalize:
        pmask = eligible & ~in_mask
        penalties[pmask] = mul_floordiv(base[pmask], weight, wd)
    return rewards, penalties


def _altair_inactivity_deltas(spec, plane: StatePlane, eligible: np.ndarray,
                              prev: int) -> Tuple[np.ndarray, np.ndarray]:
    n = plane.n
    rewards = np.zeros(n, dtype=np.uint64)
    penalties = np.zeros(n, dtype=np.uint64)
    tt = int(spec.TIMELY_TARGET_FLAG_INDEX)
    matching_target = plane.participation_mask(tt, prev, prev)
    pmask = eligible & ~matching_target
    denominator = int(spec.config.INACTIVITY_SCORE_BIAS) * _inactivity_quotient(spec)
    penalties[pmask] = pairwise_mul_floordiv(
        plane.effective_balance[pmask], plane.inactivity_scores[pmask], denominator
    )
    return rewards, penalties


def _altair_rewards_and_penalties(spec, state, plane: StatePlane) -> None:
    prev, cur = _epochs(spec, state)
    incr = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    tab = plane.total_active_balance(cur, incr)
    brpi = incr * int(spec.BASE_REWARD_FACTOR) // math.isqrt(tab)
    leak = _is_leak(spec, state, prev)
    eligible = plane.eligible_mask(prev)
    increments = plane.effective_balance // np.uint64(incr)
    active_increments = tab // incr
    wd = int(spec.WEIGHT_DENOMINATOR)
    head = int(spec.TIMELY_HEAD_FLAG_INDEX)

    deltas: List[Tuple[np.ndarray, np.ndarray]] = []
    for flag_index, weight in enumerate(spec.PARTICIPATION_FLAG_WEIGHTS):
        in_mask = plane.participation_mask(flag_index, prev, prev)
        upi = plane.total_balance(in_mask, incr) // incr
        deltas.append(
            _flag_deltas(increments, in_mask, eligible, brpi, int(weight), upi,
                         active_increments, wd, leak, flag_index != head)
        )
    deltas.append(_altair_inactivity_deltas(spec, plane, eligible, prev))

    balances = plane.balances
    for rewards, penalties in deltas:  # the spec applies pair by pair
        balances = apply_deltas(balances, rewards, penalties)
    plane.writeback_balances(state, balances)


def vectorized_process_rewards_and_penalties(spec, state) -> None:
    if int(spec.get_current_epoch(state)) == int(spec.GENESIS_EPOCH):
        return
    plane = StatePlane(state)
    if spec.fork == "phase0":
        _phase0_rewards_and_penalties(spec, state, plane)
    else:
        _altair_rewards_and_penalties(spec, state, plane)


# ---------------------------------------------------------------------------
# Inactivity-score updates (altair+)
# ---------------------------------------------------------------------------

def vectorized_process_inactivity_updates(spec, state) -> None:
    prev, cur = _epochs(spec, state)
    if cur == int(spec.GENESIS_EPOCH):
        return
    plane = StatePlane(state)
    tt = int(spec.TIMELY_TARGET_FLAG_INDEX)
    participating = plane.participation_mask(tt, prev, prev)
    eligible = plane.eligible_mask(prev)
    scores = plane.inactivity_scores.copy()

    dec = eligible & participating
    scores[dec] -= np.minimum(np.uint64(1), scores[dec])
    inc = eligible & ~participating
    scores[inc] += np.uint64(int(spec.config.INACTIVITY_SCORE_BIAS))
    if not _is_leak(spec, state, prev):
        recovery = np.uint64(int(spec.config.INACTIVITY_SCORE_RECOVERY_RATE))
        scores[eligible] -= np.minimum(recovery, scores[eligible])
    plane.writeback_inactivity_scores(state, scores)


# ---------------------------------------------------------------------------
# Effective-balance hysteresis
# ---------------------------------------------------------------------------

def vectorized_process_effective_balance_updates(spec, state) -> None:
    plane = StatePlane(state)
    incr = np.uint64(int(spec.EFFECTIVE_BALANCE_INCREMENT))
    hysteresis = np.uint64(
        int(spec.EFFECTIVE_BALANCE_INCREMENT) // int(spec.HYSTERESIS_QUOTIENT)
    )
    down = hysteresis * np.uint64(int(spec.HYSTERESIS_DOWNWARD_MULTIPLIER))
    up = hysteresis * np.uint64(int(spec.HYSTERESIS_UPWARD_MULTIPLIER))
    balances, eff = plane.balances, plane.effective_balance
    needs_update = (balances + down < eff) | (eff + up < balances)
    trimmed = np.minimum(
        balances - balances % incr, np.uint64(int(spec.MAX_EFFECTIVE_BALANCE))
    )
    plane.writeback_validator_column(
        state, "effective_balance", np.where(needs_update, trimmed, eff)
    )


# ---------------------------------------------------------------------------
# Registry updates (eligibility, ejections, activation churn)
# ---------------------------------------------------------------------------

def vectorized_process_registry_updates(spec, state) -> None:
    plane = StatePlane(state)
    cur = int(spec.get_current_epoch(state))
    far = np.uint64(U64_MAX)

    # Activation-queue eligibility
    queue_eligible = (plane.activation_eligibility_epoch == far) & (
        plane.effective_balance == np.uint64(int(spec.MAX_EFFECTIVE_BALANCE))
    )
    new_eligibility = np.where(
        queue_eligible, np.uint64(cur + 1), plane.activation_eligibility_epoch
    )

    # Ejections: initiate_validator_exit's queue is sequential state —
    # simulate (queue epoch, churn-at-epoch) scalars over the masked rows
    # in index order; everything else stays vectorized.
    active_cur = plane.active_mask(cur)
    eject_rows = np.nonzero(
        active_cur & (plane.effective_balance <= np.uint64(int(spec.config.EJECTION_BALANCE)))
    )[0]
    new_exit = plane.exit_epoch.copy()
    new_withdrawable = plane.withdrawable_epoch.copy()
    churn_limit = max(
        int(spec.config.MIN_PER_EPOCH_CHURN_LIMIT),
        int(active_cur.sum()) // int(spec.config.CHURN_LIMIT_QUOTIENT),
    )
    activation_exit_epoch = cur + 1 + int(spec.MAX_SEED_LOOKAHEAD)
    known_exits = new_exit[new_exit != far]
    queue_epoch = max(
        int(known_exits.max()) if known_exits.size else 0, activation_exit_epoch
    )
    churn = int((new_exit == np.uint64(queue_epoch)).sum())
    withdrawability_delay = int(spec.config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY)
    for i in eject_rows:
        if new_exit[i] != far:
            continue
        if churn >= churn_limit:
            queue_epoch += 1
            churn = 0
        withdrawable_at = queue_epoch + withdrawability_delay
        if queue_epoch > U64_MAX or withdrawable_at > U64_MAX:
            # the spec surfaces this as Epoch()'s uint64 bound check
            raise ValueError(f"Epoch out of range: {withdrawable_at}")
        new_exit[i] = queue_epoch
        new_withdrawable[i] = withdrawable_at
        churn += 1

    # Dequeue activations up to the churn limit, (eligibility epoch, index)
    # order — stable argsort on the epoch column IS that order.
    finalized = np.uint64(int(state.finalized_checkpoint.epoch))
    candidates = np.nonzero((new_eligibility <= finalized) & (plane.activation_epoch == far))[0]
    order = candidates[np.argsort(new_eligibility[candidates], kind="stable")]
    new_activation = plane.activation_epoch.copy()
    new_activation[order[:churn_limit]] = np.uint64(activation_exit_epoch)

    plane.writeback_validator_column(state, "activation_eligibility_epoch", new_eligibility)
    plane.writeback_validator_column(state, "exit_epoch", new_exit)
    plane.writeback_validator_column(state, "withdrawable_epoch", new_withdrawable)
    plane.writeback_validator_column(state, "activation_epoch", new_activation)


# ---------------------------------------------------------------------------
# Slashings
# ---------------------------------------------------------------------------

def vectorized_process_slashings(spec, state) -> None:
    plane = StatePlane(state)
    cur = int(spec.get_current_epoch(state))
    incr = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    total_balance = plane.total_active_balance(cur, incr)
    adjusted = min(
        sum(int(s) for s in state.slashings) * _slashings_multiplier(spec),
        total_balance,
    )
    target_epoch = cur + int(spec.EPOCHS_PER_SLASHINGS_VECTOR) // 2
    mask = plane.slashed & (plane.withdrawable_epoch == np.uint64(target_epoch))
    if not mask.any():
        return
    quotients = plane.effective_balance[mask] // np.uint64(incr)
    penalties = mul_floordiv(quotients, adjusted, total_balance) * np.uint64(incr)
    balances = plane.balances.copy()
    hit = balances[mask]
    balances[mask] = np.where(penalties > hit, np.uint64(0), hit - penalties)
    plane.writeback_balances(state, balances)


# ---------------------------------------------------------------------------
# Full withdrawals (capella family)
# ---------------------------------------------------------------------------

def vectorized_process_full_withdrawals(spec, state) -> None:
    """Capella's registry sweep: the fully-withdrawable mask (eth1
    credential prefix, withdrawable_epoch <= epoch < fully_withdrawn_epoch)
    is computed as one vector compare; only the hit rows take the spec's
    sequential withdraw_balance path (the withdrawals_queue append order
    and withdrawal_index increments are sequential state, exactly like
    the exit queue in registry updates)."""
    plane = StatePlane(state)
    cur = int(spec.get_current_epoch(state))
    prefix = bytes(spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX)[:1]
    eth1_credentialed = np.fromiter(
        (bytes(v.withdrawal_credentials)[:1] == prefix for v in state.validators),
        dtype=bool,
        count=plane.n,
    )
    e = np.uint64(cur)
    mask = (
        eth1_credentialed
        & (plane.withdrawable_epoch <= e)
        & (e < plane.fully_withdrawn_epoch)
    )
    for i in np.nonzero(mask)[0]:  # index order == the spec's loop order
        idx = int(i)
        spec.withdraw_balance(state, spec.ValidatorIndex(idx), state.balances[idx])
        state.validators[idx].fully_withdrawn_epoch = cur
