"""Engine compute-backend hook — the ops/ convention applied to the
protocol plane.

The NumPy host path is always available and always correct; the jnp
device path is OPT-IN, exactly like ``bls.use_backend("jax")`` and
``use_device_hasher()`` on the crypto plane. Stages route their bulk
elementwise delta arithmetic through :func:`delta_kernel` when the jax
backend is active AND the row count clears ``DEVICE_MIN_ROWS`` (a
device dispatch costs ~100us; small registries never win) AND the
stage's own overflow guard proved the products fit 64 bits (the jitted
kernel wraps silently where NumPy's guarded helpers would fall back to
exact object ints — so the guard decides the dispatch, not the kernel).
"""
from __future__ import annotations

from typing import Optional

_active = "numpy"

DEVICE_MIN_ROWS = 4096  # below this, dispatch overhead beats the kernel
_DEFAULT_DEVICE_MIN_ROWS = 4096


def use_backend(name: str = "numpy") -> None:
    """Select the engine compute backend: ``numpy`` (host, default) or
    ``jax`` (jitted uint64 kernels; requires jax importable)."""
    global _active, DEVICE_MIN_ROWS
    if name not in ("numpy", "jax"):
        raise ValueError(f"unknown engine backend {name!r} (have numpy, jax)")
    if name == "jax":
        from . import ops_jax  # noqa: F401  (import error = backend unavailable)
    else:
        DEVICE_MIN_ROWS = _DEFAULT_DEVICE_MIN_ROWS
    _active = name


def active() -> str:
    return _active


def delta_kernel() -> Optional[object]:
    """The jitted flag-delta kernel when the jax backend is active, else
    None (callers take the NumPy path)."""
    if _active != "jax":
        return None
    from . import ops_jax

    return ops_jax.flag_deltas
