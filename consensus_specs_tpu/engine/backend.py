"""Engine compute-backend hook — the ops/ convention applied to the
protocol plane.

The NumPy host path is always available and always correct; the jnp
device path is OPT-IN, exactly like ``bls.use_backend("jax")`` and
``use_device_hasher()`` on the crypto plane. Stages route their bulk
elementwise delta arithmetic through :func:`dispatch_delta_kernel` when
the jax backend is active AND the row count clears ``DEVICE_MIN_ROWS``
(a device dispatch costs ~100us; small registries never win) AND the
stage's own overflow guard proved the products fit 64 bits (the jitted
kernel wraps silently where NumPy's guarded helpers would fall back to
exact object ints — so the guard decides the dispatch, not the kernel).

Resilience (consensus_specs_tpu/resilience): selecting or dispatching
the jax backend runs supervised — an unimportable jax quarantines the
``engine.jax`` capability and stays on numpy with a recorded event; a
transient dispatch failure retries with backoff; a deterministic one
(miscompile-class) quarantines the backend so every later stage call
takes the bit-identical numpy path. Chaos points ``engine.import`` and
``engine.dispatch`` let tests inject all three fault classes.
"""
from __future__ import annotations

from typing import Optional

from .. import obs
from ..resilience import chaos, is_quarantined, record_event, supervised

_active = "numpy"

DEVICE_MIN_ROWS = 4096  # below this, dispatch overhead beats the kernel
_DEFAULT_DEVICE_MIN_ROWS = 4096

CAPABILITY = "engine.jax"


def use_backend(name: str = "numpy") -> str:
    """Select the engine compute backend: ``numpy`` (host, default) or
    ``jax`` (jitted uint64 kernels). Returns the backend actually
    installed: asking for ``jax`` when it is quarantined or unimportable
    degrades to ``numpy`` with a recorded event instead of raising."""
    global _active, DEVICE_MIN_ROWS
    if name not in ("numpy", "jax"):
        raise ValueError(f"unknown engine backend {name!r} (have numpy, jax)")
    if name == "jax":
        def _probe_import():
            chaos("engine.import")
            from ..sched import configure_compile_cache

            configure_compile_cache()  # knob-gated; before any jit builds
            from . import ops_jax  # noqa: F401  (import error = unavailable)

        try:
            supervised(_probe_import, domain="engine", capability=CAPABILITY)
        except Exception:
            # quarantined (event already recorded): numpy takes over
            _active = "numpy"
            DEVICE_MIN_ROWS = _DEFAULT_DEVICE_MIN_ROWS
            return _active
    else:
        DEVICE_MIN_ROWS = _DEFAULT_DEVICE_MIN_ROWS
    _active = name
    return _active


def active() -> str:
    return _active


def delta_kernel() -> Optional[object]:
    """The jitted flag-delta kernel when the jax backend is active (and
    not quarantined), else None (callers take the NumPy path)."""
    if _active != "jax" or is_quarantined(CAPABILITY):
        return None
    from . import ops_jax

    return ops_jax.flag_deltas


def dispatch_delta_kernel(*args) -> Optional[tuple]:
    """Supervised device dispatch of the flag-delta kernel.

    Returns the kernel's (rewards, penalties) or None when the caller
    must take the NumPy path — backend off, quarantined, or the dispatch
    just failed terminally (in which case the capability is now
    quarantined and the event recorded). Transient faults retry in
    place; the numpy fallback is bit-identical by the crosscheck
    harness's guarantee, so degradation never changes results.
    """
    kernel = delta_kernel()
    if kernel is None:
        return None

    def _dispatch():
        chaos("engine.dispatch")
        return kernel(*args)

    rows = getattr(args[0], "shape", (0,))[0] if args else 0
    try:
        with obs.kernel_span("engine.delta_kernel", rows=int(rows)):
            return supervised(_dispatch, domain="engine", capability=CAPABILITY)
    except Exception as e:
        # supervised() already quarantined + recorded; belt-and-braces in
        # case classification re-raised without a capability
        record_event("fallback", domain="engine", capability=CAPABILITY,
                     detail=f"delta kernel dispatch failed: {type(e).__name__}: {e}")
        return None
