"""Vectorized state-transition engine: structure-of-arrays epoch
processing behind the compiled spec modules.

The crypto plane (BLS, KZG, SHA-256) got device-batched rounds ago; the
protocol plane still ran the spec's per-validator Python loops. This
subsystem is the protocol plane's batching layer:

- :mod:`plane` — ``StatePlane``, the SoA mirror of BeaconState's
  registry-axis columns, with exact (overflow-guarded) uint64 helpers
  and sparse write-back that preserves SSZ dirty-tracking.
- :mod:`stages` — vectorized ``process_*`` implementations of the hot
  epoch sub-transitions for the phase0 and altair fork families.
- :mod:`backend` / :mod:`ops_jax` — the NumPy-always / jnp-opt-in
  backend hook, the ``ops/`` convention applied to protocol math.
- :mod:`crosscheck` — the differential harness that holds every stage
  to hash_tree_root bit-identity against the interpreted oracle on
  randomized states (epoch processing on the host reference path is the
  oracle here, exactly as ``crypto/`` is the oracle for ``ops/``).

Install model: ``use_vectorized_epoch()`` swaps the stage functions in
every built (and every future) spec module via the specs.build module
hook — the same switchable-backend shape as ``use_device_hasher()`` and
``bls.use_backend("jax")``. Wrappers keep the interpreted function on
``__wrapped__`` and preserve ``__name__`` so ``epoch_process_steps()``
staging, generators, and the replayer see the same public surface
either way. ``use_interpreted_epoch()`` restores the originals.
"""
from __future__ import annotations

from typing import Dict

from .. import obs
from ..specs import build as _build
from . import stages
from .backend import active as backend_name  # noqa: F401  (public surface)
from .backend import use_backend

__all__ = [
    "use_vectorized_epoch",
    "use_interpreted_epoch",
    "is_vectorized",
    "use_backend",
    "backend_name",
    "STAGE_NAMES",
    "SUPPORTED_FORKS",
]

# The hot registry-axis sub-transitions with SoA implementations.
STAGE_NAMES = (
    "process_justification_and_finalization",
    "process_rewards_and_penalties",
    "process_inactivity_updates",
    "process_effective_balance_updates",
    "process_registry_updates",
    "process_slashings",
)

# Production chain only: R&D branches (sharding/custody_game/das/eip4844)
# may re-shape epoch processing and are never auto-wrapped.
SUPPORTED_FORKS = ("phase0", "altair", "bellatrix", "capella")

_enabled = False


def _wrap_stage(spec, name: str):
    impl = getattr(stages, f"vectorized_{name}")
    interpreted = getattr(spec, name)

    def wrapped(state):
        with obs.span(f"epoch.{name}", fork=spec.fork, engine="vectorized"):
            return impl(spec, state)

    wrapped.__name__ = name
    wrapped.__qualname__ = f"engine.{name}[{spec.fork}]"
    wrapped.__doc__ = interpreted.__doc__
    wrapped.__wrapped__ = interpreted
    wrapped.engine_vectorized = True
    return wrapped


def _install_on(spec) -> None:
    """specs.build module hook: swap stage functions on one module."""
    if getattr(spec, "fork", None) not in SUPPORTED_FORKS:
        return
    for name in STAGE_NAMES:
        current = getattr(spec, name, None)
        if current is None or getattr(current, "engine_vectorized", False):
            continue
        setattr(spec, name, _wrap_stage(spec, name))


def _uninstall_from(spec) -> None:
    for name in STAGE_NAMES:
        current = getattr(spec, name, None)
        if current is not None and getattr(current, "engine_vectorized", False):
            setattr(spec, name, current.__wrapped__)


def use_vectorized_epoch() -> None:
    """Route the hot epoch stages of every built (and future) spec module
    through the SoA engine. Idempotent."""
    global _enabled
    _enabled = True
    _build.register_module_hook(_install_on)


def use_interpreted_epoch() -> None:
    """Restore the interpreted spec functions everywhere. Idempotent."""
    global _enabled
    _enabled = False
    _build.unregister_module_hook(_install_on)
    for mod in _build.cached_modules():
        _uninstall_from(mod)


def is_vectorized() -> bool:
    return _enabled


def stage_status(spec) -> Dict[str, bool]:
    """{stage name: engine-installed?} for one spec module (diagnostics)."""
    return {
        name: getattr(getattr(spec, name, None), "engine_vectorized", False)
        for name in STAGE_NAMES
        if hasattr(spec, name)
    }
