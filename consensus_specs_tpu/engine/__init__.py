"""Vectorized state-transition engine: structure-of-arrays epoch
processing behind the compiled spec modules.

The crypto plane (BLS, KZG, SHA-256) got device-batched rounds ago; the
protocol plane still ran the spec's per-validator Python loops. This
subsystem is the protocol plane's batching layer:

- :mod:`plane` — ``StatePlane``, the SoA mirror of BeaconState's
  registry-axis columns, with exact (overflow-guarded) uint64 helpers
  and sparse write-back that preserves SSZ dirty-tracking.
- :mod:`stages` — vectorized ``process_*`` implementations of the hot
  epoch sub-transitions across the production fork families (phase0 /
  altair / bellatrix+capella quotient deltas, capella's
  full-withdrawals registry sweep).
- :mod:`attestations` — committee resolution in array form, plus the
  batched block-path ``process_attestations_batch`` installed by
  :func:`use_batched_attestations` (sequentially exact, incl.
  rejection order — the chain simulator's hot loop, docs/SIM.md).
- :mod:`backend` / :mod:`ops_jax` — the NumPy-always / jnp-opt-in
  backend hook, the ``ops/`` convention applied to protocol math.
- :mod:`crosscheck` — the differential harness that holds every stage
  to hash_tree_root bit-identity against the interpreted oracle on
  randomized states (epoch processing on the host reference path is the
  oracle here, exactly as ``crypto/`` is the oracle for ``ops/``).

Install model: ``use_vectorized_epoch()`` swaps the stage functions in
every built (and every future) spec module via the specs.build module
hook — the same switchable-backend shape as ``use_device_hasher()`` and
``bls.use_backend("jax")``. Wrappers keep the interpreted function on
``__wrapped__`` and preserve ``__name__`` so ``epoch_process_steps()``
staging, generators, and the replayer see the same public surface
either way. ``use_interpreted_epoch()`` restores the originals.
"""
from __future__ import annotations

from typing import Dict

from .. import obs
from ..specs import build as _build
from . import stages
from .backend import active as backend_name  # noqa: F401  (public surface)
from .backend import use_backend

__all__ = [
    "use_vectorized_epoch",
    "use_interpreted_epoch",
    "is_vectorized",
    "use_batched_attestations",
    "use_direct_attestations",
    "is_batched_attestations",
    "use_backend",
    "backend_name",
    "STAGE_NAMES",
    "SUPPORTED_FORKS",
]

# The hot registry-axis sub-transitions with SoA implementations.
# process_full_withdrawals exists only on the capella family; _install_on
# skips the name on spec modules that lack it.
STAGE_NAMES = (
    "process_justification_and_finalization",
    "process_rewards_and_penalties",
    "process_inactivity_updates",
    "process_effective_balance_updates",
    "process_registry_updates",
    "process_slashings",
    "process_full_withdrawals",
)

# Production chain only: R&D branches (sharding/custody_game/das/eip4844)
# may re-shape epoch processing and are never auto-wrapped.
SUPPORTED_FORKS = ("phase0", "altair", "bellatrix", "capella")

_enabled = False


def _wrap_stage(spec, name: str):
    impl = getattr(stages, f"vectorized_{name}")
    interpreted = getattr(spec, name)

    def wrapped(state):
        with obs.span(f"epoch.{name}", fork=spec.fork, engine="vectorized"):
            return impl(spec, state)

    wrapped.__name__ = name
    wrapped.__qualname__ = f"engine.{name}[{spec.fork}]"
    wrapped.__doc__ = interpreted.__doc__
    wrapped.__wrapped__ = interpreted
    wrapped.engine_vectorized = True
    return wrapped


def _install_on(spec) -> None:
    """specs.build module hook: swap stage functions on one module."""
    if getattr(spec, "fork", None) not in SUPPORTED_FORKS:
        return
    for name in STAGE_NAMES:
        current = getattr(spec, name, None)
        if current is None or getattr(current, "engine_vectorized", False):
            continue
        setattr(spec, name, _wrap_stage(spec, name))


def _uninstall_from(spec) -> None:
    for name in STAGE_NAMES:
        current = getattr(spec, name, None)
        if current is not None and getattr(current, "engine_vectorized", False):
            setattr(spec, name, current.__wrapped__)


def use_vectorized_epoch() -> None:
    """Route the hot epoch stages of every built (and future) spec module
    through the SoA engine. Idempotent."""
    global _enabled
    _enabled = True
    _build.register_module_hook(_install_on)


def use_interpreted_epoch() -> None:
    """Restore the interpreted spec functions everywhere. Idempotent."""
    global _enabled
    _enabled = False
    _build.unregister_module_hook(_install_on)
    for mod in _build.cached_modules():
        _uninstall_from(mod)


def is_vectorized() -> bool:
    return _enabled


# ---------------------------------------------------------------------------
# Batched block-path attestations (engine/attestations.py)
# ---------------------------------------------------------------------------

_batched_atts = False


def _wrap_process_operations(spec):
    from .attestations import process_attestations_batch

    interpreted = spec.process_operations

    def wrapped(state, body):
        with obs.span("block.process_operations", fork=spec.fork,
                      engine="batched", attestations=len(body.attestations)):
            # the fork modules share this operation ORDER (capella appends
            # one op family); the attestation sweep is the batched path
            assert len(body.deposits) == min(
                spec.MAX_DEPOSITS,
                state.eth1_data.deposit_count - state.eth1_deposit_index,
            )
            for op in body.proposer_slashings:
                spec.process_proposer_slashing(state, op)
            for op in body.attester_slashings:
                spec.process_attester_slashing(state, op)
            process_attestations_batch(spec, state, body.attestations)
            for op in body.deposits:
                spec.process_deposit(state, op)
            for op in body.voluntary_exits:
                spec.process_voluntary_exit(state, op)
            if hasattr(body, "bls_to_execution_changes"):  # capella family
                for op in body.bls_to_execution_changes:
                    spec.process_bls_to_execution_change(state, op)

    wrapped.__name__ = "process_operations"
    wrapped.__qualname__ = f"engine.process_operations[{spec.fork}]"
    wrapped.__doc__ = interpreted.__doc__
    wrapped.__wrapped__ = interpreted
    wrapped.engine_batched_atts = True
    return wrapped


def _install_batched_atts_on(spec) -> None:
    if getattr(spec, "fork", None) not in SUPPORTED_FORKS:
        return
    current = getattr(spec, "process_operations", None)
    if current is None or getattr(current, "engine_batched_atts", False):
        return
    spec.process_operations = _wrap_process_operations(spec)


def use_batched_attestations() -> None:
    """Route every built (and future) spec module's block-body
    attestation sweep through the batched committee-cached path
    (engine/attestations.process_attestations_batch). Idempotent."""
    global _batched_atts
    _batched_atts = True
    _build.register_module_hook(_install_batched_atts_on)


def use_direct_attestations() -> None:
    """Restore the interpreted per-attestation loop everywhere."""
    global _batched_atts
    _batched_atts = False
    _build.unregister_module_hook(_install_batched_atts_on)
    for mod in _build.cached_modules():
        current = getattr(mod, "process_operations", None)
        if current is not None and getattr(current, "engine_batched_atts", False):
            mod.process_operations = current.__wrapped__


def is_batched_attestations() -> bool:
    return _batched_atts


def stage_status(spec) -> Dict[str, bool]:
    """{stage name: engine-installed?} for one spec module (diagnostics)."""
    return {
        name: getattr(getattr(spec, name, None), "engine_vectorized", False)
        for name in STAGE_NAMES
        if hasattr(spec, name)
    }
