"""Vectorized pending-attestation resolution (phase0 family).

Phase0 epoch accounting keys everything on *who attested*: committee
membership per (slot, index) sliced out of the swap-or-not permutation,
intersected with each attestation's aggregation bits. The interpreted
path materializes Python sets per attestation per component (source,
target, head, inclusion — four passes); here each attestation's member
rows are gathered ONCE as a NumPy index array from the cached shuffle
permutation, and every component reduces those arrays with boolean
scatters. Bit-identical by construction: the permutation is the spec's
own cached ``_shuffle_permutation``, the slicing mirrors
compute_committee's integer bounds exactly.
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np


class EpochCommittees:
    """Committee geometry of one epoch, in array form."""

    def __init__(self, spec, state, epoch: int) -> None:
        self.epoch = int(epoch)
        self.active = np.asarray(
            [int(i) for i in spec.get_active_validator_indices(state, epoch)],
            dtype=np.int64,
        )
        seed = spec.get_seed(state, epoch, spec.DOMAIN_BEACON_ATTESTER)
        self.perm = spec._shuffle_permutation(len(self.active), seed)
        self.committees_per_slot = int(spec.get_committee_count_per_slot(state, epoch))
        self.slots_per_epoch = int(spec.SLOTS_PER_EPOCH)
        self.count = self.committees_per_slot * self.slots_per_epoch

    def committee(self, slot: int, index: int) -> np.ndarray:
        """compute_committee's slice of the shuffled active set
        (beacon-chain.md:807) as validator-index rows."""
        i = (int(slot) % self.slots_per_epoch) * self.committees_per_slot + int(index)
        n = len(self.active)
        start = n * i // self.count
        end = n * (i + 1) // self.count
        assert end <= n  # the spec's per-element bound assert, batched
        return self.active[self.perm[start:end]]


def resolve_members(spec, state, attestations: Sequence,
                    cache: Dict[int, EpochCommittees]) -> List[Tuple[object, np.ndarray]]:
    """[(attestation, attesting validator rows)] — get_attesting_indices
    for every attestation in one pass, committees cached per epoch."""
    out = []
    for a in attestations:
        epoch = int(spec.compute_epoch_at_slot(a.data.slot))
        comm = cache.get(epoch)
        if comm is None:
            comm = cache[epoch] = EpochCommittees(spec, state, epoch)
        members = comm.committee(int(a.data.slot), int(a.data.index))
        bits = np.fromiter(a.aggregation_bits, dtype=bool, count=len(a.aggregation_bits))
        assert len(bits) == len(members)  # process_attestation's length contract
        out.append((a, members[bits]))
    return out


def attester_mask(n: int, resolved: Sequence[Tuple[object, np.ndarray]],
                  slashed: np.ndarray) -> np.ndarray:
    """get_unslashed_attesting_indices as a row mask: the union of all
    attesting rows, minus slashed."""
    mask = np.zeros(n, dtype=bool)
    for _, members in resolved:
        mask[members] = True
    return mask & ~slashed
