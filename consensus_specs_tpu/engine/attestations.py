"""Vectorized pending-attestation resolution (phase0 family).

Phase0 epoch accounting keys everything on *who attested*: committee
membership per (slot, index) sliced out of the swap-or-not permutation,
intersected with each attestation's aggregation bits. The interpreted
path materializes Python sets per attestation per component (source,
target, head, inclusion — four passes); here each attestation's member
rows are gathered ONCE as a NumPy index array from the cached shuffle
permutation, and every component reduces those arrays with boolean
scatters. Bit-identical by construction: the permutation is the spec's
own cached ``_shuffle_permutation``, the slicing mirrors
compute_committee's integer bounds exactly.
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np


class EpochCommittees:
    """Committee geometry of one epoch, in array form."""

    def __init__(self, spec, state, epoch: int) -> None:
        self.epoch = int(epoch)
        self.active = np.asarray(
            [int(i) for i in spec.get_active_validator_indices(state, epoch)],
            dtype=np.int64,
        )
        seed = spec.get_seed(state, epoch, spec.DOMAIN_BEACON_ATTESTER)
        self.perm = spec._shuffle_permutation(len(self.active), seed)
        self.committees_per_slot = int(spec.get_committee_count_per_slot(state, epoch))
        self.slots_per_epoch = int(spec.SLOTS_PER_EPOCH)
        self.count = self.committees_per_slot * self.slots_per_epoch

    def committee(self, slot: int, index: int) -> np.ndarray:
        """compute_committee's slice of the shuffled active set
        (beacon-chain.md:807) as validator-index rows."""
        i = (int(slot) % self.slots_per_epoch) * self.committees_per_slot + int(index)
        n = len(self.active)
        start = n * i // self.count
        end = n * (i + 1) // self.count
        assert end <= n  # the spec's per-element bound assert, batched
        return self.active[self.perm[start:end]]


def resolve_members(spec, state, attestations: Sequence,
                    cache: Dict[int, EpochCommittees]) -> List[Tuple[object, np.ndarray]]:
    """[(attestation, attesting validator rows)] — get_attesting_indices
    for every attestation in one pass, committees cached per epoch."""
    out = []
    for a in attestations:
        epoch = int(spec.compute_epoch_at_slot(a.data.slot))
        comm = cache.get(epoch)
        if comm is None:
            comm = cache[epoch] = EpochCommittees(spec, state, epoch)
        members = comm.committee(int(a.data.slot), int(a.data.index))
        bits = np.fromiter(a.aggregation_bits, dtype=bool, count=len(a.aggregation_bits))
        assert len(bits) == len(members)  # process_attestation's length contract
        out.append((a, members[bits]))
    return out


def attester_mask(n: int, resolved: Sequence[Tuple[object, np.ndarray]],
                  slashed: np.ndarray) -> np.ndarray:
    """get_unslashed_attesting_indices as a row mask: the union of all
    attesting rows, minus slashed."""
    mask = np.zeros(n, dtype=bool)
    for _, members in resolved:
        mask[members] = True
    return mask & ~slashed


# ---------------------------------------------------------------------------
# Batched block-path process_attestation (all four production forks)
# ---------------------------------------------------------------------------

def _assert_valid_indexed(spec, state, attestation, attesting: np.ndarray) -> None:
    """The spec's `assert is_valid_indexed_attestation(state,
    get_indexed_attestation(state, attestation))` with the committee
    gather reused: attesting rows are unique permutation slots, so
    sorted(rows) IS sorted(set(...)) and the container build + signature
    adjudication are the spec's own."""
    indexed = spec.IndexedAttestation(
        attesting_indices=sorted(int(i) for i in attesting),
        data=attestation.data,
        signature=attestation.signature,
    )
    assert spec.is_valid_indexed_attestation(state, indexed)


def _writeback_participation(column, new: np.ndarray, old: np.ndarray) -> None:
    for i in np.nonzero(new != old)[0]:
        column[int(i)] = int(new[i])


def process_attestations_batch(spec, state, attestations) -> None:
    """Sequentially-exact batch of the spec's per-attestation
    ``process_attestation`` loop (the block body's attestation sweep).

    Semantics contract: bit-identical to
    ``for a in attestations: spec.process_attestation(state, a)`` —
    including the assert ORDER on invalid input and the partial state
    mutation an invalid attestation leaves behind (earlier valid
    attestations stay applied; the block-level caller discards the
    state, but the differential tests hold the batch to the oracle's
    exact wreckage). What is batched:

    - committee resolution: one :class:`EpochCommittees` per target
      epoch (one shuffle-permutation slice table) instead of a
      ``get_beacon_committee`` walk per attestation;
    - altair-family participation flags: both epoch columns are
      mirrored as uint8 arrays once, each attestation's newly-set flags
      are a vector compare + scatter over its member rows, and the
      proposer-reward numerator is a vector gather-sum of the
      precomputed base-reward column (constant across the batch — no
      operation between attestations changes effective balances);
    - per-block invariants (proposer index, base reward per increment)
      resolved once instead of per attestation.

    The phase0 family appends PendingAttestations (cheap) but still
    wins the committee cache and the single proposer resolution.
    """
    atts = list(attestations)
    if not atts:
        return
    n = len(state.validators)
    prev_ep = spec.get_previous_epoch(state)
    cur_ep = spec.get_current_epoch(state)
    cache: Dict[int, EpochCommittees] = {}
    proposer = None  # resolved once, lazily (constant while state.slot is fixed)
    post_altair = hasattr(state, "current_epoch_participation")
    if post_altair:
        cur_col = np.fromiter(state.current_epoch_participation, dtype=np.uint8, count=n)
        prev_col = np.fromiter(state.previous_epoch_participation, dtype=np.uint8, count=n)
        cur_snap, prev_snap = cur_col.copy(), prev_col.copy()
        incr = np.uint64(int(spec.EFFECTIVE_BALANCE_INCREMENT))
        brpi = np.uint64(int(spec.get_base_reward_per_increment(state)))
        base_reward = (
            np.fromiter((int(v.effective_balance) for v in state.validators),
                        dtype=np.uint64, count=n) // incr
        ) * brpi
        weights = [int(w) for w in spec.PARTICIPATION_FLAG_WEIGHTS]
        wd, pw = int(spec.WEIGHT_DENOMINATOR), int(spec.PROPOSER_WEIGHT)
        proposer_reward_denominator = (wd - pw) * wd // pw
    try:
        for a in atts:
            data = a.data
            # the spec's rejection ladder, verbatim order
            assert data.target.epoch in (prev_ep, cur_ep)
            assert data.target.epoch == spec.compute_epoch_at_slot(data.slot)
            assert (data.slot + spec.MIN_ATTESTATION_INCLUSION_DELAY
                    <= state.slot <= data.slot + spec.SLOTS_PER_EPOCH)
            assert data.index < spec.get_committee_count_per_slot(state, data.target.epoch)

            epoch = int(data.target.epoch)
            comm = cache.get(epoch)
            if comm is None:
                comm = cache[epoch] = EpochCommittees(spec, state, epoch)
            members = comm.committee(int(data.slot), int(data.index))
            assert len(a.aggregation_bits) == len(members)
            bits = np.fromiter(a.aggregation_bits, dtype=bool, count=len(members))
            attesting = members[bits]
            if proposer is None:
                proposer = spec.ValidatorIndex(int(spec.get_beacon_proposer_index(state)))

            if not post_altair:
                pending = spec.PendingAttestation(
                    data=data,
                    aggregation_bits=a.aggregation_bits,
                    inclusion_delay=state.slot - data.slot,
                    proposer_index=proposer,
                )
                if data.target.epoch == cur_ep:
                    assert data.source == state.current_justified_checkpoint
                    state.current_epoch_attestations.append(pending)
                else:
                    assert data.source == state.previous_justified_checkpoint
                    state.previous_epoch_attestations.append(pending)
                # signature last (cheapest rejections first), like the spec
                _assert_valid_indexed(spec, state, a, attesting)
                continue

            # altair family: flag indices raise on source mismatch (the
            # spec's assert is inside get_attestation_participation_flag_indices)
            flag_indices = spec.get_attestation_participation_flag_indices(
                state, data, state.slot - data.slot
            )
            _assert_valid_indexed(spec, state, a, attesting)
            col = cur_col if data.target.epoch == cur_ep else prev_col
            numerator = 0
            for flag_index in flag_indices:
                flag = np.uint8(1 << int(flag_index))
                fresh = attesting[(col[attesting] & flag) == 0]
                if fresh.size:
                    col[fresh] |= flag
                    numerator += int(base_reward[fresh].sum(dtype=object)) * weights[int(flag_index)]
            reward = spec.Gwei(numerator // proposer_reward_denominator)
            spec.increase_balance(state, proposer, reward)
    finally:
        # the mirrors land in the SSZ columns on EVERY exit path, so a
        # mid-batch rejection leaves exactly the oracle's partial state
        if post_altair:
            _writeback_participation(state.current_epoch_participation, cur_col, cur_snap)
            _writeback_participation(state.previous_epoch_participation, prev_col, prev_snap)
