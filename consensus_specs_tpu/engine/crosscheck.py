"""Differential cross-check harness: the interpreted spec is the oracle
for the vectorized engine, exactly as ``crypto/`` is the oracle for
``ops/``.

For every vectorized stage, randomized registry states are run through
BOTH implementations and the post-states must be bit-identical under
``hash_tree_root`` — not "close", not "same balances": the same Merkle
root. The state factory synthesizes registries directly (deterministic
fake pubkeys — epoch processing never opens them), so it is fast enough
for tier-1 CI and independent of the BLS key table.

Randomization deliberately covers the nasty rows: slashed validators at
the exact slashing-penalty epoch, sub-ejection effective balances,
pending activation queues crossing the churn limit, inactivity scores
large enough to overflow a naive uint64 product, leak and non-leak
finality gaps, and (phase0) pending attestations with mixed
target/head matches and duplicate-index inclusion delays.

Run directly for a manual sweep:
    python -m consensus_specs_tpu.engine.crosscheck
"""
from __future__ import annotations

import sys
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import STAGE_NAMES, SUPPORTED_FORKS, stages
from ..specs import build_spec

MIN_EPOCHS_FOR_REWARDS = 2  # justification/rewards short-circuit below this


def _fake_pubkey(i: int) -> bytes:
    # 48 deterministic bytes; never fed to BLS (epoch stages don't verify)
    return bytes([0xAA]) + i.to_bytes(8, "little") * 5 + bytes(7)


def _random_validator(spec, rng, i: int, current_epoch: int):
    incr = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    max_eff = int(spec.MAX_EFFECTIVE_BALANCE)
    far = int(spec.FAR_FUTURE_EPOCH)
    epsv = int(spec.EPOCHS_PER_SLASHINGS_VECTOR)

    roll = rng.random()
    if roll < 0.6:
        effective_balance = max_eff
    elif roll < 0.8:
        effective_balance = incr * int(rng.integers(1, max_eff // incr + 1))
    else:  # at/below ejection balance — feeds the exit queue
        effective_balance = incr * int(rng.integers(0, int(spec.config.EJECTION_BALANCE) // incr + 1))

    slashed = bool(rng.random() < 0.15)

    r = rng.random()
    if r < 0.70:
        activation_epoch, eligibility = 0, 0
    elif r < 0.85:  # pending in the activation queue
        activation_epoch = far
        eligibility = far if rng.random() < 0.4 else int(rng.integers(0, current_epoch + 2))
    else:  # scheduled future activation
        activation_epoch = current_epoch + int(rng.integers(1, 6))
        eligibility = int(rng.integers(0, current_epoch + 1))

    r = rng.random()
    if r < 0.70:
        exit_epoch: int = far
        withdrawable = far
    elif r < 0.85:
        exit_epoch = int(rng.integers(max(1, current_epoch - 2), current_epoch + 8))
        withdrawable = exit_epoch + int(spec.config.MIN_VALIDATOR_WITHDRAWABILITY_DELAY)
    else:
        # long-exited and ALREADY withdrawable — the rows capella's
        # full-withdrawals sweep must actually withdraw
        exit_epoch = int(rng.integers(0, max(1, current_epoch)))
        withdrawable = int(rng.integers(exit_epoch, current_epoch + 1))
    if slashed and rng.random() < 0.5:
        # land exactly on the proportional-penalty epoch
        withdrawable = current_epoch + epsv // 2

    fields = dict(
        pubkey=_fake_pubkey(i),
        withdrawal_credentials=(
            bytes(spec.ETH1_ADDRESS_WITHDRAWAL_PREFIX) + bytes(11) + i.to_bytes(20, "little")
            if rng.random() < 0.5
            else bytes(spec.BLS_WITHDRAWAL_PREFIX) + bytes(31)
        ),
        effective_balance=effective_balance,
        slashed=slashed,
        activation_eligibility_epoch=eligibility,
        activation_epoch=activation_epoch,
        exit_epoch=exit_epoch,
        withdrawable_epoch=withdrawable,
    )
    if "fully_withdrawn_epoch" in spec.Validator._fields:  # capella
        fields["fully_withdrawn_epoch"] = far
    return spec.Validator(**fields)


def _phase0_pending_attestations(spec, state, rng, epoch: int) -> List:
    """Pending attestations with valid committee geometry and a mix of
    target/head matches; bits sized to the real committees."""
    atts = []
    committees_per_slot = int(spec.get_committee_count_per_slot(state, spec.Epoch(epoch)))
    start = int(spec.compute_start_slot_at_epoch(spec.Epoch(epoch)))
    spe = int(spec.SLOTS_PER_EPOCH)
    n = len(state.validators)
    for slot in range(start, min(start + spe, int(state.slot))):
        for index in range(committees_per_slot):
            if rng.random() < 0.3:
                continue
            committee = spec.get_beacon_committee(
                state, spec.Slot(slot), spec.CommitteeIndex(index)
            )
            bits = [bool(rng.random() < 0.6) for _ in committee]
            target_root = (
                spec.get_block_root(state, spec.Epoch(epoch))
                if rng.random() < 0.7
                else bytes(rng.integers(0, 256, 32, dtype=np.uint8))
            )
            head_root = (
                spec.get_block_root_at_slot(state, spec.Slot(slot))
                if rng.random() < 0.7
                else bytes(rng.integers(0, 256, 32, dtype=np.uint8))
            )
            atts.append(
                spec.PendingAttestation(
                    aggregation_bits=spec.Bitlist[spec.MAX_VALIDATORS_PER_COMMITTEE](bits),
                    data=spec.AttestationData(
                        slot=slot,
                        index=index,
                        beacon_block_root=head_root,
                        source=state.previous_justified_checkpoint,
                        target=spec.Checkpoint(epoch=epoch, root=target_root),
                    ),
                    inclusion_delay=int(rng.integers(1, spe + 1)),
                    proposer_index=int(rng.integers(0, n)),
                )
            )
    return atts


def random_epoch_state(spec, seed: int = 0, n_validators: int = 80, epoch: int = 3,
                       leak: Optional[bool] = None):
    """A randomized BeaconState positioned at the last slot of ``epoch``
    (where process_epoch fires), registry-axis fields fuzzed."""
    rng = np.random.default_rng(seed)
    spe = int(spec.SLOTS_PER_EPOCH)
    slot = epoch * spe + spe - 1

    state = spec.BeaconState(
        genesis_time=0,
        slot=slot,
        fork=spec.Fork(
            previous_version=spec.config.GENESIS_FORK_VERSION,
            current_version=spec.config.GENESIS_FORK_VERSION,
            epoch=0,
        ),
        latest_block_header=spec.BeaconBlockHeader(
            body_root=spec.hash_tree_root(spec.BeaconBlockBody())
        ),
    )

    for i in range(int(spec.SLOTS_PER_HISTORICAL_ROOT)):
        state.block_roots[i] = bytes(rng.integers(0, 256, 32, dtype=np.uint8))
        state.state_roots[i] = bytes(rng.integers(0, 256, 32, dtype=np.uint8))
    for i in range(int(spec.EPOCHS_PER_HISTORICAL_VECTOR)):
        state.randao_mixes[i] = bytes(rng.integers(0, 256, 32, dtype=np.uint8))
    for i in range(int(spec.EPOCHS_PER_SLASHINGS_VECTOR)):
        if rng.random() < 0.3:
            state.slashings[i] = int(rng.integers(0, 64)) * int(spec.EFFECTIVE_BALANCE_INCREMENT)

    incr = int(spec.EFFECTIVE_BALANCE_INCREMENT)
    for i in range(n_validators):
        v = _random_validator(spec, rng, i, epoch)
        state.validators.append(v)
        state.balances.append(
            min(int(v.effective_balance) + int(rng.integers(0, 2 * incr)), 2**62)
        )

    # Finality plumbing: leak=True opens the inactivity-leak gap wide,
    # leak=False keeps finality fresh, None randomizes.
    if leak is True:
        finalized_epoch = 0
    elif leak is False:
        finalized_epoch = max(0, epoch - 2)
    else:
        finalized_epoch = int(rng.integers(0, max(1, epoch - 1)))
    root_of = lambda e: spec.get_block_root(state, spec.Epoch(e)) if e < epoch else b"\x00" * 32  # noqa: E731
    state.finalized_checkpoint = spec.Checkpoint(
        epoch=finalized_epoch, root=root_of(finalized_epoch)
    )
    pj = int(rng.integers(finalized_epoch, epoch))
    cj = int(rng.integers(pj, epoch))
    state.previous_justified_checkpoint = spec.Checkpoint(epoch=pj, root=root_of(pj))
    state.current_justified_checkpoint = spec.Checkpoint(epoch=cj, root=root_of(cj))
    for i in range(int(spec.JUSTIFICATION_BITS_LENGTH)):
        state.justification_bits[i] = bool(rng.random() < 0.5)

    if hasattr(state, "previous_epoch_participation"):  # altair family
        flags = rng.integers(0, 8, n_validators, dtype=np.uint8)
        state.previous_epoch_participation = [int(f) for f in flags]
        flags = rng.integers(0, 8, n_validators, dtype=np.uint8)
        state.current_epoch_participation = [int(f) for f in flags]
        scores = rng.integers(0, 1 << 20, n_validators).astype(object)
        # a few rows large enough that effective_balance * score wraps a
        # naive uint64 product (forcing the guarded-multiply fallback)
        # while the resulting PENALTY still fits Gwei — scores past that
        # make the interpreted oracle itself raise, i.e. unreachable states
        for i in rng.choice(n_validators, size=max(1, n_validators // 16), replace=False):
            scores[i] = int(rng.integers(1 << 34, 1 << 40))
        state.inactivity_scores = [int(s) for s in scores]
    else:  # phase0: pending attestations drive the accounting
        state.previous_epoch_attestations = _phase0_pending_attestations(
            spec, state, rng, epoch - 1
        )
        state.current_epoch_attestations = _phase0_pending_attestations(
            spec, state, rng, epoch
        )
    return state


def stages_for(spec) -> List[str]:
    return [n for n in STAGE_NAMES if hasattr(spec, n)]


def crosscheck_stage(spec, name: str, state) -> Tuple[bool, str, str]:
    """(identical?, interpreted root, vectorized root) for one stage on
    one state. Unwraps an installed engine so the oracle side is always
    the interpreted spec function."""
    current = getattr(spec, name)
    interpreted = getattr(current, "__wrapped__", current)
    vectorized = getattr(stages, f"vectorized_{name}")
    a, b = state.copy(), state.copy()
    interpreted(a)
    vectorized(spec, b)
    ra, rb = bytes(a.hash_tree_root()), bytes(b.hash_tree_root())
    return ra == rb, ra.hex(), rb.hex()


def run_crosscheck(forks: Sequence[str] = SUPPORTED_FORKS, preset: str = "minimal",
                   seeds: Sequence[int] = (0, 1), n_validators: int = 80,
                   epochs: Sequence[int] = (3, 6)) -> Dict:
    """Sweep every stage x fork x seed x epoch; returns a report with any
    divergences under ``failures``."""
    checked, failures = 0, []
    for fork in forks:
        spec = build_spec(fork, preset)
        for seed in seeds:
            for epoch in epochs:
                for leak in (False, True):
                    state = random_epoch_state(
                        spec, seed=seed, n_validators=n_validators, epoch=epoch, leak=leak
                    )
                    for name in stages_for(spec):
                        same, ra, rb = crosscheck_stage(spec, name, state)
                        checked += 1
                        if not same:
                            failures.append(
                                {"fork": fork, "stage": name, "seed": seed,
                                 "epoch": epoch, "leak": leak,
                                 "interpreted": ra, "vectorized": rb}
                            )
    return {"checked": checked, "failures": failures}


def main() -> int:
    report = run_crosscheck()
    print(f"crosscheck: {report['checked']} stage runs, "
          f"{len(report['failures'])} divergences")
    for f in report["failures"]:
        print(f"DIVERGED {f['fork']}/{f['stage']} seed={f['seed']} epoch={f['epoch']} "
              f"leak={f['leak']}: {f['interpreted']} != {f['vectorized']}")
    return 1 if report["failures"] else 0


if __name__ == "__main__":
    sys.exit(main())
