"""Seeded random SSZ object generation with modes + chaos — drives the
ssz_static fuzz vectors (ref: eth2spec/debug/random_value.py)."""
from __future__ import annotations

from enum import Enum
from random import Random

from consensus_specs_tpu.ssz.types import (
    Bitlist,
    Bitvector,
    ByteList,
    ByteVector,
    Container,
    List,
    Union,
    Vector,
    boolean,
    uint,
)

# in case the RNG returns a heavy list length, cap it (same spirit as
# random_value.py:12)
MAX_LIST_LENGTH = 10


class RandomizationMode(Enum):
    mode_random = 0
    mode_zero = 1
    mode_max = 2
    mode_nil_count = 3
    mode_one_count = 4
    mode_max_count = 5

    def to_name(self) -> str:
        return {
            RandomizationMode.mode_random: "random",
            RandomizationMode.mode_zero: "zero",
            RandomizationMode.mode_max: "max",
            RandomizationMode.mode_nil_count: "nil",
            RandomizationMode.mode_one_count: "one",
            RandomizationMode.mode_max_count: "max_count",
        }[self]

    def is_changing(self) -> bool:
        return self.value in (0, 4, 5)


def get_random_ssz_object(rng: Random, typ, max_bytes_length: int, max_list_length: int,
                          mode: RandomizationMode, chaos: bool):
    """Random value of the given SSZ type (ref random_value.py:38-160).
    With ``chaos`` the mode itself is randomized per element."""
    if chaos:
        mode = rng.choice(list(RandomizationMode))

    if issubclass(typ, ByteList):
        if mode == RandomizationMode.mode_nil_count:
            length = 0
        elif mode == RandomizationMode.mode_max_count:
            length = min(typ.limit, max_bytes_length)
        elif mode == RandomizationMode.mode_one_count:
            length = 1
        elif mode == RandomizationMode.mode_zero:
            length = 0
        else:
            length = rng.randint(0, min(typ.limit, max_bytes_length))
        return typ(get_random_bytes_list(rng, length))

    if issubclass(typ, ByteVector):
        if mode == RandomizationMode.mode_zero:
            return typ(b"\x00" * typ.length)
        if mode == RandomizationMode.mode_max:
            return typ(b"\xff" * typ.length)
        return typ(get_random_bytes_list(rng, typ.length))

    if issubclass(typ, boolean):
        if mode == RandomizationMode.mode_zero:
            return typ(False)
        if mode == RandomizationMode.mode_max:
            return typ(True)
        return typ(rng.choice((True, False)))

    if issubclass(typ, uint):
        if mode == RandomizationMode.mode_zero:
            return typ(0)
        if mode == RandomizationMode.mode_max:
            return typ(2 ** (typ.byte_len * 8) - 1)
        return typ(rng.randint(0, 2 ** (typ.byte_len * 8) - 1))

    if issubclass(typ, Bitvector):
        if mode == RandomizationMode.mode_zero:
            return typ([False] * typ.length)
        if mode == RandomizationMode.mode_max:
            return typ([True] * typ.length)
        return typ([rng.choice((True, False)) for _ in range(typ.length)])

    if issubclass(typ, Bitlist):
        if mode == RandomizationMode.mode_nil_count or mode == RandomizationMode.mode_zero:
            length = 0
        elif mode == RandomizationMode.mode_one_count:
            length = min(1, typ.limit)
        elif mode == RandomizationMode.mode_max_count:
            length = min(typ.limit, max_list_length)
        else:
            length = rng.randint(0, min(typ.limit, max_list_length))
        return typ([rng.choice((True, False)) for _ in range(length)])

    if issubclass(typ, Vector):
        return typ([
            get_random_ssz_object(rng, typ.element_type, max_bytes_length, max_list_length, mode, chaos)
            for _ in range(typ.length)
        ])

    if issubclass(typ, List):
        if mode == RandomizationMode.mode_nil_count or mode == RandomizationMode.mode_zero:
            length = 0
        elif mode == RandomizationMode.mode_one_count:
            length = min(1, typ.limit)
        elif mode == RandomizationMode.mode_max_count:
            length = min(typ.limit, max_list_length)
        else:
            length = rng.randint(0, min(typ.limit, max_list_length))
        return typ([
            get_random_ssz_object(rng, typ.element_type, max_bytes_length, max_list_length, mode, chaos)
            for _ in range(length)
        ])

    if issubclass(typ, Container):
        return typ(**{
            name: get_random_ssz_object(rng, field_typ, max_bytes_length, max_list_length, mode, chaos)
            for name, field_typ in typ.fields().items()
        })

    if issubclass(typ, Union):
        selector = rng.randrange(len(typ.options)) if mode == RandomizationMode.mode_random else 0
        opt = typ.options[selector]
        value = None if opt is None else get_random_ssz_object(
            rng, opt, max_bytes_length, max_list_length, mode, chaos
        )
        return typ(selector, value)

    raise TypeError(f"can't generate random value for {typ}")


def get_random_bytes_list(rng: Random, length: int) -> bytes:
    return bytes(rng.getrandbits(8) for _ in range(length))
