"""Debug tooling: SSZ value <-> jsonable encoding and seeded random object
generation (ref: eth2spec/debug/{encode,decode,random_value}.py)."""
