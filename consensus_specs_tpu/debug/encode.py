"""SSZ value → jsonable/yamlable structure (ref: eth2spec/debug/encode.py)."""
from __future__ import annotations

from consensus_specs_tpu.ssz.types import (
    ByteList,
    ByteVector,
    Container,
    Union,
    boolean,
    uint,
    _BitsBase,
    _SequenceBase,
)


def encode(value):
    if isinstance(value, boolean):
        return bool(value)
    if isinstance(value, uint):
        # wider-than-64-bit uints go to strings (yaml precision), matching
        # the reference vector format (debug/encode.py: > 8 byte length)
        return int(value) if value.type_byte_length() <= 8 else str(int(value))
    if isinstance(value, (ByteVector, ByteList)):
        return "0x" + bytes(value).hex()
    if isinstance(value, _BitsBase):
        return "0x" + value.encode_bytes().hex()
    if isinstance(value, _SequenceBase):
        return [encode(element) for element in value]
    if isinstance(value, Container):
        return {name: encode(getattr(value, name)) for name in value.fields()}
    if isinstance(value, Union):
        return {"selector": int(value.selector), "value": None if value.value is None else encode(value.value)}
    if isinstance(value, (bytes, bytearray)):
        return "0x" + bytes(value).hex()
    raise TypeError(f"can't encode {value!r} of type {type(value)}")
