"""jsonable structure → SSZ value (ref: eth2spec/debug/decode.py)."""
from __future__ import annotations

from consensus_specs_tpu.ssz.types import (
    ByteList,
    ByteVector,
    Container,
    Union,
    _BitsBase,
    _SequenceBase,
    boolean,
    uint,
)


def decode(data, typ):
    if issubclass(typ, boolean):
        return typ(data)
    if issubclass(typ, uint):
        return typ(int(data))
    if issubclass(typ, (ByteVector, ByteList)):
        return typ(bytes.fromhex(data[2:] if isinstance(data, str) and data.startswith("0x") else data))
    if issubclass(typ, _BitsBase):
        raw = bytes.fromhex(data[2:]) if isinstance(data, str) else bytes(data)
        return typ.decode_bytes(raw)
    if issubclass(typ, _SequenceBase):
        return typ([decode(element, typ.element_type) for element in data])
    if issubclass(typ, Container):
        return typ(**{
            name: decode(data[name], field_typ)
            for name, field_typ in typ.fields().items()
        })
    if issubclass(typ, Union):
        selector = int(data["selector"])
        opt = typ.options[selector]
        value = None if opt is None else decode(data["value"], opt)
        return typ(selector, value)
    raise TypeError(f"can't decode into {typ}")
